"""MatrixTable: 2-D row-sharded parameter matrix with row-batch Add/Get.

TPU-native equivalent of the reference MatrixTable family
(ref: include/multiverso/table/matrix_table.h, src/table/matrix_table.cpp and
the newer include/multiverso/table/matrix.h / src/table/matrix.cpp). The
reference row-shards across servers in contiguous blocks
(src/table/matrix_table.cpp:24-45) and routes row ids to servers by
``row_id / rows_per_server`` (:266-313). Here the same layout is
``NamedSharding(mesh, P(axis, None))`` and row routing is XLA gather/scatter
over ICI.

Row-batch ops and XLA static shapes: row-id sets have dynamic size, which
fights jit compilation (SURVEY §7 "hard parts"). We bucket the batch size to
the next power of two, pad the id list with a dedicated *scratch row* that
lives in the table's row padding (never logically visible), and mask nothing:
padded entries gather the scratch row, compute garbage, and scatter garbage
back into the scratch row only. One compiled program per bucket size.

Updater locality parity: the reference server applies the updater only to the
*received* rows of a row Add (untouched rows keep their momentum/adagrad state
frozen). We reproduce that with gather -> per-row updater -> scatter, instead
of a full-table update with a zero-padded delta (which would decay untouched
rows under momentum).

Duplicate row ids within one call are pre-aggregated host-side
(``np.add.at``), matching the reference's per-row accumulation order-free.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from multiverso_tpu import updaters as updaters_lib
from multiverso_tpu.ops import row_assemble as _rowasm
from multiverso_tpu.serving import hotcache as _hotcache
from multiverso_tpu.table import ArrayLike, Table
from multiverso_tpu.updaters import AddOption
from multiverso_tpu.utils import config
from multiverso_tpu.utils.dashboard import monitor

# NOTE: the hand-written Pallas row gather/scatter kernels that once sat
# behind a "pallas" flag were REMOVED (r4): measured on-chip, XLA's native
# gather/scatter beat them at every bucket size tried (375 vs 408 us row
# add at 4k rows; 1.1 vs 3.2 ms scatter at 49k), so they were dead weight.
# The winning Pallas kernels live in ops/attention_kernels.py (flash
# attention fwd+bwd, default ON in the transformer).


def _bucket_size(k: int, cap: int) -> int:
    # one bucketing rule repo-wide (ops/row_assemble.bucket_rows is the
    # shared home): the cache mirror's jit-trace buckets and the table
    # layer's must never drift apart, or warm programs retrace
    return min(_rowasm.bucket_rows(k), cap)


class MatrixTable(Table):
    def __init__(self, num_row: int, num_col: int, dtype=jnp.float32,
                 updater: Union[str, updaters_lib.Updater, None] = None,
                 name: str = "matrix",
                 init=None, seed: Optional[int] = None,
                 init_scale: float = 0.0):
        super().__init__((int(num_row), int(num_col)), dtype=dtype,
                         updater=updater, name=name, init=init, seed=seed,
                         init_scale=init_scale)
        # hot-row training cache (flag train_cache_rows; ISSUE 11): a
        # full-hit get serves host rows with no device gather/transfer.
        # Write-through is exact here even multi-process: the collective
        # row add hands every process the UNION delta the updater applies,
        # so a plain-add table's cached copy tracks the device rows
        # bit-for-bit
        self._train_cache = _hotcache.make_train_cache(
            name, int(num_col), self.dtype,
            writethrough_ok=(getattr(self.updater, "name", "")
                             == "default"))

    @property
    def num_row(self) -> int:
        return self.shape[0]

    @property
    def num_col(self) -> int:
        return self.shape[1]

    @property
    def _scratch_row(self) -> int:
        # Table.__init__ pads rows to a multiple of shards with >= 1 spare.
        return self._padded_rows - 1

    # ------------------------------------------------------------------ #
    # jitted row programs (one per bucket size)
    # ------------------------------------------------------------------ #
    def _state_row_axis(self, leaf) -> Optional[int]:
        """Axis of ``leaf`` that corresponds to the table row axis, or None."""
        nd, pd = np.ndim(leaf), len(self._padded_shape)
        if nd >= pd and tuple(np.shape(leaf)[nd - pd:]) == self._padded_shape:
            return nd - pd
        return None

    def _row_update_fn(self, bucket: int):
        key = ("row_update", bucket)
        fn = self._jit_cache.get(key)
        if fn is not None:
            return fn

        def _update(data, ustate, ids, vals, opt):
            state = self.functional_add_rows(
                {"data": data, "ustate": ustate}, ids, vals, opt)
            token = jnp.ravel(state["data"])[0]
            return state["data"], state["ustate"], token

        fn = jax.jit(_update, donate_argnums=(0, 1))
        self._jit_cache[key] = fn
        return fn

    def _row_get_fn(self):
        # one cached fn: jit's own shape-keyed trace cache handles the
        # per-bucket variation
        fn = self._jit_cache.get("row_get")
        if fn is None:
            fn = jax.jit(lambda data, ids: jnp.take(data, ids, axis=0))
            self._jit_cache["row_get"] = fn
        return fn

    def _prep_ids(self, row_ids, values: Optional[np.ndarray] = None
                  ) -> Tuple[np.ndarray, Optional[np.ndarray], int,
                             Optional[np.ndarray]]:
        """Dedupe, validate, and bucket-pad a row-id batch.

        Returns (padded_ids, padded_vals, unique_count, inverse) where
        ``inverse`` maps each original position to its unique slot (used by
        get_rows to re-expand duplicates). Deduping both directions keeps the
        unique count <= num_row <= padded_rows, so the bucket cap can never
        underflow the pad.
        """
        raw = np.asarray(row_ids)
        if raw.size == 0:
            raise ValueError("empty row_ids")
        if not np.issubdtype(raw.dtype, np.integer):
            raise TypeError(f"row_ids must be integers, got dtype "
                            f"{raw.dtype} (silent float truncation would "
                            f"hit arbitrary rows)")
        ids = raw.astype(np.int32).reshape(-1)
        if np.any((ids < 0) | (ids >= self.num_row)):
            raise IndexError(f"row id out of range [0, {self.num_row})")
        uids, inv = np.unique(ids, return_inverse=True)
        if values is not None:
            vals = np.asarray(values, dtype=self.dtype).reshape(
                ids.size, self.num_col)
            acc = np.zeros((uids.size, self.num_col), dtype=np.float64)
            np.add.at(acc, inv, vals.astype(np.float64))
            vals = acc.astype(self.dtype)
        else:
            vals = None
        ids = uids.astype(np.int32)
        k = ids.size
        bucket = _bucket_size(k, self._padded_rows)
        pad = bucket - k
        if pad:
            ids = np.concatenate(
                [ids, np.full(pad, self._scratch_row, np.int32)])
            if vals is not None:
                vals = np.concatenate(
                    [vals, np.zeros((pad, self.num_col), self.dtype)])
        return ids, vals, k, inv

    def _union_across_processes(self, ids: np.ndarray, vals: np.ndarray
                                ) -> Tuple[np.ndarray, np.ndarray]:
        """Merge per-process (ids, vals) into the deduped union with summed
        values, identically on every process. Ids arrive bucket-padded with
        the scratch row; sizes differ per process, so the allgather pads to
        the global max bucket with scratch/zero first."""
        from jax.experimental import multihost_utils
        n = np.array([ids.size], np.int64)
        max_n = int(np.max(multihost_utils.process_allgather(n, tiled=False)))
        if ids.size < max_n:
            pad = max_n - ids.size
            ids = np.concatenate(
                [ids, np.full(pad, self._scratch_row, np.int32)])
            vals = np.concatenate(
                [vals, np.zeros((pad, self.num_col), self.dtype)])
        gids = np.asarray(multihost_utils.process_allgather(ids, tiled=False))
        gvals = np.asarray(multihost_utils.process_allgather(vals,
                                                             tiled=False))
        flat_ids = gids.reshape(-1)
        flat_vals = gvals.reshape(-1, self.num_col)
        keep = flat_ids != self._scratch_row
        uids, inv = np.unique(flat_ids[keep], return_inverse=True)
        acc = np.zeros((uids.size, self.num_col), np.float64)
        np.add.at(acc, inv, flat_vals[keep].astype(np.float64))
        ids = uids.astype(np.int32)
        vals = acc.astype(self.dtype)
        bucket = _bucket_size(ids.size, self._padded_rows)
        if bucket > ids.size:
            pad = bucket - ids.size
            ids = np.concatenate(
                [ids, np.full(pad, self._scratch_row, np.int32)])
            vals = np.concatenate(
                [vals, np.zeros((pad, self.num_col), self.dtype)])
        return ids, vals

    # ------------------------------------------------------------------ #
    # public row ops (ref matrix_table.h:26-75 overload family)
    # ------------------------------------------------------------------ #
    def add_rows_async(self, row_ids, values,
                       opt: Optional[AddOption] = None) -> int:
        opt = opt or AddOption()
        self._mark_mutated()
        with monitor(f"table[{self.name}].add_rows"), self._dispatch_lock:
            ids, vals, _, _ = self._prep_ids(row_ids, values)
            if self._zoo.size() > 1:
                # collective row add; per-process id sets may DIFFER (the
                # WordEmbedding traffic pattern, ref communicator.cpp:
                # 104-142): processes agree on the union of their ids and
                # sum the contributions. Still lockstep (every process must
                # call) — the uncoordinated path is multiverso_tpu.ps.
                ids, vals = self._union_across_processes(ids, vals)
            if self._train_cache is not None:
                # the UNION delta — exactly what the updater applies (pad
                # slots point at scratch_row >= num_row: never cached, so
                # their zero vals are ignored by the cache)
                self._train_cache.on_push(ids, vals)
            fn = self._row_update_fn(ids.size)
            self._data, self._ustate, token = fn(
                self._data, self._ustate,
                jax.device_put(ids, self._replicated),
                jax.device_put(vals, self._replicated), opt)
            # subclass hook, fed the ids ACTUALLY applied (the cross-process
            # union, not just this worker's set): the sparse table's dirty
            # bits must cover rows other workers contributed
            self._rows_applied(ids)
            self._version_applied()
        return self._track(token)

    def _rows_applied(self, ids: np.ndarray) -> None:
        """Called under the dispatch lock with the final (deduped, padded,
        cross-process-unioned) row ids of an add. Default: nothing."""

    def add_rows(self, row_ids, values, opt: Optional[AddOption] = None) -> None:
        self.wait(self.add_rows_async(row_ids, values, opt))

    def get_rows_async(self, row_ids) -> int:
        self._flush_host_adds()   # row reads see prior whole-table adds
        with monitor(f"table[{self.name}].get_rows"), self._dispatch_lock:
            ids, _, k, inv = self._prep_ids(row_ids)
            tc = self._train_cache
            uids = ids[:k]
            token = 0
            if tc is not None:
                tc.on_get()
                # serve_full: token + membership + gather in ONE cache
                # lock hold (a wait()-thread fill_since cannot skew
                # positions mid-serve); pushes order against the token
                # via _dispatch_lock, which both paths hold. All-or-
                # nothing: the partial path below refetches ALL k rows
                # from the device, so a partial host gather is wasted
                token, buf = tc.serve_full(uids.astype(np.int64))
                if buf is not None:
                    # full hit: serve the host copy — no device gather,
                    # no device->host transfer (write-through keeps it
                    # bit-identical to the device rows; invalidate
                    # guarantees pushed rows can't be here)
                    tc.count(k, 0)
                    return self._track(buf, lambda b: b[inv])
                tc.count(0, k)
            fn = self._row_get_fn()
            rows = fn(self._data, jax.device_put(ids, self._replicated))
            try:
                rows.copy_to_host_async()
            except AttributeError:
                pass

            def _fin(r):
                host = self._to_host(r)[:k]
                if tc is not None:
                    # warm for the next block, reconciled against pushes
                    # dispatched since the token (fill_since replay)
                    tc.fill_since(uids.astype(np.int64), host, token)
                return host[inv]

            return self._track(rows, _fin)

    def get_rows(self, row_ids, out: Optional[np.ndarray] = None) -> np.ndarray:
        host = self.wait(self.get_rows_async(row_ids))
        if out is not None:
            np.copyto(out.reshape(host.shape), host)
            return out
        return host

    def get_row(self, row_id: int, out: Optional[np.ndarray] = None) -> np.ndarray:
        row = self.get_rows([row_id])
        if out is not None:
            np.copyto(out.reshape(self.num_col), row[0])
            return out
        return row[0]

    def add_row(self, row_id: int, values,
                opt: Optional[AddOption] = None) -> None:
        self.add_rows([row_id], np.asarray(values).reshape(1, -1), opt)

    # ------------------------------------------------------------------ #
    # hot-row training cache (serving/hotcache.TrainRowCache) — same
    # surface as AsyncMatrixTable so the WE block driver is plane-blind
    # ------------------------------------------------------------------ #
    def train_cache_stats(self) -> Optional[Dict]:
        tc = self._train_cache
        return None if tc is None else tc.stats()

    def train_cache_device_block(self, row_ids, bucket: int):
        """Fused gather+pad device serve when EVERY id is cached (see
        AsyncMatrixTable.train_cache_device_block); None = fall back to
        get_rows_async, which counts its own hit/miss."""
        tc = self._train_cache
        if tc is None:
            return None
        return tc.device_block_counted(row_ids, bucket)

    # ------------------------------------------------------------------ #
    # functional plane for in-graph row traffic (used by word2vec)
    # ------------------------------------------------------------------ #
    def functional_add_rows(self, state: Dict[str, Any], ids: jax.Array,
                            vals: jax.Array,
                            opt: Optional[AddOption] = None) -> Dict[str, Any]:
        """Pure row-batch add; ``ids``/``vals`` static-shaped, caller masks
        unused slots by pointing them at scratch_row with zero vals."""
        opt = opt or AddOption()
        row_axes = jax.tree.map(self._state_row_axis, state["ustate"])
        rows = jnp.take(state["data"], ids, axis=0)

        def gather(leaf, axis):
            return jnp.take(leaf, ids, axis=axis) if axis is not None else leaf

        gstate = jax.tree.map(gather, state["ustate"], row_axes)
        new_rows, new_gstate = self.updater.apply(rows, gstate, vals, opt)
        data = state["data"].at[ids].set(new_rows)

        def scatter(leaf, new_leaf, axis):
            if axis is None:
                return new_leaf
            idx = (slice(None),) * axis + (ids,)
            return leaf.at[idx].set(new_leaf)

        ustate = jax.tree.map(scatter, state["ustate"], new_gstate, row_axes)
        return {"data": data, "ustate": ustate}

    @property
    def scratch_row(self) -> int:
        return self._scratch_row


class MatrixTableOption:
    """ref DEFINE_TABLE_TYPE option parity for mv.create_table."""

    def __init__(self, num_row: int, num_col: int, dtype=jnp.float32,
                 updater=None, init=None, seed=None, init_scale: float = 0.0):
        self.num_row, self.num_col = num_row, num_col
        self.dtype = dtype
        self.updater = updater
        self.init = init
        self.seed = seed
        self.init_scale = init_scale

    def build(self, name: str = "matrix") -> MatrixTable:
        return MatrixTable(self.num_row, self.num_col, dtype=self.dtype,
                           updater=self.updater, name=name, init=self.init,
                           seed=self.seed, init_scale=self.init_scale)
