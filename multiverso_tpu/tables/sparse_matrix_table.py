"""SparseMatrixTable: stale-row tracking + minimal host transfer.

TPU-native equivalent of the reference sparse matrix protocol
(ref: include/multiverso/table/matrix.h + src/table/matrix.cpp:432-572 and the
older src/table/sparse_matrix_table.cpp). The reference server keeps
``up_to_date_[worker][row]`` dirty bits: a Get returns *only the rows that are
stale for the requesting worker* (caller passes worker_id in GetOption,
matrix.cpp:475-483), and an Add marks the touched rows stale for every worker
(:516-540). The SparseFilter additionally compresses the wire payload to
(index, value) pairs (sparse_matrix_table.cpp:147-153).

Here the expensive "wire" is device<->host transfer (HBM -> host DMA), and the
protocol becomes two-phase:

1. a jitted op gathers the dirty bits for the requested rows for this worker
   and clears them (one tiny bool vector to host);
2. only the stale rows are gathered and transferred (bucketed, so XLA shapes
   stay static), then merged into a worker-side host cache.

Fresh rows never cross the wire — the same bandwidth win the reference gets,
achieved with ICI/DMA instead of MPI messages. The (index, value) pairing of
the SparseFilter is inherent in the row-batch encoding.

``is_pipeline`` parity (matrix.cpp:407-418 doubles per-worker state slots to
tolerate double-buffered prefetch): JAX async dispatch already sequences the
clear-bits op against later adds, so no extra slots are needed; the
double-buffer utility lives in utils/async_buffer.py.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from multiverso_tpu import updaters as updaters_lib
from multiverso_tpu.tables.matrix_table import MatrixTable
from multiverso_tpu.updaters import AddOption
from multiverso_tpu.utils.dashboard import monitor
from multiverso_tpu.zoo import Zoo


class SparseMatrixTable(MatrixTable):
    def __init__(self, num_row: int, num_col: int, dtype=jnp.float32,
                 updater: Union[str, updaters_lib.Updater, None] = None,
                 name: str = "sparse_matrix",
                 init=None, seed: Optional[int] = None,
                 init_scale: float = 0.0,
                 num_workers: Optional[int] = None):
        super().__init__(num_row, num_col, dtype=dtype, updater=updater,
                         name=name, init=init, seed=seed,
                         init_scale=init_scale)
        self._n_workers = num_workers or Zoo.get().num_workers()
        # dirty[worker, row]: True = row changed since this worker last pulled
        # it. Starts all-True so the first Get pulls everything
        # (ref matrix.cpp: up_to_date_ starts false).
        dirty_spec = NamedSharding(self._mesh, P(None, self._axis))
        self._dirty = jax.device_put(
            np.ones((self._n_workers, self._padded_rows), dtype=bool),
            dirty_spec)
        # Worker-side row caches (the reference worker's local buffer the
        # sparse Get merges into), allocated lazily per worker AND keyed by
        # row: the workload class this table exists for (21M vocab x 300 dim,
        # ref Applications/WordEmbedding/README.md) makes a dense
        # (num_row, num_col) host mirror ~25 GB per worker — the cache must
        # cost O(rows actually pulled), not O(table).
        self._cache: dict = {}

    def _worker_cache(self, worker_id: int) -> "_RowCache":
        if not (0 <= worker_id < self._n_workers):
            raise IndexError(
                f"worker_id {worker_id} out of range [0, {self._n_workers})")
        cache = self._cache.get(worker_id)
        if cache is None:
            cache = self._cache[worker_id] = _RowCache(self.num_col,
                                                       self.dtype)
        return cache

    def cache_nbytes(self, worker_id: int) -> int:
        """Host bytes held by ``worker_id``'s row cache (diagnostic)."""
        return self._worker_cache(worker_id).nbytes

    # ------------------------------------------------------------------ #
    # jitted helpers
    # ------------------------------------------------------------------ #
    def _mark_dirty_fn(self, bucket: int):
        key = ("mark_dirty", bucket)
        fn = self._jit_cache.get(key)
        if fn is None:
            fn = jax.jit(lambda dirty, ids: dirty.at[:, ids].set(True),
                         donate_argnums=(0,))
            self._jit_cache[key] = fn
        return fn

    def _take_stale_fn(self, bucket: int):
        key = ("take_stale", bucket)
        fn = self._jit_cache.get(key)
        if fn is None:
            def _take(dirty, ids, wid):
                mask = dirty[wid, ids]
                dirty = dirty.at[wid, ids].set(False)
                return dirty, mask
            fn = jax.jit(_take, donate_argnums=(0,))
            self._jit_cache[key] = fn
        return fn

    # ------------------------------------------------------------------ #
    # ops
    # ------------------------------------------------------------------ #
    def _rows_applied(self, ids: np.ndarray) -> None:
        """Mark the applied rows stale for every worker. Fed the CROSS-
        PROCESS UNION by MatrixTable.add_rows_async, so rows contributed
        only by other workers still invalidate this worker's cache (ref
        matrix.cpp:516-540 marks on the server, which sees the union by
        construction). Pad slots point at the scratch row — marking it is
        harmless (it is never a visible row)."""
        self._dirty = self._mark_dirty_fn(ids.size)(
            self._dirty, jax.device_put(ids, self._replicated))

    def add_async(self, delta, opt: Optional[AddOption] = None) -> int:
        msg_id = super().add_async(delta, opt)
        # Whole-table add dirties every row for every worker. The reference's
        # sparse mode auto-detects nonzero rows of a full add
        # (matrix.cpp:147-182); callers with sparse deltas should use
        # add_rows, which is that detection done at the source.
        fn = self._jit_cache.get("dirty_all")
        if fn is None:
            fn = self._jit_cache["dirty_all"] = jax.jit(jnp.ones_like)
        self._dirty = fn(self._dirty)
        return msg_id

    def get_rows_sparse(self, row_ids, worker_id: int = 0) -> np.ndarray:
        """Pull rows, transferring only the ones stale for ``worker_id``.

        Returns the requested rows (fresh ones served from the worker cache).
        ref matrix.cpp:475-483 (GetOption.worker_id) + :540-572 (stale-only
        reply).
        """
        self._flush_host_adds()   # row reads see prior whole-table adds
        with monitor(f"table[{self.name}].get_rows_sparse"), self._dispatch_lock:
            cache = self._worker_cache(worker_id)
            ids = np.asarray(row_ids, dtype=np.int64).reshape(-1)
            uids, _, k, inv = self._prep_ids(row_ids)
            dev_ids = jax.device_put(uids, self._replicated)
            self._dirty, mask = self._take_stale_fn(uids.size)(
                self._dirty, dev_ids, worker_id)
            mask_host = self._to_host(mask)[:k]
            stale = uids[:k][mask_host]
            if stale.size:
                rows = super().get_rows(stale)
                cache.put(stale, rows)
            return cache.take(ids)

    def stale_fraction(self, row_ids, worker_id: int = 0) -> float:
        """Diagnostic: fraction of the requested rows that would transfer."""
        self._worker_cache(worker_id)  # validates worker_id
        if np.asarray(row_ids).size == 0:
            return 0.0
        uids, _, k, _ = self._prep_ids(row_ids)
        key = ("stale_frac", uids.size)
        fn = self._jit_cache.get(key)
        if fn is None:
            fn = self._jit_cache[key] = jax.jit(
                lambda dirty, ids, wid: dirty[wid, ids])
        mask = self._to_host(fn(self._dirty,
                                jax.device_put(uids, self._replicated),
                                worker_id))[:k]
        return float(mask.mean()) if k else 0.0


class _RowCache:
    """Row-keyed worker cache: a sorted-key index (row_id -> slot, resolved
    with ``np.searchsorted`` so lookups stay vectorized) over a growable
    (slots, num_col) buffer. Memory is O(distinct rows pulled) with amortized
    doubling — the sparse analogue of the reference worker's local row buffer
    (ref src/table/matrix.cpp worker side), sized for 21M-vocab tables."""

    def __init__(self, num_col: int, dtype):
        self._num_col = int(num_col)
        self._dtype = dtype
        self._keys = np.empty(0, np.int64)    # sorted distinct row ids
        self._slots = np.empty(0, np.int64)   # buffer slot per sorted key
        self._buf = np.empty((0, self._num_col), dtype)
        self._n = 0                           # slots in use

    @property
    def nbytes(self) -> int:
        return self._buf.nbytes + self._keys.nbytes + self._slots.nbytes

    def _ensure(self, extra: int) -> None:
        need = self._n + extra
        if need <= self._buf.shape[0]:
            return
        cap = max(8, self._buf.shape[0])
        while cap < need:
            cap *= 2
        buf = np.empty((cap, self._num_col), self._dtype)
        buf[: self._buf.shape[0]] = self._buf
        self._buf = buf

    def _locate(self, ids: np.ndarray):
        """(insertion positions, found mask) of ``ids`` in the key index."""
        pos = np.searchsorted(self._keys, ids)
        if self._keys.size == 0:
            return pos, np.zeros(ids.size, bool)
        clip = np.minimum(pos, self._keys.size - 1)
        return clip, self._keys[clip] == ids

    def put(self, ids: np.ndarray, rows: np.ndarray) -> None:
        """Insert/overwrite rows; ``ids`` must be distinct (callers pass the
        unique stale subset of an already-deduped batch)."""
        ids = np.asarray(ids, np.int64)
        clip, found = self._locate(ids)
        n_new = int(ids.size - found.sum())
        self._ensure(n_new)
        slots = np.empty(ids.size, np.int64)
        slots[found] = self._slots[clip[found]]
        if n_new:
            new_slots = np.arange(self._n, self._n + n_new)
            slots[~found] = new_slots
            # insert at their searchsorted positions: O(K + n log n), not a
            # full re-sort of the K cached keys per pull
            order = np.argsort(ids[~found], kind="stable")
            nk, ns = ids[~found][order], new_slots[order]
            at = np.searchsorted(self._keys, nk)
            self._keys = np.insert(self._keys, at, nk)
            self._slots = np.insert(self._slots, at, ns)
            self._n += n_new
        self._buf[slots] = rows

    def take(self, ids: np.ndarray) -> np.ndarray:
        """Rows for ``ids``; every id must be cached (fresh rows were pulled
        by an earlier sparse Get — dirty bits start all-True, so a never-
        pulled row is always stale and lands in the cache first)."""
        ids = np.asarray(ids, np.int64)
        clip, found = self._locate(ids)
        if not found.all():
            raise KeyError(
                f"rows {ids[~found][:5].tolist()}... not cached (stale "
                "protocol invariant violated)")
        return self._buf[self._slots[clip]]


class SparseMatrixTableOption:
    def __init__(self, num_row: int, num_col: int, dtype=jnp.float32,
                 updater=None, init=None, seed=None, init_scale: float = 0.0,
                 num_workers: Optional[int] = None):
        self.num_row, self.num_col = num_row, num_col
        self.dtype = dtype
        self.updater = updater
        self.init = init
        self.seed = seed
        self.init_scale = init_scale
        self.num_workers = num_workers

    def build(self, name: str = "sparse_matrix") -> SparseMatrixTable:
        return SparseMatrixTable(
            self.num_row, self.num_col, dtype=self.dtype,
            updater=self.updater, name=name, init=self.init, seed=self.seed,
            init_scale=self.init_scale, num_workers=self.num_workers)
