from multiverso_tpu.tables.array_table import ArrayTable
from multiverso_tpu.tables.matrix_table import MatrixTable
from multiverso_tpu.tables.kv_table import KVTable
from multiverso_tpu.tables.sparse_matrix_table import SparseMatrixTable

__all__ = ["ArrayTable", "MatrixTable", "KVTable", "SparseMatrixTable"]
