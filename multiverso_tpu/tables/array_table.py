"""ArrayTable: 1-D dense sharded parameter vector.

TPU-native equivalent of the reference ArrayTable
(ref: include/multiverso/table/array_table.h, src/table/array_table.cpp).
The reference shards contiguous ranges across server processes
(src/table/array_table.cpp:11-21) and hand-partitions each Add/Get blob per
server (:68-95). Here the contiguous-range sharding is exactly a
``NamedSharding(mesh, P(axis))`` over the table mesh axis — XLA emits the
shard-wise scatter/gather the reference hand-rolled, and the updater runs on
all shards in parallel (:116-141 -> updaters/__init__.py).
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from multiverso_tpu import updaters as updaters_lib
from multiverso_tpu.table import Table


class ArrayTable(Table):
    def __init__(self, size: int, dtype=jnp.float32,
                 updater: Union[str, updaters_lib.Updater, None] = None,
                 name: str = "array",
                 init=None, seed: Optional[int] = None,
                 init_scale: float = 0.0, wire_filter: str = "none"):
        super().__init__((int(size),), dtype=dtype, updater=updater,
                         name=name, init=init, seed=seed,
                         init_scale=init_scale, wire_filter=wire_filter)

    @property
    def size(self) -> int:
        return self.shape[0]


class ArrayTableOption:
    """ref DEFINE_TABLE_TYPE option struct (table_interface.h:77-80) parity:
    ``mv.create_table(ArrayTableOption(size))``."""

    def __init__(self, size: int, dtype=jnp.float32, updater=None,
                 init=None, seed=None, init_scale: float = 0.0):
        self.size = size
        self.dtype = dtype
        self.updater = updater
        self.init = init
        self.seed = seed
        self.init_scale = init_scale

    def build(self, name: str = "array") -> ArrayTable:
        return ArrayTable(self.size, dtype=self.dtype, updater=self.updater,
                          name=name, init=self.init, seed=self.seed,
                          init_scale=self.init_scale)
