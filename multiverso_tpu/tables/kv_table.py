"""KVTable: sparse key-value table.

TPU-native equivalent of the reference KVTable
(ref: include/multiverso/table/kv_table.h — a header-only
``unordered_map<Key,Val>`` hash-sharded ``key % num_servers`` across servers,
used as the global word-count aggregator in WordEmbedding). Scalar KV traffic
has no business on the MXU; the idiomatic TPU design keeps it host-side: a
process-local dict with reference Add/Get semantics, aggregated across
processes on demand with a host allgather (the one place DCN, not ICI, is the
right wire). ``store``/``load`` are actually implemented — the reference left
them stubbed (kv_table.h:101-119).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, Optional, Tuple

import numpy as np

from multiverso_tpu.utils.dashboard import monitor
from multiverso_tpu.zoo import Zoo


class KVTable:
    def __init__(self, dtype=np.int64, name: str = "kv"):
        self.name = name
        self.dtype = np.dtype(dtype)
        self._store: Dict[int, float] = {}
        self._lock = threading.Lock()
        self._zoo = Zoo.get()
        self.table_id = self._zoo.register_table(self)

    def add(self, keys: Iterable[int], values: Iterable) -> None:
        """ref kv_table.h Add: accumulate into the shard map."""
        with monitor(f"table[{self.name}].add"), self._lock:
            for k, v in zip(keys, values):
                self._store[int(k)] = self._store.get(int(k), 0) + v

    def get(self, keys: Optional[Iterable[int]] = None,
            global_: bool = False) -> Dict[int, float]:
        """ref kv_table.h Get (:44-99): the reference pulls the
        *server-aggregated* value — every worker's Adds are summed on the
        hash-sharded servers before a Get sees them. ``global_=True``
        reproduces that: it returns cross-process aggregated values
        (a host allgather; every process must call it — a collective,
        like every host-plane multi-controller op here). The default
        ``global_=False`` is the process-local view (single-process the
        two are identical). Unlike :meth:`allreduce` this does NOT
        overwrite the local store, so it is safe to call repeatedly
        between Adds."""
        if global_ and self._zoo.size() > 1:
            with monitor(f"table[{self.name}].get"):
                merged = self._merged()
                if keys is None:
                    return merged
                return {int(k): merged.get(int(k), 0) for k in keys}
        with monitor(f"table[{self.name}].get"), self._lock:
            if keys is None:
                return dict(self._store)
            return {int(k): self._store.get(int(k), 0) for k in keys}

    def raw(self) -> Dict[int, float]:
        """ref kv_table.h raw(): the worker-local cache view."""
        return self.get()

    def __getitem__(self, key: int):
        return self._store.get(int(key), 0)

    def allreduce(self) -> Dict[int, float]:
        """Aggregate counts across processes and COMMIT the merged view as
        the new local store (model-average style; idempotence hazard: calling
        it twice without intervening Adds multiplies by the process count —
        use ``get(global_=True)`` for a repeatable aggregated read). With one
        process this is a no-op view."""
        if self._zoo.size() == 1:
            return self.get()
        merged = self._merged()
        with self._lock:
            self._store = dict(merged)
        return merged

    def _merged(self) -> Dict[int, float]:
        """Non-destructive cross-process sum of every process's store.
        Host allgather over the JAX distributed client rather than device
        collectives: KV payloads are ragged and tiny."""
        from jax.experimental import multihost_utils
        with self._lock:
            items = sorted(self._store.items())
        keys = np.array([k for k, _ in items], dtype=np.int64)
        vals = np.array([v for _, v in items], dtype=np.float64)
        # Host allgather needs identical shapes per process; key sets are
        # ragged, so first agree on the max length, then pad with a -1
        # sentinel key.
        n = np.array([keys.size], dtype=np.int64)
        max_n = int(np.max(multihost_utils.process_allgather(n, tiled=False)))
        pad = max_n - keys.size
        if pad:
            keys = np.concatenate([keys, np.full(pad, -1, np.int64)])
            vals = np.concatenate([vals, np.zeros(pad, np.float64)])
        gk = multihost_utils.process_allgather(keys, tiled=False)
        gv = multihost_utils.process_allgather(vals, tiled=False)
        merged: Dict[int, float] = {}
        for krow, vrow in zip(np.atleast_2d(gk), np.atleast_2d(gv)):
            for k, v in zip(krow, vrow):
                if k >= 0:
                    merged[int(k)] = merged.get(int(k), 0) + v
        return merged

    # ------------------------------------------------------------------ #
    # checkpoint — implemented, unlike the reference stub
    # ------------------------------------------------------------------ #
    def store(self, stream) -> None:
        items = sorted(self._store.items())
        np.save(stream, np.array([k for k, _ in items], dtype=np.int64),
                allow_pickle=False)
        np.save(stream, np.array([v for _, v in items], dtype=np.float64),
                allow_pickle=False)

    def load(self, stream) -> None:
        keys = np.load(stream)
        vals = np.load(stream)
        with self._lock:
            self._store = {int(k): self.dtype.type(v).item()
                           for k, v in zip(keys, vals)}


class KVTableOption:
    def __init__(self, dtype=np.int64):
        self.dtype = dtype

    def build(self, name: str = "kv") -> KVTable:
        return KVTable(dtype=self.dtype, name=name)
