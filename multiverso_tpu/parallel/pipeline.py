"""Pipeline parallelism: a GPipe-style microbatch ring over a mesh axis.

Completes the strategy surface (SURVEY §2.10: the reference's only
"pipeline" is communication/compute double-buffering; layer pipelining was
out of its scope). Stage s of a stack of identical blocks lives on device s
of the ``pp`` axis; microbatches enter at stage 0, activations hop stage to
stage over ICI via ``ppermute``, and the bubble is the classic
``(n_stages - 1) / (n_stages - 1 + n_micro)`` fraction.

TPU-first shape discipline: ONE ``lax.scan`` over ``n_micro + n_stages - 1``
ticks compiles a single pipelined body; every tick does (ingest -> stage fn
-> emit -> rotate) with static shapes, so XLA overlaps the ppermute with the
next tick's compute. Per-stage parameters are a stacked ``[n_stages, ...]``
pytree sharded over ``pp`` — the same layout `lax.scan` uses for a deep
stack on one chip, just distributed.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiverso_tpu.zoo import Zoo
from multiverso_tpu.utils.platform import shard_map as _shard_map


def shard_stages(stacked_params: Any, axis: str = "pp",
                 mesh: Optional[Mesh] = None) -> Any:
    """Place a [n_stages, ...]-stacked param pytree stage-sharded."""
    mesh = mesh or Zoo.get().mesh()

    def put(x):
        spec = P(axis, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, stacked_params)


def _check_param_specs(param_specs: Any, axis: str) -> None:
    """Shared validation for the stage-weight spec override: every spec
    must lead with the pipeline axis (the leading dim is the stage dim)."""
    if param_specs is None:
        return
    for path, spec in jax.tree_util.tree_leaves_with_path(
            param_specs, is_leaf=lambda s: isinstance(s, P)):
        if not spec or spec[0] != axis:
            raise ValueError(
                f"param_specs leaf {jax.tree_util.keystr(path)} must "
                f"lead with the pipeline axis {axis!r}, got {spec}")


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any, x: jax.Array,
                   n_micro: int, axis: str = "pp",
                   mesh: Optional[Mesh] = None,
                   batch_axis: Optional[str] = None,
                   param_specs: Any = None) -> jax.Array:
    """Run ``x`` [B, ...] through ``n_stages`` pipelined applications of
    ``stage_fn``; batch is split into ``n_micro`` microbatches on the fly.

    ``stage_params`` leaves are [n_stages, ...] (use :func:`shard_stages`);
    ``stage_fn(params_for_one_stage, act) -> act`` must preserve the
    activation shape (the identical-blocks contract of layer pipelining).
    On a multi-axis mesh pass ``batch_axis`` to shard the microbatch dim
    (each batch shard runs its own pipeline over the same stage weights).

    ``param_specs``: optional PartitionSpec pytree (same structure as
    ``stage_params``) when stage weights are sharded over ADDITIONAL mesh
    axes beyond the leading ``axis`` dim — e.g. tensor parallelism inside
    each stage, ``P('pp', None, None, 'tp')``. Each spec's first entry must
    be ``axis``; ``stage_fn`` then sees tp-local weight shards and may use
    ``jax.lax.psum`` over those axes (it runs inside this shard_map).
    """
    mesh = mesh or Zoo.get().mesh()
    n_stages = mesh.shape[axis]
    for path, leaf in jax.tree_util.tree_leaves_with_path(stage_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stage_params leaf {jax.tree_util.keystr(path)} has leading "
                f"dim {leaf.shape[0]}, expected n_stages={n_stages} "
                f"(mesh axis {axis!r}); fold extra layers into stage_fn")
    _check_param_specs(param_specs, axis)
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible by {n_micro} microbatches")
    mb = b // n_micro
    xs = x.reshape(n_micro, mb, *x.shape[1:])

    def body(params, xs):
        # params: this stage's slice, leading stage-dim of 1
        params = jax.tree.map(lambda p: p[0], params)
        idx = jax.lax.axis_index(axis)
        last = n_stages - 1
        fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            act, outs = carry
            # stage 0 ingests microbatch t while it exists; later stages
            # keep the activation that just arrived on the ring
            inp = xs[jnp.minimum(t, n_micro - 1)]
            act = jnp.where(idx == 0, inp, act)
            act = stage_fn(params, act)
            # stage n-1 emits microbatch t-(n-1) once the fill ends
            slot = jnp.clip(t - last, 0, n_micro - 1)
            valid = (idx == last) & (t >= last)
            outs = outs.at[slot].add(jnp.where(valid, act, 0.0))
            act = jax.lax.ppermute(act, axis, fwd)
            return (act, outs), None

        act0 = jnp.zeros(xs.shape[1:], xs.dtype)
        outs0 = jnp.zeros_like(xs)
        (_, outs), _ = jax.lax.scan(
            tick, (act0, outs0), jnp.arange(n_micro + n_stages - 1))
        # every stage holds zeros except the last; psum replicates the result
        return jax.lax.psum(outs, axis)

    pspec = (param_specs if param_specs is not None
             else jax.tree.map(lambda _: P(axis), stage_params))
    xspec = P(None, batch_axis) if batch_axis else P()
    out = _shard_map(body, mesh=mesh,
                        in_specs=(pspec, xspec), out_specs=xspec,
                        check_vma=False)(stage_params, xs)
    return out.reshape(b, *x.shape[1:])


def shard_stages_interleaved(stacked_params: Any, n_stages: int,
                             axis: str = "pp",
                             mesh: Optional[Mesh] = None) -> Any:
    """Regroup a [n_total, ...] stage stack for the interleaved schedule
    and place it: global stage g runs as chunk v = g // n_stages on device
    d = g % n_stages, so the [n_total, ...] leaves become [n_stages,
    n_chunks, ...] (device-major) sharded over ``axis``."""
    mesh = mesh or Zoo.get().mesh()

    def regroup(p):
        if p.shape[0] % n_stages:
            raise ValueError(f"stage count {p.shape[0]} not divisible by "
                             f"n_stages={n_stages}")
        v = p.shape[0] // n_stages
        p = p.reshape(v, n_stages, *p.shape[1:]).swapaxes(0, 1)
        return jax.device_put(
            p, NamedSharding(mesh, P(axis, *([None] * (p.ndim - 1)))))

    return jax.tree.map(regroup, stacked_params)


def pipeline_apply_interleaved(stage_fn: Callable[[Any, jax.Array],
                                                  jax.Array],
                               stage_params: Any, x: jax.Array,
                               axis: str = "pp",
                               mesh: Optional[Mesh] = None,
                               batch_axis: Optional[str] = None,
                               param_specs: Any = None) -> jax.Array:
    """Interleaved (virtual-chunk) pipeline: each device holds ``n_chunks``
    NON-contiguous stages, Megatron's interleaved schedule adapted to the
    microbatch ring.

    vs :func:`pipeline_apply` (GPipe): with the stack split into V chunks
    per device, an activation circles the ring V times, and a device works
    on chunk v of one microbatch while later microbatches are still in its
    earlier chunks. Fill/drain cost is ``n_stages - 1`` ticks of ONE
    chunk's work instead of the whole per-device stack — the bubble
    fraction drops from (S-1)/(S-1+M) to (S-1)/(S-1+M*V) for the same
    microbatch count. The price: V times more ppermute hops (cheap on the
    ICI torus) and a fixed microbatch count of ``n_stages``.

    ``stage_params`` leaves are [n_stages, n_chunks, ...] (use
    :func:`shard_stages_interleaved`); batch must split into exactly
    ``n_stages`` microbatches; ``stage_fn(chunk_params, act) -> act``
    applies one chunk. ``param_specs`` shards chunk weights over extra
    mesh axes exactly as in :func:`pipeline_apply` (each spec must lead
    with ``axis``).
    """
    mesh = mesh or Zoo.get().mesh()
    n_stages = mesh.shape[axis]
    leaves = jax.tree_util.tree_leaves_with_path(stage_params)
    n_chunks = leaves[0][1].shape[1] if leaves else 1
    for path, leaf in leaves:
        if leaf.shape[0] != n_stages or leaf.shape[1] != n_chunks:
            raise ValueError(
                f"stage_params leaf {jax.tree_util.keystr(path)} has "
                f"leading dims {leaf.shape[:2]}, expected "
                f"({n_stages}, {n_chunks})")
    _check_param_specs(param_specs, axis)
    b = x.shape[0]
    if b % n_stages:
        raise ValueError(f"batch {b} not divisible by the interleaved "
                         f"schedule's fixed n_micro={n_stages}")
    mb = b // n_stages
    xs = x.reshape(n_stages, mb, *x.shape[1:])

    def body(params, xs):
        params = jax.tree.map(lambda p: p[0], params)  # [V, ...] local
        idx = jax.lax.axis_index(axis)
        S, V = n_stages, n_chunks
        fwd = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            act, outs = carry
            u = t - idx                    # ticks since this device's first
            v = jnp.clip(u // S, 0, V - 1)  # chunk this device runs now
            # device 0 ingests microbatch t during the first S ticks; later
            # ticks it continues chunks arriving back around the ring
            act = jnp.where((idx == 0) & (t < S),
                            xs[jnp.clip(t, 0, S - 1)], act)
            pv = jax.tree.map(
                lambda q: jax.lax.dynamic_index_in_dim(
                    q, v, 0, keepdims=False), params)
            act = stage_fn(pv, act)
            # last device emits microbatch u - (V-1)S while running the
            # final chunk
            slot = jnp.clip(u - (V - 1) * S, 0, S - 1)
            valid = (idx == S - 1) & (u >= (V - 1) * S) & (u < V * S)
            outs = outs.at[slot].add(jnp.where(valid, act, 0.0))
            act = jax.lax.ppermute(act, axis, fwd)
            return (act, outs), None

        act0 = jnp.zeros(xs.shape[1:], xs.dtype)
        outs0 = jnp.zeros_like(xs)
        (_, outs), _ = jax.lax.scan(
            tick, (act0, outs0), jnp.arange(S * V + S - 1))
        return jax.lax.psum(outs, axis)

    pspec = (param_specs if param_specs is not None
             else jax.tree.map(lambda _: P(axis), stage_params))
    xspec = P(None, batch_axis) if batch_axis else P()
    out = _shard_map(body, mesh=mesh,
                        in_specs=(pspec, xspec), out_specs=xspec,
                        check_vma=False)(stage_params, xs)
    return out.reshape(b, *x.shape[1:])
