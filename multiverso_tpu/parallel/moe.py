"""Expert parallelism: a mixture-of-experts layer over a mesh axis.

Rounds out the modern-strategy surface (SURVEY §2.10: the 2015 reference has
DP + parameter-storage sharding only; SP/CP live in parallel/ring.py, EP
here). Experts are sharded over the ``ep`` mesh axis — each device owns
``num_experts / ep`` expert MLPs — and tokens travel to their experts and
back via ``all_to_all`` over ICI, the TPU-native equivalent of the
dispatch/combine messaging a parameter server would do per-row.

Design choices, TPU-first:

* **Static capacity**: each device sends exactly ``capacity`` tokens to each
  expert shard (truncate-and-pad, like every production TPU MoE) so all
  shapes are static for XLA; dropped tokens fall back to the residual path.
* **Top-1 (switch) or top-k (GShard) routing** with a jittable router —
  ``top_k=1`` gates by the raw expert probability, ``top_k>1`` by the
  renormalized top-k probabilities — plus the standard auxiliary
  load-balance loss returned to the caller.
* One ``all_to_all`` out, one back; expert compute is a single batched
  einsum over the local experts — MXU-shaped, no scalar loops.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiverso_tpu.zoo import Zoo
from multiverso_tpu.utils.platform import (
    axis_size as _axis_size, shard_map as _shard_map)


class MoEConfig(NamedTuple):
    num_experts: int
    dim: int
    hidden: int
    capacity_factor: float = 1.25
    axis: str = "ep"
    top_k: int = 1


def init_experts(cfg: MoEConfig, seed: int = 0, dtype=jnp.float32) -> Dict:
    """[E, ...]-stacked expert MLP params + router; shard E over the ep axis
    with :func:`shard_experts`."""
    rng = np.random.default_rng(seed)
    e, d, h = cfg.num_experts, cfg.dim, cfg.hidden
    mk = lambda *s, scale: jnp.asarray(rng.normal(0, scale, s), dtype)
    return {
        "w1": mk(e, d, h, scale=1 / np.sqrt(d)),
        "w2": mk(e, h, d, scale=1 / np.sqrt(h)),
        "router": mk(d, e, scale=1 / np.sqrt(d)),
    }


def shard_experts(params: Dict, cfg: MoEConfig,
                  mesh: Optional[Mesh] = None) -> Dict:
    """Place expert weights expert-sharded (router replicated)."""
    mesh = mesh or Zoo.get().mesh()
    shard = NamedSharding(mesh, P(cfg.axis))
    repl = NamedSharding(mesh, P())
    return {
        "w1": jax.device_put(params["w1"], shard),
        "w2": jax.device_put(params["w2"], shard),
        "router": jax.device_put(params["router"], repl),
    }


def top_k_gates(probs, kk: int):
    """Top-k expert selection with the gating convention shared by training
    (:func:`_route`) and decode (models/transformer.generate): raw top
    probability for k=1 (switch), renormalized top-k for k>1 (GShard).
    Returns (gates [T, K], topi [T, K])."""
    topv, topi = jax.lax.top_k(probs, kk)
    gates = topv if kk == 1 else topv / topv.sum(-1, keepdims=True)
    return gates, topi


def _route(probs, kk: int, capacity: int):
    """Priority routing over the [T, E] expert probabilities: assignments
    are flattened **k-major** ([all 1st choices, then all 2nd choices, ...])
    so every token's 1st choice wins the capacity race against any token's
    2nd choice — the GShard/Switch fill order. Returns (expert, gate, pos,
    keep, onehot), each over the K*T assignments; gates are the raw top
    probability for k=1 (switch) and renormalized for k>1 (GShard)."""
    t, e = probs.shape
    gates, topi = top_k_gates(probs, kk)                   # [T, K]
    expert = topi.T.reshape(-1)                            # [K*T]
    gate = gates.T.reshape(-1)
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.int32)    # [K*T, E]
    pos = (jnp.cumsum(onehot, 0) * onehot).sum(-1) - 1     # per-expert slot
    keep = pos < capacity
    return expert, gate, pos, keep, onehot


def _local_moe(x, w1, w2, router, cfg: MoEConfig, capacity: int,
               batch_axis: Optional[str] = None):
    """Per-shard body. x: [T_local, D]; w1/w2: local experts [E_local, ...]."""
    ax = cfg.axis
    n = _axis_size(ax)
    e = cfg.num_experts
    e_local = e // n
    t = x.shape[0]

    kk = cfg.top_k
    logits = x @ router                                    # [T, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), -1)
    expert, gate, pos, keep, onehot = _route(probs, kk, capacity)

    # dispatch buffer: [E, capacity, D] (one slice per destination expert)
    x_rep = jnp.tile(x, (kk, 1))                           # [K*T, D] k-major
    slot = jnp.where(keep, pos, capacity)                  # overflow -> pad row
    dispatch = jnp.zeros((e, capacity + 1, x.shape[1]), x.dtype)
    dispatch = dispatch.at[expert, slot].add(x_rep)
    dispatch = dispatch[:, :capacity]                      # [E, C, D]

    # all_to_all: [E, C, D] -> group by shard -> each device ends up with
    # its local experts' tokens from every peer: [n, E_local, C, D]
    dispatch = dispatch.reshape(n, e_local, capacity, -1)
    recv = jax.lax.all_to_all(dispatch, ax, split_axis=0, concat_axis=0,
                              tiled=False)                 # [n, E_local, C, D]

    # expert compute, batched over local experts: [E_local, n*C, D]
    xin = recv.transpose(1, 0, 2, 3).reshape(e_local, n * capacity, -1)
    h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", xin, w1))
    out = jnp.einsum("ech,ehd->ecd", h, w2)                # [E_local, n*C, D]

    # route back: inverse all_to_all
    back = out.reshape(e_local, n, capacity, -1).transpose(1, 0, 2, 3)
    combined = jax.lax.all_to_all(back, ax, split_axis=0, concat_axis=0,
                                  tiled=False)             # [n, E_local, C, D]
    combined = combined.reshape(e, capacity, -1)           # [E, C, D]

    # gather each surviving assignment's expert output (dropped -> 0) and
    # sum a token's k contributions (k-major flatten)
    y = combined[expert, jnp.minimum(pos, capacity - 1)]   # [K*T, D]
    y = jnp.where(keep[:, None], y, 0.0) * gate[:, None].astype(x.dtype)
    y = y.reshape(kk, t, -1).sum(0)                        # [T, D]

    # load-balance aux loss (switch for k=1, GShard-normalized for k>1)
    me = probs.mean(0)                                     # [E]
    ce = onehot.astype(jnp.float32).reshape(kk, t, e).sum(0).mean(0) / kk
    aux = e * jnp.sum(me * ce)
    # reduce over every axis the tokens are sharded on, so the returned
    # scalars really are replicated (out_specs=P() asserts it)
    reduce_axes = (ax,) if batch_axis is None else (ax, batch_axis)
    aux = jax.lax.pmean(aux, reduce_axes)
    # dropped = tokens whose EVERY assignment overflowed (full residual
    # fallback), matching the "dropped tokens fall back" contract
    token_dropped = 1.0 - keep.reshape(kk, t).any(axis=0)
    frac_dropped = jax.lax.pmean(token_dropped.mean(), reduce_axes)
    return y, aux, frac_dropped


def moe_layer(x: jax.Array, params: Dict, cfg: MoEConfig,
              mesh: Optional[Mesh] = None,
              batch_axis: Optional[str] = None
              ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Apply the expert-parallel MoE to tokens [B, T, D] sharded over
    ``cfg.axis`` on T (and optionally ``batch_axis`` on B). Returns
    (output [B, T, D], aux_loss scalar, dropped_fraction scalar —
    the fraction of tokens whose every routed choice overflowed capacity
    and that therefore fell back to the residual path with zero output)."""
    mesh = mesh or Zoo.get().mesh()
    n = mesh.shape[cfg.axis]
    if cfg.num_experts % n:
        raise ValueError(
            f"{cfg.num_experts} experts not divisible by {n} shards")
    b, t, d = x.shape
    if t % n:
        raise ValueError(f"token dim {t} not divisible by {n} {cfg.axis!r} "
                         "shards")
    if batch_axis and b % mesh.shape[batch_axis]:
        raise ValueError(f"batch dim {b} not divisible by "
                         f"{mesh.shape[batch_axis]} {batch_axis!r} shards")
    if not 1 <= cfg.top_k <= cfg.num_experts:
        raise ValueError(f"top_k={cfg.top_k} out of range for "
                         f"{cfg.num_experts} experts")
    local_tokens = b * t // n // (mesh.shape[batch_axis] if batch_axis else 1)
    capacity = max(1, int(cfg.capacity_factor * local_tokens * cfg.top_k
                          / cfg.num_experts))

    xspec = P(batch_axis, cfg.axis, None)
    espec = P(cfg.axis)

    def body(x, w1, w2, router):
        xb = x.reshape(-1, d)
        y, aux, dropped = _local_moe(xb, w1, w2, router, cfg, capacity,
                                     batch_axis)
        return y.reshape(x.shape), aux, dropped

    y, aux, dropped = _shard_map(
        body, mesh=mesh,
        in_specs=(xspec, espec, espec, P()),
        out_specs=(xspec, P(), P()), check_vma=False)(
            x, params["w1"], params["w2"], params["router"])
    return y, aux, dropped
