"""Expert parallelism: a mixture-of-experts layer over a mesh axis.

Rounds out the modern-strategy surface (SURVEY §2.10: the 2015 reference has
DP + parameter-storage sharding only; SP/CP live in parallel/ring.py, EP
here). Experts are sharded over the ``ep`` mesh axis — each device owns
``num_experts / ep`` expert MLPs — and tokens travel to their experts and
back via ``all_to_all`` over ICI, the TPU-native equivalent of the
dispatch/combine messaging a parameter server would do per-row.

Design choices, TPU-first:

* **Static capacity**: each device sends exactly ``capacity`` tokens to each
  expert shard (truncate-and-pad, like every production TPU MoE) so all
  shapes are static for XLA; dropped tokens fall back to the residual path.
* **Top-1 routing** (switch-style) with a jittable router; routing logits
  get a gumbel option for load-balancing exploration, plus the standard
  auxiliary load-balance loss returned to the caller.
* One ``all_to_all`` out, one back; expert compute is a single batched
  einsum over the local experts — MXU-shaped, no scalar loops.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiverso_tpu.zoo import Zoo


class MoEConfig(NamedTuple):
    num_experts: int
    dim: int
    hidden: int
    capacity_factor: float = 1.25
    axis: str = "ep"


def init_experts(cfg: MoEConfig, seed: int = 0, dtype=jnp.float32) -> Dict:
    """[E, ...]-stacked expert MLP params + router; shard E over the ep axis
    with :func:`shard_experts`."""
    rng = np.random.default_rng(seed)
    e, d, h = cfg.num_experts, cfg.dim, cfg.hidden
    mk = lambda *s, scale: jnp.asarray(rng.normal(0, scale, s), dtype)
    return {
        "w1": mk(e, d, h, scale=1 / np.sqrt(d)),
        "w2": mk(e, h, d, scale=1 / np.sqrt(h)),
        "router": mk(d, e, scale=1 / np.sqrt(d)),
    }


def shard_experts(params: Dict, cfg: MoEConfig,
                  mesh: Optional[Mesh] = None) -> Dict:
    """Place expert weights expert-sharded (router replicated)."""
    mesh = mesh or Zoo.get().mesh()
    shard = NamedSharding(mesh, P(cfg.axis))
    repl = NamedSharding(mesh, P())
    return {
        "w1": jax.device_put(params["w1"], shard),
        "w2": jax.device_put(params["w2"], shard),
        "router": jax.device_put(params["router"], repl),
    }


def _local_moe(x, w1, w2, router, cfg: MoEConfig, capacity: int,
               batch_axis: Optional[str] = None):
    """Per-shard body. x: [T_local, D]; w1/w2: local experts [E_local, ...]."""
    ax = cfg.axis
    n = jax.lax.axis_size(ax)
    e = cfg.num_experts
    e_local = e // n
    t = x.shape[0]

    logits = x @ router                                    # [T, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), -1)
    expert = jnp.argmax(probs, -1)                         # [T]
    gate = jnp.take_along_axis(probs, expert[:, None], 1)[:, 0]

    # position of each token within its expert's send buffer
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.int32)    # [T, E]
    pos = jnp.cumsum(onehot, 0) * onehot                   # 1-based
    pos = (pos.sum(-1) - 1)                                # [T], per-expert slot
    keep = pos < capacity

    # dispatch buffer: [E, capacity, D] (one slice per destination expert)
    slot = jnp.where(keep, pos, capacity)                  # overflow -> pad row
    dispatch = jnp.zeros((e, capacity + 1, x.shape[1]), x.dtype)
    dispatch = dispatch.at[expert, slot].add(x)
    dispatch = dispatch[:, :capacity]                      # [E, C, D]

    # all_to_all: [E, C, D] -> group by shard -> each device ends up with
    # its local experts' tokens from every peer: [n, E_local, C, D]
    dispatch = dispatch.reshape(n, e_local, capacity, -1)
    recv = jax.lax.all_to_all(dispatch, ax, split_axis=0, concat_axis=0,
                              tiled=False)                 # [n, E_local, C, D]

    # expert compute, batched over local experts: [E_local, n*C, D]
    xin = recv.transpose(1, 0, 2, 3).reshape(e_local, n * capacity, -1)
    h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", xin, w1))
    out = jnp.einsum("ech,ehd->ecd", h, w2)                # [E_local, n*C, D]

    # route back: inverse all_to_all
    back = out.reshape(e_local, n, capacity, -1).transpose(1, 0, 2, 3)
    combined = jax.lax.all_to_all(back, ax, split_axis=0, concat_axis=0,
                                  tiled=False)             # [n, E_local, C, D]
    combined = combined.reshape(e, capacity, -1)           # [E, C, D]

    # gather each surviving token's expert output; dropped tokens get 0
    y = combined[expert, jnp.minimum(pos, capacity - 1)]   # [T, D]
    y = jnp.where(keep[:, None], y, 0.0) * gate[:, None].astype(x.dtype)

    # switch-transformer load-balance aux loss
    me = probs.mean(0)                                     # [E]
    ce = onehot.astype(jnp.float32).mean(0)                # [E]
    aux = e * jnp.sum(me * ce)
    # reduce over every axis the tokens are sharded on, so the returned
    # scalars really are replicated (out_specs=P() asserts it)
    reduce_axes = (ax,) if batch_axis is None else (ax, batch_axis)
    aux = jax.lax.pmean(aux, reduce_axes)
    frac_dropped = jax.lax.pmean(1.0 - keep.mean(), reduce_axes)
    return y, aux, frac_dropped


def moe_layer(x: jax.Array, params: Dict, cfg: MoEConfig,
              mesh: Optional[Mesh] = None,
              batch_axis: Optional[str] = None
              ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Apply the expert-parallel MoE to tokens [B, T, D] sharded over
    ``cfg.axis`` on T (and optionally ``batch_axis`` on B). Returns
    (output [B, T, D], aux_loss scalar, dropped_fraction scalar)."""
    mesh = mesh or Zoo.get().mesh()
    n = mesh.shape[cfg.axis]
    if cfg.num_experts % n:
        raise ValueError(
            f"{cfg.num_experts} experts not divisible by {n} shards")
    b, t, d = x.shape
    if t % n:
        raise ValueError(f"token dim {t} not divisible by {n} {cfg.axis!r} "
                         "shards")
    if batch_axis and b % mesh.shape[batch_axis]:
        raise ValueError(f"batch dim {b} not divisible by "
                         f"{mesh.shape[batch_axis]} {batch_axis!r} shards")
    local_tokens = b * t // n // (mesh.shape[batch_axis] if batch_axis else 1)
    capacity = max(1, int(cfg.capacity_factor * local_tokens
                          / cfg.num_experts))

    xspec = P(batch_axis, cfg.axis, None)
    espec = P(cfg.axis)

    def body(x, w1, w2, router):
        xb = x.reshape(-1, d)
        y, aux, dropped = _local_moe(xb, w1, w2, router, cfg, capacity,
                                     batch_axis)
        return y.reshape(x.shape), aux, dropped

    y, aux, dropped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(xspec, espec, espec, P()),
        out_specs=(xspec, P(), P()), check_vma=False)(
            x, params["w1"], params["w2"], params["router"])
    return y, aux, dropped
