"""Collectives over the device mesh.

TPU-native equivalent of the reference's CPU collective engine
(ref: src/net/allreduce_engine.cpp — Bruck all-gather for small payloads,
recursive-halving reduce-scatter + Bruck for large, over point-to-point
SendRecv; src/net/allreduce_topo.cpp — the hop maps). On TPU every one of
those algorithms collapses into a single XLA collective routed on the ICI
torus by the compiler — ``psum`` / ``all_gather`` / ``psum_scatter`` inside
``shard_map``. The topology math (BruckMap/RecursiveHalvingMap) is subsumed
by hardware routing and is an explicit non-goal (SURVEY §2.2).

These helpers are host-plane conveniences: they take a host or device array,
run the collective over the Zoo mesh's table axis, and hand the result back.
In-graph code should call ``jax.lax.psum`` etc. directly inside its own
``shard_map``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from multiverso_tpu.zoo import Zoo


def _mesh_axis(axis: Optional[str]):
    zoo = Zoo.get()
    mesh = zoo.mesh()
    return mesh, (axis or zoo.shard_axis())


def all_reduce(x, axis: Optional[str] = None) -> jax.Array:
    """Sum the per-shard slices of an axis-sharded array into a replicated
    result — the reference Allreduce over per-node buffers
    (ref AllreduceEngine::Allreduce). Input: sharded [n] (n = shards * chunk);
    output: replicated [chunk] = sum of all chunks."""
    mesh, ax = _mesh_axis(axis)
    x = jnp.asarray(x)

    @partial(jax.shard_map, mesh=mesh, in_specs=P(ax), out_specs=P(),
             check_vma=False)
    def _psum(v):
        return jax.lax.psum(v, ax)

    return _psum(x)


def all_gather(x, axis: Optional[str] = None) -> jax.Array:
    """Concatenate the shards of an axis-sharded array on every shard
    (ref AllreduceEngine::Allgather)."""
    mesh, ax = _mesh_axis(axis)
    x = jnp.asarray(x)

    @partial(jax.shard_map, mesh=mesh, in_specs=P(ax), out_specs=P(),
             check_vma=False)
    def _ag(v):
        return jax.lax.all_gather(v, ax, tiled=True)

    return _ag(x)


def reduce_scatter(x, axis: Optional[str] = None) -> jax.Array:
    """Sum a replicated array and leave each shard with its slice
    (ref AllreduceEngine::ReduceScatter). Input: replicated [n]; output:
    sharded [n] (each device holds n/shards)."""
    mesh, ax = _mesh_axis(axis)
    x = jnp.asarray(x)

    @partial(jax.shard_map, mesh=mesh, in_specs=P(), out_specs=P(ax),
             check_vma=False)
    def _rs(v):
        n = jax.lax.axis_size(ax)
        i = jax.lax.axis_index(ax)
        chunk = v.shape[0] // n
        return jax.lax.dynamic_slice_in_dim(v, i * chunk, chunk)

    return _rs(x)


def broadcast(x, root: int = 0, axis: Optional[str] = None) -> jax.Array:
    """Every shard adopts shard ``root``'s value (controller-broadcast
    analogue, ref src/controller.cpp membership broadcast)."""
    mesh, ax = _mesh_axis(axis)
    x = jnp.asarray(x)

    @partial(jax.shard_map, mesh=mesh, in_specs=P(ax), out_specs=P(),
             check_vma=False)
    def _bc(v):
        full = jax.lax.all_gather(v, ax)
        return full[root]

    return _bc(x)
