"""Collectives over the device mesh.

TPU-native equivalent of the reference's CPU collective engine
(ref: src/net/allreduce_engine.cpp — Bruck all-gather for small payloads,
recursive-halving reduce-scatter + Bruck for large, over point-to-point
SendRecv; src/net/allreduce_topo.cpp — the hop maps). On TPU every one of
those algorithms collapses into a single XLA collective routed on the ICI
torus by the compiler — ``psum`` / ``all_gather`` / ``psum_scatter`` inside
``shard_map``. The topology math (BruckMap/RecursiveHalvingMap) is subsumed
by hardware routing and is an explicit non-goal (SURVEY §2.2).

These helpers are host-plane conveniences: they take a host or device array,
run the collective over the Zoo mesh's table axis (or an explicit ``mesh``,
for harnesses running before/without the Zoo — the same override ring/tp
take), and hand the result back. In-graph code should call ``jax.lax.psum``
etc. directly inside its own ``shard_map``.

Observability (ISSUE 12): every entry point wraps its dispatch in
``telemetry/devstats.collective_span`` — op/bytes/duration land as
Dashboard ``coll[op]`` monitors (zoo shutdown report), flight-recorder
``coll.begin``/``coll.end`` events, a step-profiler async span, and the
MSG_STATS ``"devices"`` block; a compile fired inside is keyed to THIS
mesh's shape. ``tools/check_obs_surface.py`` asserts the wrapping
statically, so a future collective op cannot ship dark (the crack
MSG_SNAPSHOT once slipped through). Span durations are host
dispatch(+compile) time — jax dispatch is async, so a non-blocking
caller's span excludes device execution.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiverso_tpu.telemetry import devstats as _devstats
from multiverso_tpu.utils.platform import (
    axis_size as _axis_size, shard_map as _shard_map)
from multiverso_tpu.zoo import Zoo


def _mesh_axis(axis: Optional[str], mesh: Optional[Mesh] = None):
    if mesh is not None:
        return mesh, (axis or mesh.axis_names[-1])
    zoo = Zoo.get()
    mesh = zoo.mesh()
    return mesh, (axis or zoo.shard_axis())


def process_sum(arr: np.ndarray) -> np.ndarray:
    """Sum identical-shaped per-process host arrays across the
    multi-controller world with ONE jitted device AllReduce — the
    device-side replacement for allgather-then-numpy-sum (which made
    every host download world x size bytes and reduce on CPU; the
    reference reduce-scattered for exactly this reason, ref
    src/net/allreduce_engine.cpp:39-53). Per-host transfer stays O(size)
    regardless of world size, and the reduction itself rides ICI/DCN.

    Single-process: identity. Called collectively (every process, same
    shape) like every other host-plane collective."""
    world = jax.process_count()
    if world == 1:
        return arr
    mesh, sharding, reducer = _process_sum_setup(world)
    with _devstats.collective_span("process_sum", arr.nbytes, mesh=mesh):
        rep = mesh.devices.flat[jax.process_index()]
        _devstats.note_transfer(arr.nbytes, "h2d")
        mine = jax.device_put(arr[None], rep)
        garr = jax.make_array_from_single_device_arrays(
            (world,) + arr.shape, sharding, [mine])
        out = reducer(garr)
        _devstats.note_transfer(arr.nbytes, "d2h")
        return np.asarray(out.addressable_shards[0].data).astype(arr.dtype)


_PSUM_SETUP = {}


def _process_sum_setup(world: int):
    """Mesh + jitted reducer for process_sum, built once per topology —
    a per-call jit(lambda) would re-trace every invocation (jax's
    dispatch cache keys on function identity), turning each table sync
    into a compile."""
    hit = _PSUM_SETUP.get(world)
    if hit is not None:
        return hit
    from jax.sharding import Mesh
    # one representative device per process, in process order: the
    # reduction needs each process's contribution exactly once, whatever
    # the local device count is
    rep = {}
    for d in sorted(jax.devices(), key=lambda d: d.id):
        rep.setdefault(d.process_index, d)
    mesh = Mesh(np.array([rep[p] for p in range(world)]), ("proc",))
    sharding = NamedSharding(mesh, P("proc"))
    reducer = jax.jit(lambda x: x.sum(axis=0),
                      out_shardings=NamedSharding(mesh, P()))
    _PSUM_SETUP[world] = (mesh, sharding, reducer)
    return _PSUM_SETUP[world]


# mapped-collective cache, keyed (op, mesh, axis[, root]). Two perf
# bugs the devstats compiles_by_mesh counter caught: rebuilding the
# shard_map closure per call defeated every fn-identity cache (25
# compiles for 25 all_reduce calls), and EAGER shard_map re-lowers per
# call on the legacy (jax.experimental) path even for one stable
# closure — so the cached callable is jax.jit(shard_map(...)), the
# idiom process_sum already uses: compile once per (op, mesh, shape),
# C++ fast path after. Mesh is hashable/eq by (devices, axis_names);
# bounded by the few (op, mesh) configurations a process ever builds.
_MAPPED = {}


def _mapped(key, build):
    fn = _MAPPED.get(key)
    if fn is None:
        fn = _MAPPED[key] = jax.jit(build())
    return fn


def all_reduce(x, axis: Optional[str] = None,
               mesh: Optional[Mesh] = None) -> jax.Array:
    """Sum the per-shard slices of an axis-sharded array into a replicated
    result — the reference Allreduce over per-node buffers
    (ref AllreduceEngine::Allreduce). Input: sharded [n] (n = shards * chunk);
    output: replicated [chunk] = sum of all chunks."""
    mesh, ax = _mesh_axis(axis, mesh)
    x = jnp.asarray(x)

    def build():
        @partial(_shard_map, mesh=mesh, in_specs=P(ax), out_specs=P(),
                 check_vma=False)
        def _psum(v):
            return jax.lax.psum(v, ax)
        return _psum

    with _devstats.collective_span("all_reduce", x.nbytes, mesh=mesh):
        return _mapped(("all_reduce", mesh, ax), build)(x)


def all_gather(x, axis: Optional[str] = None,
               mesh: Optional[Mesh] = None) -> jax.Array:
    """Concatenate the shards of an axis-sharded array on every shard
    (ref AllreduceEngine::Allgather)."""
    mesh, ax = _mesh_axis(axis, mesh)
    x = jnp.asarray(x)

    def build():
        @partial(_shard_map, mesh=mesh, in_specs=P(ax), out_specs=P(),
                 check_vma=False)
        def _ag(v):
            return jax.lax.all_gather(v, ax, tiled=True)
        return _ag

    with _devstats.collective_span("all_gather", x.nbytes, mesh=mesh):
        return _mapped(("all_gather", mesh, ax), build)(x)


def reduce_scatter(x, axis: Optional[str] = None,
                   mesh: Optional[Mesh] = None) -> jax.Array:
    """Sum a replicated array and leave each shard with its slice
    (ref AllreduceEngine::ReduceScatter). Input: replicated [n]; output:
    sharded [n] (each device holds n/shards)."""
    mesh, ax = _mesh_axis(axis, mesh)
    x = jnp.asarray(x)

    def build():
        @partial(_shard_map, mesh=mesh, in_specs=P(), out_specs=P(ax),
                 check_vma=False)
        def _rs(v):
            n = _axis_size(ax)
            i = jax.lax.axis_index(ax)
            chunk = v.shape[0] // n
            return jax.lax.dynamic_slice_in_dim(v, i * chunk, chunk)
        return _rs

    with _devstats.collective_span("reduce_scatter", x.nbytes, mesh=mesh):
        return _mapped(("reduce_scatter", mesh, ax), build)(x)


def broadcast(x, root: int = 0, axis: Optional[str] = None,
              mesh: Optional[Mesh] = None) -> jax.Array:
    """Every shard adopts shard ``root``'s value (controller-broadcast
    analogue, ref src/controller.cpp membership broadcast)."""
    mesh, ax = _mesh_axis(axis, mesh)
    x = jnp.asarray(x)

    def build():
        @partial(_shard_map, mesh=mesh, in_specs=P(ax), out_specs=P(),
                 check_vma=False)
        def _bc(v):
            full = jax.lax.all_gather(v, ax)
            return full[root]
        return _bc

    with _devstats.collective_span("broadcast", x.nbytes, mesh=mesh):
        return _mapped(("broadcast", mesh, ax, root), build)(x)
