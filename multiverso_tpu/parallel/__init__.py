from multiverso_tpu.parallel.collectives import (
    all_gather, all_reduce, broadcast, reduce_scatter)
from multiverso_tpu.parallel.worker_map import make_worker_mesh, worker_step
from multiverso_tpu.parallel.ring import (
    ring_attention, sequence_shard, ulysses_attention,
    zigzag_ring_attention, zigzag_shard_ids)
from multiverso_tpu.parallel.moe import (
    MoEConfig, init_experts, moe_layer, shard_experts)
from multiverso_tpu.parallel.pipeline import (
    pipeline_apply, pipeline_apply_interleaved, shard_stages,
    shard_stages_interleaved)
from multiverso_tpu.parallel.tp import (
    column_parallel, mlp_block, row_parallel, transformer_fsdp_rules,
    transformer_tp_rules)

__all__ = [
    "all_gather", "all_reduce", "broadcast", "reduce_scatter",
    "make_worker_mesh", "worker_step",
    "ring_attention", "sequence_shard", "ulysses_attention",
    "zigzag_ring_attention", "zigzag_shard_ids",
    "MoEConfig", "init_experts", "moe_layer", "shard_experts",
    "pipeline_apply", "pipeline_apply_interleaved", "shard_stages",
    "shard_stages_interleaved",
    "column_parallel", "mlp_block", "row_parallel", "transformer_fsdp_rules",
    "transformer_tp_rules",
]
