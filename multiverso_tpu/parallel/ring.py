"""Long-context sequence/context parallelism: ring attention + Ulysses.

The reference predates transformers — it has no sequence axis (SURVEY §5
"long-context: absent"). For this framework long context is first-class: two
standard context-parallel schemes over the mesh, built from XLA collectives
on ICI:

* **Ring attention** (blockwise attention with ``ppermute``): Q stays local,
  K/V blocks rotate around the ring; a numerically-stable online softmax
  (running max / denominator) accumulates the output, so sequence length
  scales with the number of chips at O(S_local^2) memory.
* **Ulysses-style all-to-all**: sequence-sharded -> head-sharded via
  ``all_to_all``, full attention locally, then back. Cheaper collectives when
  head count >= shard count.

Both are pure functions usable inside jit over any mesh axis.

Numerics note: on TPU the MXU's default matmul precision is bfloat16, so the
blockwise (ring) and monolithic attention orders can differ by ~5e-3 for
float32 inputs. Pass ``precision="float32"`` (or wrap the call in
``jax.default_matmul_precision("float32")``) when bit-level agreement with a
reference matters; training is fine at the default.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiverso_tpu.zoo import Zoo


def sequence_shard(x, axis_name: Optional[str] = None, seq_dim: int = 2):
    """device_put a [B, H, S, D] array sequence-sharded over the mesh."""
    zoo = Zoo.get()
    mesh = zoo.mesh()
    ax = axis_name or zoo.shard_axis()
    spec = [None] * x.ndim
    spec[seq_dim] = ax
    return jax.device_put(jnp.asarray(x), NamedSharding(mesh, P(*spec)))


def _ring_attention_local(q, k, v, axis_name: str, scale: float,
                          causal: bool = False):
    """Per-shard body: local q [B,H,Sq,D] against rotating k/v blocks."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    neg_inf = jnp.asarray(-1e30, q.dtype)
    # global token positions of this shard's queries
    qpos = idx * sq + jnp.arange(sq)

    def body(carry, t):
        k_blk, v_blk, m, l, o = carry
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
        if causal:
            # after t rotations the visiting k/v block is block (idx - t) % n
            j = (idx - t) % n
            kpos = j * sk + jnp.arange(sk)
            allowed = qpos[:, None] >= kpos[None, :]
        else:
            allowed = None
        if allowed is not None:
            s = jnp.where(allowed[None, None], s, neg_inf)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        if allowed is not None:
            # fully-masked rows would otherwise get exp(neg_inf-neg_inf)=1
            p = jnp.where(allowed[None, None], p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        o = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m_new, l, o), None

    m0 = jnp.full((b, h, sq), neg_inf, q.dtype)
    l0 = jnp.zeros((b, h, sq), q.dtype)
    o0 = jnp.zeros_like(q)
    (_, _, _, l, o), _ = jax.lax.scan(body, (k, v, m0, l0, o0),
                                      jnp.arange(n))
    return o / l[..., None]


def ring_attention(q, k, v, axis_name: Optional[str] = None,
                   mesh: Optional[Mesh] = None,
                   precision: Optional[str] = None,
                   causal: bool = False,
                   batch_axis: Optional[str] = None,
                   head_axis: Optional[str] = None):
    """Ring attention over sequence-sharded [B, H, S, D] arrays; causal
    masking uses global block positions so the online softmax sees exactly
    the lower-triangular scores. ``batch_axis`` additionally shards B (the
    dp x sp layout of the transformer model family) and ``head_axis``
    shards H (tensor parallelism composed with the sequence ring — heads
    are embarrassingly parallel inside the ring body). Returns the
    sequence-sharded output.

    Causal note: with contiguous block assignment shard i only has useful
    work on i+1 of its n ring steps (the rest are fully masked), so ~half
    the attention FLOPs are masked out and the ring is load-imbalanced;
    acceptable at the current scale since the masked einsums still overlap
    the ppermute. A striped/zigzag block assignment is the known fix if
    causal ring becomes the bottleneck."""
    zoo = Zoo.get()
    mesh = mesh or zoo.mesh()
    ax = axis_name or zoo.shard_axis()
    if head_axis and q.shape[1] % mesh.shape[head_axis]:
        raise ValueError(f"heads {q.shape[1]} not divisible by "
                         f"{mesh.shape[head_axis]} {head_axis!r} shards")
    scale = 1.0 / (q.shape[-1] ** 0.5)
    spec = P(batch_axis, head_axis, ax, None)

    fn = partial(_ring_attention_local, axis_name=ax, scale=scale,
                 causal=causal)
    mapped = jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                           out_specs=spec, check_vma=False)
    if precision is not None:
        with jax.default_matmul_precision(precision):
            return mapped(q, k, v)
    return mapped(q, k, v)


def ulysses_attention(q, k, v, axis_name: Optional[str] = None,
                      mesh: Optional[Mesh] = None,
                      causal: bool = False,
                      batch_axis: Optional[str] = None):
    """All-to-all sequence parallelism: resharding sequence->heads, local
    full attention, heads->sequence. Head count must be divisible by the
    shard count."""
    zoo = Zoo.get()
    mesh = mesh or zoo.mesh()
    ax = axis_name or zoo.shard_axis()
    n = mesh.shape[ax]
    if q.shape[1] % n:
        raise ValueError(f"heads {q.shape[1]} not divisible by shards {n}")
    scale = 1.0 / (q.shape[-1] ** 0.5)
    spec = P(batch_axis, None, ax, None)

    def local(q, k, v):
        # [B, H, S/n, D] -> all_to_all -> [B, H/n, S, D]
        def seq2head(x):
            return jax.lax.all_to_all(x, ax, split_axis=1, concat_axis=2,
                                      tiled=True)

        def head2seq(x):
            return jax.lax.all_to_all(x, ax, split_axis=2, concat_axis=1,
                                      tiled=True)

        qh, kh, vh = seq2head(q), seq2head(k), seq2head(v)
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
        if causal:
            sfull = qh.shape[2]
            mask = jnp.tril(jnp.ones((sfull, sfull), bool))
            s = jnp.where(mask[None, None], s, jnp.asarray(-1e30, s.dtype))
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
        return head2seq(o)

    return jax.shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)


def reference_attention(q, k, v, causal: bool = False):
    """Unsharded softmax attention (test oracle; also the recompute
    backward of ops/attention_kernels.flash_attention). Scores and softmax
    in f32 regardless of input dtype, output in the input dtype — the same
    numerics as the flash kernel."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2:]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, -1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)
