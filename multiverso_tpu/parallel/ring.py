"""Long-context sequence/context parallelism: ring attention + Ulysses.

The reference predates transformers — it has no sequence axis (SURVEY §5
"long-context: absent"). For this framework long context is first-class: two
standard context-parallel schemes over the mesh, built from XLA collectives
on ICI:

* **Ring attention** (blockwise attention with ``ppermute``): Q stays local,
  K/V blocks rotate around the ring; a numerically-stable online softmax
  (running max / denominator) accumulates the output, so sequence length
  scales with the number of chips at O(S_local^2) memory.
* **Ulysses-style all-to-all**: sequence-sharded -> head-sharded via
  ``all_to_all``, full attention locally, then back. Cheaper collectives when
  head count >= shard count.

Both are pure functions usable inside jit over any mesh axis.

Numerics note: on TPU the MXU's default matmul precision is bfloat16, so the
blockwise (ring) and monolithic attention orders can differ by ~5e-3 for
float32 inputs. Pass ``precision="float32"`` (or wrap the call in
``jax.default_matmul_precision("float32")``) when bit-level agreement with a
reference matters; training is fine at the default.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiverso_tpu.telemetry import devstats as _devstats
from multiverso_tpu.utils.platform import (
    axis_size as _axis_size, shard_map as _shard_map)
from multiverso_tpu.zoo import Zoo

# jit-wrapped shard_map callable cache keyed on EVERY closed-over
# parameter — the parallel/collectives.py discipline: rebuilding the
# closure per call defeats every fn-identity cache, and eager legacy
# shard_map re-lowers per call (the 25-calls-=-25-compiles pathology
# the devstats compiles_by_mesh counter measured)
_MAPPED = {}


def _mapped(key, build):
    fn = _MAPPED.get(key)
    if fn is None:
        fn = _MAPPED[key] = jax.jit(build())
    return fn


def sequence_shard(x, axis_name: Optional[str] = None, seq_dim: int = 2):
    """device_put a [B, H, S, D] array sequence-sharded over the mesh."""
    zoo = Zoo.get()
    mesh = zoo.mesh()
    ax = axis_name or zoo.shard_axis()
    spec = [None] * x.ndim
    spec[seq_dim] = ax
    x = jnp.asarray(x)
    # host->device transfer through the devstats chokepoint (the sharded
    # upload is exactly the device-plane cost the scale curve attributes)
    _devstats.note_transfer(x.nbytes, "h2d")
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))


def _online_update(qc, kc, vc, scale, allowed, m, l, o):
    """One block of the numerically-stable online softmax: fold the scores
    of ``qc @ kc^T`` (masked where ``allowed`` is False; None = no mask)
    into the running (max, denominator, output) state. Shared by the
    contiguous and zigzag ring bodies."""
    neg_inf = jnp.asarray(-1e30, qc.dtype)
    s = jnp.einsum("bhqd,bhkd->bhqk", qc, kc) * scale
    if allowed is not None:
        s = jnp.where(allowed[None, None], s, neg_inf)
    m_new = jnp.maximum(m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    if allowed is not None:
        # fully-masked rows would otherwise get exp(neg_inf-neg_inf)=1
        p = jnp.where(allowed[None, None], p, 0.0)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + p.sum(axis=-1)
    o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vc)
    return m_new, l_new, o_new


def _ring_attention_local(q, k, v, axis_name: str, scale: float,
                          causal: bool = False):
    """Per-shard body: local q [B,H,Sq,D] against rotating k/v blocks."""
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    neg_inf = jnp.asarray(-1e30, q.dtype)
    # global token positions of this shard's queries
    qpos = idx * sq + jnp.arange(sq)

    def body(carry, t):
        k_blk, v_blk, m, l, o = carry
        if causal:
            # after t rotations the visiting k/v block is block (idx - t) % n
            j = (idx - t) % n
            kpos = j * sk + jnp.arange(sk)
            allowed = qpos[:, None] >= kpos[None, :]
        else:
            allowed = None
        m, l, o = _online_update(q, k_blk, v_blk, scale, allowed, m, l, o)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m, l, o), None

    m0 = jnp.full((b, h, sq), neg_inf, q.dtype)
    l0 = jnp.zeros((b, h, sq), q.dtype)
    o0 = jnp.zeros_like(q)
    (_, _, _, l, o), _ = jax.lax.scan(body, (k, v, m0, l0, o0),
                                      jnp.arange(n))
    return o / l[..., None]


def ring_attention(q, k, v, axis_name: Optional[str] = None,
                   mesh: Optional[Mesh] = None,
                   precision: Optional[str] = None,
                   causal: bool = False,
                   batch_axis: Optional[str] = None,
                   head_axis: Optional[str] = None):
    """Ring attention over sequence-sharded [B, H, S, D] arrays; causal
    masking uses global block positions so the online softmax sees exactly
    the lower-triangular scores. ``batch_axis`` additionally shards B (the
    dp x sp layout of the transformer model family) and ``head_axis``
    shards H (tensor parallelism composed with the sequence ring — heads
    are embarrassingly parallel inside the ring body). Returns the
    sequence-sharded output.

    Causal note: with contiguous block assignment shard i only has useful
    work on i+1 of its n ring steps (the rest are fully masked), so ~half
    the attention FLOPs are masked out and the ring is load-imbalanced.
    :func:`zigzag_ring_attention` is the balanced fix — every shard does
    exactly half the pairs every tick and dead pairs are skipped, not
    masked."""
    zoo = Zoo.get()
    mesh = mesh or zoo.mesh()
    ax = axis_name or zoo.shard_axis()
    if head_axis and q.shape[1] % mesh.shape[head_axis]:
        raise ValueError(f"heads {q.shape[1]} not divisible by "
                         f"{mesh.shape[head_axis]} {head_axis!r} shards")
    scale = 1.0 / (q.shape[-1] ** 0.5)
    spec = P(batch_axis, head_axis, ax, None)

    # every closed-over value is in the key: a head-dim change moves
    # `scale`, and `precision` is trace-time (the context wraps the
    # first call, which is when the cached fn traces)
    mapped = _mapped(
        ("ring", mesh, ax, scale, causal, batch_axis, head_axis,
         precision),
        lambda: _shard_map(
            partial(_ring_attention_local, axis_name=ax, scale=scale,
                    causal=causal),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False))
    nbytes = q.nbytes + k.nbytes + v.nbytes
    with _devstats.collective_span("ring_attention", nbytes, mesh=mesh):
        if precision is not None:
            with jax.default_matmul_precision(precision):
                return mapped(q, k, v)
        return mapped(q, k, v)


def ulysses_attention(q, k, v, axis_name: Optional[str] = None,
                      mesh: Optional[Mesh] = None,
                      causal: bool = False,
                      batch_axis: Optional[str] = None):
    """All-to-all sequence parallelism: resharding sequence->heads, local
    full attention, heads->sequence. Head count must be divisible by the
    shard count."""
    zoo = Zoo.get()
    mesh = mesh or zoo.mesh()
    ax = axis_name or zoo.shard_axis()
    n = mesh.shape[ax]
    if q.shape[1] % n:
        raise ValueError(f"heads {q.shape[1]} not divisible by shards {n}")
    scale = 1.0 / (q.shape[-1] ** 0.5)
    spec = P(batch_axis, None, ax, None)

    def local(q, k, v):
        # [B, H, S/n, D] -> all_to_all -> [B, H/n, S, D]
        def seq2head(x):
            return jax.lax.all_to_all(x, ax, split_axis=1, concat_axis=2,
                                      tiled=True)

        def head2seq(x):
            return jax.lax.all_to_all(x, ax, split_axis=2, concat_axis=1,
                                      tiled=True)

        qh, kh, vh = seq2head(q), seq2head(k), seq2head(v)
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
        if causal:
            sfull = qh.shape[2]
            mask = jnp.tril(jnp.ones((sfull, sfull), bool))
            s = jnp.where(mask[None, None], s, jnp.asarray(-1e30, s.dtype))
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
        return head2seq(o)

    nbytes = q.nbytes + k.nbytes + v.nbytes
    mapped = _mapped(
        ("ulysses", mesh, ax, scale, causal, batch_axis),
        lambda: _shard_map(local, mesh=mesh,
                           in_specs=(spec, spec, spec), out_specs=spec))
    with _devstats.collective_span("ulysses_attention", nbytes, mesh=mesh):
        return mapped(q, k, v)


def zigzag_shard_ids(seq_len: int, n: int) -> "jnp.ndarray":
    """Global token order for the zigzag layout: shard i owns chunks i and
    2n-1-i of the 2n equal chunks. Returns the permutation ``perm`` such
    that ``x[..., perm, :]`` is zigzag-ordered (shard-major);
    ``jnp.argsort(perm)`` inverts it."""
    if seq_len % (2 * n):
        raise ValueError(f"seq {seq_len} not divisible by 2n={2 * n} chunks")
    c = seq_len // (2 * n)
    order = []
    for i in range(n):
        order.extend(range(i * c, (i + 1) * c))                    # chunk i
        j = 2 * n - 1 - i
        order.extend(range(j * c, (j + 1) * c))                    # chunk 2n-1-i
    import numpy as _np
    return jnp.asarray(_np.asarray(order, _np.int32))


def _zigzag_ring_local(q, k, v, axis_name: str, scale: float):
    """Per-shard causal body, zigzag layout. Local q/k/v are
    [B, H, 2c, D] = concat(chunk_lo=i, chunk_hi=2n-1-i). Causal liveness of
    each (q-chunk, k-chunk) pair is decided per tick with ``lax.switch`` so
    dead pairs cost nothing and every shard computes exactly 2 of 4 pairs
    every tick — balanced, ~half the FLOPs of masked contiguous ring."""
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, s2, d = q.shape
    c = s2 // 2
    neg_inf = jnp.asarray(-1e30, q.dtype)
    ar = jnp.arange(c)

    def chunk_attn(qc, kc, vc, qpos0, kpos0, mode, m, l, o):
        """Online-softmax update of (m, l, o) for one chunk pair.
        mode: 0 dead, 1 diagonal (triangular mask), 2 fully live."""

        def dead(_):
            return m, l, o

        def live(masked):
            allowed = ((qpos0 + ar)[:, None] >= (kpos0 + ar)[None, :]
                       if masked else None)
            return _online_update(qc, kc, vc, scale, allowed, m, l, o)

        return jax.lax.switch(mode, [dead,
                                     lambda _: live(True),
                                     lambda _: live(False)], None)

    def body(carry, t):
        k_blk, v_blk, st_lo, st_hi = carry
        j = (idx - t) % n                      # owner of the visiting block
        k_lo, k_hi = k_blk[:, :, :c], k_blk[:, :, c:]
        v_lo, v_hi = v_blk[:, :, :c], v_blk[:, :, c:]
        qpos_lo = idx * c                      # chunk i
        qpos_hi = (2 * n - 1 - idx) * c        # chunk 2n-1-i
        kpos_lo = j * c
        kpos_hi = (2 * n - 1 - j) * c
        # pair liveness (see chunk algebra in ring docstring): q_lo vs k_hi
        # is always dead; q_hi vs k_lo always fully live
        m1 = jnp.where(idx > j, 2, jnp.where(idx == j, 1, 0))  # q_lo,k_lo
        m4 = jnp.where(idx < j, 2, jnp.where(idx == j, 1, 0))  # q_hi,k_hi
        st_lo = chunk_attn(q[:, :, :c], k_lo, v_lo, qpos_lo, kpos_lo,
                           m1, *st_lo)
        st_hi = chunk_attn(q[:, :, c:], k_lo, v_lo, qpos_hi, kpos_lo,
                           jnp.int32(2), *st_hi)
        st_hi = chunk_attn(q[:, :, c:], k_hi, v_hi, qpos_hi, kpos_hi,
                           m4, *st_hi)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, st_lo, st_hi), None

    def init_state():
        return (jnp.full((b, h, c), neg_inf, q.dtype),
                jnp.zeros((b, h, c), q.dtype),
                jnp.zeros((b, h, c, d), q.dtype))

    (_, _, (_, l_lo, o_lo), (_, l_hi, o_hi)), _ = jax.lax.scan(
        body, (k, v, init_state(), init_state()), jnp.arange(n))
    return jnp.concatenate([o_lo / l_lo[..., None],
                            o_hi / l_hi[..., None]], axis=2)


def zigzag_ring_attention(q, k, v, axis_name: Optional[str] = None,
                          mesh: Optional[Mesh] = None,
                          precision: Optional[str] = None,
                          batch_axis: Optional[str] = None,
                          head_axis: Optional[str] = None):
    """Causal ring attention with the balanced zigzag layout. Inputs
    [B, H, S, D] must be permuted into zigzag sequence order first
    (``x[:, :, zigzag_shard_ids(S, n), :]``); the output comes back in the
    same layout. Always causal — for non-causal use :func:`ring_attention`,
    whose contiguous ring is already balanced when nothing is masked."""
    zoo = Zoo.get()
    mesh = mesh or zoo.mesh()
    ax = axis_name or zoo.shard_axis()
    n = mesh.shape[ax]
    if q.shape[2] % (2 * n):
        raise ValueError(f"seq {q.shape[2]} not divisible by 2n={2 * n}")
    if head_axis and q.shape[1] % mesh.shape[head_axis]:
        raise ValueError(f"heads {q.shape[1]} not divisible by "
                         f"{mesh.shape[head_axis]} {head_axis!r} shards")
    scale = 1.0 / (q.shape[-1] ** 0.5)
    spec = P(batch_axis, head_axis, ax, None)
    mapped = _mapped(
        ("zigzag", mesh, ax, scale, batch_axis, head_axis, precision),
        lambda: _shard_map(
            partial(_zigzag_ring_local, axis_name=ax, scale=scale),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False))
    nbytes = q.nbytes + k.nbytes + v.nbytes
    with _devstats.collective_span("zigzag_ring_attention", nbytes,
                                   mesh=mesh):
        if precision is not None:
            with jax.default_matmul_precision(precision):
                return mapped(q, k, v)
        return mapped(q, k, v)


def reference_attention(q, k, v, causal: bool = False):
    """Unsharded softmax attention (test oracle for the flash and ring
    kernels). Scores and softmax in f32 regardless of input dtype, output
    in the input dtype — the same numerics as the flash kernel."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2:]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, -1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)
