"""Tensor (model) parallelism: Megatron-style sharded matmuls over a mesh axis.

The reference's "model parallelism" is parameter-*storage* sharding (SURVEY
§2.10: tables row-sharded across servers, ref src/table/matrix_table.cpp:24-45
— the compute still happens whole on each worker). Here compute itself is
sharded: attention heads and MLP hidden units split over a ``tp`` axis, the
classic column-parallel -> row-parallel pairing so each layer needs exactly
one psum on its output.

Two surfaces, both TPU-first:

* **GSPMD rules** (:func:`transformer_tp_rules`, :func:`shard_params`): place
  the transformer param tree with TP layouts and let XLA insert the
  collectives — the scaling-book recipe (mesh + sharding annotations, no
  hand-written comms). :func:`constrain` is the activation-side hint.
* **Explicit primitives** (:func:`column_parallel`, :func:`row_parallel`):
  shard_map building blocks for users composing their own blocks; the psum
  placement is spelled out.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiverso_tpu.telemetry import devstats as _devstats
from multiverso_tpu.utils.platform import shard_map as _shard_map
from multiverso_tpu.zoo import Zoo


def transformer_tp_rules(axis: str = "tp") -> Dict[str, Any]:
    """PartitionSpec tree for models/transformer.py params (leading layer dim
    on the stacked leaves): qkv/w1 column-parallel (output dim sharded),
    wo/w2 row-parallel (input dim sharded), embeddings vocab-sharded, norms
    replicated."""
    return {
        "embed": P(axis, None),
        "pos": P(None, None),
        "layers": {
            "wqkv": P(None, None, axis),
            "wo": P(None, axis, None),
            "w1": P(None, None, axis),
            "w2": P(None, axis, None),
            "ln1": P(None, None),
            "ln2": P(None, None),
        },
        "ln_f": P(None),
    }


def transformer_fsdp_rules(axis: str = "fsdp",
                           moe: bool = False) -> Dict[str, Any]:
    """FSDP / ZeRO-3 layout for models/transformer.py params: every large
    leaf is split on one dimension over the data-parallel axis, so each
    chip STORES 1/n of the model while computing on its own batch shard
    (set ``batch_axis=axis`` too). XLA inserts the all-gather on use and
    the reduce-scatter on the gradients — the scaling-book FSDP recipe,
    no hand-written comms. Tiny norm vectors stay replicated. ``moe=True``
    matches the MoE param tree (expert stacks split on their model dim,
    leaving the expert dim free for a separate ep axis)."""
    layers = {
        "wqkv": P(None, axis, None),
        "wo": P(None, axis, None),
        "ln1": P(None, None),
        "ln2": P(None, None),
    }
    if moe:
        layers.update({
            "moe_w1": P(None, None, axis, None),
            "moe_w2": P(None, None, axis, None),
            "moe_router": P(None, axis, None),
        })
    else:
        layers.update({
            "w1": P(None, axis, None),
            "w2": P(None, axis, None),
        })
    return {
        "embed": P(axis, None),
        "pos": P(axis, None),
        "layers": layers,
        "ln_f": P(None),
    }


def shard_params(params: Any, rules: Any,
                 mesh: Optional[Mesh] = None) -> Any:
    """device_put a param pytree according to a matching PartitionSpec tree."""
    mesh = mesh or Zoo.get().mesh()
    # the whole-tree upload is a device-plane cost the scale curve
    # attributes — count it once through the devstats chokepoint
    _devstats.note_transfer(
        sum(int(getattr(leaf, "nbytes", 0))
            for leaf in jax.tree.leaves(params)), "h2d")
    # rules must mirror params' container structure with a PartitionSpec at
    # each array-leaf position (tree.map stops descending at params' leaves,
    # so the P tuples are picked up whole — but a P standing in for a whole
    # subtree is a structure mismatch)
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params, rules)


def constrain(x: jax.Array, spec: P, mesh: Optional[Mesh] = None) -> jax.Array:
    """with_sharding_constraint shorthand (trace-time mesh from the Zoo)."""
    mesh = mesh or Zoo.get().mesh()
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def _lead_spec(x, x_spec: Optional[P]) -> tuple:
    """Sharding of x's leading (non-contracted) dims, padded to ndim-1."""
    lead = tuple(x_spec) if x_spec is not None else ()
    if len(lead) > x.ndim - 1:
        raise ValueError(f"x_spec {x_spec} longer than x's {x.ndim - 1} "
                         "leading dims")
    return lead + (None,) * (x.ndim - 1 - len(lead))


# jit-wrapped shard_map callable cache keyed on every closed-over
# parameter (the parallel/collectives.py discipline — a per-call
# closure rebuild re-lowers/recompiles every call on the legacy
# shard_map path; the devstats compiles_by_mesh counter measured it)
_MAPPED = {}


def _mapped(key, build):
    fn = _MAPPED.get(key)
    if fn is None:
        fn = _MAPPED[key] = jax.jit(build())
    return fn


def column_parallel(x: jax.Array, w: jax.Array, axis: str = "tp",
                    mesh: Optional[Mesh] = None,
                    x_spec: Optional[P] = None) -> jax.Array:
    """y = x @ w with w column-sharded [D, M/n per shard]; output stays
    sharded on its last dim (no collective — pair with :func:`row_parallel`).
    x: [..., D]; pass ``x_spec`` (a PartitionSpec over x's leading dims,
    e.g. ``P('dp')``) to keep batch-sharded activations sharded instead of
    gathering them to every device."""
    mesh = mesh or Zoo.get().mesh()
    lead = _lead_spec(x, x_spec)

    def body(x, w):
        return x @ w

    with _devstats.collective_span("column_parallel",
                                   x.nbytes + w.nbytes, mesh=mesh):
        return _mapped(
            ("col", mesh, axis, lead),
            lambda: _shard_map(
                body, mesh=mesh,
                in_specs=(P(*lead, None), P(None, axis)),
                out_specs=P(*lead, axis), check_vma=False))(x, w)


def row_parallel(x: jax.Array, w: jax.Array, axis: str = "tp",
                 mesh: Optional[Mesh] = None,
                 x_spec: Optional[P] = None) -> jax.Array:
    """y = x @ w with x last-dim-sharded and w row-sharded [M/n, D]; the
    partial products psum over ``axis`` — the single collective of the
    column->row Megatron pair. ``x_spec`` shards x's leading dims as in
    :func:`column_parallel`."""
    mesh = mesh or Zoo.get().mesh()
    lead = _lead_spec(x, x_spec)

    def body(x, w):
        return jax.lax.psum(x @ w, axis)

    with _devstats.collective_span("row_parallel",
                                   x.nbytes + w.nbytes, mesh=mesh):
        return _mapped(
            ("row", mesh, axis, lead),
            lambda: _shard_map(
                body, mesh=mesh,
                in_specs=(P(*lead, axis), P(axis, None)),
                out_specs=P(*lead, None), check_vma=False))(x, w)


def mlp_block(x: jax.Array, w1: jax.Array, w2: jax.Array,
              axis: str = "tp", mesh: Optional[Mesh] = None,
              x_spec: Optional[P] = None) -> jax.Array:
    """gelu(x @ w1) @ w2 with the hidden dim sharded: column_parallel ->
    local gelu -> row_parallel (one psum total). ``x_spec`` keeps
    batch-sharded inputs sharded through the pair."""
    mesh = mesh or Zoo.get().mesh()
    h = column_parallel(x, w1, axis, mesh, x_spec)
    h = jax.nn.gelu(h)
    return row_parallel(h, w2, axis, mesh, x_spec)
