"""Multi-worker BSP training over a worker mesh axis.

TPU-native equivalent of the reference's synchronous data parallelism: N
workers push deltas, the SyncServer's vector clocks force every i-th Get to
see the same state on all workers (ref: src/server.cpp:68-222 SyncServer,
flag -sync=true). On TPU BSP is the *hardware-native* mode: one jitted SPMD
step where each logical worker computes on its batch shard and the deltas
meet in a ``psum`` — the vector-clock machinery is replaced by the data
dependency itself (SURVEY §7 design stance).

``worker_step`` builds that step for any per-worker gradient function plus a
parameter table: grads are psum-averaged over the worker axis and applied
through the table's updater, all in one compiled program.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiverso_tpu.updaters import AddOption
from multiverso_tpu.utils.platform import shard_map as _shard_map
from multiverso_tpu.zoo import Zoo


def make_worker_mesh(num_workers: int, axis: str = "worker",
                     shard_axis: str = "mv") -> Mesh:
    """A (worker, shard) mesh over all local devices: batch parallel over
    ``worker``, table rows over ``shard``. num_workers must divide the device
    count."""
    devices = np.asarray(jax.devices())
    if devices.size % num_workers:
        raise ValueError(
            f"{num_workers} workers do not divide {devices.size} devices")
    return Mesh(devices.reshape(num_workers, devices.size // num_workers),
                (axis, shard_axis))


def worker_step(table, grad_fn: Callable, learning_rate: float = 0.1,
                axis: str = "worker",
                opt: Optional[AddOption] = None) -> Callable:
    """Build ``step(state, batch) -> (state, loss)`` where ``batch`` leading
    dim is sharded over the worker axis; each worker's gradient is computed
    on its shard, psum-averaged (the BSP merge), lr-premultiplied and applied
    via the table updater.

    ``grad_fn(params_flat, batch_shard) -> (loss, grad_flat)`` runs per
    worker; params are replicated across workers (each worker sees the same
    table state — the SyncServer guarantee).
    """
    mesh = Zoo.get().mesh()
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {axis!r} axis: {mesh.axis_names}")
    opt = opt or AddOption(learning_rate=learning_rate)
    shard_ax = [a for a in mesh.axis_names if a != axis]

    def step(state, batch):
        data = state["data"]

        @partial(_shard_map, mesh=mesh,
                 in_specs=(P(), P(axis)), out_specs=(P(), P()),
                 check_vma=False)
        def _grads(params, local_batch):
            loss, grad = grad_fn(params, local_batch)
            # BSP merge: average the per-worker gradients over ICI
            grad = jax.lax.pmean(grad, axis)
            loss = jax.lax.pmean(loss, axis)
            return loss, grad

        loss, grad = _grads(data, batch)
        delta = learning_rate * grad
        new_state = table.functional_add(state, delta, opt)
        return new_state, loss

    return step
