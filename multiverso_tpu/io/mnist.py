"""MNIST idx-format loader.

BASELINE config 1 trains LogisticRegression on MNIST; the reference's example
downloads it (Applications/LogisticRegression/example/run.sh). This
environment has no egress, so the loader reads pre-downloaded idx files when
present and callers fall back to synthetic data otherwise.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

_FILES = {
    "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


def _open(path: str):
    if os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rb")
    return open(path, "rb")


def _read_idx(path: str) -> np.ndarray:
    with _open(path) as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def available(data_dir: str) -> bool:
    img, lbl = _FILES["train"]
    return any(os.path.exists(os.path.join(data_dir, img) + ext)
               for ext in ("", ".gz"))


def load(data_dir: str, split: str = "train",
         flatten: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (images [N, 784] float32 in [0,1], labels [N] int32)."""
    img_name, lbl_name = _FILES[split]
    images = _read_idx(os.path.join(data_dir, img_name)).astype(np.float32) / 255.0
    labels = _read_idx(os.path.join(data_dir, lbl_name)).astype(np.int32)
    if flatten:
        images = images.reshape(len(labels), -1)
    else:
        images = images[..., None]  # NHWC
    return images, labels


def load_real(data_dir: Optional[str] = None):
    """Best REAL handwritten-digit data available (tier-4 convergence runs,
    BASELINE config 1): MNIST idx files when present (``data_dir`` or
    $MV_MNIST_DIR), else scikit-learn's bundled UCI handwritten digits
    (1797 real 8x8 samples — real data, shipped in the image; MNIST itself
    cannot be downloaded in a zero-egress environment).

    Returns dict(x_train, y_train, x_test, y_test, provenance).
    """
    data_dir = data_dir or os.environ.get("MV_MNIST_DIR", "")
    if data_dir and available(data_dir):
        xtr, ytr = load(data_dir, "train")
        xte, yte = load(data_dir, "test")
        return {"x_train": xtr, "y_train": ytr, "x_test": xte,
                "y_test": yte, "provenance": "mnist-idx"}
    from sklearn.datasets import load_digits  # bundled real data
    d = load_digits()
    x = (d.data / 16.0).astype(np.float32)
    y = d.target.astype(np.int32)
    # deterministic 80/20 split, stratified-ish by shuffling with a fixed
    # seed (the dataset is ordered)
    rng = np.random.default_rng(0)
    perm = rng.permutation(len(y))
    x, y = x[perm], y[perm]
    cut = int(0.8 * len(y))
    return {"x_train": x[:cut], "y_train": y[:cut],
            "x_test": x[cut:], "y_test": y[cut:],
            "provenance": "uci-digits-8x8 (sklearn bundled, real)"}
