"""URI-dispatched streams.

TPU-native equivalent of the reference IO layer
(ref: include/multiverso/io/io.h:24-132 — Stream/StreamFactory/TextReader with
``file://`` vs ``hdfs://`` URI dispatch; the working remote backend was
src/io/hdfs_stream.cpp:1-157). The cloud-storage scheme of the TPU era is
``gs://``; any non-local scheme is dispatched through fsspec, so ``gs://``
(via gcsfs), ``s3://``, ``memory://`` (the fake-FS test backend), etc. all
work through the same factory — the analogue of the reference's pluggable
StreamFactory per URI scheme. Local paths (bare or ``file://``) are
first-class and never touch fsspec.
"""

from __future__ import annotations

import io as _io
import os
from typing import IO, Iterator, Optional


class Stream:
    """Thin binary stream wrapper (ref io.h Stream: Read/Write/Good)."""

    def __init__(self, fileobj: IO[bytes], uri: str):
        self._f = fileobj
        self.uri = uri

    def write(self, data: bytes) -> int:
        return self._f.write(data)

    def read(self, size: int = -1) -> bytes:
        return self._f.read(size)

    def good(self) -> bool:
        return not self._f.closed

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "Stream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # numpy save/load compatibility
    def seek(self, *args):
        return self._f.seek(*args)

    def tell(self):
        return self._f.tell()

    def readinto(self, b):
        return self._f.readinto(b)

    def readline(self, *args):
        return self._f.readline(*args)

    def flush(self):
        return self._f.flush()


def _open_fsspec(uri: str, mode: str) -> IO[bytes]:
    """Remote stream via fsspec (ref src/io/hdfs_stream.cpp — the reference's
    one remote backend; fsspec gives us gs/s3/memory/... through one seam)."""
    try:
        import fsspec
    except ImportError as e:  # pragma: no cover - fsspec is in the image
        raise NotImplementedError(
            f"{uri!r} needs fsspec for remote schemes (reference analogue: "
            "hdfs:// needed libhdfs)") from e
    fs, path = fsspec.core.url_to_fs(uri)
    if "w" in mode or "a" in mode:
        parent = path.rsplit("/", 1)[0]
        if parent and parent != path:
            try:
                fs.makedirs(parent, exist_ok=True)
            except Exception:
                pass  # flat namespaces (gs buckets) have no real dirs
    return fs.open(path, mode)


def open_stream(uri: str, mode: str = "rb") -> Stream:
    """ref StreamFactory::GetStream (io.h) — dispatch on URI scheme."""
    if "b" not in mode:
        mode += "b"
    if uri.startswith("file://"):
        path = uri[len("file://"):]
    elif "://" in uri:
        return Stream(_open_fsspec(uri, mode), uri)
    else:
        path = uri
    if "w" in mode or "a" in mode:
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
    return Stream(open(path, mode), uri)


class TextReader:
    """Line reader over a Stream (ref io.h TextReader::GetLine)."""

    def __init__(self, uri_or_stream, buf_size: int = 1 << 20):
        if isinstance(uri_or_stream, Stream):
            self._stream = uri_or_stream
        else:
            self._stream = open_stream(uri_or_stream, "rb")
        self._wrapped = _io.TextIOWrapper(
            _io.BufferedReader(self._stream._f, buf_size), encoding="utf-8",
            errors="replace")

    def get_line(self) -> Optional[str]:
        line = self._wrapped.readline()
        return line.rstrip("\n") if line else None

    def __iter__(self) -> Iterator[str]:
        while True:
            line = self.get_line()
            if line is None:
                return
            yield line

    def close(self) -> None:
        self._wrapped.close()
