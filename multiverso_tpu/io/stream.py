"""URI-dispatched streams.

TPU-native equivalent of the reference IO layer
(ref: include/multiverso/io/io.h:24-132 — Stream/StreamFactory/TextReader with
``file://`` vs ``hdfs://`` URI dispatch). The cloud-storage scheme of the TPU
era is ``gs://``; it is gated on an optional dependency (gcsfs/tf.io) and
raises a clear error when unavailable in this zero-egress environment. Local
paths (bare or ``file://``) are first-class.
"""

from __future__ import annotations

import io as _io
import os
from typing import IO, Iterator, Optional


class Stream:
    """Thin binary stream wrapper (ref io.h Stream: Read/Write/Good)."""

    def __init__(self, fileobj: IO[bytes], uri: str):
        self._f = fileobj
        self.uri = uri

    def write(self, data: bytes) -> int:
        return self._f.write(data)

    def read(self, size: int = -1) -> bytes:
        return self._f.read(size)

    def good(self) -> bool:
        return not self._f.closed

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "Stream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # numpy save/load compatibility
    def seek(self, *args):
        return self._f.seek(*args)

    def tell(self):
        return self._f.tell()

    def readinto(self, b):
        return self._f.readinto(b)

    def readline(self, *args):
        return self._f.readline(*args)

    def flush(self):
        return self._f.flush()


def open_stream(uri: str, mode: str = "rb") -> Stream:
    """ref StreamFactory::GetStream (io.h) — dispatch on URI scheme."""
    if "b" not in mode:
        mode += "b"
    if uri.startswith("file://"):
        path = uri[len("file://"):]
    elif uri.startswith("gs://"):
        raise NotImplementedError(
            "gs:// streams need gcsfs/tensorflow-io; not available in this "
            "environment (reference analogue: hdfs:// needed libhdfs)")
    elif "://" in uri:
        raise ValueError(f"unsupported stream scheme in {uri!r}")
    else:
        path = uri
    if "w" in mode or "a" in mode:
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
    return Stream(open(path, mode), uri)


class TextReader:
    """Line reader over a Stream (ref io.h TextReader::GetLine)."""

    def __init__(self, uri_or_stream, buf_size: int = 1 << 20):
        if isinstance(uri_or_stream, Stream):
            self._stream = uri_or_stream
        else:
            self._stream = open_stream(uri_or_stream, "rb")
        self._wrapped = _io.TextIOWrapper(
            _io.BufferedReader(self._stream._f, buf_size), encoding="utf-8",
            errors="replace")

    def get_line(self) -> Optional[str]:
        line = self._wrapped.readline()
        return line.rstrip("\n") if line else None

    def __iter__(self) -> Iterator[str]:
        while True:
            line = self.get_line()
            if line is None:
                return
            yield line

    def close(self) -> None:
        self._wrapped.close()
