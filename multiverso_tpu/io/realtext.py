"""Real-text corpus access (the tier-4 text8 stand-in).

BASELINE config 2 trains WordEmbedding on text8, which cannot be fetched
in a zero-egress environment. ``data/realtext.txt.gz`` is a committed
shard of REAL English prose harvested from the image's package
documentation and docstrings, normalized exactly like text8 (wikifil:
lowercase a-z + single spaces — see tools/build_corpus.py). ~1.3M tokens,
~18k distinct words, Zipfian as natural language is.

If an actual text8 file is present ($MV_TEXT8 or data/text8), it is
preferred.
"""

from __future__ import annotations

import gzip
import os
import tempfile
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SHARD = os.path.join(_REPO, "data", "realtext.txt.gz")


def provenance() -> str:
    if _text8_path():
        return "text8"
    return "realtext (image docs/docstrings, text8-normalized, real English)"


def _text8_path() -> Optional[str]:
    for cand in (os.environ.get("MV_TEXT8", ""),
                 os.path.join(_REPO, "data", "text8")):
        if cand and os.path.exists(cand):
            return cand
    return None


def load_tokens(max_tokens: Optional[int] = None) -> List[str]:
    t8 = _text8_path()
    if t8 is not None:
        with open(t8) as f:
            text = f.read() if max_tokens is None else f.read(
                max_tokens * 12)
    else:
        with gzip.open(_SHARD, "rt") as f:
            text = f.read() if max_tokens is None else f.read(
                max_tokens * 12)
    toks = text.split()
    if max_tokens is not None:
        toks = toks[:max_tokens]
    return toks


def materialize(path: Optional[str] = None) -> str:
    """Decompress the shard to a plain file (for -train_file style CLIs);
    returns the path. Cached across calls."""
    t8 = _text8_path()
    if t8 is not None:
        return t8
    path = path or os.path.join(tempfile.gettempdir(),
                                "mv_realtext.txt")
    if not os.path.exists(path) or os.path.getsize(path) == 0:
        with gzip.open(_SHARD, "rb") as src, open(path, "wb") as dst:
            dst.write(src.read())
    return path
