"""LM data pipeline: token packing + prefetched, mesh-sharded batches.

The reference's data story is background-thread readers feeding fixed-size
blocks (LR SampleReader ring buffer, WE DataBlock queue — SURVEY §2.7);
this is the same capability for the transformer family: a flat token
stream is packed into fixed [seq+1] windows (static shapes for XLA), and
an iterator yields (tokens, targets) pairs already ``shard_batch``-placed
over the model's mesh axes, with the NEXT batch's host->device transfer
overlapped behind the current step via AsyncBuffer (the ref's
double-buffered prefetch, util/async_buffer.h).
"""

from __future__ import annotations

import functools
from typing import Iterator, Optional, Tuple

import jax
import numpy as np

from multiverso_tpu.utils.async_buffer import AsyncBuffer


def _window(ids: np.ndarray, n: int, seq_len: int) -> np.ndarray:
    """[N, seq+1] overlapping windows with one vectorized view (no
    Python-level per-window slicing)."""
    view = np.lib.stride_tricks.sliding_window_view(
        ids[: n * seq_len + 1], seq_len + 1)
    return np.ascontiguousarray(view[::seq_len]).astype(np.int32)


def pack_tokens(ids: np.ndarray, seq_len: int,
                drop_remainder: bool = True) -> np.ndarray:
    """Pack a flat token stream into [N, seq_len + 1] windows (each row
    holds inputs ``[:-1]`` and next-token targets ``[1:]``). Windows
    overlap by one token so no target is lost at a boundary. With
    ``drop_remainder=False`` use :func:`pack_tokens_padded` instead — it
    returns the target mask that keeps pad positions out of the loss."""
    ids = np.asarray(ids).reshape(-1)
    n = (ids.size - 1) // seq_len
    if not drop_remainder:
        raise ValueError("padding needs a target mask; use "
                         "pack_tokens_padded")
    if n < 1:
        raise ValueError(f"stream of {ids.size} tokens is shorter than one "
                         f"window of {seq_len + 1}")
    return _window(ids, n, seq_len)


def pack_tokens_padded(ids: np.ndarray, seq_len: int, pad_id: int = 0
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Like :func:`pack_tokens` but keeps the ragged tail, zero-padding the
    last window. Returns (windows [N, seq+1], target_mask [N, seq]) —
    feed the mask to ``loss_fn``/``TokenBatches(masks=...)`` so fabricated
    pad targets never count."""
    ids = np.asarray(ids).reshape(-1)
    if ids.size < 2:
        raise ValueError("need at least 2 tokens (one target)")
    n = -(-(ids.size - 1) // seq_len)  # ceil
    pad = n * seq_len + 1 - ids.size
    real_targets = ids.size - 1
    if pad:
        ids = np.concatenate([ids, np.full(pad, pad_id, ids.dtype)])
    windows = _window(ids, n, seq_len)
    mask = (np.arange(n * seq_len) < real_targets).reshape(n, seq_len)
    return windows, mask.astype(np.float32)


class TokenBatches:
    """Iterate (tokens, targets) device batches over an epoch.

    Shuffles windows per epoch, groups them into [batch, seq] pairs, and
    ``shard_batch``-places each pair for ``cfg``'s mesh axes; the next
    batch's placement runs on a background thread while the caller's step
    executes (set ``prefetch=False`` to disable)."""

    def __init__(self, windows: np.ndarray, batch_size: int, cfg,
                 mesh=None, seed: int = 0, prefetch: bool = True,
                 masks: Optional[np.ndarray] = None):
        if windows.ndim != 2:
            raise ValueError("windows must be [N, seq+1] (use pack_tokens)")
        if windows.shape[0] < batch_size:
            raise ValueError(f"{windows.shape[0]} windows < batch_size "
                             f"{batch_size}")
        if masks is not None and masks.shape != (windows.shape[0],
                                                 windows.shape[1] - 1):
            raise ValueError(f"masks shape {masks.shape} != "
                             f"{(windows.shape[0], windows.shape[1] - 1)}")
        self._windows = windows
        self._masks = masks
        self._batch = batch_size
        self._cfg = cfg
        self._mesh = mesh
        self._rng = np.random.default_rng(seed)
        self._prefetch = prefetch

    def __len__(self) -> int:
        return self._windows.shape[0] // self._batch

    def _place(self, idx: np.ndarray):
        from multiverso_tpu.models.transformer import shard_batch
        rows = self._windows[idx]
        out = (shard_batch(rows[:, :-1], self._cfg, self._mesh),
               shard_batch(rows[:, 1:], self._cfg, self._mesh))
        if self._masks is not None:
            # the mask must stay in ORIGINAL order — loss_fn permutes it
            # itself for zigzag — so place it without shard_batch's perm
            mask_cfg = (self._cfg._replace(attn="local")
                        if self._cfg.attn == "zigzag" else self._cfg)
            out += (shard_batch(self._masks[idx], mask_cfg, self._mesh),)
        return out

    def __iter__(self) -> Iterator[Tuple[jax.Array, ...]]:
        """Yields (tokens, targets) pairs, or (tokens, targets, mask)
        triples when the batches carry padding masks."""
        order = self._rng.permutation(self._windows.shape[0])
        nb = len(self)
        batches = (order[i * self._batch: (i + 1) * self._batch]
                   for i in range(nb))
        if not self._prefetch:
            for idx in batches:
                yield self._place(idx)
            return
        it = iter(batches)

        def pull():
            idx = next(it, None)
            return None if idx is None else self._place(idx)

        buf = AsyncBuffer(pull)
        try:
            while True:
                batch = buf.get()  # kicks off the next pull in background
                if batch is None:
                    return
                yield batch
        finally:
            buf.stop()


@functools.lru_cache(maxsize=16)
def _eval_fns(cfg):
    """Jitted loss closures per config (cached, so repeated per-epoch
    evaluation compiles once)."""
    from multiverso_tpu.models import transformer as tfm
    return (jax.jit(lambda p, a, b: tfm.loss_fn(p, a, b, cfg)),
            jax.jit(lambda p, a, b, m: tfm.loss_fn(p, a, b, cfg, mask=m)))


def evaluate_perplexity(params, batches, cfg,
                        loss_fn=None) -> Tuple[float, float]:
    """Mean next-token loss and perplexity over an iterable of
    (tokens, targets[, mask]) batches (e.g. a :class:`TokenBatches` with
    ``prefetch`` on — evaluation overlaps transfer too; masked batches
    keep padding out of the score)."""
    plain, masked = (loss_fn, loss_fn) if loss_fn else _eval_fns(cfg)
    total, count = 0.0, 0
    for batch in batches:
        if len(batch) == 3:
            tok, tgt, m = batch
            total += float(masked(params, tok, tgt, m))
        else:
            tok, tgt = batch
            total += float(plain(params, tok, tgt))
        count += 1
    if count == 0:
        raise ValueError("no batches to evaluate")
    mean = total / count
    return mean, float(np.exp(mean))
