from multiverso_tpu.io.stream import Stream, TextReader, open_stream
from multiverso_tpu.io.sample_reader import SampleReader
from multiverso_tpu.io.lm_data import (TokenBatches, evaluate_perplexity,
                                       pack_tokens, pack_tokens_padded)

__all__ = ["SampleReader", "Stream", "TextReader", "TokenBatches",
           "evaluate_perplexity", "open_stream", "pack_tokens",
           "pack_tokens_padded"]
