from multiverso_tpu.io.stream import Stream, TextReader, open_stream
from multiverso_tpu.io.sample_reader import SampleReader

__all__ = ["Stream", "TextReader", "open_stream", "SampleReader"]
