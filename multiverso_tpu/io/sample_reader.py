"""Background-thread sample reader with a bounded ring buffer.

TPU-native equivalent of the reference LR SampleReader
(ref: Applications/LogisticRegression/src/reader.cpp — a background thread
fills a ring buffer of parsed samples while training consumes them; variants
for text/libsvm, weighted, and binary-sparse formats, plus per-chunk key sets
for sparse pulls).

Formats:
* ``libsvm``:       ``label idx:val idx:val ...`` (indices 0-based here)
* ``dense``:        ``label v0 v1 v2 ...``
* ``weight``:       ``label:weight idx:val ...`` — per-sample importance
  weight pre-scaled into the feature values, so the gradient is weighted
  without touching the objective (ref reader.h:96-114
  WeightedSampleReader::ParseLine, reader.cpp:243-287: values * weight)
* ``weight_dense``: ``label:weight v0 v1 ...`` (the reference's weighted
  reader with sparse=false)
* ``bsparse``:      binary presence-only sparse records — per sample
  ``u64 n, i32 label, f64 weight, u64 keys[n]`` little-endian, every
  present feature's value = weight (ref reader.h:118-146
  BSparseSampleReader, reader.cpp:376-438 ParseSample; layout matches the
  reference's size_t/int/double record so files interoperate)

The reader yields fixed-size minibatches as dense numpy arrays ready for
device_put — batching/padding happens here on the host thread, keeping XLA
shapes static (the TPU analogue of the reference's minibatch assembly). For
sparse objectives it also reports the active-key set per chunk (the
``SparseBlock<bool>`` keys the reference feeds to sparse pulls).
"""

from __future__ import annotations

import queue
import struct
import threading
import time
from typing import (IO, Any, Callable, Iterator, Optional, Sequence, Set,
                    Tuple)

import numpy as np

from multiverso_tpu.io.stream import TextReader, open_stream
from multiverso_tpu.telemetry import profiler as _prof

FORMATS = ("libsvm", "dense", "weight", "weight_dense", "bsparse")

_BS_HEAD = struct.Struct("<qid")   # n, label, weight (size_t, int, double)


def _parse_weight_head(tok: str) -> Tuple[int, float]:
    """``label:weight`` head token (weight optional, default 1)."""
    lab, _, w = tok.partition(":")
    return int(float(lab)), (float(w) if w else 1.0)


def parse_line(line: str, input_dim: int, fmt: str) -> Optional[Tuple[int, np.ndarray]]:
    parts = line.split()
    if not parts:
        return None
    weight = 1.0
    if fmt in ("weight", "weight_dense"):
        label, weight = _parse_weight_head(parts[0])
    else:
        label = int(float(parts[0]))
    x = np.zeros(input_dim, dtype=np.float32)
    if fmt in ("dense", "weight_dense"):
        vals = np.asarray(parts[1:], dtype=np.float32)
        x[: vals.size] = vals[:input_dim]
    else:  # libsvm / weight
        for tok in parts[1:]:
            idx, _, val = tok.partition(":")
            i = int(idx)
            if 0 <= i < input_dim:
                x[i] = float(val)
    if weight != 1.0:
        x *= weight   # ref reader.cpp:258-262 — importance weight folded
    return label, x   # into the values, gradient scales implicitly


def write_bsparse_sample(stream: IO[bytes], label: int,
                         keys: Sequence[int], weight: float = 1.0) -> None:
    """Append one binary-sparse record (the format ``fmt="bsparse"``
    reads; see module docstring for the layout)."""
    keys = np.asarray(keys, np.int64)
    stream.write(_BS_HEAD.pack(keys.size, int(label), float(weight)))
    stream.write(keys.astype("<i8").tobytes())


def _iter_bsparse(uri: str, input_dim: int
                  ) -> Iterator[Tuple[int, np.ndarray]]:
    """Record iterator for the binary presence-only format."""
    with open_stream(uri, "rb") as s:
        while True:
            head = s.read(_BS_HEAD.size)
            if not head:
                return
            if len(head) < _BS_HEAD.size:
                raise ValueError(f"{uri}: truncated bsparse record header")
            n, label, weight = _BS_HEAD.unpack(head)
            # 100M keys/sample (800 MB) is far beyond any real record: a
            # bigger n means a corrupt/misaligned file, and trusting it
            # would attempt the allocation before the short-read check
            if n < 0 or n > 100_000_000:
                raise ValueError(f"{uri}: implausible key count {n} "
                                 "(corrupt or non-bsparse file?)")
            raw = s.read(8 * n)
            if len(raw) < 8 * n:
                raise ValueError(f"{uri}: truncated bsparse key block")
            keys = np.frombuffer(raw, "<i8")
            x = np.zeros(input_dim, np.float32)
            x[keys[(keys >= 0) & (keys < input_dim)]] = weight
            yield label, x


class BlockPrepareQueue:
    """Bounded K-deep ORDERED prefetch queue over a finite work list.

    The WordEmbedding block pipeline's producer side (ISSUE 11): ``fn(item,
    index)`` runs on ``threads`` producer threads for items AHEAD of the
    consumer, at most ``depth`` outstanding (claimed-but-unconsumed), and
    :meth:`next` yields results strictly IN ORDER — so a pure ``fn`` gives
    bit-identical results to calling it inline, regardless of thread
    scheduling. Generalizes this module's single-reader ring (SampleReader)
    to N producers with ordered delivery; the same profiler contract
    applies: each production interval lands as an ``io.produce`` async span
    attached to whichever step it overlapped (``attach="any"``), and the
    consumer's blocked time is the ``io_wait`` phase of ITS step.

    A producer exception is delivered at the corresponding :meth:`next`
    call (order preserved) and ends the queue. ``close()`` releases the
    threads early; they are daemons either way.
    """

    def __init__(self, items: Sequence[Any],
                 fn: Callable[[Any, int], Any],
                 depth: int = 4, threads: int = 2):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._items = items
        self._fn = fn
        self._depth = int(depth)
        self._cond = threading.Condition()
        self._results: dict = {}          # index -> ("ok"|"err", payload)
        self._next_claim = 0              # producer side
        self._next_emit = 0               # consumer side
        self._closed = False
        self._threads = [
            threading.Thread(target=self._produce, daemon=True,
                             name=f"mv-blockprep-{i}")
            for i in range(max(1, min(int(threads), len(items) or 1)))]
        for t in self._threads:
            t.start()

    def _produce(self) -> None:
        n = len(self._items)
        while True:
            with self._cond:
                while (not self._closed and self._next_claim < n
                       and self._next_claim - self._next_emit
                       >= self._depth):
                    self._cond.wait()
                if self._closed or self._next_claim >= n:
                    return
                i = self._next_claim
                self._next_claim += 1
            t0 = time.time()
            try:
                out = ("ok", self._fn(self._items[i], i))
            except BaseException as e:   # noqa: BLE001 — delivered in
                out = ("err", e)         # order at the consumer's next()
            t1 = time.time()
            with self._cond:
                if self._closed:   # closed mid-produce: drop the payload
                    return         # (close() already purged _results)
                self._results[i] = out
                self._cond.notify_all()
            if _prof.enabled():
                _prof.note_async("io.produce", t0, t1, attach="any")

    def next(self) -> Any:
        """The next result in submission order (io_wait-timed when the
        producers are behind). Raises StopIteration past the last item,
        or the producer's exception for THIS index."""
        i = self._next_emit
        if i >= len(self._items):
            raise StopIteration
        with _prof.phase("io_wait"):
            with self._cond:
                while i not in self._results and not self._closed:
                    self._cond.wait()
                if i not in self._results:
                    raise RuntimeError("BlockPrepareQueue closed while "
                                       f"item {i} was pending")
                kind, payload = self._results.pop(i)
                self._next_emit = i + 1
                self._cond.notify_all()
        if kind == "err":
            self.close()
            raise payload
        return payload

    def __iter__(self) -> Iterator[Any]:
        while True:
            try:
                yield self.next()
            except StopIteration:
                return

    def close(self) -> None:
        with self._cond:
            self._closed = True
            # ends the queue for REAL: already-produced later items are
            # dropped, so a post-error/post-close next() deterministically
            # raises instead of racing the producers for whatever they
            # happened to finish first
            self._results.clear()
            self._cond.notify_all()

    def __enter__(self) -> "BlockPrepareQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class SampleReader:
    """Iterate (X, y, keys) minibatches from a sample file.

    ``keys`` is the sorted active-feature-id set of the batch (sparse-pull
    support); for dense format it is None.
    """

    def __init__(self, uri: str, input_dim: int, batch_size: int,
                 fmt: str = "libsvm", capacity: int = 8,
                 loop_epochs: int = 1, drop_remainder: bool = False):
        if fmt not in FORMATS:
            raise ValueError(f"unknown sample format {fmt!r}; "
                             f"known: {FORMATS}")
        self.input_dim = input_dim
        self.batch_size = batch_size
        self.fmt = fmt
        self.drop_remainder = drop_remainder
        self._uri = uri
        self._loop_epochs = loop_epochs
        self._queue: "queue.Queue" = queue.Queue(maxsize=capacity)
        self._thread = threading.Thread(target=self._fill, daemon=True)
        self._error: Optional[BaseException] = None
        self._thread.start()

    @property
    def _dense_like(self) -> bool:
        """Dense formats carry no sparse key set."""
        return self.fmt in ("dense", "weight_dense")

    def _samples(self) -> Iterator[Tuple[int, np.ndarray]]:
        if self.fmt == "bsparse":
            yield from _iter_bsparse(self._uri, self.input_dim)
            return
        reader = TextReader(self._uri)
        try:
            for line in reader:
                parsed = parse_line(line, self.input_dim, self.fmt)
                if parsed is not None:
                    yield parsed
        finally:
            reader.close()

    def _fill(self) -> None:
        try:
            for _ in range(self._loop_epochs):
                xs, ys, keys = [], [], set()
                t_batch0 = time.time()
                for label, x in self._samples():
                    ys.append(label)
                    xs.append(x)
                    if not self._dense_like:
                        keys.update(np.nonzero(x)[0].tolist())
                    if len(xs) == self.batch_size:
                        self._emit(xs, ys, keys, t_batch0)
                        xs, ys, keys = [], [], set()
                        t_batch0 = time.time()
                if xs and not self.drop_remainder:
                    self._emit(xs, ys, keys, t_batch0)
            self._queue.put(None)
        except BaseException as e:
            self._error = e
            self._queue.put(None)

    def _emit(self, xs, ys, keys: Set[int],
              t_batch0: Optional[float] = None) -> None:
        X = np.stack(xs)
        y = np.asarray(ys, dtype=np.int32)
        k = (None if self._dense_like
             else np.asarray(sorted(keys), dtype=np.int64))
        # stamp the interval's end BEFORE the put: a full queue blocks
        # put() on backpressure (the consumer is the bottleneck), and
        # folding that wait into io.produce would name the input
        # pipeline the critical path precisely when the producer is
        # idle — inverting the diagnosis
        t_done = time.time()
        self._queue.put((X, y, k))
        # step profiler: the producer thread holds no step of its own,
        # so its per-batch parse+assemble interval attaches to the
        # process's current step ("any") — which is how input-pipeline
        # work shows up on the timeline of the training step it
        # overlapped (or stalled)
        if t_batch0 is not None and _prof.enabled():
            _prof.note_async("io.produce", t_batch0, t_done,
                             attach="any")

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]]:
        while True:
            # io_wait: time the CONSUMER (the training step's thread)
            # blocked on the producer — the "input pipeline is the
            # critical path" phase, visible per step when profiling
            with _prof.phase("io_wait"):
                item = self._queue.get()
            if item is None:
                if self._error is not None:
                    raise self._error
                return
            yield item
