"""Background-thread sample reader with a bounded ring buffer.

TPU-native equivalent of the reference LR SampleReader
(ref: Applications/LogisticRegression/src/reader.cpp — a background thread
fills a ring buffer of parsed samples while training consumes them; variants
for text/libsvm, weighted, and binary-sparse formats, plus per-chunk key sets
for sparse pulls).

Formats:
* ``libsvm``: ``label idx:val idx:val ...`` (indices 0-based here)
* ``dense``:  ``label v0 v1 v2 ...``

The reader yields fixed-size minibatches as dense numpy arrays ready for
device_put — batching/padding happens here on the host thread, keeping XLA
shapes static (the TPU analogue of the reference's minibatch assembly). For
sparse objectives it also reports the active-key set per chunk (the
``SparseBlock<bool>`` keys the reference feeds to sparse pulls).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional, Set, Tuple

import numpy as np

from multiverso_tpu.io.stream import TextReader


def parse_line(line: str, input_dim: int, fmt: str) -> Optional[Tuple[int, np.ndarray]]:
    parts = line.split()
    if not parts:
        return None
    label = int(float(parts[0]))
    x = np.zeros(input_dim, dtype=np.float32)
    if fmt == "dense":
        vals = np.asarray(parts[1:], dtype=np.float32)
        x[: vals.size] = vals[:input_dim]
    else:  # libsvm
        for tok in parts[1:]:
            idx, _, val = tok.partition(":")
            i = int(idx)
            if 0 <= i < input_dim:
                x[i] = float(val)
    return label, x


class SampleReader:
    """Iterate (X, y, keys) minibatches from a sample file.

    ``keys`` is the sorted active-feature-id set of the batch (sparse-pull
    support); for dense format it is None.
    """

    def __init__(self, uri: str, input_dim: int, batch_size: int,
                 fmt: str = "libsvm", capacity: int = 8,
                 loop_epochs: int = 1, drop_remainder: bool = False):
        self.input_dim = input_dim
        self.batch_size = batch_size
        self.fmt = fmt
        self.drop_remainder = drop_remainder
        self._uri = uri
        self._loop_epochs = loop_epochs
        self._queue: "queue.Queue" = queue.Queue(maxsize=capacity)
        self._thread = threading.Thread(target=self._fill, daemon=True)
        self._error: Optional[BaseException] = None
        self._thread.start()

    def _fill(self) -> None:
        try:
            for _ in range(self._loop_epochs):
                reader = TextReader(self._uri)
                xs, ys, keys = [], [], set()
                for line in reader:
                    parsed = parse_line(line, self.input_dim, self.fmt)
                    if parsed is None:
                        continue
                    label, x = parsed
                    ys.append(label)
                    xs.append(x)
                    if self.fmt != "dense":
                        keys.update(np.nonzero(x)[0].tolist())
                    if len(xs) == self.batch_size:
                        self._emit(xs, ys, keys)
                        xs, ys, keys = [], [], set()
                reader.close()
                if xs and not self.drop_remainder:
                    self._emit(xs, ys, keys)
            self._queue.put(None)
        except BaseException as e:
            self._error = e
            self._queue.put(None)

    def _emit(self, xs, ys, keys: Set[int]) -> None:
        X = np.stack(xs)
        y = np.asarray(ys, dtype=np.int32)
        k = np.asarray(sorted(keys), dtype=np.int64) if self.fmt != "dense" else None
        self._queue.put((X, y, k))

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]]:
        while True:
            item = self._queue.get()
            if item is None:
                if self._error is not None:
                    raise self._error
                return
            yield item
