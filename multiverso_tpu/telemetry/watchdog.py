"""Watchdog: per-request deadlines over the flight recorder's in-flight
table.

The PS plane's failure bound today is ``ps_timeout`` (300 s default —
generous because a cold shard's first apply jit-compiles). A wedged
``_SendWindow`` flush or a silently stopped peer therefore costs minutes
of wall-clock before ANYTHING complains, and when it finally does, the
evidence is one timeout string. The watchdog closes that gap with two
earlier thresholds over the recorder's live in-flight ops:

* older than ``watchdog_slow_ms`` — log ONE structured slow-request
  record (JSON: the op, its age, the recorder's recent event window) per
  offending op, and record EV_SLOW in the ring.
* older than ``watchdog_stuck_s`` — the plane is wedged: dump the full
  ring PLUS per-thread Python stacks (``sys._current_frames`` —
  faulthandler-style, but into the same JSONL artifact postmortem
  merges) and record EV_STUCK. Dumps rate-limit to one per
  ``watchdog_stuck_s`` so a long hang produces a fresh artifact, not a
  disk flood.

The verdict of the last check (``last_verdict()``) is the liveness
summary ``MSG_HEALTH`` serves and ``elastic.Heartbeat`` beacons as
``last_health`` — the bit that lets a supervisor distinguish "dead"
from "alive but stuck". One daemon thread per process, started by the
first PSService (flag ``watchdog``); ``check_once()`` is separable so
tests drive thresholds deterministically.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, Optional

from multiverso_tpu.telemetry import flightrec
from multiverso_tpu.utils import config, log

config.define_bool(
    "watchdog", True,
    "run the PS watchdog thread (per-request slow/stuck deadlines over "
    "the flight recorder; docs/OBSERVABILITY.md). The thread wakes "
    "every watchdog_interval_s and costs nothing between wakeups")
config.define_float(
    "watchdog_slow_ms", 1000.0,
    "in-flight request age (ms) past which the watchdog logs one "
    "structured slow-request record with the recorder's recent window")
config.define_float(
    "watchdog_stuck_s", 30.0,
    "in-flight request age (s) past which the watchdog declares the "
    "plane stuck: full flight-recorder dump + per-thread Python stacks "
    "(rate-limited to one dump per this interval)")
config.define_float(
    "watchdog_interval_s", 0.5,
    "watchdog wakeup period in seconds")


class Watchdog:
    """One per process; see module docstring."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._verdict: Dict[str, Any] = {
            "status": "ok", "oldest_inflight_s": 0.0, "inflight": 0,
            "checked": False}
        # (peer, msg_id) keys already slow-logged — one structured
        # record per offending op, not one per wakeup
        self._reported: set = set()
        # -inf, not 0.0: time.monotonic() is seconds-since-boot on
        # Linux, and a 0.0 sentinel would rate-limit away the FIRST
        # stuck dump on any box wedging within watchdog_stuck_s of boot
        self._last_stuck_dump = float("-inf")

    # ------------------------------------------------------------------ #
    def check_once(self) -> Dict[str, Any]:
        """One deadline sweep; returns (and stores) the verdict."""
        slow_s = config.get_flag("watchdog_slow_ms") / 1e3
        stuck_s = config.get_flag("watchdog_stuck_s")
        snap = flightrec.RECORDER.inflight_snapshot()
        oldest = max((e[2] for e in snap), default=0.0)
        status = "ok"
        if snap and oldest >= stuck_s:
            status = "stuck"
            self._trip_stuck(snap, oldest, stuck_s)
        elif snap and oldest >= slow_s:
            status = "slow"
        if snap:
            self._report_slow(snap, slow_s)
        # memory leak verdicts ride the same sweep (telemetry/memstats):
        # an aged read-epoch pin hoarding retired COW buffers is a wedge
        # the in-flight table cannot see — the byte ledger can. Late
        # import (memstats imports flightrec; the watchdog must stay
        # importable standalone) and fault-isolated like everything
        # else in this loop.
        try:
            from multiverso_tpu.telemetry import memstats as _memstats
            _memstats.LEDGER.check_verdicts()
        except Exception as e:   # noqa: BLE001 — verdicts must never
            log.debug("memstats verdict sweep failed: %s", e)  # kill it
        # live keys only: an op that completed may reuse its msg id much
        # later on a reconnected peer and must be reportable again
        live = {(p, mid) for p, mid, _, _, _ in snap}
        verdict = {"status": status,
                   "oldest_inflight_s": round(oldest, 3),
                   "inflight": len(snap), "checked": True,
                   "ts": round(time.time(), 3)}
        with self._lock:
            self._reported &= live
            self._verdict = verdict
        return dict(verdict)

    def _report_slow(self, snap, slow_s: float) -> None:
        # claim under the lock: check_once's prune (`&= live`) runs
        # under it too, and an unlocked add from a concurrent on-demand
        # check_once could be discarded mid-intersection — the same op
        # would then structured-log twice (off the hot path; cheap)
        with self._lock:
            fresh = [e for e in snap
                     if e[2] >= slow_s
                     and (e[0], e[1]) not in self._reported]
            for e in fresh:
                self._reported.add((e[0], e[1]))
        if not fresh:
            return
        # ONE bounded snapshot per sweep, not per offending op: the
        # copy runs under the recorder's lock — the hot path's lock —
        # so it must touch 10 slots, not the whole 4096-slot ring
        recent = [{"ev": flightrec.EV_NAMES.get(s[2], s[2]),
                   "peer": s[3], "msg_id": s[5],
                   "mono": round(s[1], 3)}
                  for s in flightrec.RECORDER.snapshot(last=10)]
        for p, mid, age, mt, nb in fresh:
            flightrec.record(flightrec.EV_SLOW, peer=p, msg_type=mt,
                             msg_id=mid, nbytes=nb)
            log.error("watchdog: slow request %s", json.dumps({
                "peer": p, "msg_id": mid, "type": mt,
                "age_s": round(age, 3), "nbytes": nb, "recent": recent}))

    def _trip_stuck(self, snap, oldest: float, stuck_s: float) -> None:
        now = time.monotonic()
        with self._lock:
            if now - self._last_stuck_dump < stuck_s:
                return
            self._last_stuck_dump = now
        age, p, mid, mt = flightrec.RECORDER.oldest_inflight() or (
            oldest, -1, -1, 0)
        flightrec.record(flightrec.EV_STUCK, peer=p, msg_type=mt,
                         msg_id=mid)
        path = flightrec.dump_global(
            f"watchdog stuck: oldest in-flight op {age:.1f}s "
            f"(peer {p}, msg {mid})", stacks=True)
        log.error("watchdog: PS plane STUCK — oldest in-flight op "
                  "%.1fs old (peer %d, msg %d, %d in flight); %s",
                  age, p, mid, len(snap),
                  f"dumped {path}" if path else
                  "no flightrec_dir/metrics_dir configured, dump skipped")

    # ------------------------------------------------------------------ #
    def last_verdict(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._verdict)

    def start(self) -> "Watchdog":
        with self._lock:
            if self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._loop, name="mv-watchdog", daemon=True)
                self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(
                max(config.get_flag("watchdog_interval_s"), 0.05)):
            try:
                self.check_once()
            except Exception as e:   # noqa: BLE001 — the watchdog must
                log.error("watchdog check failed: %s", e)  # outlive bugs

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def reset(self) -> None:
        """Test isolation: stop the thread and forget verdicts."""
        self.stop()
        with self._lock:
            self._verdict = {"status": "ok", "oldest_inflight_s": 0.0,
                             "inflight": 0, "checked": False}
            self._reported.clear()
            self._last_stuck_dump = float("-inf")


WATCHDOG = Watchdog()


def ensure_started() -> Optional[Watchdog]:
    """Start the process watchdog if the flag allows (idempotent; the
    first PSService calls this)."""
    if not config.get_flag("watchdog"):
        return None
    return WATCHDOG.start()


def check_once() -> Dict[str, Any]:
    return WATCHDOG.check_once()


def last_verdict() -> Dict[str, Any]:
    return WATCHDOG.last_verdict()


def stop_global() -> None:
    WATCHDOG.stop()


def reset() -> None:
    WATCHDOG.reset()
