"""Tenant attribution plane: who is doing what to the PS fleet.

ROADMAP 5(a): "millions of users" means unequal workloads sharing one
fleet, and until this module every counter, histogram, sketch and
admission bucket in the stack was tenant-blind — a zipf storm from one
tenant was indistinguishable from organic load.  This module is the
merge point for three accounting surfaces that all key on one tenant id:

* **Identity** — ``current()`` resolves the effective tenant for a call:
  the innermost :func:`tenant_scope` override, else the ``tenant_id``
  flag, else ``None`` (the default tenant).  The id rides wire meta
  under ``wire.TENANT_META_KEY`` and — like every modern meta key — is
  unknown to the native C++ server's whitelist, so stamped frames punt
  to the Python plane: one implementation on both wire planes.  Frames
  are stamped ONLY for non-default tenants, so default traffic keeps
  the cached meta bytes and the native fast path untouched.

* **Shard side** — each shard owns a :class:`TenantMeter`: per-tenant
  op/byte counters plus a Space-Saving sketch (reusing
  ``telemetry/hotkeys.py``) for ranking past the exact-entry cap.  The
  default-tenant path is ONE attribute read + ONE dict increment per
  op (benign-race, the same tolerance as the shard's ``_stat_gets``);
  named tenants pay a small lock and cap at ``tenant_track_max`` exact
  entries (overflow folds into ``"~other"``, the sketch keeps ranking).

* **Serve side** — the process-global :data:`LEDGER` records per-
  ``(table, tenant)`` served/shed/deferred counts, a PR-3 latency
  histogram, and served staleness at the pool/replica boundary, and
  runs the NOISY-NEIGHBOR verdict sweep: one tenant's interval traffic
  share crosses ``tenant_storm_share`` while ANOTHER tenant degrades
  (sheds, defers, or serves near its staleness bound) -> one structured
  log + one flightrec event per episode (PR-10 verdict discipline),
  deduped until the condition clears.

``stats_snapshot()`` is the MSG_STATS ``"tenants"`` block; the
aggregator dedupes it per process and sums the shard meters per rank,
``mvtop``/``dump_metrics`` render it, the exporter emits ``mv_tenant_*``
gauges, and ``bench_chaos --scenario noisy_neighbor`` gates on it.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from multiverso_tpu.telemetry import flightrec as _flight
from multiverso_tpu.telemetry import hotkeys as _hotkeys
from multiverso_tpu.telemetry.histogram import Histogram
from multiverso_tpu.utils import config, log

config.define_string(
    "tenant_id", "",
    "Process-default tenant id stamped on PS traffic (wire meta key "
    "'tn'). Empty = the default tenant: frames stay unstamped and keep "
    "the native fast path + cached meta bytes. Per-call overrides via "
    "tenants.tenant_scope() win over this flag.")
config.define_float(
    "tenant_storm_share", 0.6,
    "Noisy-neighbor verdict threshold: a tenant whose share of the "
    "interval's serve traffic crosses this (with >= 2 tenants active) "
    "is a storm candidate; the verdict fires when another tenant "
    "degrades (sheds, defers, or serves near its staleness bound) in "
    "the same interval.")
config.define_float(
    "tenant_infer_qps", 0.0,
    "Default per-(table, tenant) infer admission budget (qps) applied "
    "lazily to NAMED tenants with no explicit set_tenant_limit. 0 = "
    "no per-tenant bucket (the table-wide budget still applies).")
config.define_float(
    "tenant_add_qps", 0.0,
    "Per-(table, tenant) client-side add budget (qps) at the send "
    "window. Over-budget train adds are COUNTED as deferred, never "
    "dropped (writes are sacred); 0 disables the bucket.")
config.define_int(
    "tenant_track_max", 32,
    "Exact per-tenant entries kept per shard meter and per serve-ledger "
    "table; tenants past the cap fold into '~other' (the Space-Saving "
    "sketch still ranks them).")
config.define_float(
    "tenant_stale_frac", 0.9,
    "Fraction of a read's staleness bound at which a tenant's served "
    "age counts as degraded for the noisy-neighbor verdict sweep.")

# the unnamed tenant's display key in every stats block
DEFAULT_TENANT = "default"
# fold-in key once a meter passes tenant_track_max exact entries
OTHER_TENANT = "~other"

_tls = threading.local()


def current() -> Optional[str]:
    """Effective tenant id for this call: innermost :func:`tenant_scope`
    override > ``tenant_id`` flag > ``None`` (default tenant). An
    override of ``""`` explicitly selects the default tenant."""
    tn = getattr(_tls, "tenant", None)
    if tn is not None:
        return tn or None
    tn = config.get_flag("tenant_id")
    return tn or None


@contextlib.contextmanager
def tenant_scope(tenant: Optional[str]) -> Iterator[None]:
    """Per-call override: every PS op issued inside the block is
    attributed (and wire-stamped) as ``tenant``. Nests; ``None``/``""``
    select the default tenant explicitly."""
    prev = getattr(_tls, "tenant", None)
    _tls.tenant = tenant or ""
    try:
        yield
    finally:
        _tls.tenant = prev


def label(tenant: Optional[str]) -> str:
    """Stats-block display key for a resolved tenant id."""
    return tenant if tenant else DEFAULT_TENANT


# ---------------------------------------------------------------------- #
# shard-side meter
# ---------------------------------------------------------------------- #
class TenantMeter:
    """Per-shard per-tenant op/byte counters + Space-Saving ranking.

    The default-tenant path (the overwhelmingly common one) is one
    attribute read and one dict increment — benign-race by design, the
    same tolerance the shard's ``_stat_gets`` documents. Named tenants
    take a lock: they are the minority traffic attribution exists for,
    and exactness there is what the two-tenant oracle test checks.
    """

    __slots__ = ("default", "_named", "_cap", "_sketch", "_lock")

    def __init__(self, track_max: Optional[int] = None,
                 sketch_capacity: int = 64) -> None:
        self.default = {"ops": 0, "add_bytes": 0, "get_bytes": 0}
        self._named: Dict[str, Dict[str, int]] = {}
        self._cap = int(config.get_flag("tenant_track_max")
                        if track_max is None else track_max)
        self._sketch = (_hotkeys.SpaceSaving(sketch_capacity)
                        if sketch_capacity > 0 else None)
        self._lock = threading.Lock()

    def note(self, tenant: Optional[str], ops: int = 1,
             add_bytes: int = 0, get_bytes: int = 0) -> None:
        if not tenant:
            d = self.default
            d["ops"] += ops
            if add_bytes:
                d["add_bytes"] += add_bytes
            if get_bytes:
                d["get_bytes"] += get_bytes
            return
        with self._lock:
            e = self._named.get(tenant)
            if e is None:
                key = (tenant if len(self._named) < self._cap
                       else OTHER_TENANT)
                e = self._named.get(key)
                if e is None:
                    e = self._named[key] = {
                        "ops": 0, "add_bytes": 0, "get_bytes": 0}
            e["ops"] += ops
            e["add_bytes"] += add_bytes
            e["get_bytes"] += get_bytes
        if self._sketch is not None:
            self._sketch.offer_key(tenant, ops)

    def to_dict(self) -> Dict[str, Any]:
        """The shard-stats ``"tenants"`` sub-entry: {tenant: counters},
        plus the ranking sketch once named traffic exists. Empty dict
        when the meter never counted (the shard omits the key)."""
        out: Dict[str, Any] = {}
        d = self.default
        if d["ops"] or d["add_bytes"] or d["get_bytes"]:
            out[DEFAULT_TENANT] = dict(d)
        with self._lock:
            for k, v in self._named.items():
                out[k] = dict(v)
        if out and self._sketch is not None and self._sketch.total:
            out["~sketch"] = self._sketch.to_dict()
        return out


# ---------------------------------------------------------------------- #
# serve-side ledger + verdict engine
# ---------------------------------------------------------------------- #
class TenantLedger:
    """Process-global per-(table, tenant) serve accounting + the
    noisy-neighbor verdict sweep (see module docstring). One instance
    per process (:data:`LEDGER`), shared by every pool/replica."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # table -> tenant -> entry
        self._tables: Dict[str, Dict[str, Dict[str, Any]]] = {}
        # sweep state: (table, tenant) -> (served, shed, deferred)
        self._prev: Dict[tuple, tuple] = {}
        self._shares: Dict[str, float] = {}
        self._episode_open = False
        self._episodes = 0
        self._verdicts: List[Dict[str, Any]] = []

    # ------------------------------------------------------------------ #
    def _entry(self, table: str, tenant: Optional[str]) -> Dict[str, Any]:
        t = self._tables.get(table)
        if t is None:
            t = self._tables[table] = {}
        key = tenant if tenant else DEFAULT_TENANT
        e = t.get(key)
        if e is None:
            if (key != DEFAULT_TENANT
                    and len(t) >= int(config.get_flag("tenant_track_max"))):
                key = OTHER_TENANT
                e = t.get(key)
            if e is None:
                e = t[key] = {"served": 0, "shed": 0, "deferred": 0,
                              "hist": Histogram(), "max_age_s": 0.0,
                              "win_age_frac": 0.0}
        return e

    def note_serve(self, table: str, tenant: Optional[str],
                   ms: Optional[float] = None,
                   age_s: Optional[float] = None,
                   bound_s: Optional[float] = None) -> None:
        """One served read at the pool/replica boundary."""
        with self._lock:
            e = self._entry(table, tenant)
            e["served"] += 1
            if ms is not None:
                e["hist"].observe(ms)
            if age_s is not None:
                if age_s > e["max_age_s"]:
                    e["max_age_s"] = age_s
                if bound_s and bound_s > 0:
                    frac = age_s / bound_s
                    if frac > e["win_age_frac"]:
                        e["win_age_frac"] = frac

    def note_shed(self, table: str, tenant: Optional[str],
                  n: int = 1) -> None:
        """A shed read (admission refused it). One flightrec record per
        shed — sheds are rare by construction (the budget already
        throttled the caller) and each is forensic signal."""
        with self._lock:
            self._entry(table, tenant)["shed"] += n
        _flight.record(_flight.EV_TENANT_SHED,
                       note=f"{table}:{label(tenant)}"[:120])

    def note_deferred(self, table: str, tenant: Optional[str],
                      n: int = 1) -> None:
        """A deferred op: a read that forced a synchronous freshness
        refresh, or an over-budget train add that was counted (never
        dropped) at the send window."""
        with self._lock:
            self._entry(table, tenant)["deferred"] += n

    # ------------------------------------------------------------------ #
    # noisy-neighbor verdict sweep
    # ------------------------------------------------------------------ #
    def sweep(self, now: Optional[float] = None) -> Optional[Dict]:
        """One verdict interval: per-tenant traffic shares from the
        served+shed deltas since the last sweep; fires/clears the
        noisy-neighbor episode (one structured log + one flightrec
        event per episode). Runs on every ``stats_snapshot`` pull —
        the same pull-driven cadence as the memstats gauges."""
        storm_share = float(config.get_flag("tenant_storm_share"))
        stale_frac = float(config.get_flag("tenant_stale_frac"))
        fired: Optional[Dict] = None
        with self._lock:
            d_ops: Dict[str, int] = {}
            degraded: Dict[str, List[str]] = {}
            for table, tens in self._tables.items():
                for tn, e in tens.items():
                    key = (table, tn)
                    ps, pk, pd = self._prev.get(key, (0, 0, 0))
                    ds = e["served"] - ps
                    dk = e["shed"] - pk
                    dd = e["deferred"] - pd
                    self._prev[key] = (e["served"], e["shed"],
                                       e["deferred"])
                    d_ops[tn] = d_ops.get(tn, 0) + ds + dk
                    why = []
                    if dk > 0:
                        why.append("shed")
                    if dd > 0:
                        why.append("deferred")
                    if e["win_age_frac"] >= stale_frac > 0:
                        why.append("stale")
                    e["win_age_frac"] = 0.0
                    if why:
                        degraded.setdefault(tn, []).extend(
                            w for w in why if w not in
                            degraded.get(tn, []))
            total = sum(d_ops.values())
            active = [tn for tn, d in d_ops.items() if d > 0]
            if total > 0:
                self._shares = {tn: round(d / total, 4)
                                for tn, d in d_ops.items()}
            storm = None
            if total > 0 and len(active) >= 2:
                top = max(active, key=lambda tn: d_ops[tn])
                if d_ops[top] / total >= storm_share:
                    storm = top
            victims = sorted(tn for tn in degraded if tn != storm)
            cond = storm is not None and bool(victims)
            if cond and not self._episode_open:
                self._episode_open = True
                self._episodes += 1
                fired = {
                    "kind": "noisy-neighbor",
                    "tenant": storm,
                    "share": round(d_ops[storm] / total, 4),
                    "victims": victims,
                    "why": sorted({w for v in victims
                                   for w in degraded[v]}),
                    "ts": round(time.time() if now is None else now, 3),
                }
                self._verdicts.append(fired)
                del self._verdicts[:-16]
            elif not cond and self._episode_open:
                self._episode_open = False
                log.info("tenants: noisy-neighbor episode cleared")
        if fired is not None:
            _flight.record(
                _flight.EV_TENANT_VERDICT,
                note=(f"noisy-neighbor {fired['tenant']} "
                      f"share={fired['share']:.2f}")[:120])
            log.error("tenants: noisy-neighbor verdict %s",
                      json.dumps(fired))
        return fired

    # ------------------------------------------------------------------ #
    # consumer shapes
    # ------------------------------------------------------------------ #
    def stats_snapshot(self) -> Dict[str, Any]:
        """The MSG_STATS ``"tenants"`` block. Process-global like the
        serving block (the aggregator dedupes by (host, pid)); empty
        dict (block omitted) on processes that never served. Pulling a
        snapshot runs one verdict sweep."""
        self.sweep()
        from multiverso_tpu.serving import admission as _admission
        with self._lock:
            tables: Dict[str, Any] = {}
            for table, tens in self._tables.items():
                tt: Dict[str, Any] = {}
                for tn, e in tens.items():
                    tt[tn] = {
                        "served": e["served"],
                        "shed": e["shed"],
                        "deferred": e["deferred"],
                        "max_age_s": round(e["max_age_s"], 4),
                        "infer": e["hist"].as_dict(),
                    }
                tables[table] = tt
            out: Dict[str, Any] = {}
            if tables:
                out["tables"] = tables
                out["shares"] = dict(self._shares)
                out["episodes"] = self._episodes
                out["active"] = self._episode_open
                if self._verdicts:
                    out["verdict"] = dict(self._verdicts[-1])
        adm = _admission.tenant_stats_all()
        if adm:
            out["admission"] = adm
            out.setdefault("episodes", self._episodes)
            out.setdefault("active", self._episode_open)
        return out

    def episodes(self) -> int:
        with self._lock:
            return self._episodes

    def verdicts(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._verdicts)

    def reset(self) -> None:
        """Test isolation helper (mirrors memstats.LEDGER.reset)."""
        with self._lock:
            self._tables.clear()
            self._prev.clear()
            self._shares.clear()
            self._episode_open = False
            self._episodes = 0
            self._verdicts.clear()


LEDGER = TenantLedger()


def stats_snapshot() -> Dict[str, Any]:
    return LEDGER.stats_snapshot()


def reset() -> None:
    """Test isolation: drop the ledger AND this thread's scope override
    (a test that crashed inside tenant_scope must not re-attribute its
    neighbors' traffic)."""
    LEDGER.reset()
    _tls.tenant = None
