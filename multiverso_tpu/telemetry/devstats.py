"""Device-plane observability: transfers, collectives, mesh-keyed compiles.

Every observability plane built so far (PRs 3/4/6/9/10) measures the
HOST plane — wire latency, step phases, bytes in Python-owned buffers.
The scale-out work (ROADMAP item 1: N-shard topologies on a device
mesh; item 4: the PS-bypassing allreduce plane) is judged by
DEVICE-plane costs this rank could not see: host<->device transfer
bytes, which mesh configuration triggered a recompile, where the live
device bytes sit, and what each collective moved. This module is that
layer — four gauges sharing the flight-recorder's cost discipline
(cheap increments at instrumented sites, everything else pull-only):

* **Transfer chokepoint** — :func:`note_transfer` counts host<->device
  bytes PER DIRECTION (``h2d``/``d2h``). It generalizes the PR-9
  instrumented-site accounting into one funnel: the word-embedding and
  DLRM pipelines, ``sequence_shard``/``shard_params`` device_puts, and
  ``process_sum``'s round trip all report here, and the h2d side still
  feeds the step profiler's per-step ``transfer_bytes`` delta.
* **Mesh-keyed compile events** — a ``jax.monitoring`` duration
  listener (the PR-9 hook, extended) attributes every backend compile
  to the ACTIVE mesh shape: :func:`mesh_scope` (collective spans push
  it automatically) or the Zoo's :func:`set_default_mesh`. A recompile
  now names which mesh configuration triggered it — the signal the
  1->2->4->8 scale harness keys its compile accounting on.
* **Per-device census rollup** — :func:`device_rollup` groups the
  PR-10 ``jax.live_arrays()`` census BY DEVICE (sharded arrays are
  attributed per addressable shard), so "which chip holds the bytes"
  is a stats pull, not a forensic dump.
* **Collective spans** — :func:`collective_span` wraps every
  ``parallel/`` collective entry point: op/bytes/duration land as
  Dashboard monitors (``coll[op]`` timed + ``.calls``/``.bytes``
  counters in the zoo shutdown report), flight-recorder
  ``coll.begin``/``coll.end`` events, a step-profiler async span
  (``attach="any"``), and this module's per-op tally. Durations are
  HOST dispatch+compile wall time — jax dispatch is async, so a
  non-blocking caller's span excludes device execution (same caveat
  as every Dashboard monitor around jitted code).

The rollup rides MSG_STATS as the ``"devices"`` block
(:func:`stats_snapshot`): ``aggregator.merge_cluster`` merges it per
rank with (host, pid)-deduped cluster totals, ``tools/mvtop.py`` grows
a device panel, ``tools/dump_metrics.py`` renders it, and the exporter
emits ``mv_dev_*`` Prometheus gauges. A payload WITHOUT the block (an
older peer in a mixed-version cluster) renders as "-" everywhere — the
block is additive, never required.

**Compile hygiene** (the scale-out gate): :func:`capture_hygiene`
scopes a structured ``warnings`` + jax-logger capture around dryrun
compiles and classifies SPMD remat / sharding-fallback / donation
warnings into a machine-readable report keyed (jitted fn, mesh shape).
``tools/bench_scale.py`` asserts the report CLEAN in-run for the
shipped workload at every mesh shape; :func:`dump_hygiene` writes
``compile-hygiene-rank<r>.json`` for ``tools/mvprof.py --report``.

Cost discipline: the ``devstats`` flag (default ON) gates every
recording site behind one attribute read; counters are one int add
under a lock at per-batch (not per-row) sites; the live-arrays walk
runs only on a stats pull. ``tools/bench_small_add.py`` asserts the
PR-2 0.03-0.06 ms small-add band in-run with the plane live.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import warnings
from typing import Any, Dict, List, Optional

from multiverso_tpu.utils import config

config.define_bool(
    "devstats", True,
    "device-plane observability (telemetry/devstats.py): host<->device "
    "transfer byte counters, per-mesh-shape compile attribution, "
    "collective op spans (Dashboard coll[op] monitors + flightrec "
    "coll.begin/end + profiler async spans), and the per-device "
    "live-arrays rollup in the MSG_STATS 'devices' block. On by "
    "default: one attribute read gates every site; the live-arrays "
    "walk runs only on a stats pull, never on a hot path")

# directions the transfer chokepoint accepts — anything else raises at
# the instrumented site (a typo'd direction must not open a third,
# never-rendered counter)
_DIRECTIONS = ("h2d", "d2h")

# compile events with no mesh scope active (host-plane jits, warmup
# before any mesh exists) key under this label
_NO_MESH = "unmeshed"


# ---------------------------------------------------------------------- #
# mesh labels
# ---------------------------------------------------------------------- #
def mesh_label(mesh: Any) -> str:
    """Canonical label for a mesh configuration: ``"{'mv': 4}"`` for a
    ``jax.sharding.Mesh``; dicts/strings pass through (bench harnesses
    and tests hand shapes around without building a Mesh)."""
    if mesh is None:
        return _NO_MESH
    if isinstance(mesh, str):
        return mesh
    if isinstance(mesh, dict):
        return str(dict(mesh))
    names = getattr(mesh, "axis_names", None)
    devs = getattr(mesh, "devices", None)
    if names is not None and devs is not None:
        return str(dict(zip(names, devs.shape)))
    return str(mesh)


# ---------------------------------------------------------------------- #
# compile-hygiene classification (pure; oracle-tested)
# ---------------------------------------------------------------------- #
# category -> lowercase substrings; first hit wins, in order — remat and
# sharding fallbacks are the SPMD warnings the scale harness gates on,
# donation is the PR-9 signal lifted to the same report
_HYGIENE_PATTERNS = (
    ("remat", ("remat", "rematerial")),
    ("sharding-fallback", ("could not infer sharding",
                           "falling back to replicat",
                           "fully replicated",
                           "sharding propagation",
                           "resharding",
                           "spmd partition")),
    ("donation", ("donated buffers were not usable",)),
    ("spmd", ("spmd",)),
)


def classify_compile_warning(message: str) -> Optional[str]:
    """SPMD-hygiene category for one warning/log message, or None for
    noise (deprecations, user warnings) that is NOT a compile-hygiene
    finding. Substring match, case-insensitive — the exact wordings
    move across jax/XLA versions, the vocabulary does not."""
    low = str(message).lower()
    for cat, needles in _HYGIENE_PATTERNS:
        for n in needles:
            if n in low:
                return cat
    return None


class _LogTap(logging.Handler):
    """Captures jax-logger records during a hygiene scope (XLA routes
    some SPMD diagnostics through logging, not warnings)."""

    def __init__(self) -> None:
        super().__init__(level=logging.WARNING)
        self.messages: List[str] = []

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self.messages.append(record.getMessage())
        except Exception:   # noqa: BLE001 — a bad log record must not
            pass            # fail the compile it decorates


# ---------------------------------------------------------------------- #
# device census rollup (pull-only; injectable for tests)
# ---------------------------------------------------------------------- #
def device_rollup(arrays: Optional[List[Any]] = None
                  ) -> Optional[Dict[str, Dict[str, int]]]:
    """Live JAX buffers grouped BY DEVICE: ``{device: {"bytes",
    "arrays"}}``. Sharded arrays are attributed per addressable shard
    (each device is charged exactly the bytes it holds); ``arrays``
    injects a fixture list so the grouping is testable without a live
    backend. None when JAX is unavailable; {} when nothing is live."""
    if arrays is None:
        try:
            import jax
            arrays = jax.live_arrays()
        except Exception:   # noqa: BLE001 — census is best-effort
            return None
    per: Dict[str, List[int]] = {}
    for a in arrays:
        try:
            shards = getattr(a, "addressable_shards", None)
            if shards:
                for s in shards:
                    g = per.setdefault(str(s.device), [0, 0])
                    g[0] += int(s.data.nbytes)
                    g[1] += 1
            else:
                dev = ",".join(sorted(str(d) for d in a.devices()))
                g = per.setdefault(dev, [0, 0])
                g[0] += int(a.nbytes)
                g[1] += 1
        except Exception:   # noqa: BLE001 — a buffer donated/deleted
            continue        # mid-walk must not fail the rollup
    return {d: {"bytes": b, "arrays": n}
            for d, (b, n) in sorted(per.items())}


# ---------------------------------------------------------------------- #
# the span / scope contexts
# ---------------------------------------------------------------------- #
class _NullCtx:
    """Shared no-op context — the flag-off path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullCtx()


class _MeshScope:
    __slots__ = ("_ds", "_label")

    def __init__(self, ds: "DevStats", label: str):
        self._ds = ds
        self._label = label

    def __enter__(self):
        stack = getattr(self._ds._tls, "mesh_stack", None)
        if stack is None:
            stack = self._ds._tls.mesh_stack = []
        stack.append(self._label)
        return self._label

    def __exit__(self, *exc):
        try:
            self._ds._tls.mesh_stack.pop()
        except (AttributeError, IndexError):
            pass
        return False


class _CollSpan:
    """One collective op's span: Dashboard + flightrec + profiler +
    the per-op tally, and a mesh scope so a compile triggered inside
    is keyed to the op's mesh."""

    __slots__ = ("_ds", "_op", "_nbytes", "_scope", "_t0")

    def __init__(self, ds: "DevStats", op: str, nbytes: int,
                 label: Optional[str]):
        self._ds = ds
        self._op = op
        self._nbytes = int(nbytes)
        self._scope = (_MeshScope(ds, label) if label is not None
                       else None)

    def __enter__(self):
        from multiverso_tpu.telemetry import flightrec as _flight
        if self._scope is not None:
            self._scope.__enter__()
        self._t0 = time.time()
        _flight.record(_flight.EV_COLL_BEGIN, nbytes=self._nbytes,
                       note=f"coll.{self._op}")
        return self

    def __exit__(self, *exc):
        from multiverso_tpu.telemetry import flightrec as _flight
        from multiverso_tpu.telemetry import profiler as _profiler
        from multiverso_tpu.utils.dashboard import Dashboard
        t1 = time.time()
        if self._scope is not None:
            self._scope.__exit__()
        ms = (t1 - self._t0) * 1e3
        with self._ds._lock:
            d = self._ds._coll.setdefault(
                self._op, {"calls": 0, "bytes": 0, "ms": 0.0})
            d["calls"] += 1
            d["bytes"] += self._nbytes
            d["ms"] = round(d["ms"] + ms, 4)
        Dashboard.get(f"coll[{self._op}]").observe_ms(ms)
        Dashboard.get(f"coll[{self._op}].calls").incr()
        Dashboard.get(f"coll[{self._op}].bytes").incr(self._nbytes)
        _flight.record(_flight.EV_COLL_END, nbytes=self._nbytes,
                       note=f"coll.{self._op}")
        # the wire-hiding question for collectives is the same as for
        # PS round-trips: attach to whatever step is open, any thread
        _profiler.note_async(f"coll.{self._op}", self._t0, t1,
                             attach="any")
        return False


# ---------------------------------------------------------------------- #
# the process-global gauge set
# ---------------------------------------------------------------------- #
class DevStats:
    """One per process (like the FlightRecorder/StepProfiler);
    in-process multi-rank worlds share it — the same documented
    collapse, deduped by (host, pid) in the cluster merge."""

    def __init__(self) -> None:
        self.enabled = True       # plain attribute: THE site gate
        self.rank = 0
        self._rank_pinned = False
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._default_mesh: Optional[str] = None
        # direction -> [ops, bytes]
        self._transfers: Dict[str, List[int]] = {
            d: [0, 0] for d in _DIRECTIONS}
        # op -> {"calls", "bytes", "ms"}
        self._coll: Dict[str, Dict[str, Any]] = {}
        # mesh label -> {"compiles", "compile_s"}
        self._compiles: Dict[str, Dict[str, Any]] = {}
        self._listener_installed = False
        # hygiene report: entries + per-scope check log
        self._hygiene: List[Dict[str, Any]] = []
        self._hygiene_checked: List[Dict[str, Any]] = []

    # ------------------------------------------------------------------ #
    def configure(self, rank: Optional[int] = None) -> None:
        """Adopt the ``devstats`` flag (PSService init / Zoo.start);
        idempotent, first caller's rank sticks."""
        if rank is not None and not self._rank_pinned:
            self.rank = int(rank)
            self._rank_pinned = True
        self.enabled = bool(config.get_flag("devstats"))
        if self.enabled:
            self._install_listener()

    def _install_listener(self) -> None:
        with self._lock:
            if self._listener_installed:
                return
            self._listener_installed = True
        try:
            import jax.monitoring as _jm
            _jm.register_event_duration_secs_listener(self._on_duration)
        except Exception:   # noqa: BLE001 — device telemetry must
            pass            # degrade, not break, on exotic builds

    def _on_duration(self, name: str, dur: float, **kw) -> None:
        # same event the PR-9 profiler counts globally; here each
        # compile is ADDITIONALLY keyed to the active mesh shape
        if not name.endswith("backend_compile_duration") \
                or not self.enabled:
            return
        label = self._mesh_label()
        with self._lock:
            d = self._compiles.setdefault(
                label, {"compiles": 0, "compile_s": 0.0})
            d["compiles"] += 1
            d["compile_s"] = round(d["compile_s"] + float(dur), 6)

    # ------------------------------------------------------------------ #
    # mesh context
    # ------------------------------------------------------------------ #
    def _mesh_label(self) -> str:
        stack = getattr(self._tls, "mesh_stack", None)
        if stack:
            return stack[-1]
        return self._default_mesh or _NO_MESH

    def mesh_scope(self, mesh: Any):
        """Key compiles fired inside this scope (on this thread) to
        ``mesh``'s shape. Collective spans push one automatically."""
        if not self.enabled:
            return _NULL
        return _MeshScope(self, mesh_label(mesh))

    def set_default_mesh(self, mesh: Any) -> None:
        """Process-default mesh label (Zoo.start's adopted mesh) for
        compiles with no explicit scope on their thread."""
        self._default_mesh = mesh_label(mesh) if mesh is not None else None

    # ------------------------------------------------------------------ #
    # recording sites
    # ------------------------------------------------------------------ #
    def note_transfer(self, nbytes: int, direction: str = "h2d") -> None:
        """THE host<->device transfer chokepoint. ``h2d`` additionally
        feeds the step profiler's per-step transfer delta (the PR-9
        counter this generalizes)."""
        if direction not in _DIRECTIONS:
            raise ValueError(f"direction {direction!r}: expected one of "
                             f"{_DIRECTIONS}")
        if self.enabled:
            with self._lock:
                g = self._transfers[direction]
                g[0] += 1
                g[1] += int(nbytes)
        if direction == "h2d":
            from multiverso_tpu.telemetry import profiler as _profiler
            _profiler.note_transfer(int(nbytes))

    def collective_span(self, op: str, nbytes: int, mesh: Any = None):
        """Span context for one collective call — see module
        docstring. No-op (shared context, no allocation) when the
        ``devstats`` flag is off."""
        if not self.enabled:
            return _NULL
        return _CollSpan(self, op, nbytes,
                         mesh_label(mesh) if mesh is not None else None)

    # ------------------------------------------------------------------ #
    # compile hygiene
    # ------------------------------------------------------------------ #
    def capture_hygiene(self, fn: str, mesh: Any = None):
        """Scope a dryrun compile: captured ``warnings`` + jax-logger
        messages are classified (:func:`classify_compile_warning`) and
        classified hits land in the report keyed (``fn``, mesh shape).
        Returns the context manager; the report accumulates across
        scopes until :meth:`reset`."""
        return _HygieneScope(self, fn,
                             mesh_label(mesh) if mesh is not None
                             else self._mesh_label())

    def _hygiene_commit(self, fn: str, label: str,
                        messages: List[str]) -> List[Dict[str, Any]]:
        entries = []
        for m in messages:
            cat = classify_compile_warning(m)
            if cat:
                entries.append({"fn": fn, "mesh": label,
                                "category": cat,
                                "message": str(m)[:240]})
        with self._lock:
            self._hygiene_checked.append(
                {"fn": fn, "mesh": label, "captured": len(messages),
                 "findings": len(entries)})
            self._hygiene.extend(entries)
        return entries

    def hygiene_report(self) -> Dict[str, Any]:
        """The machine-readable compile-hygiene report: every scoped
        dryrun checked, every classified finding, and the headline
        ``clean`` verdict ``bench_scale`` asserts in-run."""
        with self._lock:
            return {"clean": not self._hygiene,
                    "checked": list(self._hygiene_checked),
                    "findings": list(self._hygiene)}

    def dump_hygiene(self, directory: str,
                     rank: Optional[int] = None) -> str:
        """Write ``compile-hygiene-rank<r>.json`` (atomic replace) for
        ``tools/mvprof.py --report``; returns the path."""
        r = self.rank if rank is None else rank
        rep = self.hygiene_report()
        rep["rank"] = r
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, f"compile-hygiene-rank{r}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rep, f, indent=1)
        os.replace(tmp, path)
        return path

    # ------------------------------------------------------------------ #
    # reads
    # ------------------------------------------------------------------ #
    def stats_snapshot(self) -> Optional[Dict[str, Any]]:
        """The MSG_STATS ``"devices"`` block: per-direction transfer
        counters, per-op collective tallies, per-mesh-shape compile
        events, and the per-device live-buffer rollup. None when the
        flag is off AND when there is nothing to report (no activity,
        no live buffers) — older-peer payloads simply lack the block,
        and every renderer degrades to "-"."""
        if not self.enabled:
            return None
        with self._lock:
            transfers = {d: {"ops": g[0], "bytes": g[1]}
                         for d, g in self._transfers.items() if g[0]}
            colls = {op: dict(d) for op, d in self._coll.items()}
            compiles = {k: dict(v) for k, v in self._compiles.items()}
            findings = len(self._hygiene)
        per_device = device_rollup()
        # findings count as activity: a rank whose compiles all hit the
        # persistent cache can still carry a DIRTY hygiene report, and
        # omitting the block would keep mvtop's HYGIENE FINDINGS header
        # and mv_dev_hygiene_findings dark exactly when they matter
        if not (transfers or colls or compiles or per_device
                or findings):
            return None
        out: Dict[str, Any] = {"transfers": transfers,
                               "collectives": colls,
                               "compiles_by_mesh": compiles}
        if per_device:
            out["per_device"] = per_device
        if findings:
            out["hygiene_findings"] = findings
        return out

    def reset(self) -> None:
        """Test isolation: drop counters/report and unpin; the jax
        listener stays installed (idempotent, costs one substring
        check per compile) and re-reads ``self.enabled``."""
        with self._lock:
            self._transfers = {d: [0, 0] for d in _DIRECTIONS}
            self._coll.clear()
            self._compiles.clear()
            self._hygiene.clear()
            self._hygiene_checked.clear()
            self._rank_pinned = False
            self.rank = 0
            self._default_mesh = None
        self._tls = threading.local()
        self.enabled = True


class _HygieneScope:
    __slots__ = ("_ds", "_fn", "_label", "_wctx", "_caught", "_tap",
                 "_loggers", "_mesh_scope", "entries")

    def __init__(self, ds: DevStats, fn: str, label: str):
        self._ds = ds
        self._fn = fn
        self._label = label
        self.entries: List[Dict[str, Any]] = []

    def __enter__(self):
        self._wctx = warnings.catch_warnings(record=True)
        self._caught = self._wctx.__enter__()
        warnings.simplefilter("always")
        self._tap = _LogTap()
        # ONE tap on the root "jax" logger: every jax._src.* record
        # reaches it via logger propagation, and a second handler on
        # "jax._src" double-counted each SPMD diagnostic in the report
        self._loggers = [logging.getLogger("jax")]
        for lg in self._loggers:
            lg.addHandler(self._tap)
        self._mesh_scope = _MeshScope(self._ds, self._label)
        self._mesh_scope.__enter__()
        return self

    def __exit__(self, *exc):
        self._mesh_scope.__exit__()
        for lg in self._loggers:
            lg.removeHandler(self._tap)
        messages = [str(w.message) for w in self._caught]
        self._wctx.__exit__(*exc)
        messages += self._tap.messages
        self.entries = self._ds._hygiene_commit(
            self._fn, self._label, messages)
        return False


DEVSTATS = DevStats()


# module-level wrappers (the call-site idiom, like telemetry.profiler)
def enabled() -> bool:
    return DEVSTATS.enabled


def configure(rank: Optional[int] = None) -> None:
    DEVSTATS.configure(rank)


def note_transfer(nbytes: int, direction: str = "h2d") -> None:
    DEVSTATS.note_transfer(nbytes, direction)


def collective_span(op: str, nbytes: int, mesh: Any = None):
    return DEVSTATS.collective_span(op, nbytes, mesh=mesh)


def mesh_scope(mesh: Any):
    return DEVSTATS.mesh_scope(mesh)


def set_default_mesh(mesh: Any) -> None:
    DEVSTATS.set_default_mesh(mesh)


def capture_hygiene(fn: str, mesh: Any = None):
    return DEVSTATS.capture_hygiene(fn, mesh=mesh)


def hygiene_report() -> Dict[str, Any]:
    return DEVSTATS.hygiene_report()


def dump_hygiene(directory: str, rank: Optional[int] = None) -> str:
    return DEVSTATS.dump_hygiene(directory, rank=rank)


def stats_snapshot() -> Optional[Dict[str, Any]]:
    return DEVSTATS.stats_snapshot()


def reset() -> None:
    DEVSTATS.reset()
