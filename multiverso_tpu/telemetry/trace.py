"""Wire-correlated trace spans for the async PS plane.

One logical op (say a windowed ``add_rows_async``) crosses four threads
and two processes: caller enqueue -> window flusher -> peer socket ->
shard apply wave. A per-request **trace ID** minted at the client rides
the frame meta (``ps/wire.TRACE_META_KEY``, and each MSG_BATCH inner
frame's own meta), so spans recorded independently on the client
(enqueue, window flush, ack) and on the owning shard (serve, wave apply)
stitch into one causal chain by ID.

Spans are Chrome ``trace_event`` complete events (``"ph": "X"``) with
``ts``/``dur`` in microseconds of ``time.time()`` — an absolute clock, so
events from every rank of a single-host run land on one Perfetto
timeline (``pid`` = PS rank, ``tid`` = OS thread). Files are JSONL (one
event per line, append-friendly across crashes);
``tools/dump_metrics.py to-perfetto`` wraps them into the
``{"traceEvents": [...]}`` envelope viewers expect (``python tools/dump_metrics.py to-perfetto in.jsonl out.json``),
and they sit next to the XLA traces from ``utils/profiling.py`` for
side-by-side timelines.

Cost discipline: everything is OFF unless the ``trace_ids`` flag is set.
The hot-path check is one module function returning a plain bool
attribute — no flag-registry lock, no allocation. Natively-served ops
(zero-Python C++ fast path) are not traced by design: the punt path
(MSG_BATCH, compressed wires, MSG_STATS) and the pure-Python plane are.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional

from multiverso_tpu.utils import config

config.define_bool(
    "trace_ids", False,
    "mint per-request trace IDs on async-PS client ops, carry them in "
    "frame meta, and record trace_event spans on both endpoints "
    "(telemetry/trace.py). Off by default: tracing must cost nothing "
    "when unused. Spans dump to metrics_dir as trace-rank<r>.jsonl")

# bounded span buffer: a forgotten always-on tracer must cap memory, not
# OOM a training run; 200k events is hours of windowed PS traffic
_MAX_EVENTS = 200_000


class Tracer:
    """Process-global span recorder (one per process, like Dashboard)."""

    def __init__(self) -> None:
        self.enabled = False     # plain attribute: the hot-path gate
        self.rank = 0
        self._rank_pinned = False
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=_MAX_EVENTS)
        self._next_id = 0

    # ------------------------------------------------------------------ #
    def configure(self, rank: Optional[int] = None) -> None:
        """Adopt the ``trace_ids`` flag (called from PSService init and
        Zoo.start — the points where flags are settled); idempotent.
        The FIRST caller's rank sticks: a process holding several
        PSContexts (bench workers, test fixtures) must not have the
        last-constructed rank clobber the pid/ID-space of spans already
        attributed to the first — in-process multi-rank spans then all
        carry the first rank, a known (and documented) collapse."""
        if rank is not None and not self._rank_pinned:
            self.rank = int(rank)
            self._rank_pinned = True
        self.enabled = bool(config.get_flag("trace_ids"))

    def new_id(self) -> int:
        """Mint a trace ID unique across processes: the pinned rank in
        the high bits, a process-local counter below (fits JSON's
        exact-int range). Several in-process ranks share one tracer and
        therefore one ID space — still unique, attributed to the first
        rank (see :meth:`configure`)."""
        with self._lock:
            self._next_id += 1
            n = self._next_id
        return ((self.rank & 0xFFFF) << 32) | (n & 0xFFFFFFFF)

    # ------------------------------------------------------------------ #
    def add_span(self, name: str, t0: float, t1: float,
                 trace: Optional[int] = None, cat: str = "ps",
                 args: Optional[Dict] = None) -> None:
        """Record a complete span; ``t0``/``t1`` are ``time.time()``
        seconds. No-op when disabled (callers usually pre-check
        :func:`enabled` to skip even the clock reads)."""
        if not self.enabled:
            return
        a = dict(args) if args else {}
        if trace is not None:
            a["trace"] = trace
        ev = {
            "name": name, "cat": cat, "ph": "X",
            "ts": int(t0 * 1e6), "dur": max(int((t1 - t0) * 1e6), 0),
            "pid": self.rank, "tid": threading.get_ident() & 0x7FFFFFFF,
            "args": a,
        }
        # append under the lock: dump()'s snapshot-then-clear would
        # otherwise drop a span landing between its two steps
        with self._lock:
            self._events.append(ev)

    @contextmanager
    def span(self, name: str, trace: Optional[int] = None,
             cat: str = "ps", **args) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        t0 = time.time()
        try:
            yield
        finally:
            self.add_span(name, t0, time.time(), trace=trace, cat=cat,
                          args=args or None)

    # ------------------------------------------------------------------ #
    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._events)

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._next_id = 0
        self._rank_pinned = False

    def dump(self, path: str, append: bool = True) -> int:
        """Write buffered spans as JSONL; returns the event count. The
        buffer drains (a second dump appends only NEW spans), so the
        periodic exporter can stream without duplicating. The file write
        stays under the lock: two concurrent dumps to the same path
        (exporter tick racing a context-close flush) must not interleave
        their lines mid-record."""
        with self._lock:
            events, n = list(self._events), len(self._events)
            self._events.clear()
            if not events:
                return 0
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(path, "a" if append else "w") as f:
                for e in events:
                    f.write(json.dumps(e) + "\n")
        return n


TRACER = Tracer()


def enabled() -> bool:
    """THE hot-path gate (attribute read, no locks)."""
    return TRACER.enabled


def configure(rank: Optional[int] = None) -> None:
    TRACER.configure(rank)


def new_id() -> int:
    return TRACER.new_id()


def add_span(name: str, t0: float, t1: float, trace: Optional[int] = None,
             cat: str = "ps", args: Optional[Dict] = None) -> None:
    TRACER.add_span(name, t0, t1, trace=trace, cat=cat, args=args)


def span(name: str, trace: Optional[int] = None, cat: str = "ps", **args):
    return TRACER.span(name, trace=trace, cat=cat, **args)


def trace_path(directory: str, rank: Optional[int] = None) -> str:
    """Canonical per-rank trace file path under a metrics dir."""
    r = TRACER.rank if rank is None else rank
    return os.path.join(directory, f"trace-rank{r}.jsonl")


def dump_to(directory: str) -> int:
    """Dump buffered spans to the canonical per-rank file (no-op and 0
    when tracing never recorded anything)."""
    return TRACER.dump(trace_path(directory))
