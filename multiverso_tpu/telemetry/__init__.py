"""Telemetry plane: histogram metrics, wire-correlated trace spans, and
the periodic metrics exporter.

The reference's observability story was a count/total-ms ``Monitor``
registry printed at shutdown (ref include/multiverso/dashboard.h:16-73);
``utils/dashboard.py`` keeps that surface for parity but its Monitors now
carry a fixed-bucket log-scale latency histogram from this package, so
the shutdown report (and any exporter) sees p50/p90/p99/max — tail
regressions on the batched, compressed PS plane do not hide behind a
stable mean.

Nine cooperating pieces:

* :mod:`~multiverso_tpu.telemetry.histogram` — the lock-free (caller-
  synchronized) log2-bucket histogram every Monitor embeds.
* :mod:`~multiverso_tpu.telemetry.trace` — per-request trace IDs carried
  in PS frame meta (``ps/wire.TRACE_META_KEY``) and ``trace_event``-format
  spans recorded on both endpoints, dumped as JSONL for Perfetto
  (``tools/dump_metrics.py to-perfetto`` wraps them for the viewer)
  alongside the XLA traces from ``utils/profiling.py``.
* :mod:`~multiverso_tpu.telemetry.exporter` — flag-gated background
  thread (``metrics_interval_s`` / ``metrics_dir``) dumping Dashboard +
  shard snapshots as JSONL and Prometheus-style text.
* :mod:`~multiverso_tpu.telemetry.flightrec` — the ALWAYS-ON black box:
  a fixed-slot ring of the last N wire events / state transitions plus
  the live in-flight request table, dumped atomically as JSONL at fault
  time (fatal log, SIGTERM/SIGABRT, peer death, watchdog trip,
  Zoo.stop); ``tools/postmortem.py`` merges per-rank dumps.
* :mod:`~multiverso_tpu.telemetry.watchdog` — per-request slow/stuck
  deadlines over the recorder's in-flight table; its verdict feeds the
  ``MSG_HEALTH`` RPC and ``elastic.Heartbeat`` beacons.
* :mod:`~multiverso_tpu.telemetry.hotkeys` — the always-on, bounded-
  memory Space-Saving heavy-hitter sketch each shard keeps over its
  served row ids; feeds ``stats()["hotkeys"]`` and the cluster top-K +
  cache-hit-if-cached curve.
* :mod:`~multiverso_tpu.telemetry.memstats` — the ALWAYS-ON byte
  ledger: every owning component (shard, send window, table, replica,
  checkpointer) registers pull-only memory gauges; a flag-gated
  sampler adds host RSS + a ``jax.live_arrays()`` device census, leak
  verdicts (epoch-hoard, retention-leak, rss-creep) ride the watchdog
  sweep, and every flight-recorder dump carries the ledger + sample
  history for OOM forensics (docs/OBSERVABILITY.md "Memory view").
* :mod:`~multiverso_tpu.telemetry.devstats` — the DEVICE plane:
  host<->device transfer byte counters (one chokepoint, per
  direction), per-mesh-shape compile attribution off the
  ``jax.monitoring`` hook, collective op spans (every
  ``parallel/collectives.py`` entry lands Dashboard ``coll[op]``
  monitors, flightrec ``coll.begin``/``coll.end`` events, and a
  step-profiler async span), the per-device ``jax.live_arrays()``
  rollup riding MSG_STATS as the ``"devices"`` block, and the SPMD
  compile-hygiene capture ``tools/bench_scale.py`` asserts clean
  (docs/OBSERVABILITY.md "Device view & scale curves").
* :mod:`~multiverso_tpu.telemetry.aggregator` — the controller-side
  cluster plane: flag-gated (``stats_poll_interval_s``) polling of
  every rank's MSG_STATS + MSG_HEALTH over one-shot probe connections,
  exact histogram merge, shard-skew + rate derivation, and the rolling
  ``cluster.jsonl``/``cluster.prom`` series ``tools/mvtop.py`` renders
  live.

See docs/OBSERVABILITY.md for the end-to-end story (including the
MSG_STATS / MSG_HEALTH RPCs in ``ps/service.py``).
"""

from multiverso_tpu.telemetry.histogram import Histogram  # noqa: F401
