"""Per-step critical-path profiler: compute vs wire vs stall.

PR 3 (trace spans) shows WHAT each side of the async plane did; PR 6
(cluster rates) shows HOW MUCH; neither shows the *overlap* — whether a
PS round-trip actually hid under compute or silently became the critical
path. ROADMAP item 2's headline fact (``we.prepare`` at 118 ms/block now
costs more than ``we.block`` at 78 ms) had to be inferred by hand from
monitor averages. This module makes that a first-class, per-step,
per-rank measurement.

The model: the harness/app brackets each training **step**
(:func:`step`) and marks **phases** inside it (:func:`phase` —
``prepare``, ``compute``, ``ps_wait``, ``io_wait``, ...; phases nest,
and nested time is attributed to the innermost mark). In-flight PS ops
are **async spans** (:func:`async_begin`/:meth:`AsyncSpan.end`, or the
retroactive :func:`note_async`): intervals that may start on the step's
thread and end on a peer recv thread. At step exit the profiler
computes — with interval-union math, never sum-of-averages:

* **wall** — step exit minus step entry;
* **per-phase exclusive time** — each phase's own interval minus its
  nested children (per-thread stack accounting, so a ``ps_wait`` inside
  ``compute`` debits compute);
* **attributed fraction** — ``|union(all phase + async intervals)| /
  wall`` (the WE bench asserts >= 0.9 in-run);
* **overlap credit** — per async span, ``|span ∩ union(phase
  intervals)|``: wire time that ran under marked host work and
  therefore did NOT extend the critical path;
* **stall fraction** — ``(wall - |union(everything)|) / wall``: wall
  time no instrument claims — scheduler bubbles, GIL waits, unmarked
  work.

A JAX-side counter hook (:func:`jax_counters`) samples, at step
boundaries, jit compile counts + compile seconds (via ``jax.monitoring``
duration listeners), per-watched-function retrace counts
(``watch_jit`` — compile-cache size deltas, the per-function
attribution the global listener cannot give), donation-rejection counts
(a ``warnings`` hook on jax's "Some donated buffers were not usable"),
and host->device transfer bytes fed by instrumented sites
(:func:`note_transfer` — an accounting of the marked pipelines, not an
XLA hook). Deltas are attributed to the step that triggered them, so a
silent mid-run recompile names its step.

Cost discipline: everything is OFF unless the ``step_profile`` flag is
set — the hot-path gate is one attribute read, :func:`step`/
:func:`phase` return a shared null context (no allocation), and
``tools/bench_small_add.py``'s in-run 0.03-0.06 ms p50 band holds with
the flag at its default. Step records are JSON-safe dicts in a bounded
drain-on-dump buffer; the exporter appends them to
``profile-rank<r>.jsonl`` under ``metrics_dir`` (the same lifecycle as
trace spans) and ``tools/mvprof.py`` merges them with PR-3 trace files
into a per-step critical-path report and a Perfetto timeline.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from multiverso_tpu.utils import config

config.define_bool(
    "step_profile", False,
    "per-step critical-path profiler (telemetry/profiler.py): apps "
    "bracket steps and mark prepare/compute/ps_wait/io_wait phases + "
    "async PS spans; records per-step wall, per-phase exclusive time, "
    "overlap credit and stall fraction (interval-union math) plus jit "
    "compile/retrace counters sampled at step boundaries. Off by "
    "default: one attribute read on the hot path. Records dump to "
    "metrics_dir as profile-rank<r>.jsonl; tools/mvprof.py reports")

# bounded record buffer: a forgotten always-on profiler must cap memory
# (same rule as the tracer); 4096 steps is hours of block-scale training
_MAX_RECORDS = 4096
# per-step interval detail cap: mvprof's timeline needs the raw spans,
# but a step that marks thousands of phases (a tight io_wait loop) must
# not grow its record without bound — past the cap only the aggregate
# numbers keep accumulating and the record says how many were dropped
_MAX_SPANS_PER_STEP = 512


# ---------------------------------------------------------------------- #
# interval math (pure; tests run these against brute-force oracles)
# ---------------------------------------------------------------------- #
def union_intervals(intervals: Sequence[Tuple[float, float]]
                    ) -> List[Tuple[float, float]]:
    """Merge ``(t0, t1)`` intervals into a disjoint sorted union."""
    ivs = sorted((float(a), float(b)) for a, b in intervals if b > a)
    out: List[Tuple[float, float]] = []
    for a, b in ivs:
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1] = (out[-1][0], b)
        else:
            out.append((a, b))
    return out


def union_length(intervals: Sequence[Tuple[float, float]]) -> float:
    """Total length of the union — THE anti-sum-of-averages primitive:
    two phases covering the same wall-clock second count it once."""
    return sum(b - a for a, b in union_intervals(intervals))


def _intersect_disjoint(span: Tuple[float, float],
                        merged: Sequence[Tuple[float, float]]) -> float:
    """``|span ∩ merged|`` for an ALREADY disjoint sorted union —
    finalize intersects one precomputed phase union against every
    async span, and re-merging per span would be pure wasted work."""
    a0, b0 = span
    if b0 <= a0:
        return 0.0
    total = 0.0
    for a, b in merged:
        lo, hi = max(a, a0), min(b, b0)
        if hi > lo:
            total += hi - lo
    return total


def intersect_length(span: Tuple[float, float],
                     intervals: Sequence[Tuple[float, float]]) -> float:
    """``|span ∩ union(intervals)|`` — the overlap-credit primitive."""
    return _intersect_disjoint(span, union_intervals(intervals))


def _clip(t0: float, t1: float, lo: float, hi: float
          ) -> Optional[Tuple[float, float]]:
    a, b = max(t0, lo), min(t1, hi)
    return (a, b) if b > a else None


# ---------------------------------------------------------------------- #
# step-record readers (pure; tools/mvprof.py and tools/dump_metrics.py
# both render step JSONL — ONE aggregation definition, the same rule
# that makes aggregator.merge_cluster shared by mvtop)
# ---------------------------------------------------------------------- #
def step_top_phase(rec: Dict[str, Any]
                   ) -> Tuple[Optional[str], float]:
    """(name, exclusive ms) of a step record's critical-path phase —
    (None, 0.0) for a phaseless step."""
    name, ms = None, 0.0
    for n, d in (rec.get("phases") or {}).items():
        v = float(d.get("ms", 0.0))
        if v > ms:
            name, ms = n, v
    return name, ms


def aggregate_step_records(records: Sequence[Dict[str, Any]]
                           ) -> Dict[str, Any]:
    """Aggregate a list of ``kind == "step"`` records: wall/stall/
    attributed/overlap sums, per-phase exclusive totals, critical-path
    win counts, and the recompile table (steps with compiles + summed
    per-function retraces)."""
    steps = [r for r in records if r.get("kind") == "step"]
    out: Dict[str, Any] = {
        "steps": len(steps),
        "wall_ms": sum(float(r.get("wall_ms", 0.0)) for r in steps),
        "stall_ms": sum(float(r.get("stall_ms", 0.0)) for r in steps),
        "attributed_ms": sum(float(r.get("attributed_ms", 0.0))
                             for r in steps),
        "overlap_ms": sum(float(r.get("overlap_ms", 0.0))
                          for r in steps),
    }
    phases: Dict[str, float] = {}
    wins: Dict[str, int] = {}
    recompile_steps: List[Dict[str, Any]] = []
    retraces: Dict[str, int] = {}
    for r in steps:
        for n, d in (r.get("phases") or {}).items():
            phases[n] = phases.get(n, 0.0) + float(d.get("ms", 0.0))
        top, _ = step_top_phase(r)
        if top:
            wins[top] = wins.get(top, 0) + 1
        j = r.get("jax") or {}
        if j.get("compiles"):
            recompile_steps.append(
                {"step": r.get("step"), "name": r.get("name"),
                 "compiles": j.get("compiles"),
                 "compile_s": j.get("compile_s"),
                 "by_fn": j.get("retraces_by_fn", {})})
        for fn, k in (j.get("retraces_by_fn") or {}).items():
            retraces[fn] = retraces.get(fn, 0) + int(k)
    out["phases_ms"] = {n: round(v, 4) for n, v in sorted(phases.items())}
    out["critical_path_wins"] = dict(
        sorted(wins.items(), key=lambda kv: -kv[1]))
    out["recompile_steps"] = recompile_steps
    out["retraces_by_fn"] = retraces
    return out


# ---------------------------------------------------------------------- #
# JAX counter hook (global monotonic counters; steps take deltas)
# ---------------------------------------------------------------------- #
class _JaxCounters:
    """Process-global compile/transfer/donation counters. Installed
    lazily the first time profiling is enabled; the listeners stay for
    the process lifetime (jax offers no public unregister) but cost
    nothing between compiles."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.installed = False
        self.compiles = 0          # backend compiles (includes retraces)
        self.compile_s = 0.0       # seconds inside backend compilation
        self.traces = 0            # jaxpr traces (cache misses)
        self.donation_rejected = 0
        self.transfer_bytes = 0    # instrumented-site accounting
        # invoked (OUTSIDE this lock — the profiler's own lock nests
        # the other way) once per backend compile, so the steady-state
        # classification can be per EVENT, not per window delta
        self.on_compile: Optional[Callable[[], None]] = None

    # ------------------------------------------------------------------ #
    def install(self) -> None:
        """Register the jax.monitoring duration listener (once) and
        chain the donation-warning counter in front of
        ``warnings.showwarning``. The warning hook re-wraps whenever
        something else replaced ``showwarning`` since the last install
        (pytest's capture, ``catch_warnings`` blocks): ``install`` runs
        on every enabled ``configure()``, so the hook survives those
        save/restore cycles."""
        with self._lock:
            first = not self.installed
            self.installed = True
        if first:
            try:
                import jax.monitoring as _jm
                _jm.register_event_duration_secs_listener(
                    self._on_duration)
            except Exception:   # noqa: BLE001 — profiling must degrade,
                pass            # not break the run, on exotic builds
        try:
            import warnings
            if getattr(warnings.showwarning, "_mv_donation_hook", False):
                return
            prev = warnings.showwarning

            def _showwarning(message, category, filename, lineno,
                             file=None, line=None, _prev=prev):
                try:
                    if "donated buffers were not usable" in str(message):
                        with self._lock:
                            self.donation_rejected += 1
                except Exception:   # noqa: BLE001
                    pass
                return _prev(message, category, filename, lineno,
                             file=file, line=line)

            _showwarning._mv_donation_hook = True
            warnings.showwarning = _showwarning
        except Exception:   # noqa: BLE001
            pass

    def _on_duration(self, name: str, dur: float, **kw) -> None:
        # /jax/core/compile/backend_compile_duration fires once per XLA
        # compile (first trace AND every retrace); jaxpr_trace_duration
        # fires per jaxpr trace. Substring match: the exact prefixes
        # have moved across jax versions.
        if name.endswith("backend_compile_duration"):
            with self._lock:
                self.compiles += 1
                self.compile_s += float(dur)
                cb = self.on_compile
            if cb is not None:
                cb()   # off this lock: the callback takes the profiler's
        elif name.endswith("jaxpr_trace_duration"):
            with self._lock:
                self.traces += 1

    # ------------------------------------------------------------------ #
    def note_transfer(self, nbytes: int) -> None:
        with self._lock:
            self.transfer_bytes += int(nbytes)

    def note_donation_rejected(self, n: int = 1) -> None:
        with self._lock:
            self.donation_rejected += int(n)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"compiles": self.compiles,
                    "compile_s": round(self.compile_s, 6),
                    "traces": self.traces,
                    "donation_rejected": self.donation_rejected,
                    "transfer_bytes": self.transfer_bytes}

    def reset(self) -> None:
        with self._lock:
            self.compiles = 0
            self.compile_s = 0.0
            self.traces = 0
            self.donation_rejected = 0
            self.transfer_bytes = 0


class AsyncSpan:
    """One in-flight async interval (a PS round-trip). ``end()`` may run
    on any thread (peer recv callbacks); idempotent — racing closers
    (reply callback vs. the wait() fallback) record one interval."""

    __slots__ = ("name", "t0", "t1", "_step", "trace")

    def __init__(self, name: str, step: "Step",
                 trace: Optional[int] = None):
        self.name = name
        self.t0 = time.time()
        self.t1: Optional[float] = None
        self._step = step
        self.trace = trace

    def end(self, t: Optional[float] = None) -> None:
        step = self._step
        if step is None:
            return
        self._step = None
        self.t1 = time.time() if t is None else t
        step._async_done(self)


class Step:
    """One profiled step (per-thread; see module docstring). Created by
    :func:`step` — apps never construct one directly, but MAY pass the
    object to ``phase(..., step=s)`` / ``note_async(..., step=s)`` from
    OTHER threads (producer threads contributing to a consumer's step:
    the cross-thread attribution surface)."""

    __slots__ = ("name", "index", "t0", "t1", "_lock", "_intervals",
                 "_dropped", "_excl", "_counts", "_open_async",
                 "_async_done_list", "_jax0", "_watch0", "_finalized",
                 "_warmup", "record")

    def __init__(self, name: str, index: int):
        self.name = name
        self.index = index
        self.t0 = time.time()
        self.t1: Optional[float] = None
        self._lock = threading.Lock()
        # closed phase/async intervals: (kind, name, t0, t1)
        self._intervals: List[Tuple[str, str, float, float]] = []
        self._dropped = 0
        self._excl: Dict[str, float] = {}     # phase -> exclusive secs
        self._counts: Dict[str, int] = {}     # phase/async -> marks
        self._open_async: List[AsyncSpan] = []
        self._async_done_list: List[AsyncSpan] = []
        self._jax0: Dict[str, Any] = {}
        self._watch0: Dict[str, int] = {}
        self._finalized = False
        self._warmup = False   # first step on its thread (set by begin)
        self.record: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------------ #
    def _add_interval(self, kind: str, name: str, t0: float, t1: float,
                      excl: Optional[float] = None) -> None:
        with self._lock:
            if self._finalized:
                return
            if len(self._intervals) < _MAX_SPANS_PER_STEP:
                self._intervals.append((kind, name, t0, t1))
            else:
                self._dropped += 1
            self._counts[name] = self._counts.get(name, 0) + 1
            if excl is not None:
                self._excl[name] = self._excl.get(name, 0.0) + excl

    def _async_begin(self, span: AsyncSpan) -> None:
        with self._lock:
            if self._finalized:
                span._step = None
                return
            self._open_async.append(span)

    def _async_done(self, span: AsyncSpan) -> None:
        with self._lock:
            if self._finalized:
                return
            try:
                self._open_async.remove(span)
            except ValueError:
                pass
            self._async_done_list.append(span)

    # ------------------------------------------------------------------ #
    def _finalize(self, jax_now: Dict[str, Any],
                  watch_now: Dict[str, int], rank: int) -> Dict[str, Any]:
        t1 = time.time()
        with self._lock:
            self._finalized = True
            self.t1 = t1
            # in-flight async ops at step end: clip at the boundary —
            # their overlap up to here was real; the remainder belongs
            # to no step (recorded as open so mvprof can say so)
            open_spans = list(self._open_async)
            done_spans = list(self._async_done_list)
            intervals = list(self._intervals)
            dropped = self._dropped
            excl = dict(self._excl)
            counts = dict(self._counts)
        wall = max(t1 - self.t0, 1e-9)
        phase_ivs = [(a, b) for k, _n, a, b in intervals if k == "phase"]
        phase_union = union_intervals(
            [iv for iv in (
                _clip(a, b, self.t0, t1) for a, b in phase_ivs)
             if iv])
        async_detail: Dict[str, Dict[str, Any]] = {}
        all_ivs = list(phase_union)
        overlap_s = 0.0
        for span, open_ in ([(s, False) for s in done_spans]
                            + [(s, True) for s in open_spans]):
            s1 = t1 if span.t1 is None else span.t1
            iv = _clip(span.t0, s1, self.t0, t1)
            if iv is None:
                continue
            all_ivs.append(iv)
            ov = _intersect_disjoint(iv, phase_union)
            overlap_s += ov
            d = async_detail.setdefault(
                span.name, {"ms": 0.0, "overlap_ms": 0.0, "count": 0,
                            "open": 0})
            d["ms"] += (iv[1] - iv[0]) * 1e3
            d["overlap_ms"] += ov * 1e3
            d["count"] += 1
            if open_:
                d["open"] += 1
        attributed = union_length(all_ivs)
        stall = max(wall - attributed, 0.0)
        phases = {n: {"ms": round(s * 1e3, 4),
                      "count": counts.get(n, 0)}
                  for n, s in sorted(excl.items())}
        for d in async_detail.values():
            for k in ("ms", "overlap_ms"):
                d[k] = round(d[k], 4)
        jax_delta: Dict[str, Any] = {}
        for k, v in jax_now.items():
            v0 = self._jax0.get(k, 0)
            jax_delta[k] = (round(v - v0, 6)
                            if isinstance(v, float) else int(v - v0))
        retr = {n: int(watch_now.get(n, 0) - c0)
                for n, c0 in sorted(self._watch0.items())
                if watch_now.get(n, 0) - c0 > 0}
        if retr:
            jax_delta["retraces_by_fn"] = retr
        spans_out = []
        for k, n, a, b in intervals:
            iv = _clip(a, b, self.t0, t1)
            if iv:
                spans_out.append([k, n, round((iv[0] - self.t0) * 1e6),
                                  round((iv[1] - self.t0) * 1e6)])
        for span, open_ in ([(s, False) for s in done_spans]
                            + [(s, True) for s in open_spans]):
            s1 = t1 if span.t1 is None else span.t1
            iv = _clip(span.t0, s1, self.t0, t1)
            if iv and len(spans_out) < 2 * _MAX_SPANS_PER_STEP:
                spans_out.append(
                    ["async", span.name,
                     round((iv[0] - self.t0) * 1e6),
                     round((iv[1] - self.t0) * 1e6)]
                    + (["open"] if open_ else []))
        rec = {
            "kind": "step", "name": self.name, "step": self.index,
            "rank": rank, "ts": round(self.t0, 6),
            "wall_ms": round(wall * 1e3, 4),
            "attributed_ms": round(attributed * 1e3, 4),
            "attributed_fraction": round(min(attributed / wall, 1.0), 4),
            "overlap_ms": round(overlap_s * 1e3, 4),
            "stall_ms": round(stall * 1e3, 4),
            "stall_fraction": round(stall / wall, 4),
            "phases": phases,
            "async": async_detail,
            "jax": jax_delta,
            "spans": spans_out,
            "tid": threading.get_ident() & 0x7FFFFFFF,
        }
        if dropped:
            rec["spans_dropped"] = dropped
        self.record = rec
        return rec


class _NullCtx:
    """Shared no-op context (the flag-off path allocates nothing)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullCtx()


class _PhaseCtx:
    """One phase mark on one thread. Nesting: a per-thread stack debits
    the parent's exclusive time by the child's span, so exclusive times
    sum to <= the union and never double-count."""

    __slots__ = ("_name", "_step", "_t0", "_child", "_tls")

    def __init__(self, name: str, step: "Step", tls):
        self._name = name
        self._step = step
        self._tls = tls
        self._child = 0.0

    def __enter__(self):
        stack = getattr(self._tls, "phase_stack", None)
        if stack is None:
            stack = self._tls.phase_stack = []
        stack.append(self)
        self._t0 = time.time()
        return self

    def __exit__(self, *exc):
        t1 = time.time()
        span = t1 - self._t0
        stack = self._tls.phase_stack
        try:
            stack.remove(self)
        except ValueError:
            pass
        if stack:
            stack[-1]._child += span
        self._step._add_interval(
            "phase", self._name, self._t0, t1,
            excl=max(span - self._child, 0.0))
        return False


class _StepCtx:
    __slots__ = ("_prof", "_name", "_step")

    def __init__(self, prof: "StepProfiler", name: str):
        self._prof = prof
        self._name = name

    def __enter__(self) -> Step:
        self._step = self._prof._begin_step(self._name)
        return self._step

    def __exit__(self, *exc):
        self._prof._end_step(self._step)
        return False


class StepProfiler:
    """Process-global profiler (one per process, like Tracer/Recorder);
    in-process multi-rank worlds share it, attributed to the first
    configured rank — the same documented collapse as trace IDs."""

    def __init__(self) -> None:
        self.enabled = False      # plain attribute: THE hot-path gate
        self.rank = 0
        self._rank_pinned = False
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=_MAX_RECORDS)
        self._tls = threading.local()
        self._next_index = 0
        self._steps_total = 0
        # last-begun still-open step, any thread: the attach="any"
        # fallback for producer threads that hold no step of their own
        self._current_any: Optional[Step] = None
        self.jax = _JaxCounters()
        # name -> jitted fn (strong ref is fine: jitted fns are
        # module/app-lifetime objects; the dict is small and explicit)
        self._watched: Dict[str, Any] = {}
        # aggregate totals that survive the drain-on-dump record buffer
        self._agg_phase_ms: Dict[str, float] = {}
        self._agg_stall_ms = 0.0
        self._agg_wall_ms = 0.0
        self._agg_attr_ms = 0.0
        self._agg_overlap_ms = 0.0
        # steady-state recompiles, classified per compile EVENT (the
        # jax hook calls _note_compile_event outside its own lock): a
        # compile counts as steady iff at that moment at least one step
        # is open and NO open step is a warmup step (each thread's
        # FIRST step). Window-delta classification would turn one
        # shared warm compile into a phantom steady recompile on every
        # concurrently-open step (the 2-trainer DLRM shape).
        self._steady_recompiles = 0
        self._open_count = 0
        self._open_warmup = 0
        self.jax.on_compile = self._note_compile_event

    def _note_compile_event(self) -> None:
        with self._lock:
            if self._open_count > 0 and self._open_warmup == 0:
                self._steady_recompiles += 1

    # ------------------------------------------------------------------ #
    def configure(self, rank: Optional[int] = None) -> None:
        """Adopt the ``step_profile`` flag (PSService init / Zoo.start);
        idempotent, first caller's rank sticks."""
        if rank is not None and not self._rank_pinned:
            self.rank = int(rank)
            self._rank_pinned = True
        self.enabled = bool(config.get_flag("step_profile"))
        if self.enabled:
            self.jax.install()

    # ------------------------------------------------------------------ #
    # marking API (module-level wrappers below are the call-site idiom)
    # ------------------------------------------------------------------ #
    def step(self, name: str = "step"):
        if not self.enabled:
            return _NULL
        return _StepCtx(self, name)

    def _begin_step(self, name: str) -> Step:
        begun = getattr(self._tls, "steps_begun", 0)
        self._tls.steps_begun = begun + 1
        with self._lock:
            idx = self._next_index
            self._next_index += 1
            self._open_count += 1
            if begun == 0:
                self._open_warmup += 1
        s = Step(name, idx)
        s._warmup = begun == 0
        s._jax0 = self.jax.snapshot()
        s._watch0 = self._watch_sizes()
        self._tls.step = s
        self._current_any = s
        return s

    def _end_step(self, s: Step) -> Dict[str, Any]:
        rec = s._finalize(self.jax.snapshot(), self._watch_sizes(),
                          self.rank)
        if getattr(self._tls, "step", None) is s:
            self._tls.step = None
        with self._lock:
            if self._current_any is s:
                self._current_any = None
            self._open_count = max(self._open_count - 1, 0)
            if s._warmup:
                self._open_warmup = max(self._open_warmup - 1, 0)
            self._records.append(rec)
            self._steps_total += 1
            for n, d in rec["phases"].items():
                self._agg_phase_ms[n] = (self._agg_phase_ms.get(n, 0.0)
                                         + d["ms"])
            self._agg_stall_ms += rec["stall_ms"]
            self._agg_wall_ms += rec["wall_ms"]
            self._agg_attr_ms += rec["attributed_ms"]
            self._agg_overlap_ms += rec["overlap_ms"]
        return rec

    def current_step(self) -> Optional[Step]:
        return getattr(self._tls, "step", None)

    def phase(self, name: str, step: Optional[Step] = None):
        """Phase mark on the calling thread, attributed to its active
        step (or an explicit ``step`` handle from another thread);
        no-op context when disabled or no step is active."""
        if not self.enabled:
            return _NULL
        s = step if step is not None else getattr(self._tls, "step", None)
        if s is None or s._finalized:
            return _NULL
        return _PhaseCtx(name, s, self._tls)

    def async_begin(self, name: str, step: Optional[Step] = None,
                    attach: str = "thread",
                    trace: Optional[int] = None) -> Optional[AsyncSpan]:
        """Open an async span (a PS round-trip). ``attach="any"`` falls
        back to the process's last-begun open step when the calling
        thread holds none (producer threads). Returns None when nothing
        to attach to — callers guard with ``if span is not None``."""
        if not self.enabled:
            return None
        s = step if step is not None else getattr(self._tls, "step", None)
        if s is None and attach == "any":
            s = self._current_any
        if s is None or s._finalized:
            return None
        span = AsyncSpan(name, s, trace=trace)
        s._async_begin(span)
        return span

    def note_async(self, name: str, t0: float, t1: float,
                   step: Optional[Step] = None,
                   attach: str = "thread") -> None:
        """Retroactive async span (``time.time()`` seconds) — for call
        sites that only learn the interval after the fact (a producer
        thread's per-batch parse)."""
        if not self.enabled or t1 <= t0:
            return
        s = step if step is not None else getattr(self._tls, "step", None)
        if s is None and attach == "any":
            s = self._current_any
        if s is None or s._finalized:
            return
        span = AsyncSpan(name, s)
        span.t0 = t0
        s._async_begin(span)
        span.end(t1)

    # ------------------------------------------------------------------ #
    # jax-side counters
    # ------------------------------------------------------------------ #
    def watch_jit(self, name: str, fn: Any) -> None:
        """Register a jitted function for per-function retrace
        attribution (``_cache_size()`` deltas per step — the signal
        ``jax.monitoring`` listeners cannot attribute). Idempotent by
        name; silently skipped for objects without a cache size."""
        if getattr(fn, "_cache_size", None) is None:
            return
        with self._lock:
            self._watched.setdefault(name, fn)

    def _watch_sizes(self) -> Dict[str, int]:
        with self._lock:
            watched = list(self._watched.items())
        out = {}
        for n, fn in watched:
            try:
                out[n] = int(fn._cache_size())
            except Exception:   # noqa: BLE001 — a dead/exotic fn must
                out[n] = 0      # not break step finalize
        return out

    def jax_counters(self) -> Dict[str, Any]:
        """Current global counter snapshot (installs the hooks on first
        use so a bare caller can sample without a step)."""
        self.jax.install()
        out = self.jax.snapshot()
        out["watched"] = self._watch_sizes()
        return out

    def note_transfer(self, nbytes: int) -> None:
        if self.enabled:
            self.jax.note_transfer(nbytes)

    # ------------------------------------------------------------------ #
    # reads / dumps
    # ------------------------------------------------------------------ #
    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._records)

    def summary(self) -> Dict[str, Any]:
        """Aggregate across every finalized step THIS process ran
        (survives the drain-on-dump buffer): per-phase exclusive totals,
        attributed/stall fractions over the summed wall clock, overlap
        credit, and the steady-state recompile count — compile EVENTS
        that fired while steps were open and every thread's FIRST step
        had already closed (warmup compiles are expected; these are
        not). Per-step ``jax`` deltas are process-global counter
        windows: concurrently-open steps each see a compile that fired
        during their overlap — the steady count here is per-event and
        does not double-count."""
        with self._lock:
            wall = self._agg_wall_ms
            return {
                "steps": self._steps_total,
                "wall_ms": round(wall, 3),
                "attributed_fraction": (
                    round(self._agg_attr_ms / wall, 4) if wall else 0.0),
                "stall_fraction": (
                    round(self._agg_stall_ms / wall, 4) if wall else 0.0),
                "overlap_ms": round(self._agg_overlap_ms, 3),
                "phases": {n: round(v, 3) for n, v in
                           sorted(self._agg_phase_ms.items())},
                "steady_recompiles": self._steady_recompiles,
                "jax": self.jax.snapshot(),
            }

    def stats_snapshot(self) -> Optional[Dict[str, Any]]:
        """Compact block for MSG_STATS payloads / mvtop's per-rank
        columns; None when profiling never ran (payloads stay
        unchanged)."""
        if not self._steps_total:
            return None
        s = self.summary()
        return {"steps": s["steps"],
                "stall_fraction": s["stall_fraction"],
                "attributed_fraction": s["attributed_fraction"],
                "steady_recompiles": s["steady_recompiles"],
                "compiles": s["jax"]["compiles"],
                "phases": s["phases"]}

    def profile_path(self, directory: str,
                     rank: Optional[int] = None) -> str:
        r = self.rank if rank is None else rank
        return os.path.join(directory, f"profile-rank{r}.jsonl")

    def dump_to(self, directory: str) -> int:
        """Append buffered step records as JSONL and drain (the
        exporter streams without duplicating — same contract as
        Tracer.dump)."""
        with self._lock:
            recs, n = list(self._records), len(self._records)
            self._records.clear()
        if not recs:
            return 0
        os.makedirs(directory, exist_ok=True)
        with open(self.profile_path(directory), "a") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        return n

    def reset(self) -> None:
        """Test isolation: drop records/aggregates and unpin; the jax
        listener stays installed (idempotent, costs nothing idle) but
        its counters rewind."""
        with self._lock:
            self._records.clear()
            self._next_index = 0
            self._steps_total = 0
            self._current_any = None
            self._watched.clear()
            self._agg_phase_ms.clear()
            self._agg_stall_ms = 0.0
            self._agg_wall_ms = 0.0
            self._agg_attr_ms = 0.0
            self._agg_overlap_ms = 0.0
            self._steady_recompiles = 0
            self._open_count = 0
            self._open_warmup = 0
            self._rank_pinned = False
            self.rank = 0
        self._tls = threading.local()
        self.jax.reset()
        self.enabled = False


PROFILER = StepProfiler()


# module-level wrappers (the call-site idiom, like telemetry.trace)
def enabled() -> bool:
    """THE hot-path gate (attribute read, no locks)."""
    return PROFILER.enabled


def configure(rank: Optional[int] = None) -> None:
    PROFILER.configure(rank)


def step(name: str = "step"):
    return PROFILER.step(name)


def phase(name: str, step: Optional[Step] = None):
    return PROFILER.phase(name, step=step)


def current_step() -> Optional[Step]:
    return PROFILER.current_step()


def async_begin(name: str, step: Optional[Step] = None,
                attach: str = "thread",
                trace: Optional[int] = None) -> Optional[AsyncSpan]:
    return PROFILER.async_begin(name, step=step, attach=attach,
                                trace=trace)


def note_async(name: str, t0: float, t1: float,
               step: Optional[Step] = None, attach: str = "thread"
               ) -> None:
    PROFILER.note_async(name, t0, t1, step=step, attach=attach)


def note_transfer(nbytes: int) -> None:
    PROFILER.note_transfer(nbytes)


def watch_jit(name: str, fn: Any) -> None:
    PROFILER.watch_jit(name, fn)


def jax_counters() -> Dict[str, Any]:
    return PROFILER.jax_counters()


def records() -> List[Dict[str, Any]]:
    return PROFILER.records()


def summary() -> Dict[str, Any]:
    return PROFILER.summary()


def stats_snapshot() -> Optional[Dict[str, Any]]:
    return PROFILER.stats_snapshot()


def dump_to(directory: str) -> int:
    return PROFILER.dump_to(directory)


def reset() -> None:
    PROFILER.reset()
