"""Bounded-memory heavy-hitter sketch for PS row traffic.

Space-Saving (Metwally/Agrawal/El Abbadi, "Efficient computation of
frequent and top-k elements in data streams"): keep at most ``capacity``
(key, count, err) entries; a known key increments in O(1), an unknown
key evicts the current minimum and inherits its count as the new entry's
overestimate bound (``err``). Guarantees, independent of stream length:

* every tracked key's true frequency f satisfies
  ``count - err <= f <= count``;
* any key whose true frequency exceeds ``total / capacity`` is tracked —
  the zipf heads this sketch exists for are far above that bar.

Design constraints, in order:

1. The shard serve paths call this per request (always-on, like the
   flight recorder), so a recorded op must stay O(1): one dict lookup +
   one list increment for a known key. Eviction uses a lazy min-heap
   (exactly one heap entry per tracked key; a stale top re-pushes at its
   live count) — amortized O(log capacity), and since pushed counts are
   lower bounds that only grow, the first popped entry whose pushed
   count matches its live count IS the true minimum.
2. Bounded memory: ``capacity`` dict entries + ``capacity`` heap entries,
   a few KB at the default. Batches above :data:`BATCH_SAMPLE` rows are
   stride-sampled at the stride's weight — relative frequencies AND the
   raw-traffic count scale survive uniform sampling (a key served via
   chunked mega-gets ranks correctly against one served via 1-row ops),
   and a 100k-row chunked get must not pay 100k dict ops.
3. Mergeable: :func:`merge_sketches` sums per-key across shards for the
   cluster top-K. Row-partitioned and hash-sharded PS tables give each
   shard a DISJOINT key space, so the cross-shard merge is exact — a
   pure concatenation; the summing path exists for re-partitioned runs.

Python-plane only, same rule as tracing and the serve beats: ops served
inside the native C++ fast path never cross this module (windowed adds
and chunk-requesting gets always punt to Python, so the workloads that
need cache sizing — zipf row traffic — are visible either way).
"""

from __future__ import annotations

import heapq
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from multiverso_tpu.utils import config

config.define_int(
    "hotkeys_capacity", 128,
    "per-shard Space-Saving heavy-hitter sketch size (tracked row ids on "
    "the get/add serve paths; feeds stats()['hotkeys'] and the cluster "
    "aggregator's top-K + cache-hit-if-cached curve). Always-on like the "
    "flight recorder; 0 disables the sketch entirely")

# batches above this many ids are stride-sampled before offering (see
# module docstring constraint 2)
BATCH_SAMPLE = 512


class SpaceSaving:
    """The sketch. Thread-safe: shard connection threads record
    concurrently; one internal lock per offered batch."""

    __slots__ = ("capacity", "total", "observed", "_counts", "_heap",
                 "_nbatches", "_lock")

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("SpaceSaving capacity must be positive")
        self.capacity = int(capacity)
        # key -> [count, err]; exactly one (pushed_count, key) heap entry
        # per tracked key (stale after increments, fixed lazily)
        self._counts: Dict[int, List[int]] = {}
        self._heap: List[Tuple[int, int]] = []
        self.total = 0      # offers counted (weighted; ~= raw traffic)
        self.observed = 0   # raw ids seen (pre-sampling)
        self._nbatches = 0  # rotates the sampling phase (see observe)
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._counts)

    # ------------------------------------------------------------------ #
    def _offer(self, key: int, inc: int) -> None:
        """Caller holds ``self._lock``."""
        self.total += inc
        e = self._counts.get(key)
        if e is not None:
            e[0] += inc   # heap entry goes stale; fixed at eviction time
            return
        if len(self._counts) < self.capacity:
            self._counts[key] = [inc, 0]
            heapq.heappush(self._heap, (inc, key))
            return
        # evict the true minimum: pushed counts are lower bounds, so the
        # first popped entry whose pushed count matches its live count is
        # it (stale tops re-push at their live count; each key re-pushes
        # at most once per eviction — the lock excludes new increments)
        while True:
            cnt, k = heapq.heappop(self._heap)
            live = self._counts[k][0]
            if live == cnt:
                break
            heapq.heappush(self._heap, (live, k))
        del self._counts[k]
        self._counts[key] = [cnt + inc, cnt]
        heapq.heappush(self._heap, (cnt + inc, key))

    def offer(self, key: int, inc: int = 1) -> None:
        with self._lock:
            self.observed += inc
            self._offer(int(key), int(inc))

    def offer_key(self, key, inc: int = 1) -> None:
        """Like :meth:`offer` without the int cast — the core structure
        is key-type-agnostic (heap entries compare ``(count, key)``), so
        string keys (tenant ids, telemetry/tenants.py) rank the same
        way row ids do. Don't mix key types in one sketch: a stale-top
        re-push would then compare int against str."""
        with self._lock:
            self.observed += inc
            self._offer(key, int(inc))

    def observe(self, ids, offset: int = 0) -> None:
        """Record a batch of row ids (``offset`` turns shard-local ids
        into global ones without allocating a shifted copy). Batches
        above :data:`BATCH_SAMPLE` are stride-sampled, with each sampled
        key offered at the STRIDE's weight — counts stay on the
        raw-traffic scale, so a key served through big chunked gets
        ranks against a key served through 1-row ops instead of being
        undercounted by n/BATCH_SAMPLE (the top-K and the cache-hit
        curve compare across batch sizes by construction)."""
        arr = np.asarray(ids).reshape(-1)
        n = int(arr.size)
        if n == 0:
            return
        off = int(offset)
        with self._lock:
            self.observed += n
            self._nbatches += 1
            inc = 1
            if n > BATCH_SAMPLE:
                inc = -(-n // BATCH_SAMPLE)
                # ROTATING phase: a workload re-issuing the same big
                # caller-ordered batch every step (a DLRM chunked get)
                # would otherwise sample the identical positions forever
                # — an off-stride hot key would never be observed. The
                # batch counter cycles the start through every residue,
                # so across repeats the sample is uniform.
                # start < inc <= n, so the slice is never empty
                arr = arr[self._nbatches % inc:: inc]
            for k in arr.tolist():
                self._offer(int(k) + off, inc)

    # ------------------------------------------------------------------ #
    def items(self) -> List[Tuple[int, int, int]]:
        """``(key, estimated count, overestimate bound)`` descending by
        count (true frequency is within ``[count - err, count]``)."""
        with self._lock:
            out = [(k, c, e) for k, (c, e) in self._counts.items()]
        out.sort(key=lambda t: (-t[1], t[0]))
        return out

    def top(self, k: int) -> List[Tuple[int, int, int]]:
        return self.items()[:k]

    def to_dict(self) -> Dict:
        """JSON-safe snapshot — the MSG_STATS / exporter wire shape
        (``items`` descending, same tuple order as :meth:`items`)."""
        with self._lock:
            total, observed = self.total, self.observed
            out = [[k, c, e] for k, (c, e) in self._counts.items()]
        out.sort(key=lambda t: (-t[1], t[0]))
        return {"capacity": self.capacity, "total": total,
                "observed": observed, "items": out}


# ---------------------------------------------------------------------- #
# cross-shard merge + the cache-sizing curve (aggregator/mvtop consume)
# ---------------------------------------------------------------------- #
def merge_sketches(dicts: Iterable[Optional[Dict]],
                   capacity: Optional[int] = None, key=int) -> Dict:
    """Merge :meth:`SpaceSaving.to_dict` payloads into one cluster-level
    sketch dict. Counts for a key present in several inputs sum (their
    err bounds sum too, staying conservative); PS shards partition the
    key space, so in practice this is an exact concatenation. The result
    keeps the ``capacity`` largest entries (default: the largest input
    capacity). ``key`` normalizes keys across inputs — ``int`` for row
    ids (the default), ``str`` for tenant-id sketches."""
    acc: Dict[Any, List[int]] = {}
    total = observed = cap = 0
    for d in dicts:
        if not d:
            continue
        total += int(d.get("total", 0) or 0)
        observed += int(d.get("observed", 0) or 0)
        cap = max(cap, int(d.get("capacity", 0) or 0))
        for k, c, e in d.get("items", []):
            a = acc.setdefault(key(k), [0, 0])
            a[0] += int(c)
            a[1] += int(e)
    items = sorted(([k, c, e] for k, (c, e) in acc.items()),
                   key=lambda t: (-t[1], t[0]))
    cap = int(capacity or cap or len(items))
    return {"capacity": cap, "total": total, "observed": observed,
            "items": items[:cap]}


def hit_rate_curve(sketch: Dict, points: int = 10,
                   conservative: bool = False) -> List[List[float]]:
    """Estimated cache-hit-rate-if-cached curve: ``[[k, rate], ...]`` at
    k = 1, 2, 4, ... — the fraction of sketched row traffic the top-k
    keys would have absorbed had they been device-cached. The direct
    sizing input for a hot-row cache (ROADMAP item 2) and the DLRM
    hot-user story (item 3). ``conservative=False`` (default) uses the
    raw counts — an UPPER-bound estimate, since Space-Saving counts
    overestimate within ``err`` (materially so when the sketch runs
    well under capacity-to-distinct-keys: every eviction inherits the
    minimum); ``conservative=True`` uses ``max(count - err, 0)`` — the
    guaranteed LOWER bound. Both bound the SKETCHED traffic only: a
    measured cache-hit rate over a raw request stream (the serving
    replica's, tools/bench_serving.py) can legitimately exceed even
    the upper curve, because shards sketch post-dedupe traffic — the
    curves are a sizing floor for such caches, not a bracket."""
    items = sketch.get("items", [])
    total = sketch.get("total", 0)
    if not items or not total:
        return []
    csum, acc = [], 0
    for _, c, e in items:
        acc += max(c - e, 0) if conservative else c
        csum.append(acc)
    out: List[List[float]] = []
    k = 1
    while k <= len(items) and len(out) < points:
        out.append([k, round(csum[k - 1] / total, 4)])
        k *= 2
    return out
