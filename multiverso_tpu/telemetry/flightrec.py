"""Always-on flight recorder: the PS plane's black box.

PR 3 gave the plane steady-state telemetry (histograms, trace spans,
MSG_STATS) — all of it in-memory, all of it dying with the process. The
failures that actually cost wall-clock (a stuck ``_SendWindow`` flush, a
shard queue that stops draining, a rank dead mid-barrier taking
``file_barrier``/SSP waits to their timeouts) leave no evidence behind.
This module is the production answer (cf. PyTorch's c10d flight
recorder; Dapper-style request tracing covers only the happy path): a
lock-cheap, ALWAYS-ON per-rank ring buffer of the last N wire events and
state transitions, dumped atomically as JSONL at fault time — fatal log,
SIGTERM/SIGABRT, ``Zoo.stop``, peer death with unacked traffic, or a
watchdog trip (telemetry/watchdog.py).

Cost discipline (the recorder cannot be flag-gated off — a black box
that has to be enabled before the crash is not a black box):

* **fixed slots** — ``flightrec_slots`` preallocated 8-field lists; a
  record commits one tuple into its slot with a single slice-assign
  (atomic w.r.t. signal-handler dumps). No growth, no formatting, one
  small tuple on the hot path.
* **one RLock hold** per record (~1 us). RLock, not Lock: a dump may run
  from a signal handler that interrupted the main thread mid-record,
  and a non-reentrant lock would deadlock the handler.
* timestamps are ``time.monotonic()``; the wall-clock anchor
  (``mono_to_wall``) is computed once at DUMP time so per-event cost
  stays one clock read, and tools/postmortem.py can still merge ranks
  onto one wall-clock timeline.

Beyond events, the recorder tracks **in-flight requests**: ``begin_op``
at ``_Peer.request`` (peer rank, wire msg id, type, bytes), ``end_op``
on the reply. This is what the watchdog ages, what ``MSG_HEALTH``
reports as "oldest in-flight op", and what lets a survivor's dump name
the DEAD rank's oldest unacked (src, dst, msg id) — the "who was stuck
on whom" question tools/postmortem.py answers without a repro.

Dump files (``flightrec-rank<r>.jsonl``) are written only when a
directory resolves — the ``flightrec_dir`` flag, else ``$MV_FLIGHTREC_DIR``,
else ``metrics_dir`` — so the always-on recorder never litters a run
that configured no observability output. Each dump atomically REPLACES
the rank's file; a ROUTINE dump (``routine=True`` — the Zoo.stop last
tape) is skipped once any FAULT dump exists, so a shutdown after a
watchdog trip can never overwrite the trip's stacks and in-flight
evidence with a healthy tape. Natively-served ops (the zero-Python C++
fast path) are not recorded, same rule as tracing.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

from multiverso_tpu.utils import config

config.define_int(
    "flightrec_slots", 4096,
    "flight-recorder ring size (events kept for a fault-time dump); the "
    "recorder itself is always on — this only bounds its fixed memory "
    "(~1 KB per 8 slots). See docs/OBSERVABILITY.md 'Postmortem "
    "debugging'")
config.define_string(
    "flightrec_dir", "",
    "directory for flight-recorder dumps (flightrec-rank<r>.jsonl); "
    "empty falls back to $MV_FLIGHTREC_DIR, then metrics_dir — with "
    "none of the three set, fault-time dumps are skipped (the ring "
    "still records)")

# ---------------------------------------------------------------------- #
# event kinds: small ints on the hot path, names in dumps
# ---------------------------------------------------------------------- #
EV_SEND = 1            # client request on the wire (begin_op)
EV_ACK = 2             # reply completed the request (end_op ok)
EV_ERR = 3             # request failed (end_op not-ok / peer sweep)
EV_RECV = 4            # server side: request arrived on a conn thread
EV_REPLY = 5           # server side: reply handed to the socket
EV_WIN_ENQ = 6         # send window: logical add queued for an owner
EV_WIN_FLUSH = 7       # send window: one owner's flush started
EV_WIN_FLUSH_END = 8   # send window: flush's frames are on the conn
EV_WIN_ACK = 9         # send window: a frame's batch ack fanned out
EV_APPLY = 10          # shard: one updater dispatch applied
EV_WAVE = 11           # shard: one MSG_BATCH conflict-free wave applied
EV_BARRIER_ENTER = 12  # barrier/file_barrier entered
EV_BARRIER_EXIT = 13   # barrier/file_barrier satisfied
EV_BARRIER_TIMEOUT = 14
EV_SSP_WAIT = 15       # SSP clock blocked on stragglers
EV_SSP_TIMEOUT = 16
EV_PEER_DEAD = 17      # a peer connection was observed dead
EV_FATAL = 18          # Logger.fatal fired
EV_SIGNAL = 19         # SIGTERM/SIGABRT reached the dump handler
EV_SLOW = 20           # watchdog: request older than watchdog_slow_ms
EV_STUCK = 21          # watchdog: request older than watchdog_stuck_s
EV_STATE = 22          # free-form state transition (note names it)
EV_SSP_RESOLVED = 23   # a blocked SSP wait resolved (pairs EV_SSP_WAIT)
EV_GET_SERVE = 24      # shard: a get pinned an epoch to serve off-lock
EV_GET_CHUNK = 25      # service: one streamed-reply sub-frame sent
EV_GET_WIN = 26        # client get coalescer: one batched fetch shipped
# elastic shard failover lifecycle (ps/failover.py, docs/FAILOVER.md):
# postmortem renders these five as the recovery timeline
EV_FAILOVER_DETECT = 27   # supervisor confirmed a dead|stuck rank
EV_FAILOVER_RESPAWN = 28  # supervisor launched the replacement
EV_FAILOVER_RESTORE = 29  # a shard restored from its checkpoint
EV_FAILOVER_REPLAY = 30   # replay plane: frame re-flushed / dedup'd
EV_FAILOVER_REJOIN = 31   # restored incarnation is serving again
# serving plane (PR 8's coverage gap, closed in PR 9): snapshot serves
# and replica refreshes ride the same tape as gets/adds
EV_SNAPSHOT_SERVE = 32    # shard: MSG_SNAPSHOT export served
EV_REPLICA_PULL = 33      # client: one ReadReplica refresh completed
# memory observability plane (telemetry/memstats.py): leak verdicts +
# the OOM-forensics dump trigger, one event per episode (deduped by
# the ledger until the condition clears — never a per-sweep flood)
EV_MEM_HOARD = 34         # epoch-hoard: aged pin holding retired buffers
EV_MEM_LEAK = 35          # retention-leak: replay tail growing, live owner
EV_MEM_RSS = 36           # rss-creep / rss soft-limit trip
EV_MEM_DUMP = 37          # OOM forensics dump fired (MemoryError/limit)
# device plane (telemetry/devstats.py): collective op begin/end — every
# parallel/collectives.py entry point marks both edges (note carries
# "coll.<op>", nbytes the payload), so a hang inside a mesh collective
# is visible on the tape like a wedged wire op
EV_COLL_BEGIN = 38        # collective op dispatched (host side)
EV_COLL_END = 39          # collective op returned to the caller
# fault-injection wire plane (ps/faults.py, docs/FAILOVER.md "Chaos
# scenarios"): every INJECTED fault lands its own event (note carries
# the kind — drop/delay/duplicate/reorder/partition/reset/slow_serve/
# drop_reply), so injected and organic faults are distinguishable in
# tools/postmortem.py timelines; plane arm/disarm/phase transitions
# mark the scenario's envelope on the same tape
EV_FAULT_INJECT = 40      # one fault injected into the wire plane
EV_FAULT_PLANE = 41       # fault plane armed / disarmed / phase flip
# tenant attribution plane (telemetry/tenants.py): a per-tenant budget
# shed is POLICY, not incident — it lands its own event (note carries
# "table:tenant") so a chaos run's sheds read as intended throttling in
# postmortem timelines; the noisy-neighbor verdict is one event per
# episode, deduped by the ledger until the condition clears (the same
# discipline as the EV_MEM_* verdicts)
EV_TENANT_SHED = 42       # admission refused a read on a tenant budget
EV_TENANT_VERDICT = 43    # noisy-neighbor episode opened
# SLO sentinel (telemetry/slo.py): ONE event per episode transition —
# the burn-rate alert firing and later clearing each land exactly one
# ring write (sentinel-deduped like the tenant verdict), so a chaos
# run's tape reads objective-first without per-poll flooding
EV_SLO_FIRED = 44         # an objective's burn-rate episode opened
EV_SLO_CLEARED = 45       # the episode's fast window re-entered budget

EV_NAMES = {
    EV_SEND: "send", EV_ACK: "ack", EV_ERR: "err", EV_RECV: "recv",
    EV_REPLY: "reply", EV_WIN_ENQ: "win.enqueue",
    EV_WIN_FLUSH: "win.flush", EV_WIN_FLUSH_END: "win.flush_end",
    EV_WIN_ACK: "win.ack", EV_APPLY: "shard.apply",
    EV_WAVE: "shard.wave", EV_BARRIER_ENTER: "barrier.enter",
    EV_BARRIER_EXIT: "barrier.exit",
    EV_BARRIER_TIMEOUT: "barrier.timeout", EV_SSP_WAIT: "ssp.wait",
    EV_SSP_TIMEOUT: "ssp.timeout", EV_PEER_DEAD: "peer.dead",
    EV_FATAL: "fatal", EV_SIGNAL: "signal", EV_SLOW: "watchdog.slow",
    EV_STUCK: "watchdog.stuck", EV_STATE: "state",
    EV_SSP_RESOLVED: "ssp.resolved", EV_GET_SERVE: "get.serve",
    EV_GET_CHUNK: "get.chunk", EV_GET_WIN: "get.window",
    EV_FAILOVER_DETECT: "failover.detect",
    EV_FAILOVER_RESPAWN: "failover.respawn",
    EV_FAILOVER_RESTORE: "failover.restore",
    EV_FAILOVER_REPLAY: "failover.replay",
    EV_FAILOVER_REJOIN: "failover.rejoin",
    EV_SNAPSHOT_SERVE: "snapshot.serve",
    EV_REPLICA_PULL: "replica.pull",
    EV_MEM_HOARD: "mem.epoch_hoard",
    EV_MEM_LEAK: "mem.retention_leak",
    EV_MEM_RSS: "mem.rss",
    EV_MEM_DUMP: "mem.oom_dump",
    EV_COLL_BEGIN: "coll.begin",
    EV_COLL_END: "coll.end",
    EV_FAULT_INJECT: "fault.inject",
    EV_FAULT_PLANE: "fault.plane",
    EV_TENANT_SHED: "tenant.shed",
    EV_TENANT_VERDICT: "tenant.verdict",
    EV_SLO_FIRED: "slo.fired",
    EV_SLO_CLEARED: "slo.cleared",
}

# ---------------------------------------------------------------------- #
# wire-opcode -> ring-event coverage map. Every MSG_* opcode defined in
# ps/service.py MUST have an entry here naming the ring events that mark
# its lifecycle on the tape — tools/check_obs_surface.py asserts the
# mapping statically (tier-1). PR 8's MSG_SNAPSHOT shipped with no
# flightrec/trace coverage precisely because nothing forced the
# question; an EMPTY tuple is a legitimate answer (probe traffic is
# deliberately excluded so 2 Hz polling cannot wrap the tape past
# pre-wedge evidence, PR 4) but it must be GIVEN, not forgotten.
# ---------------------------------------------------------------------- #
MSG_EV_COVERAGE = {
    "MSG_REPLY_OK": (EV_ACK, EV_REPLY),
    "MSG_REPLY_ERR": (EV_ERR, EV_REPLY),
    "MSG_REPLY_CHUNK": (EV_GET_CHUNK,),
    "MSG_PING": (),          # probe: excluded from the tape (PR 4)
    # data opcodes also carry EV_FAULT_INJECT where the chaos plane
    # (ps/faults.py) can touch them — an injected drop/dup/reorder on
    # an add frame is part of that opcode's lifecycle on the tape
    "MSG_ADD_ROWS": (EV_SEND, EV_RECV, EV_APPLY, EV_WIN_ENQ,
                     EV_WIN_FLUSH, EV_WIN_ACK, EV_FAULT_INJECT),
    # EV_TENANT_SHED: a read refused on a per-tenant admission budget
    # never reaches the wire, but the shed IS part of the get lifecycle
    # — the tape must show policy throttling next to the frames it
    # displaced (tools/postmortem.py renders both)
    "MSG_GET_ROWS": (EV_SEND, EV_RECV, EV_GET_SERVE, EV_GET_WIN,
                     EV_FAULT_INJECT, EV_TENANT_SHED),
    "MSG_SET_ROWS": (EV_SEND, EV_RECV, EV_APPLY),
    "MSG_ADD_FULL": (EV_SEND, EV_RECV, EV_APPLY),
    "MSG_GET_FULL": (EV_SEND, EV_RECV, EV_GET_SERVE),
    "MSG_KV_ADD": (EV_SEND, EV_RECV, EV_APPLY),
    "MSG_KV_GET": (EV_SEND, EV_RECV, EV_GET_SERVE),
    "MSG_GET_STATE": (EV_SEND, EV_RECV),
    "MSG_SET_STATE": (EV_SEND, EV_RECV),
    "MSG_BATCH": (EV_SEND, EV_RECV, EV_WAVE, EV_WIN_FLUSH, EV_WIN_ACK,
                  EV_FAULT_INJECT),
    # probe traffic itself stays off the tape (PR 4) — but the tenant
    # verdict sweep rides the stats pull and lands ONE event per
    # noisy-neighbor episode (ledger-deduped, never a per-poll flood),
    # and the SLO sentinel judges every objective on the aggregator's
    # stats poll: an episode firing/clearing is one event each,
    # sentinel-deduped under the same discipline
    "MSG_STATS": (EV_TENANT_VERDICT, EV_SLO_FIRED, EV_SLO_CLEARED),
    "MSG_HEALTH": (),        # probe: excluded from the tape (PR 4)
    "MSG_SNAPSHOT": (EV_SNAPSHOT_SERVE, EV_REPLICA_PULL,
                     EV_FAULT_INJECT, EV_TENANT_SHED),
    # multi-owner super-frame (ps/spmd.py, flag ps_fanout): carries
    # add/get sub-ops for every colocated shard of the destination
    # process — grouped applies land EV_APPLY (note "spmd ops=K"),
    # grouped gathers EV_GET_SERVE, per-sub batch dispatch EV_WAVE,
    # and the wire path the ordinary send/recv edges
    "MSG_MULTI": (EV_SEND, EV_RECV, EV_APPLY, EV_WAVE, EV_GET_SERVE,
                  EV_FAULT_INJECT),
}


# ---------------------------------------------------------------------- #
# auxiliary dump providers: other telemetry planes (memstats' byte
# ledger + sample history) register a zero-arg callable returning extra
# JSONL records to append to every dump — fault OR routine — so one
# artifact carries the wire tape AND the memory timeline. Providers run
# at DUMP time only (never on the hot path) and are individually
# fault-isolated: a provider raising must not cost the ring's tape.
# ---------------------------------------------------------------------- #
_dump_providers: List[Any] = []


def add_dump_provider(fn) -> None:
    if fn not in _dump_providers:
        _dump_providers.append(fn)


def resolve_dir() -> Optional[str]:
    """Dump directory resolution (module docstring): flag, env,
    metrics_dir, else None (= record but never write)."""
    d = config.get_flag("flightrec_dir")
    if d:
        return d
    d = os.environ.get("MV_FLIGHTREC_DIR", "")
    if d:
        return d
    d = config.get_flag("metrics_dir")
    return d or None


class FlightRecorder:
    """Process-global ring recorder (one per process, like the Tracer);
    several in-process ranks share it, attributed to the first
    configured rank — the same documented collapse as trace IDs."""

    def __init__(self, slots: Optional[int] = None):
        n = int(slots if slots is not None
                else config.get_flag("flightrec_slots"))
        self._n = max(16, n)
        # preallocated slots, fields assigned in place on record():
        # [seq, mono_ts, kind, peer, msg_type, msg_id, nbytes, note]
        self._slots: List[List[Any]] = [[0, 0.0, 0, -1, 0, -1, 0, None]
                                        for _ in range(self._n)]
        self._seq = 0
        self._lock = threading.RLock()   # RLock: dumps may run from a
        #                                  signal handler mid-record
        # (peer rank, wire msg id) -> (t0 mono, msg_type, nbytes,
        # record-in-ring flag — see begin_op)
        self._inflight: Dict[Tuple[int, int],
                             Tuple[float, int, int, bool]] = {}
        # name -> last-touch monotonic ts (serve loop, shard apply, ...)
        self._beats: Dict[str, float] = {}
        self.rank = 0
        self._rank_pinned = False
        self._dumps = 0
        self._fault_dumped = False
        self._last_dump: Optional[str] = None
        # serializes whole dumps (snapshot -> tmp write -> commit):
        # concurrent triggers (watchdog trip + peer death + Zoo.stop)
        # are exactly the multi-fault moment, and unserialized writers
        # would interleave. RLock for the same signal-handler
        # reentrancy reason as the ring lock; tmp names are ALSO unique
        # per attempt so a reentrant dump can never truncate the
        # interrupted one's half-written file
        self._dump_lock = threading.RLock()
        self._dump_attempts = 0

    # ------------------------------------------------------------------ #
    def configure(self, rank: Optional[int] = None) -> None:
        """Adopt flags (called from PSService init / Zoo.start);
        idempotent. First caller's rank sticks (see class docstring).
        The ring is resized to ``flightrec_slots`` only while still
        empty — resizing a live ring would drop the black box's tape."""
        with self._lock:
            if rank is not None and not self._rank_pinned:
                self.rank = int(rank)
                self._rank_pinned = True
            n = max(16, int(config.get_flag("flightrec_slots")))
            if n != self._n and self._seq == 0:
                self._n = n
                self._slots = [[0, 0.0, 0, -1, 0, -1, 0, None]
                               for _ in range(self._n)]

    # ------------------------------------------------------------------ #
    # hot path
    # ------------------------------------------------------------------ #
    def record(self, kind: int, peer: int = -1, msg_type: int = 0,
               msg_id: int = -1, nbytes: int = 0,
               note: Optional[str] = None) -> None:
        # slot first, seq last, each a single bytecode: a signal
        # handler's dump interrupting this method re-enters the RLock on
        # the same thread, and either ordering mistake would let its
        # snapshot emit a torn or stale record at the TAIL of the fault
        # dump — the first line an operator reads
        with self._lock:
            i = self._seq
            self._slots[i % self._n][:] = (
                i, time.monotonic(), kind, peer, msg_type, msg_id,
                nbytes, note)
            self._seq = i + 1

    def begin_op(self, peer: int, msg_id: int, msg_type: int,
                 nbytes: int = 0, record: bool = True) -> None:
        """A request left for ``peer``: record the send edge and track it
        in flight until :meth:`end_op` (one lock hold for both).
        ``record=False`` tracks WITHOUT ring events — probe traffic
        (ping/stats polls) is legitimately stuck traffic the watchdog
        should age, but its send/ack edges at supervisor polling rates
        would wrap the tape past pre-wedge evidence (same rule as the
        server-side probe exclusion)."""
        with self._lock:
            if record:
                self.record(EV_SEND, peer=peer, msg_type=msg_type,
                            msg_id=msg_id, nbytes=nbytes)
            self._inflight[(peer, msg_id)] = (time.monotonic(), msg_type,
                                              nbytes, record)

    def end_op(self, peer: int, msg_id: int, ok: bool = True) -> None:
        """Close an in-flight op. Idempotent: racing closers (reply vs.
        death-sweep vs. the send path's unwind) record ONE ack/err edge
        — an already-closed op is a silent no-op, so callers may close
        unconditionally without spraying phantom events into the ring."""
        with self._lock:
            ent = self._inflight.pop((peer, msg_id), None)
            if ent is None:
                return
            if ent[3]:   # honor begin_op's record-in-ring flag
                self.record(EV_ACK if ok else EV_ERR, peer=peer,
                            msg_type=ent[1], msg_id=msg_id)

    def fail_peer(self, peer: int, msg_ids=None) -> int:
        """Drop in-flight ops to a dead peer (AFTER the death dump: the
        dump is what preserves them); returns how many were dropped.
        ``msg_ids`` scopes the sweep to the DYING INCARNATION's own
        requests — a reconnected fresh peer may already have live ops
        under the same rank, and a rank-wide sweep would silently erase
        them from the watchdog's view (None sweeps the whole rank, for
        callers that know no newer incarnation exists). One EV_ERR marks
        the sweep — per-op events would spam the ring right when its
        tail matters most."""
        with self._lock:
            if msg_ids is None:
                gone = [k for k in self._inflight if k[0] == peer]
            else:
                gone = [(peer, m) for m in msg_ids
                        if (peer, m) in self._inflight]
            for k in gone:
                del self._inflight[k]
            if gone:
                self.record(EV_ERR, peer=peer, nbytes=len(gone),
                            note="peer died; in-flight ops failed")
            return len(gone)

    def beat(self, name: str) -> None:
        """Liveness heartbeat for a named loop (GIL-atomic dict store —
        no lock on this path)."""
        self._beats[name] = time.monotonic()

    def beat_age(self, name: str) -> Optional[float]:
        t = self._beats.get(name)
        return None if t is None else time.monotonic() - t

    # ------------------------------------------------------------------ #
    # reads
    # ------------------------------------------------------------------ #
    def snapshot(self, last: Optional[int] = None) -> List[List[Any]]:
        """Ring contents in record order (oldest first), copied.
        ``last`` bounds the work to the newest N slots — the copy runs
        under the hot path's lock, so a periodic consumer (the
        watchdog's 10-event slow-report window) must cost O(N), not an
        O(flightrec_slots) sweep of the whole ring."""
        with self._lock:
            i = self._seq
            count = min(i, self._n)
            take = count if last is None else min(last, count)
            # slots for seq [i-take, i) — index arithmetic, no full-ring
            # slice/concat even when the ring has wrapped
            return [list(self._slots[j % self._n])
                    for j in range(i - take, i)]

    def inflight_snapshot(self) -> List[Tuple[int, int, float, int, int]]:
        """[(peer, msg_id, age_s, msg_type, nbytes)], unordered."""
        now = time.monotonic()
        with self._lock:
            return [(p, mid, now - ent[0], ent[1], ent[2])
                    for (p, mid), ent in self._inflight.items()]

    def oldest_inflight(self) -> Optional[Tuple[float, int, int, int]]:
        """(age_s, peer, msg_id, msg_type) of the oldest unacked
        request, or None."""
        snap = self.inflight_snapshot()
        if not snap:
            return None
        p, mid, age, mt, _ = max(snap, key=lambda e: e[2])
        return (age, p, mid, mt)

    def dump_stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"count": self._dumps, "last": self._last_dump}

    # ------------------------------------------------------------------ #
    def dump(self, reason: str, directory: Optional[str] = None,
             stacks: bool = False, routine: bool = False) -> Optional[str]:
        """Atomically write the ring (+ in-flight table, + per-thread
        stacks when ``stacks``) as ``flightrec-rank<r>.jsonl``. Returns
        the path, or None when no directory resolves. ``routine=True``
        (the Zoo.stop last tape) is SKIPPED once a fault dump exists —
        the routine tape's only value is "last state when nothing else
        fired", and replacing a fault dump with it would destroy the
        stacks/in-flight evidence the recorder exists to preserve (a
        LATER fault dump still replaces an earlier one: the rate-limited
        refresh of a long hang). Never raises — fault paths call this
        and must still fail their own way."""
        try:
            directory = directory or resolve_dir()
            if not directory:
                return None
            if routine and self._fault_dumped:
                return None
            self._dump_lock.acquire()
        except Exception:   # noqa: BLE001
            return None
        try:
            events = self.snapshot()
            inflight = self.inflight_snapshot()
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(directory,
                                f"flightrec-rank{self.rank}.jsonl")
            with self._lock:
                self._dump_attempts += 1
                attempt = self._dump_attempts
            tmp = (f"{path}.{os.getpid()}.{threading.get_ident()}"
                   f".{attempt}.tmp")
            header = {
                "kind": "header", "rank": self.rank, "pid": os.getpid(),
                "reason": reason, "ts": round(time.time(), 6),
                # per-process monotonic -> wall anchor, so postmortem can
                # merge several ranks' events onto one timeline
                "mono_to_wall": round(time.time() - time.monotonic(), 6),
                "events": len(events), "slots": self._n,
                "dump_seq": self._dumps,
            }
            with open(tmp, "w") as f:
                f.write(json.dumps(header) + "\n")
                for s in events:
                    f.write(json.dumps({
                        "kind": "event", "seq": s[0],
                        "mono": round(s[1], 6),
                        "ev": EV_NAMES.get(s[2], s[2]), "peer": s[3],
                        "type": s[4], "msg_id": s[5], "nbytes": s[6],
                        "note": s[7]}) + "\n")
                for (p, mid, age, mt, nb) in inflight:
                    f.write(json.dumps({
                        "kind": "inflight", "peer": p, "msg_id": mid,
                        "age_s": round(age, 3), "type": mt,
                        "nbytes": nb}) + "\n")
                if stacks:
                    names = {t.ident: t.name
                             for t in threading.enumerate()}
                    for tid, frame in sys._current_frames().items():
                        lines = traceback.format_stack(frame)
                        f.write(json.dumps({
                            "kind": "stack", "tid": tid,
                            "thread": names.get(tid, "?"),
                            "frames": [ln.strip()
                                       for ln in lines[-24:]]}) + "\n")
                for prov in list(_dump_providers):
                    try:
                        for rec in prov() or ():
                            f.write(json.dumps(rec) + "\n")
                    except Exception:   # noqa: BLE001 — a provider bug
                        pass            # must not cost the ring's tape
            # commit: _dump_lock (held for this whole method) serializes
            # racing dumps, so a fault dump either finished before this
            # routine one started (the re-check below sees the flag) or
            # starts after (and correctly replaces the routine tape).
            # The ring lock is NOT held across the filesystem ops — a
            # slow disk must stall dumps, never the hot path's record().
            with self._lock:
                fault_already = self._fault_dumped
            if routine and fault_already:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                return None
            os.replace(tmp, path)
            with self._lock:
                self._dumps += 1
                if not routine:
                    self._fault_dumped = True
                self._last_dump = path
            return path
        except Exception:   # noqa: BLE001 — the black box must never
            return None     # turn a fault into a different fault
        finally:
            self._dump_lock.release()

    def reset(self) -> None:
        """Test isolation: empty the ring/in-flight table and unpin."""
        with self._lock:
            self._seq = 0
            for s in self._slots:
                s[0] = 0
                s[7] = None
            self._inflight.clear()
            self._beats.clear()
            self._rank_pinned = False
            self.rank = 0
            self._dumps = 0
            self._fault_dumped = False
            self._last_dump = None


RECORDER = FlightRecorder()


# module-level wrappers (the call-site idiom, like telemetry.trace)
def configure(rank: Optional[int] = None) -> None:
    RECORDER.configure(rank)


def record(kind: int, peer: int = -1, msg_type: int = 0, msg_id: int = -1,
           nbytes: int = 0, note: Optional[str] = None) -> None:
    RECORDER.record(kind, peer=peer, msg_type=msg_type, msg_id=msg_id,
                    nbytes=nbytes, note=note)


def begin_op(peer: int, msg_id: int, msg_type: int, nbytes: int = 0,
             record: bool = True) -> None:
    RECORDER.begin_op(peer, msg_id, msg_type, nbytes, record=record)


def end_op(peer: int, msg_id: int, ok: bool = True) -> None:
    RECORDER.end_op(peer, msg_id, ok)


def beat(name: str) -> None:
    RECORDER.beat(name)


def dump_global(reason: str, stacks: bool = False,
                routine: bool = False) -> Optional[str]:
    return RECORDER.dump(reason, stacks=stacks, routine=routine)


def dump_stats() -> Dict[str, Any]:
    return RECORDER.dump_stats()


def reset() -> None:
    RECORDER.reset()


# ---------------------------------------------------------------------- #
# fault-signal hook: dump before the previous disposition runs
# ---------------------------------------------------------------------- #
_installed: Dict[int, Any] = {}


def install_signal_handlers(signals=(signal.SIGTERM, signal.SIGABRT)
                            ) -> None:
    """Chain a dump in front of the existing SIGTERM/SIGABRT
    disposition (installed from Zoo.start). A handler installed LATER
    (e.g. bench.py's salvage) replaces this one — such owners call
    :func:`dump_global` themselves. No-op off the main thread."""
    for sig in signals:
        if sig in _installed:
            continue
        try:
            prev = signal.getsignal(sig)

            def _handler(signum, frame, _prev=prev):
                try:
                    RECORDER.record(EV_SIGNAL, note=f"signal {signum}")
                    RECORDER.dump(f"signal {signum}", stacks=True)
                except Exception:   # noqa: BLE001
                    pass
                if callable(_prev):
                    _prev(signum, frame)
                elif _prev != signal.SIG_IGN:
                    # SIG_DFL — or None, a handler installed by C code
                    # that Python cannot call: restore default + re-raise
                    # so the process still dies with the right status
                    # (swallowing SIGTERM would make it unkillable short
                    # of SIGKILL)
                    signal.signal(signum, signal.SIG_DFL)
                    os.kill(os.getpid(), signum)

            signal.signal(sig, _handler)
            _installed[sig] = prev
        except (ValueError, OSError):   # not the main thread / exotic env
            pass
