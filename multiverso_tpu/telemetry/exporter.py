"""Periodic metrics exporter: Dashboard + shard snapshots to disk.

Flag-gated (``metrics_interval_s`` > 0 and a ``metrics_dir``): a daemon
thread wakes every interval and writes

* ``metrics-rank<r>.jsonl`` — one JSON object per interval (append):
  ``{"ts": epoch_s, "rank": r, "monitors": {name: hist-dict}, "shards":
  {table: stats-dict}, "notes": {...}}`` — the same shape MSG_STATS
  returns, so ``tools/dump_metrics.py`` prints/diffs either source.
* ``metrics-rank<r>.prom`` — Prometheus text exposition (atomically
  replaced each interval), for scrape-style consumption.
* buffered trace spans (telemetry/trace.py) appended to
  ``trace-rank<r>.jsonl`` when tracing is on.

Off by default: with ``metrics_interval_s=0`` nothing starts and the
hot path never sees this module. One exporter per process (started by
the first PSService or Zoo.start, whichever comes first); ``stop()``
writes a final snapshot so short runs still leave a record.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Callable, Dict, Optional

from multiverso_tpu.utils import config, log

config.define_string(
    "metrics_dir", "",
    "directory for telemetry output (metrics-rank<r>.jsonl JSONL "
    "snapshots, metrics-rank<r>.prom Prometheus text, trace-rank<r>."
    "jsonl spans); empty disables file output")
config.define_float(
    "metrics_interval_s", 0.0,
    "seconds between background metrics exports to metrics_dir; "
    "0 disables the exporter thread (a final snapshot is still written "
    "at shutdown when metrics_dir is set)")


def _prom_name(name: str) -> str:
    """Monitor name -> a Prometheus-safe label value (names like
    ``table[we].add_rows`` keep their structure inside the label)."""
    return name.replace('"', "'").replace("\\", "/")


# monitor names of the forms ``table[X].op`` / ``ps[X].op`` carry the
# table identity inside the name; surface it as a first-class label
_NAME_TABLE_RE = re.compile(r"^(?:table|ps)\[([^\]]*)\]")


def _monitor_labels(name: str, rank) -> str:
    """Label set for one monitor line: ``name`` always, plus a ``table``
    label when the name embeds one, plus ``rank`` — so ONE scrape config
    covers an N-rank run (and the aggregator's rank="cluster" output)
    with aggregation by (table, rank) instead of regex-parsing names or
    output filenames."""
    parts = [f'name="{_prom_name(name)}"']
    m = _NAME_TABLE_RE.match(name)
    if m:
        parts.append(f'table="{_prom_name(m.group(1))}"')
    parts.append(f'rank="{rank}"')
    return "{" + ",".join(parts) + "}"


def prometheus_text(payload: Dict) -> str:
    """Render a stats payload (exporter record / MSG_STATS reply meta)
    as Prometheus text exposition."""
    lines = [
        "# HELP mv_monitor_count samples observed per monitor",
        "# TYPE mv_monitor_count counter",
        "# TYPE mv_monitor_total_ms counter",
        "# TYPE mv_monitor_p50_ms gauge",
        "# TYPE mv_monitor_p99_ms gauge",
        "# TYPE mv_monitor_max_ms gauge",
    ]
    rank = payload.get("rank", 0)
    for name in sorted(payload.get("monitors", {})):
        m = payload["monitors"][name]
        lbl = _monitor_labels(name, rank)
        lines.append(f"mv_monitor_count{lbl} {m.get('count', 0)}")
        lines.append(f"mv_monitor_total_ms{lbl} {m.get('sum_ms', 0.0)}")
        # percentile gauges only for monitors with TIMED samples: an
        # incr-only counter (count>0, timed=0) must show "no latency
        # data", not a fake 0.0 ms latency
        if m.get("timed", m.get("count")):
            for k in ("p50_ms", "p99_ms", "max_ms"):
                lines.append(f"mv_monitor_{k}{lbl} {m.get(k, 0.0)}")
    for table in sorted(payload.get("shards", {})):
        s = payload["shards"][table]
        for k, v in sorted(s.items()):
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                lines.append(
                    f'mv_shard_{k}{{table="{_prom_name(table)}",'
                    f'rank="{rank}"}} {v}')
    # memory plane (telemetry/memstats.py): process gauges + per-
    # component byte gauges off the MSG_STATS "memory" block
    mem = payload.get("memory")
    if isinstance(mem, dict):
        lines.append("# TYPE mv_mem_rss_mb gauge")
        lines.append("# TYPE mv_mem_component gauge")
        for k in ("rss_mb", "hwm_mb", "device_bytes", "samples"):
            v = mem.get(k)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                lines.append(f'mv_mem_{k}{{rank="{rank}"}} {v}')
        for k, v in sorted((mem.get("totals") or {}).items()):
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                lines.append(f'mv_mem_total_{k}{{rank="{rank}"}} {v}')
        for comp in sorted(mem.get("components") or {}):
            g = mem["components"][comp]
            if not isinstance(g, dict):
                continue
            for k, v in sorted(g.items()):
                if (isinstance(v, (int, float))
                        and not isinstance(v, bool)):
                    lines.append(
                        f'mv_mem_component{{component='
                        f'"{_prom_name(comp)}",field="{_prom_name(k)}",'
                        f'rank="{rank}"}} {v}')
    # device plane (telemetry/devstats.py): transfer/collective/compile
    # counters + the per-device live-buffer rollup off the MSG_STATS
    # "devices" block. Absent block (older peer, no device activity) =
    # no lines — the scrape simply lacks the series, never errors.
    dev = payload.get("devices")
    if isinstance(dev, dict):
        lines.append("# TYPE mv_dev_transfer_bytes counter")
        lines.append("# TYPE mv_dev_collective_calls counter")
        lines.append("# TYPE mv_dev_collective_bytes counter")
        lines.append("# TYPE mv_dev_compiles counter")
        lines.append("# TYPE mv_dev_live_bytes gauge")
        for direction, g in sorted((dev.get("transfers") or {}).items()):
            if not isinstance(g, dict):
                continue
            lbl = (f'{{direction="{_prom_name(direction)}",'
                   f'rank="{rank}"}}')
            lines.append(f"mv_dev_transfer_bytes{lbl} "
                         f"{g.get('bytes', 0)}")
            lines.append(f"mv_dev_transfer_ops{lbl} {g.get('ops', 0)}")
        for op, c in sorted((dev.get("collectives") or {}).items()):
            if not isinstance(c, dict):
                continue
            lbl = f'{{op="{_prom_name(op)}",rank="{rank}"}}'
            lines.append(f"mv_dev_collective_calls{lbl} "
                         f"{c.get('calls', 0)}")
            lines.append(f"mv_dev_collective_bytes{lbl} "
                         f"{c.get('bytes', 0)}")
            lines.append(f"mv_dev_collective_ms{lbl} {c.get('ms', 0.0)}")
        for label, c in sorted(
                (dev.get("compiles_by_mesh") or {}).items()):
            if not isinstance(c, dict):
                continue
            lbl = f'{{mesh="{_prom_name(label)}",rank="{rank}"}}'
            lines.append(f"mv_dev_compiles{lbl} {c.get('compiles', 0)}")
            lines.append(f"mv_dev_compile_seconds{lbl} "
                         f"{c.get('compile_s', 0.0)}")
        for device, g in sorted((dev.get("per_device") or {}).items()):
            if not isinstance(g, dict):
                continue
            lbl = f'{{device="{_prom_name(device)}",rank="{rank}"}}'
            lines.append(f"mv_dev_live_bytes{lbl} {g.get('bytes', 0)}")
            lines.append(f"mv_dev_live_arrays{lbl} {g.get('arrays', 0)}")
        if dev.get("hygiene_findings"):
            lines.append(f'mv_dev_hygiene_findings{{rank="{rank}"}} '
                         f"{dev['hygiene_findings']}")
    # tenant attribution plane (telemetry/tenants.py): per-(table,
    # tenant) serve counters + latency gauges + verdict state off the
    # MSG_STATS "tenants" block. Absent block = no series, like the
    # device plane.
    ten = payload.get("tenants")
    if isinstance(ten, dict):
        lines.append("# TYPE mv_tenant_served_total counter")
        lines.append("# TYPE mv_tenant_shed_total counter")
        lines.append("# TYPE mv_tenant_deferred_total counter")
        lines.append("# TYPE mv_tenant_p99_ms gauge")
        lines.append("# TYPE mv_tenant_share gauge")
        lines.append("# TYPE mv_tenant_episodes counter")
        for table in sorted(ten.get("tables") or {}):
            tt = ten["tables"][table]
            if not isinstance(tt, dict):
                continue
            for tn in sorted(tt):
                e = tt[tn]
                if not isinstance(e, dict):
                    continue
                lbl = (f'{{table="{_prom_name(table)}",'
                       f'tenant="{_prom_name(tn)}",rank="{rank}"}}')
                lines.append(f"mv_tenant_served_total{lbl} "
                             f"{e.get('served', 0)}")
                lines.append(f"mv_tenant_shed_total{lbl} "
                             f"{e.get('shed', 0)}")
                lines.append(f"mv_tenant_deferred_total{lbl} "
                             f"{e.get('deferred', 0)}")
                lines.append(f"mv_tenant_max_age_s{lbl} "
                             f"{e.get('max_age_s', 0)}")
                h = e.get("infer") or {}
                if h.get("timed"):
                    lines.append(f"mv_tenant_p50_ms{lbl} "
                                 f"{h.get('p50_ms', 0.0)}")
                    lines.append(f"mv_tenant_p99_ms{lbl} "
                                 f"{h.get('p99_ms', 0.0)}")
        for tn, sh in sorted((ten.get("shares") or {}).items()):
            if isinstance(sh, (int, float)):
                lines.append(f'mv_tenant_share{{tenant='
                             f'"{_prom_name(tn)}",rank="{rank}"}} {sh}')
        for k, a in sorted((ten.get("admission") or {}).items()):
            if not isinstance(a, dict):
                continue
            lbl = f'{{budget="{_prom_name(k)}",rank="{rank}"}}'
            lines.append(f"mv_tenant_budget_admitted{lbl} "
                         f"{a.get('admitted', 0)}")
            lines.append(f"mv_tenant_budget_shed{lbl} "
                         f"{a.get('shed', 0)}")
        lines.append(f'mv_tenant_episodes{{rank="{rank}"}} '
                     f"{ten.get('episodes', 0)}")
        lines.append(f'mv_tenant_verdict_active{{rank="{rank}"}} '
                     f"{1 if ten.get('active') else 0}")
    # SLO sentinel (telemetry/slo.py): per-objective burn-rate gauges +
    # firing state + episode counters off the MSG_STATS "slo" block.
    # Absent block (sentinel disarmed) = no series, like every plane.
    slo = payload.get("slo")
    if isinstance(slo, dict):
        lines.append("# TYPE mv_slo_firing gauge")
        lines.append("# TYPE mv_slo_burn_fast gauge")
        lines.append("# TYPE mv_slo_burn_slow gauge")
        lines.append("# TYPE mv_slo_value gauge")
        lines.append("# TYPE mv_slo_objective_episodes counter")
        lines.append("# TYPE mv_slo_episodes counter")
        for name in sorted(slo.get("objectives") or {}):
            o = slo["objectives"][name]
            if not isinstance(o, dict):
                continue
            lbl = (f'{{objective="{_prom_name(name)}",'
                   f'kind="{_prom_name(o.get("kind") or "?")}",'
                   f'table="{_prom_name(o.get("table") or "")}",'
                   f'rank="{rank}"}}')
            lines.append(f"mv_slo_firing{lbl} "
                         f"{1 if o.get('firing') else 0}")
            lines.append(f"mv_slo_burn_fast{lbl} "
                         f"{o.get('burn_fast', 0.0)}")
            lines.append(f"mv_slo_burn_slow{lbl} "
                         f"{o.get('burn_slow', 0.0)}")
            v = o.get("value")
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                lines.append(f"mv_slo_value{lbl} {v}")
            lines.append(f"mv_slo_objective_episodes{lbl} "
                         f"{o.get('episodes', 0)}")
        lines.append(f'mv_slo_episodes{{rank="{rank}"}} '
                     f"{slo.get('episodes', 0)}")
        s = slo.get("straggler")
        if isinstance(s, dict) and isinstance(s.get("rank"), int):
            lines.append(
                f'mv_slo_straggler_rank{{attribution='
                f'"{_prom_name(s.get("attribution") or "?")}",'
                f'rank="{rank}"}} {s["rank"]}')
    return "\n".join(lines) + "\n"


class MetricsExporter:
    """One per process; see module docstring."""

    def __init__(self, rank: int, directory: str, interval_s: float,
                 stats_fn: Callable[[], Dict]):
        self.rank = int(rank)
        self.directory = directory
        self.interval_s = float(interval_s)
        self._stats_fn = stats_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # serializes export_once: the periodic thread and export_global
        # (PSContext.close) share the JSONL/.prom/.tmp files — two
        # unsynchronized appends can interleave mid-line and corrupt a
        # record
        self._io_lock = threading.Lock()

    # ------------------------------------------------------------------ #
    def start(self) -> "MetricsExporter":
        if self.interval_s > 0 and self.directory and self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="mv-metrics", daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.export_once()
            except Exception as e:  # noqa: BLE001 — telemetry must not
                log.error("metrics export failed: %s", e)  # kill the run

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self.directory:
            try:
                self.export_once()   # final snapshot, even interval=0
            except Exception as e:  # noqa: BLE001
                log.error("final metrics export failed: %s", e)

    # ------------------------------------------------------------------ #
    def export_once(self) -> Dict:
        """One snapshot -> JSONL append + .prom replace (+ trace drain).
        Returns the record (tests consume it directly). Serialized on
        ``_io_lock`` — see __init__."""
        payload = dict(self._stats_fn())
        payload["ts"] = round(time.time(), 3)
        payload.setdefault("rank", self.rank)
        if not self.directory:
            return payload
        with self._io_lock:
            os.makedirs(self.directory, exist_ok=True)
            jpath = os.path.join(self.directory,
                                 f"metrics-rank{self.rank}.jsonl")
            with open(jpath, "a") as f:
                f.write(json.dumps(payload) + "\n")
            ppath = os.path.join(self.directory,
                                 f"metrics-rank{self.rank}.prom")
            tmp = ppath + ".tmp"
            with open(tmp, "w") as f:
                f.write(prometheus_text(payload))
            os.replace(tmp, ppath)
        from multiverso_tpu.telemetry import trace as _trace
        _trace.dump_to(self.directory)
        # step-profiler records stream alongside the spans (same
        # drain-on-dump contract): profile-rank<r>.jsonl feeds
        # tools/mvprof.py and dump_metrics show/diff
        from multiverso_tpu.telemetry import profiler as _profiler
        _profiler.dump_to(self.directory)
        return payload


# ------------------------------------------------------------------ #
# process-global lifecycle (first starter wins; idempotent stop)
# ------------------------------------------------------------------ #
_global: Optional[MetricsExporter] = None
_global_lock = threading.Lock()


def default_stats_fn() -> Dict:
    """Dashboard-only payload for processes without a PSService (the
    service installs a richer one that adds its shard registry).
    ``pid`` identifies the OS process: Dashboard monitors are
    PROCESS-global, so a cluster merge over in-process multi-rank
    worlds (test fixtures, bench workers) must pool each process's
    monitors once, not once per rank (aggregator.merge_cluster keys on
    the addr host + pid)."""
    from multiverso_tpu.utils.dashboard import Dashboard
    out = {
        "monitors": {name: snap.hist_dict()
                     for name, snap in Dashboard.snapshot().items()},
        "notes": Dashboard.notes(),
        "shards": {},
        "pid": os.getpid(),
    }
    # device plane: same additive "devices" block PSService.stats_payload
    # carries, so a Zoo-only process (no PS) still exports mv_dev_*
    try:
        from multiverso_tpu.telemetry import devstats as _devstats
        devices = _devstats.stats_snapshot()
        if devices:
            out["devices"] = devices
    except Exception:   # noqa: BLE001 — telemetry never breaks export
        pass
    return out


def ensure_started(rank: int,
                   stats_fn: Optional[Callable[[], Dict]] = None
                   ) -> Optional[MetricsExporter]:
    """Start the process exporter if flags enable it (idempotent; the
    first caller's ``stats_fn`` wins — a PSService starting after Zoo
    upgrades the Dashboard-only exporter to its richer payload)."""
    global _global
    directory = config.get_flag("metrics_dir")
    interval = config.get_flag("metrics_interval_s")
    if not directory:
        return None
    with _global_lock:
        if _global is None:
            _global = MetricsExporter(
                rank, directory, interval,
                stats_fn or default_stats_fn).start()
        elif stats_fn is not None and \
                _global._stats_fn is default_stats_fn:
            _global._stats_fn = stats_fn
        return _global


def export_global() -> None:
    """Write one snapshot through the process exporter WITHOUT stopping
    it — the per-context shutdown hook (a process may hold several
    PSContexts; one closing must not kill telemetry for the rest; the
    daemon thread dies with the process or at :func:`stop_global`)."""
    with _global_lock:
        exp = _global
    if exp is not None and exp.directory:
        try:
            exp.export_once()
        except Exception as e:  # noqa: BLE001 — telemetry never blocks
            log.error("metrics export at context close failed: %s", e)


def stop_global() -> None:
    global _global
    with _global_lock:
        exp, _global = _global, None
    if exp is not None:
        exp.stop()
