"""Memory observability plane: the per-component byte ledger.

Layer 0 of the reference multiverso is an explicitly ACCOUNTED memory
system — ref-counted ``Blob``s over a pooled ``SmartAllocator``
(ref include/multiverso/blob.h, allocator.h) — where every byte has an
owner. The JAX port measures everything except bytes: PRs 3/4/6/9 built
latency histograms, a flight recorder, cluster stats, and a step
profiler, yet the framework carries at least five unmetered hoards —
COW-retired epoch buffers pinned by readers (PR 5), send-window replay
tails retained past ack (PR 7), replica snapshots + device hot-row
caches (PR 8), checkpoint staging (PR 7), and the PR-1 get cache — and
the three worst review-caught bugs to date (the ``_pin_buf`` identity
anchor holding a full retired table, the per-probe socket leak, the
flusher-thread/table leak) were silent memory leaks no surface could
have flagged. This module is the byte-level answer:

* **Ledger** (always on, flightrec-style): each owning component
  registers a gauge callback it already knows how to compute —
  ``RowShard.memory_stats`` (live table buffers per dtype, pinned-epoch
  count x retired-buffer bytes with per-pin age, apply-queue pending
  bytes), ``_SendWindow.memory_stats`` (pending + replay-retained
  frames/bytes), ``Table.memory_stats`` (get cache + prefetch staging),
  ``ReadReplica.memory_stats`` (snapshot buffer, device cache, staging
  copy), checkpoint/failover staging + on-disk tag bytes. Registration
  is one dict store at construct time; gauges are computed only when a
  consumer PULLS (stats pull, sampler tick, fault dump) — the hot path
  never touches this module at all, which is the whole cost story.
* **Sampler** (flag ``memstats_interval_s``, default off): a daemon
  thread snapshotting host RSS from ``/proc/self/status``, a JAX
  device-buffer census via ``jax.live_arrays()`` grouped by
  (shape, dtype, device), and optional ``tracemalloc`` top-N when
  ``memstats_tracemalloc`` is set. Samples feed a bounded history the
  leak verdicts and bench peaks read.
* **Leak verdicts** (driven by the PR-4 watchdog's sweep and by every
  sample): a pin held past ``memstats_pin_age_s`` with retired buffers
  behind it -> ``epoch-hoard``; replay-retained bytes growing
  monotonically across ``RETENTION_K`` samples with a live owner ->
  ``retention-leak``; RSS slope over the rolling window past
  ``memstats_rss_slope_mb_s`` -> ``rss-creep``. Each verdict emits ONE
  structured log + one flight-recorder event per episode (deduped
  until the condition clears), never a per-sweep flood.
* **OOM forensics**: a ``MemoryError`` on the serve path or an RSS
  soft-limit trip (``memstats_rss_limit_mb``) dumps the ledger +
  device census + sample history through the flight recorder's fault-
  dump path (``flightrec.add_dump_provider``), so
  ``tools/postmortem.py`` renders a memory timeline next to the wire
  timeline. EVERY fault dump carries the ledger — an OOM-adjacent
  wedge is diagnosable from the artifact alone.

The ledger rides MSG_STATS as the ``"memory"`` block
(:func:`stats_snapshot`; merged per-rank by ``telemetry/aggregator.py``
with the same (host, pid) process dedupe as monitors), ``tools/mvtop.py``
renders the memory panel, and the exporter emits ``mv_mem_*``
Prometheus gauges. See docs/OBSERVABILITY.md "Memory view".
"""

from __future__ import annotations

import collections
import itertools
import json
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from multiverso_tpu.telemetry import flightrec as _flight
from multiverso_tpu.utils import config, log

config.define_float(
    "memstats_interval_s", 0.0,
    "seconds between process memory samples (host RSS from /proc, JAX "
    "device-buffer census via jax.live_arrays, ledger totals) feeding "
    "the leak verdicts and bench peaks; 0 disables the sampler thread "
    "entirely — the byte ledger itself is always on and pull-only "
    "(docs/OBSERVABILITY.md 'Memory view')")
config.define_bool(
    "memstats_tracemalloc", False,
    "include a tracemalloc top-N (by allocated bytes, per source line) "
    "in every memory sample; starts tracemalloc on first use, which "
    "costs ~2x on every Python allocation — triage only, never leave "
    "on in production")
config.define_float(
    "memstats_pin_age_s", 30.0,
    "read-epoch pin age (s) past which a shard pin with retired COW "
    "buffers behind it raises the 'epoch-hoard' leak verdict (one "
    "structured log + flightrec event per episode)")
config.define_float(
    "memstats_rss_slope_mb_s", 50.0,
    "host-RSS growth rate (MB/s) over the sampler's rolling window "
    "past which the 'rss-creep' leak verdict fires; needs "
    "memstats_interval_s > 0 for the window to exist")
config.define_float(
    "memstats_rss_limit_mb", 0.0,
    "soft RSS limit (MB): a sample observing VmRSS above it dumps the "
    "ledger + device census through the flight recorder's fault path "
    "(OOM forensics, one dump per episode); 0 disables the trip")

# consecutive samples over which a component's replay-retained bytes
# must grow monotonically (with a live owner) to call 'retention-leak'
RETENTION_K = 3
# bounded sample history (at the 1 Hz triage cadence: ~4 min of tape)
HISTORY = 240
# device-census groups kept per sample/dump (by bytes, descending)
CENSUS_TOP = 12

# new flight-recorder event ids (flightrec.py owns the registry; these
# aliases keep call sites readable)
EV_MEM_HOARD = _flight.EV_MEM_HOARD
EV_MEM_LEAK = _flight.EV_MEM_LEAK
EV_MEM_RSS = _flight.EV_MEM_RSS
EV_MEM_DUMP = _flight.EV_MEM_DUMP

# gauge keys summed into the ledger totals even though they are counts,
# not byte figures (everything ending in "_bytes" sums automatically)
_COUNT_TOTALS = ("pins", "pinned_epochs", "retired_epochs",
                 "retained_frames", "pending_ops", "armed_frames")


def read_rss() -> Tuple[Optional[float], Optional[float]]:
    """(VmRSS MB, VmHWM MB) from ``/proc/self/status`` — the kernel's
    own resident-set reading and its process-lifetime high-water mark
    (the peak no sampling cadence can miss). (None, None) off-Linux."""
    try:
        with open("/proc/self/status") as f:
            txt = f.read()
    except OSError:
        return None, None
    out: List[Optional[float]] = [None, None]
    for i, tag in enumerate(("VmRSS:", "VmHWM:")):
        j = txt.find(tag)
        if j >= 0:
            try:
                out[i] = round(int(txt[j:].split()[1]) / 1024.0, 3)
            except (ValueError, IndexError):
                pass
    if out[1] is None:
        # stripped /proc (container kernels) may omit VmHWM: fall back
        # to getrusage's kernel-tracked peak (KB on Linux)
        try:
            import resource
            out[1] = round(resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss / 1024.0, 3)
        except Exception:   # noqa: BLE001
            pass
    return out[0], out[1]


def device_census(top: int = CENSUS_TOP) -> Optional[Dict[str, Any]]:
    """Live JAX device-buffer census grouped by (shape, dtype, device):
    total bytes/arrays plus the ``top`` biggest groups. Pull-only — the
    walk costs O(live arrays) and runs ONLY on a sample or fault dump,
    never on any hot path. None when JAX is unavailable/unhappy."""
    try:
        import jax
        arrays = jax.live_arrays()
    except Exception:   # noqa: BLE001 — census is best-effort telemetry
        return None
    groups: Dict[Tuple, List[int]] = {}
    total = 0
    for a in arrays:
        try:
            nb = int(a.nbytes)
            dev = ",".join(sorted(str(d) for d in a.devices()))
            key = (str(a.shape), str(a.dtype), dev)
        except Exception:   # noqa: BLE001 — a deleted/donated buffer
            continue        # mid-walk must not fail the census
        g = groups.setdefault(key, [0, 0])
        g[0] += nb
        g[1] += 1
        total += nb
    head = sorted(groups.items(), key=lambda kv: -kv[1][0])[:top]
    return {
        "bytes": total, "arrays": sum(g[1] for g in groups.values()),
        "groups": len(groups),
        "top": [{"shape": k[0], "dtype": k[1], "device": k[2],
                 "bytes": v[0], "count": v[1]} for k, v in head],
    }


def _retained_series(components: Dict[str, Dict]) -> Dict[str, int]:
    """The per-sample retention readings the leak verdict compares:
    one entry per component reporting ``retained_bytes``, plus one per
    OWNER (``name@owner``) when the component breaks retention down —
    the verdict judges owners separately, so a dead owner's re-armed
    tail cannot mask a live owner's hoard."""
    out: Dict[str, int] = {}
    for n, g in components.items():
        if isinstance(g.get("retained_bytes"), int):
            out[n] = g["retained_bytes"]
        owners = g.get("owners")
        if isinstance(owners, dict):
            for o, og in owners.items():
                if isinstance(og, dict) and isinstance(
                        og.get("retained_bytes"), int):
                    out[f"{n}@{o}"] = og["retained_bytes"]
    return out


def _tracemalloc_top(ledger: "MemLedger",
                     n: int = 10) -> Optional[List[Dict]]:
    import tracemalloc
    if not tracemalloc.is_tracing():
        tracemalloc.start()
        ledger._tracemalloc_started = True   # ours to stop later
        return None   # first sample after start has nothing to rank yet
    stats = tracemalloc.take_snapshot().statistics("lineno")[:n]
    return [{"where": str(s.traceback), "kb": round(s.size / 1024.0, 1),
             "count": s.count} for s in stats]


def _tracemalloc_release(ledger: "MemLedger") -> None:
    """Stop tracemalloc iff WE started it: the ~2x per-allocation tax
    must not outlive the flag (or a test's ledger reset) — but a
    tracing session some other owner started is not ours to kill."""
    if not ledger._tracemalloc_started:
        return
    try:
        import tracemalloc
        if tracemalloc.is_tracing():
            tracemalloc.stop()
    except Exception:   # noqa: BLE001
        pass
    ledger._tracemalloc_started = False


class MemLedger:
    """Process-global byte ledger + sampler + verdict engine (one per
    process, like the FlightRecorder; several in-process ranks share it
    — the same documented (host, pid) collapse as the monitors)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        # name -> (weakref to the owning component, gauge method name).
        # Weak: the ledger must never extend a component's lifetime —
        # a telemetry registry keeping dead shards alive would be this
        # plane's own retention leak.
        self._components: Dict[str, Tuple[weakref.ref, str]] = {}
        self._suffix = itertools.count(1)
        self._history: collections.deque = collections.deque(
            maxlen=HISTORY)
        self._verdicts: collections.deque = collections.deque(maxlen=64)
        self._active: set = set()   # (kind, component) episodes asserted
        self._peaks: Dict[str, float] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._tracemalloc_started = False   # we own the stop iff True

    # ------------------------------------------------------------------ #
    # registration (construct-time, one dict store)
    # ------------------------------------------------------------------ #
    def register(self, name: str, obj: Any,
                 attr: str = "memory_stats") -> str:
        """Register ``obj`` as the owner of the gauges its ``attr``()
        method computes; returns the (collision-suffixed) final name.
        Dead components drop silently at the next snapshot."""
        with self._lock:
            final = name
            while final in self._components:
                ref, _ = self._components[final]
                if ref() is None:   # dead entry: reuse its name
                    break
                final = f"{name}#{next(self._suffix)}"
            self._components[final] = (weakref.ref(obj), attr)
            return final

    def unregister(self, name: str) -> None:
        with self._lock:
            self._components.pop(name, None)

    # ------------------------------------------------------------------ #
    # pulls
    # ------------------------------------------------------------------ #
    def snapshot(self) -> Dict[str, Any]:
        """{"components": {name: gauges}, "totals": {...}} — computed
        by PULLING every live component's gauge callback. Dead weakrefs
        are pruned here; a gauge that raises becomes an error entry,
        never a failed snapshot."""
        with self._lock:
            items = list(self._components.items())
        components: Dict[str, Dict] = {}
        totals: Dict[str, float] = {}
        dead: List[str] = []
        for name, (ref, attr) in items:
            obj = ref()
            if obj is None:
                dead.append(name)
                continue
            try:
                g = getattr(obj, attr)()
            except Exception as e:   # noqa: BLE001 — one bad component
                components[name] = {
                    "error": f"{type(e).__name__}: {e}"[:120]}
                continue             # must not hide the rest
            if not isinstance(g, dict):
                continue
            components[name] = g
            for k, v in g.items():
                if (isinstance(v, (int, float))
                        and not isinstance(v, bool)
                        and (k.endswith("_bytes") or k in _COUNT_TOTALS)):
                    totals[k] = totals.get(k, 0) + v
        if dead:
            with self._lock:
                for name in dead:
                    ent = self._components.get(name)
                    if ent is not None and ent[0]() is None:
                        del self._components[name]
        totals = {k: int(v) for k, v in sorted(totals.items())}
        return {"components": components, "totals": totals}

    def sample_once(self) -> Dict[str, Any]:
        """One full sample: RSS + ledger totals + device census (+
        tracemalloc when flagged), appended to the bounded history;
        updates the peak gauges and runs the verdict sweep. The
        sampler thread, the watchdog-independent manual drivers
        (tests, ``bench_extra``) and nothing else call this."""
        rss, hwm = read_rss()
        snap = self.snapshot()
        census = device_census()
        sample: Dict[str, Any] = {
            "ts": round(time.time(), 3),
            "rss_mb": rss, "hwm_mb": hwm,
            "device_bytes": None if census is None else census["bytes"],
            "totals": snap["totals"],
            # per-component (and, for windows, per-OWNER) replay
            # retention, kept per sample so the retention-leak verdict
            # can see monotonic growth at the granularity it judges
            "retained": _retained_series(snap["components"]),
        }
        if config.get_flag("memstats_tracemalloc"):
            try:
                tm = _tracemalloc_top(self)
                if tm is not None:
                    sample["tracemalloc"] = tm
            except Exception:   # noqa: BLE001 — triage aid, best-effort
                pass
        else:
            # flag cleared mid-run: release the ~2x allocation tax our
            # earlier flagged sample turned on
            _tracemalloc_release(self)
        with self._lock:
            self._history.append(sample)
            self._bump_peak("rss_mb", hwm if hwm is not None else rss)
            self._bump_peak("device_bytes", sample["device_bytes"])
            t = snap["totals"]
            self._bump_peak("retained_bytes", t.get("retained_bytes"))
            self._bump_peak("pinned_epochs", t.get("pinned_epochs"))
        self.check_verdicts(snap=snap, sample=sample)
        full = dict(sample)
        full["components"] = snap["components"]
        if census is not None:
            full["census"] = census
        return full

    def _bump_peak(self, key: str, v) -> None:
        if isinstance(v, (int, float)) and v > self._peaks.get(
                key, float("-inf")):
            self._peaks[key] = v

    def maybe_sample(self) -> Optional[Dict[str, Any]]:
        """The flag-gated entry: None without touching anything when
        ``memstats_interval_s`` is 0 — the null branch the flag-off
        tests pin (zero allocations, zero samples)."""
        if config.get_flag("memstats_interval_s") <= 0:
            return None
        return self.sample_once()

    # ------------------------------------------------------------------ #
    # leak verdicts
    # ------------------------------------------------------------------ #
    def check_verdicts(self, snap: Optional[Dict] = None,
                       sample: Optional[Dict] = None) -> List[Dict]:
        """One verdict sweep over the live gauges (+ the sample history
        for the windowed verdicts). Called by the PR-4 watchdog's
        ``check_once`` and by every sample; each (kind, component)
        episode emits ONE structured log + flightrec event and stays
        silent until the condition clears and re-fires."""
        if snap is None:
            snap = self.snapshot()
        out: List[Dict] = []
        pin_age = config.get_flag("memstats_pin_age_s")
        for name, g in snap["components"].items():
            age = g.get("oldest_pin_age_s")
            rb = g.get("retired_bytes")
            key = ("epoch-hoard", name)
            if (isinstance(age, (int, float)) and isinstance(rb, int)
                    and age > pin_age and rb > 0):
                v = self._emit(key, EV_MEM_HOARD, {
                    "oldest_pin_age_s": round(age, 3),
                    "retired_bytes": rb,
                    "retired_epochs": g.get("retired_epochs"),
                    "pins": g.get("pins")}, nbytes=rb)
                if v:
                    out.append(v)
            else:
                self._active.discard(key)
        with self._lock:
            hist = list(self._history)
        if len(hist) >= RETENTION_K:
            tail = hist[-RETENTION_K:]
            for name, g in snap["components"].items():
                if "retained_bytes" not in g:
                    continue
                owners = g.get("owners")
                if isinstance(owners, dict) and owners:
                    # per-OWNER granularity: one dead owner's re-armed
                    # tail (failover WORKING — frames awaiting the
                    # restored incarnation) must not mask another LIVE
                    # owner hoarding acked frames nothing prunes
                    targets = [(f"{name}@{o}", og)
                               for o, og in owners.items()
                               if isinstance(og, dict)]
                else:
                    targets = [(name, g)]
                for tkey, tg in targets:
                    key = ("retention-leak", tkey)
                    series = [s.get("retained", {}).get(tkey)
                              for s in tail]
                    growing = (all(isinstance(v, int) for v in series)
                               and all(series[i] < series[i + 1]
                                       for i in range(len(series) - 1))
                               and series[0] > 0)
                    live_owner = not tg.get("armed_frames")
                    if growing and live_owner:
                        v = self._emit(key, EV_MEM_LEAK, {
                            "retained_bytes": series[-1],
                            "grew_over_samples": len(series),
                            "retained_frames": tg.get(
                                "retained_frames")},
                            nbytes=series[-1])
                        if v:
                            out.append(v)
                    else:
                        self._active.discard(key)
        out.extend(self._rss_verdicts(hist, sample))
        return out

    def _rss_verdicts(self, hist: List[Dict],
                      sample: Optional[Dict]) -> List[Dict]:
        out: List[Dict] = []
        slope_mb_s = config.get_flag("memstats_rss_slope_mb_s")
        window = [s for s in hist
                  if isinstance(s.get("rss_mb"), (int, float))]
        key = ("rss-creep", "process")
        if len(window) >= 2 and slope_mb_s > 0:
            a, b = window[0], window[-1]
            dt = b["ts"] - a["ts"]
            slope = (b["rss_mb"] - a["rss_mb"]) / dt if dt > 0 else 0.0
            if slope > slope_mb_s:
                v = self._emit(key, EV_MEM_RSS, {
                    "slope_mb_s": round(slope, 3),
                    "window_s": round(dt, 3),
                    "rss_mb": b["rss_mb"]})
                if v:
                    out.append(v)
            else:
                self._active.discard(key)
        limit = config.get_flag("memstats_rss_limit_mb")
        key = ("rss-limit", "process")
        # judge the limit ONLY against a fresh sample: the watchdog's
        # sample-less sweeps must leave the episode state untouched —
        # discarding it there would let a sustained over-limit RSS
        # re-fire the verdict (and a full forensics dump) on every
        # sampler tick instead of once per episode
        if sample is not None and limit > 0:
            rss = sample.get("rss_mb")
            if isinstance(rss, (int, float)) and rss > limit:
                v = self._emit(key, EV_MEM_RSS, {
                    "rss_mb": rss, "limit_mb": limit})
                if v:
                    out.append(v)
                    # OOM forensics: the soft-limit trip IS the moment
                    # to preserve the ledger — dump through the flight
                    # recorder's fault path (one dump per episode; the
                    # providers attach the ledger + census + history)
                    oom_dump(f"memstats: rss {rss:.1f} MB over soft "
                             f"limit {limit:.1f} MB")
            else:
                self._active.discard(key)
        return out

    def _emit(self, key: Tuple[str, str], ev: int, info: Dict,
              nbytes: int = 0) -> Optional[Dict]:
        with self._lock:
            if key in self._active:
                return None
            self._active.add(key)
            verdict = {"kind": key[0], "component": key[1],
                       "ts": round(time.time(), 3)}
            verdict.update(info)
            self._verdicts.append(verdict)
        _flight.record(ev, nbytes=int(nbytes),
                       note=f"{key[0]} {key[1]}"[:120])
        log.error("memstats: %s verdict %s", key[0], json.dumps(verdict))
        return verdict

    # ------------------------------------------------------------------ #
    # consumer shapes
    # ------------------------------------------------------------------ #
    def stats_snapshot(self) -> Dict[str, Any]:
        """The MSG_STATS ``"memory"`` block (and the exporter's): the
        live ledger + RSS, the last sample's device total, and the
        recent verdicts. Pure JSON-safe data, process-global like the
        monitors (the aggregator dedupes by (host, pid))."""
        snap = self.snapshot()
        rss, hwm = read_rss()
        with self._lock:
            last = self._history[-1] if self._history else None
            verdicts = list(self._verdicts)[-8:]
            samples = len(self._history)
        return {
            "rss_mb": rss, "hwm_mb": hwm,
            "device_bytes": (last or {}).get("device_bytes"),
            "totals": snap["totals"],
            "components": snap["components"],
            "samples": samples,
            "verdicts": verdicts,
        }

    def samples(self) -> List[Dict]:
        with self._lock:
            return list(self._history)

    def verdicts(self) -> List[Dict]:
        with self._lock:
            return list(self._verdicts)

    def bench_extra(self) -> Dict[str, Any]:
        """The bench record's ``extra.memory``: one final sample, then
        the run's peaks — VmHWM for RSS (kernel-tracked, so no sampling
        cadence can under-read it), sampled high-waters for the ledger
        hoards and the device census."""
        final = self.sample_once()
        with self._lock:
            peaks = dict(self._peaks)
            samples = len(self._history)
        return {
            "peak_rss_mb": peaks.get("rss_mb", final.get("hwm_mb")),
            "peak_retained_bytes": int(peaks.get("retained_bytes", 0)),
            "peak_pinned_epochs": int(peaks.get("pinned_epochs", 0)),
            "device_high_water_bytes": (
                None if "device_bytes" not in peaks
                else int(peaks["device_bytes"])),
            "rss_mb": final.get("rss_mb"),
            "samples": samples,
            "verdicts": len(self.verdicts()),
        }

    # ------------------------------------------------------------------ #
    # sampler lifecycle
    # ------------------------------------------------------------------ #
    def start(self) -> "MemLedger":
        with self._lock:
            if self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._loop, name="mv-memstats", daemon=True)
                self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(
                max(config.get_flag("memstats_interval_s"), 0.05)):
            try:
                self.sample_once()
            except Exception as e:   # noqa: BLE001 — the sampler must
                log.error("memstats sample failed: %s", e)  # outlive bugs

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def reset(self) -> None:
        """Test isolation: stop the sampler and forget history/
        verdicts/episodes/peaks. Component REGISTRATIONS are kept:
        they are weakrefs (a test's dead shards prune themselves at
        the next snapshot), and module-level gauges registered at
        import time (checkpoint.py's) register exactly once per
        process — clearing them here would leave that plane dark for
        every test after the first."""
        self.stop()
        _tracemalloc_release(self)
        with self._lock:
            self._history.clear()
            self._verdicts.clear()
            self._active.clear()
            self._peaks.clear()


LEDGER = MemLedger()


# module-level wrappers (the call-site idiom, like flightrec/watchdog)
def register(name: str, obj: Any, attr: str = "memory_stats") -> str:
    return LEDGER.register(name, obj, attr)


def stats_snapshot() -> Dict[str, Any]:
    return LEDGER.stats_snapshot()


def sample_once() -> Dict[str, Any]:
    return LEDGER.sample_once()


def maybe_sample() -> Optional[Dict[str, Any]]:
    return LEDGER.maybe_sample()


def check_verdicts() -> List[Dict]:
    return LEDGER.check_verdicts()


def bench_extra() -> Dict[str, Any]:
    return LEDGER.bench_extra()


def ensure_started() -> Optional[MemLedger]:
    """Start the process sampler if the flag enables it (idempotent;
    the first PSService calls this, same lifecycle as the watchdog)."""
    if config.get_flag("memstats_interval_s") <= 0:
        return None
    return LEDGER.start()


def stop_global() -> None:
    LEDGER.stop()


def reset() -> None:
    LEDGER.reset()


def oom_dump(reason: str) -> Optional[str]:
    """OOM forensics entry: record the event and dump the ring + ledger
    (+ stacks) through the flight recorder's fault path. Called on a
    ``MemoryError`` crossing the serve path and on the RSS soft-limit
    trip; never raises (the fault must still fail its own way)."""
    try:
        _flight.record(EV_MEM_DUMP, note=reason[:120])
        return _flight.dump_global(reason, stacks=True)
    except Exception:   # noqa: BLE001
        return None


# ---------------------------------------------------------------------- #
# fault-dump provider: every flight-recorder dump carries the ledger +
# census + bounded sample history, so postmortem renders the memory
# timeline next to the wire timeline without any extra artifact
# ---------------------------------------------------------------------- #
def _dump_records() -> List[Dict]:
    recs: List[Dict] = []
    snap = LEDGER.snapshot()
    rss, hwm = read_rss()
    census = device_census()
    recs.append({
        "kind": "memory", "ts": round(time.time(), 3),
        "rss_mb": rss, "hwm_mb": hwm,
        "totals": snap["totals"], "components": snap["components"],
        "census": census, "verdicts": LEDGER.verdicts()[-8:],
    })
    for s in LEDGER.samples()[-48:]:
        recs.append({"kind": "memsample", "ts": s.get("ts"),
                     "rss_mb": s.get("rss_mb"),
                     "device_bytes": s.get("device_bytes"),
                     "totals": s.get("totals", {})})
    return recs


_flight.add_dump_provider(_dump_records)
