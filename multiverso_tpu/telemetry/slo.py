"""SLO sentinel — declarative objectives judged on every cluster poll.

The repo measures everything (histograms, flight recorder, cluster
aggregator, profiler, memstats, devstats, tenant ledger) but judged
almost nothing continuously: the only standing verdicts were one-off
sweeps (noisy-neighbor, leak). This module is the judging layer every
real fleet has between metrics and action:

* a **declarative spec** (flag ``slo_spec``, JSON path-or-inline like
  ``faults_spec``) declares per-(table, class, tenant) objectives —
  serve/add latency p99, served staleness, shed rate, availability,
  stall fraction, steady recompiles, chaos recovery, scale-efficiency
  floors;
* every objective is evaluated on each PR-6 aggregator poll via
  **multi-window burn-rate math** (a fast and a slow window over the
  aggregator's rolling history; pure functions, oracle-testable):
  ``burn = (bad_polls / measured_polls) / error_budget`` per window,
  where ``error_budget = 1 - target``. An episode FIRES when the fast
  burn reaches ``fast_burn`` AND the slow burn reaches ``slow_burn``
  (the classic fast+slow guard: pages on real sustained burn, not one
  noisy poll), HOLDS while firing, and CLEARS when the fast window is
  back inside budget (fast burn < 1). Polls where an objective has no
  evidence (no traffic, block absent) sit out — silence is not a
  violation;
* the **episode lifecycle** is PR-18-style: fire once -> hold -> clear,
  one structured ``log.error`` JSON + one flightrec ``slo.fired`` /
  ``slo.cleared`` EV pair per episode, a line appended to
  ``<metrics_dir>/alerts.jsonl``, ``mv_slo_*`` gauges in the exporter,
  an mvtop SLO panel, and a postmortem "SLO episodes" section;
* a **straggler detector** (:func:`straggler`) merges the per-rank
  profile + health blocks of one cluster record to name the slowest
  rank with attribution (compute vs wire vs stall) — the instrument
  ROADMAP item 1 needs before multi-host makes stragglers invisible.

The availability SLI deserves a note: one-shot health probes answer
even when a rank's data plane is wedged (that is the PR-4 design), so
reachability alone cannot see a partition. Availability here is
reachability AND progress-vs-demand: with every probed rank answering,
a table is *unavailable* only when its windowed rates show no progress
WHILE demand is provably pent (replay-retained / pending client bytes,
or a server apply backlog). No demand and no progress is idle, not an
outage — the poll sits out.

Spec format (:func:`load_spec` accepts a path or inline JSON)::

    {"fast_window_s": 60, "slow_window_s": 300,
     "fast_burn": 6.0, "slow_burn": 1.0,
     "objectives": [
       {"name": "embed-serve-p99", "kind": "serve_latency_p99",
        "table": "embed", "target": 0.99, "max": 5.0},
       {"name": "embed-avail", "kind": "availability",
        "table": "embed", "target": 0.95, "min": 1.0},
       {"name": "embed-staleness", "kind": "staleness",
        "table": "embed", "max": 2.0}]}

Every objective: ``name`` (unique), ``kind`` (one of
:data:`OBJECTIVE_KINDS`), optional ``table`` / ``tenant`` / ``monitor``
scoping, ``target`` (the SLO fraction, default 0.99 -> 1% error
budget), and a ``min`` (floor kinds: availability, scale_efficiency)
or ``max`` threshold (everything else; ``threshold_ms`` is accepted as
an alias for the latency kinds). Per-objective ``fast_burn`` /
``slow_burn`` / window overrides win over the spec-level ones.

Zero cost while disarmed: one cached flag read per poll, no state.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional

from multiverso_tpu.telemetry import flightrec as _flight
from multiverso_tpu.utils import config, log

config.define_string(
    "slo_spec", "",
    "declarative SLO spec for the sentinel (telemetry/slo.py): a JSON "
    "file path, or inline JSON when it starts with '{'. Declares "
    "per-(table, tenant) objectives judged on every cluster poll via "
    "fast+slow burn-rate windows; episodes land in alerts.jsonl, the "
    "flight recorder, and mv_slo_* gauges. Empty = sentinel disarmed "
    "(one flag read per poll, nothing else runs). docs/OBSERVABILITY.md "
    "'SLO view'")

# every judgeable SLI. tools/check_obs_surface.py lint 7 reads this
# tuple by ast and requires each kind to render in mvtop/dump_metrics —
# an objective kind no pane of glass can show is a verdict into the
# void.
OBJECTIVE_KINDS = (
    "serve_latency_p99",    # merged serve monitor p99_ms vs max
    "add_latency_p99",      # merged add_rows monitor p99_ms vs max
    "staleness",            # worst serving replica/member age_s vs max
    "shed_rate",            # windowed shed fraction of serve demand
    "availability",         # reachability AND progress-vs-demand floor
    "stall_fraction",       # worst profiled rank's stall vs max
    "steady_recompiles",    # recompiles past step 1 (max, usually 0)
    "recovery_s",           # externally noted chaos recovery seconds
    "scale_efficiency",     # externally noted E_n floor (bench_scale)
)

# floor kinds violate when the value drops BELOW "min"; every other
# kind violates when it rises ABOVE "max"
_MIN_KINDS = ("availability", "scale_efficiency")

_DEFAULTS = {"fast_window_s": 60.0, "slow_window_s": 300.0,
             "fast_burn": 6.0, "slow_burn": 1.0}


def load_spec(spec) -> Dict[str, Any]:
    """A dict passes through; a string is inline JSON (starts with
    '{') or a file path — the ``faults_spec`` convention."""
    if isinstance(spec, dict):
        return spec
    s = str(spec).strip()
    if s.startswith("{"):
        return json.loads(s)
    with open(s) as f:
        return json.load(f)


def normalize_spec(raw: Dict[str, Any]) -> Dict[str, Any]:
    """Validate + default-fill a raw spec. Raises ValueError on an
    unknown kind, a duplicate/missing name, or a floor/threshold
    mismatch — a mis-declared objective must fail at arm time, not
    judge garbage forever."""
    spec = {k: float(raw.get(k, v)) for k, v in _DEFAULTS.items()}
    objectives: List[Dict[str, Any]] = []
    seen = set()
    for o in raw.get("objectives") or []:
        kind = o.get("kind")
        if kind not in OBJECTIVE_KINDS:
            raise ValueError(f"unknown SLO objective kind {kind!r} "
                             f"(known: {', '.join(OBJECTIVE_KINDS)})")
        name = o.get("name") or kind
        if name in seen:
            raise ValueError(f"duplicate SLO objective name {name!r}")
        seen.add(name)
        obj = dict(o)
        obj["name"], obj["kind"] = name, kind
        obj["target"] = float(o.get("target", 0.99))
        if not 0.0 < obj["target"] < 1.0:
            raise ValueError(f"objective {name!r}: target must be in "
                             f"(0, 1), got {obj['target']}")
        if kind in _MIN_KINDS:
            obj["min"] = float(o.get("min", 1.0))
        else:
            # threshold_ms is the natural spelling for the latency
            # kinds; "max" is canonical for everything
            mx = o.get("max", o.get("threshold_ms"))
            obj["max"] = float(0.0 if mx is None else mx)
        for k in _DEFAULTS:
            obj[k] = float(o.get(k, spec[k]))
        objectives.append(obj)
    spec["objectives"] = objectives
    return spec


# ---------------------------------------------------------------------- #
# the pure SLI layer: one cluster record -> one measurement (or None)
# ---------------------------------------------------------------------- #
def measure(obj: Dict[str, Any], rec: Dict[str, Any],
            external: Optional[Dict[str, float]] = None
            ) -> Optional[float]:
    """One objective's SLI value out of one cluster record; ``None``
    when the record carries no evidence for it (the poll sits out of
    the burn windows — silence is not a violation). ``external`` maps
    objective name -> a value noted out-of-band (chaos recovery_s,
    bench scale efficiency) via :meth:`SLOSentinel.note_value`."""
    kind, table = obj["kind"], obj.get("table")
    if kind in ("recovery_s", "scale_efficiency"):
        v = (external or {}).get(obj["name"])
        return None if v is None else float(v)
    if kind in ("serve_latency_p99", "add_latency_p99"):
        default = (f"ps[{table}].serve" if kind == "serve_latency_p99"
                   else f"table[{table}].add_rows")
        m = (rec.get("monitors") or {}).get(obj.get("monitor") or default)
        if not isinstance(m, dict) or not m.get("timed") \
                or not m.get("count"):
            return None
        v = m.get("p99_ms")
        return float(v) if isinstance(v, (int, float)) else None
    if kind == "staleness":
        s = (rec.get("serving") or {}).get(table)
        if not isinstance(s, dict):
            return None
        ages = [e.get("age_s") for e in (s.get("replicas") or {}).values()
                if isinstance(e, dict)]
        for p in (s.get("pools") or {}).values():
            ages += [m.get("age_s") for m in (p or {}).get("members", [])
                     if isinstance(m, dict) and m.get("active")]
        ages = [a for a in ages if isinstance(a, (int, float))]
        return max(ages) if ages else None
    if kind == "shed_rate":
        s = (rec.get("serving") or {}).get(table)
        if not isinstance(s, dict):
            return None
        r = s.get("rates") or {}
        served, shed = r.get("served_per_s"), r.get("shed_per_s")
        if isinstance(served, (int, float)) \
                and isinstance(shed, (int, float)):
            total = served + shed
            # a windowed fraction that CLEARS when the storm stops —
            # the cumulative shed_rate counter never forgets
            return shed / total if total > 0 else None
        return None
    if kind == "stall_fraction":
        vals = [p.get("stall_fraction")
                for p in (rec.get("profile") or {}).values()
                if isinstance(p, dict)]
        vals = [v for v in vals if isinstance(v, (int, float))]
        return max(vals) if vals else None
    if kind == "steady_recompiles":
        vals = [p.get("steady_recompiles")
                for p in (rec.get("profile") or {}).values()
                if isinstance(p, dict)]
        vals = [v for v in vals if isinstance(v, (int, float))]
        return float(max(vals)) if vals else None
    if kind == "availability":
        return _availability(obj, rec)
    return None


def _availability(obj: Dict[str, Any], rec: Dict[str, Any]
                  ) -> Optional[float]:
    """Reachability AND progress-vs-demand (module docstring): probes
    answer through a wedged data plane, so a partition shows up as
    pent demand with zero windowed progress, not as probe failures."""
    ranks = rec.get("ranks") or {}
    if not ranks:
        return None
    world = rec.get("world") or len(ranks)
    up = sum(1 for e in ranks.values()
             if isinstance(e, dict)
             and e.get("status") not in (None, "unreachable"))
    frac = up / max(world, 1)
    if frac < 1.0:
        return frac        # hard unreachability needs no demand proof
    table = obj.get("table")
    if not table:
        return 1.0
    rates = (rec.get("rates") or {}).get(table)
    if not isinstance(rates, dict):
        return None        # first poll: no interval, no evidence
    progress = sum(rates.get(k) or 0.0
                   for k in ("adds_per_s", "gets_per_s",
                             "applies_per_s"))
    if progress > float(obj.get("progress_min", 0.5)):
        return 1.0
    tot = (rec.get("memory") or {}).get("totals") or {}
    pent = ((tot.get("retained_bytes") or 0)
            + (tot.get("pending_bytes") or 0)
            + ((rec.get("tables") or {}).get(table, {})
               .get("queue_depth") or 0))
    if pent > 0:
        return 0.0         # demand provably stuck: the outage signal
    return None            # idle is not an outage


def violates(obj: Dict[str, Any], value: float) -> bool:
    """Does one measured value breach the objective's floor/threshold?
    Pure; the burn-rate oracle test drives it on an integer grid."""
    if obj["kind"] in _MIN_KINDS:
        return value < float(obj["min"])
    return value > float(obj["max"])


def burn_rates(obj: Dict[str, Any], history: List[Dict[str, Any]],
               now: Optional[float] = None,
               external: Optional[Dict[str, float]] = None
               ) -> Dict[str, Any]:
    """Fast+slow window burn rates for one objective over the
    aggregator's rolling history. ``burn = bad_fraction /
    error_budget`` per window; a window with no measured polls burns
    0.0. ``now`` defaults to the newest record's ``ts`` (explicit in
    tests — the math is a pure function of the grid)."""
    if now is None:
        now = history[-1].get("ts", 0.0) if history else 0.0
    budget = max(1.0 - obj["target"], 1e-4)
    out: Dict[str, Any] = {"value": None}
    cache: List[tuple] = []      # (ts, value) for records in the slow
    slow_cut = now - obj["slow_window_s"]
    for rec in history:
        ts = rec.get("ts")
        if not isinstance(ts, (int, float)) or ts < slow_cut or ts > now:
            continue
        cache.append((ts, measure(obj, rec, external)))
    if cache:
        vals = [v for _ts, v in cache if v is not None]
        if vals:
            out["value"] = vals[-1]
    for label, window in (("fast", obj["fast_window_s"]),
                          ("slow", obj["slow_window_s"])):
        cut = now - window
        n = bad = 0
        for ts, v in cache:
            if ts < cut or v is None:
                continue
            n += 1
            bad += bool(violates(obj, v))
        out[label] = round((bad / n) / budget, 4) if n else 0.0
        out[f"n_{label}"], out[f"bad_{label}"] = n, bad
    return out


# ---------------------------------------------------------------------- #
# straggler detection: one record -> the named slowest rank
# ---------------------------------------------------------------------- #
def straggler(rec: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Name the slowest rank of one cluster record, with attribution:
    ``compute`` (largest exclusive profile-phase total), ``stall``
    (wall time no phase claimed), or ``wire`` (apply backlog + aged
    in-flight ops). Each component is normalized to its cluster-wide
    sum so the scales compose; the rank with the largest combined
    share is the straggler and its dominant component is the
    attribution. ``None`` below 2 ranks or when no component moved —
    a quiet cluster has no straggler."""
    ranks = rec.get("ranks") or {}
    if len(ranks) < 2:
        return None
    profile = rec.get("profile") or {}
    comp: Dict[str, Dict[str, float]] = {}
    for r, e in ranks.items():
        if not isinstance(e, dict) or e.get("status") == "unreachable":
            continue
        p = profile.get(r) or profile.get(str(r)) or {}
        phases = p.get("phases") or {}
        comp[str(r)] = {
            "compute": float(sum(v for v in phases.values()
                                 if isinstance(v, (int, float)))),
            "stall": float(p.get("stall_fraction") or 0.0),
            "wire": float((e.get("queue_depth") or 0)
                          + (e.get("oldest_inflight_s") or 0.0)),
        }
    if len(comp) < 2:
        return None
    sums = {k: sum(c[k] for c in comp.values())
            for k in ("compute", "stall", "wire")}
    if not any(sums.values()):
        return None
    scores: Dict[str, Dict[str, float]] = {}
    for r, c in comp.items():
        scores[r] = {k: (c[k] / sums[k] if sums[k] else 0.0)
                     for k in sums}
    slowest = max(scores, key=lambda r: sum(scores[r].values()))
    attribution = max(scores[slowest], key=scores[slowest].get)
    p = profile.get(slowest) or profile.get(int(slowest)
                                            if slowest.isdigit()
                                            else slowest) or {}
    phases = {n: v for n, v in (p.get("phases") or {}).items()
              if isinstance(v, (int, float))}
    top_phase = max(phases, key=phases.get) if phases else None
    return {
        "rank": int(slowest) if slowest.isdigit() else slowest,
        "attribution": attribution,
        "top_phase": top_phase,
        "score": round(sum(scores[slowest].values()), 4),
        "components": {k: round(v, 4) for k, v in comp[slowest].items()},
    }


# ---------------------------------------------------------------------- #
# the sentinel: episode lifecycle over the aggregator's poll stream
# ---------------------------------------------------------------------- #
class SLOSentinel:
    """Per-process sentinel (module-level :data:`SENTINEL` is the one
    the aggregator drives). Lazy-arms from the ``slo_spec`` flag /
    ``$MV_SLO_SPEC`` on the first poll; one cached read while
    disarmed."""

    def __init__(self, spec=None) -> None:
        self._lock = threading.Lock()
        self._spec: Optional[Dict[str, Any]] = (
            normalize_spec(load_spec(spec)) if spec else None)
        self._flag_tried = False
        self._state: Dict[str, Dict[str, Any]] = {}
        self._external: Dict[str, float] = {}
        self._episodes: List[Dict[str, Any]] = []
        self._evals = 0
        self._straggler: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------------ #
    @property
    def armed(self) -> bool:
        return self._spec is not None

    def arm(self, spec) -> "SLOSentinel":
        """Bind a spec (path / inline JSON / dict), resetting episode
        state — a new contract starts a new ledger."""
        normalized = normalize_spec(load_spec(spec))
        with self._lock:
            self._spec = normalized
            self._state = {}
            self._episodes = []
        log.info("SLO sentinel armed: %d objective(s)",
                 len(normalized["objectives"]))
        return self

    def _maybe_arm_from_flag(self) -> None:
        if self._spec is not None or self._flag_tried:
            return
        spec = config.get_flag("slo_spec") or os.environ.get(
            "MV_SLO_SPEC", "")
        if not spec:
            return
        self._flag_tried = True     # a bad spec must be loud ONCE,
        try:                        # not every poll — and never fatal
            self.arm(spec)
        except Exception as e:   # noqa: BLE001
            log.error("SLO sentinel arm failed (%s: %s); sentinel "
                      "stays disarmed", type(e).__name__, e)

    def note_value(self, name: str, value: float) -> None:
        """Feed an out-of-band SLI (chaos ``recovery_s``, bench
        ``scale_efficiency``) — measured where it happens, judged on
        the next poll like everything else."""
        with self._lock:
            self._external[name] = float(value)

    # ------------------------------------------------------------------ #
    def on_poll(self, rec: Dict[str, Any],
                history: List[Dict[str, Any]],
                directory: str = "") -> Optional[Dict[str, Any]]:
        """Judge every objective against the rolling history (which
        already includes ``rec``), run the episode lifecycle, and
        return the ``slo`` stats block (None while disarmed). Ring
        writes / structured logs / alerts.jsonl happen OUTSIDE the
        lock — the tenant-ledger discipline."""
        self._maybe_arm_from_flag()
        fired: List[Dict[str, Any]] = []
        cleared: List[Dict[str, Any]] = []
        with self._lock:
            spec = self._spec
            if spec is None:
                return None
            self._evals += 1
            now = rec.get("ts")
            objectives: Dict[str, Any] = {}
            for obj in spec["objectives"]:
                br = burn_rates(obj, history, now=now,
                                external=self._external)
                st = self._state.setdefault(
                    obj["name"], {"firing": False, "episodes": 0})
                if (not st["firing"] and br["fast"] >= obj["fast_burn"]
                        and br["slow"] >= obj["slow_burn"]):
                    st["firing"] = True
                    st["episodes"] += 1
                    fired.append(self._episode(
                        "slo.fired", obj, br, st["episodes"], now))
                elif st["firing"] and br["fast"] < 1.0:
                    # clear on the FAST window back inside budget: the
                    # slow window keeps the outage's polls for its full
                    # span, and holding an alert on history alone would
                    # page long after recovery
                    st["firing"] = False
                    cleared.append(self._episode(
                        "slo.cleared", obj, br, st["episodes"], now))
                st["burn_fast"], st["burn_slow"] = br["fast"], br["slow"]
                st["value"] = br["value"]
                objectives[obj["name"]] = {
                    "kind": obj["kind"], "table": obj.get("table"),
                    "firing": st["firing"], "episodes": st["episodes"],
                    "burn_fast": br["fast"], "burn_slow": br["slow"],
                    "value": br["value"],
                }
            self._episodes.extend(fired + cleared)
            del self._episodes[:-16]
            self._straggler = straggler(rec)
            snapshot = self._snapshot_locked(objectives)
        for ev in fired:
            _flight.record(_flight.EV_SLO_FIRED,
                           note=self._note(ev)[:120])
            log.error("SLO fired: %s", json.dumps(ev))
        for ev in cleared:
            _flight.record(_flight.EV_SLO_CLEARED,
                           note=self._note(ev)[:120])
            log.info("SLO cleared: %s", json.dumps(ev))
        if directory and (fired or cleared):
            try:
                os.makedirs(directory, exist_ok=True)
                with open(os.path.join(directory, "alerts.jsonl"),
                          "a") as f:
                    for ev in fired + cleared:
                        f.write(json.dumps(ev) + "\n")
            except OSError as e:
                log.error("alerts.jsonl append failed: %s", e)
        return snapshot

    @staticmethod
    def _episode(kind: str, obj, br, episode: int, now) -> Dict[str, Any]:
        return {"kind": kind, "objective": obj["name"],
                "objective_kind": obj["kind"], "table": obj.get("table"),
                "episode": episode, "value": br["value"],
                "burn_fast": br["fast"], "burn_slow": br["slow"],
                "ts": now}

    @staticmethod
    def _note(ev: Dict[str, Any]) -> str:
        return (f"{ev['objective']} kind={ev['objective_kind']} "
                f"ep={ev['episode']} value={ev['value']} "
                f"burn={ev['burn_fast']}/{ev['burn_slow']}")

    # ------------------------------------------------------------------ #
    def _snapshot_locked(self, objectives=None) -> Dict[str, Any]:
        if objectives is None:
            objectives = {
                name: {"firing": st.get("firing", False),
                       "episodes": st.get("episodes", 0),
                       "burn_fast": st.get("burn_fast", 0.0),
                       "burn_slow": st.get("burn_slow", 0.0),
                       "value": st.get("value")}
                for name, st in self._state.items()}
        return {
            "objectives": objectives,
            "firing": sorted(n for n, o in objectives.items()
                             if o.get("firing")),
            "episodes": sum(st.get("episodes", 0)
                            for st in self._state.values()),
            "evals": self._evals,
            "straggler": self._straggler,
            "recent": list(self._episodes[-8:]),
        }

    def stats_snapshot(self) -> Optional[Dict[str, Any]]:
        """The MSG_STATS ``slo`` block (None while disarmed — the
        payload stays additive, an un-speced cluster grows no key)."""
        with self._lock:
            if self._spec is None:
                return None
            return self._snapshot_locked()

    def reset(self) -> None:
        """Disarm + forget everything (test isolation; re-arms from
        the flag on the next poll)."""
        with self._lock:
            self._spec = None
            self._flag_tried = False
            self._state = {}
            self._external = {}
            self._episodes = []
            self._evals = 0
            self._straggler = None


SENTINEL = SLOSentinel()


def arm(spec) -> SLOSentinel:
    return SENTINEL.arm(spec)


def enabled() -> bool:
    return SENTINEL.armed


def note_value(name: str, value: float) -> None:
    SENTINEL.note_value(name, value)


def stats_snapshot() -> Optional[Dict[str, Any]]:
    return SENTINEL.stats_snapshot()


def reset() -> None:
    SENTINEL.reset()
