"""Controller-side cluster observability: one rank polls every rank.

PR 3 (MSG_STATS) and PR 4 (MSG_HEALTH) answer questions about ONE
process; every scale-out question — which shard is skewed, which rows
are hot, which rank is falling behind — needs the merged view. This
module is that aggregation layer:

* :class:`ClusterAggregator` — a background poller (flag
  ``stats_poll_interval_s``, default off) on the controller rank (PS
  rank 0) that pulls MSG_STATS + MSG_HEALTH from every rank over
  **one-shot probe connections** (the PR-4 path: a fresh conn gets a
  fresh handler thread, so a wedged data plane cannot stall the poll,
  and the reply wait is ``ps_health_timeout``-scale, not ``ps_timeout``).
* :func:`merge_cluster` — one poll's payloads -> one cluster record:
  log2 histograms merged EXACTLY (identical fixed buckets everywhere,
  ``telemetry/histogram.py``), per-table shard stats summed with a
  **shard-skew metric** (max/mean row-traffic imbalance), and the
  per-shard Space-Saving sketches merged into a cluster top-K with an
  estimated cache-hit-rate-if-cached curve (``telemetry/hotkeys.py``).
* :func:`derive_rates` — consecutive records -> windowed rates
  (applies/s, gets/s, wire bytes/s), queue-depth deltas, and the
  windowed skew over just that interval's traffic.

The rolling time series appends to ``cluster.jsonl`` (+ an atomically
replaced ``cluster.prom`` reusing the exporter's label scheme) alongside
the PR-3 per-rank exporter output in ``metrics_dir``; with no directory
set the in-memory history still accumulates (bench/mvtop consume it).
``tools/mvtop.py`` renders the same records live; the merge functions
here are pure so both consumers share one definition.

Lifecycle: the first PSService with rank 0 starts the global aggregator
when the flag enables it (:func:`ensure_started`); ``PSService.close``
stops an aggregator bound to it (:func:`stop_if_bound`) and ``Zoo.stop``
stops whatever remains (:func:`stop_global`), each with a final
short-timeout poll so short runs still leave a record.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from multiverso_tpu.telemetry import hotkeys as _hotkeys
from multiverso_tpu.telemetry import signals as _signals
from multiverso_tpu.telemetry import slo as _slo
from multiverso_tpu.telemetry.histogram import Histogram
from multiverso_tpu.utils import config, log

config.define_float(
    "stats_poll_interval_s", 0.0,
    "controller-side cluster observability: seconds between aggregator "
    "polls of every rank's MSG_STATS + MSG_HEALTH over one-shot probe "
    "connections (PS rank 0 only). Appends merged cluster records to "
    "cluster.jsonl (+ cluster.prom) under metrics_dir when set. "
    "0 disables the poller entirely")

# per-shard scalar fields copied into a cluster record's per-table
# "shards" map (the summable traffic/occupancy view; histograms and
# sketches are merged separately)
_SHARD_SCALARS = ("kind", "lo", "rows", "adds", "applies", "gets",
                  "get_bytes", "add_bytes", "queue_depth",
                  "pending_bytes", "version", "keys", "dirty_rows",
                  "cow_applies",
                  # mesh-stacked placement block (ps/spmd.py): slot ->
                  # device + grouped-apply share — mvtop's placement
                  # panel renders it per shard (a dict, passed through
                  # whole like the scalars)
                  "spmd")
# fields summed into the per-table cluster totals
_TABLE_SUMS = ("adds", "applies", "gets", "get_bytes", "add_bytes",
               "queue_depth", "rows")


def merge_hist_dicts(dicts: List[Optional[Dict]]) -> Dict:
    """Exactly merge hist-dicts (the MSG_STATS / exporter wire shape):
    every histogram in the system shares one fixed bucket table, so the
    merge is elementwise addition — cluster percentiles are computed on
    the true pooled distribution, not averaged per-rank quantiles."""
    merged = Histogram()
    count = timed = 0
    for d in dicts:
        if not d:
            continue
        t = int(d.get("timed", d.get("count", 0)) or 0)
        h = Histogram.from_nonzero(
            d.get("buckets", []), count=t,
            total=float(d.get("sum_ms", 0.0) or 0.0),
            min_ms=d.get("min_ms") if t else None,
            max_ms=d.get("max_ms") if t else None)
        merged.merge(h)
        timed += t
        count += int(d.get("count", 0) or 0)
    out = merged.as_dict()
    # count keeps incr-only (untimed) events like the source dicts do;
    # timed is the bucket mass percentiles were computed over
    out["count"] = count
    out["timed"] = timed
    return out


def _proc_key(st: Dict, rank) -> tuple:
    """The (addr host, pid) process identity used to dedupe PROCESS-
    global payload blocks (monitors, serving, profile, memory) when
    several in-process ranks report the same registry; payloads
    without a pid (older peers) fall back to per-rank identity. ONE
    definition — four merge sections key on it."""
    pid = st.get("pid")
    if pid is None:
        return ("rank", rank)
    return ((st.get("addr") or "").rsplit(":", 1)[0], pid)


def _skew(traffic: List[float]) -> float:
    """Max/mean imbalance of per-shard traffic; 1.0 = perfectly even
    (and the degenerate empty/zero cases, where no imbalance exists)."""
    vals = [float(v) for v in traffic if v is not None]
    if not vals:
        return 1.0
    mean = sum(vals) / len(vals)
    if mean <= 0:
        return 1.0
    return max(vals) / mean


def merge_cluster(stats_by_rank: Dict[int, Any],
                  health_by_rank: Dict[int, Any],
                  world: Optional[int] = None) -> Dict:
    """One poll's per-rank payloads -> the merged cluster record. Pure
    function (the aggregator thread and ``tools/mvtop.py`` share it).
    Values may be Exceptions — an unreachable rank becomes a per-rank
    error entry, never a failed poll: partial visibility of a degraded
    cluster is exactly when this record matters most."""
    rec: Dict[str, Any] = {"kind": "cluster", "ts": round(time.time(), 3)}
    ranks: Dict[str, Dict] = {}
    for r in sorted(set(stats_by_rank) | set(health_by_rank)):
        h = health_by_rank.get(r)
        if isinstance(h, BaseException) or h is None:
            ent: Dict[str, Any] = {"status": "unreachable"}
            if h is not None:
                ent["error"] = f"{type(h).__name__}: {h}"[:200]
        else:
            ent = {"status": h.get("status", "?"), "addr": h.get("addr"),
                   # incarnation generation (failover plane): a
                   # restarted shard reports its predecessor's + 1
                   "gen": h.get("gen"),
                   "native": h.get("native"),
                   "queue_depth": h.get("queue_depth"),
                   "inflight": h.get("inflight"),
                   "oldest_inflight_s": h.get("oldest_inflight_s"),
                   "serve_age_s": h.get("serve_age_s"),
                   "apply_age_s": h.get("apply_age_s")}
        st = stats_by_rank.get(r)
        if isinstance(st, BaseException):
            ent["stats_error"] = f"{type(st).__name__}: {st}"[:200]
        ranks[str(r)] = ent
    rec["ranks"] = ranks
    rec["world"] = int(world or len(ranks))
    rec["polled"] = sum(1 for st in stats_by_rank.values()
                        if isinstance(st, dict))

    # monitors: pooled histogram per name across every answering
    # PROCESS. Dashboard monitors are process-global, so two ranks
    # served from one OS process (in-process test fixtures, bench
    # workers) return the SAME registry — pooling per rank would double
    # every count. Dedupe by (addr host, pid); payloads without a pid
    # (older peers) fall back to per-rank pooling.
    by_name: Dict[str, List[Dict]] = {}
    seen_procs: set = set()
    for r in sorted(stats_by_rank):
        st = stats_by_rank[r]
        if not isinstance(st, dict):
            continue
        if st.get("pid") is not None:
            proc = _proc_key(st, r)
            if proc in seen_procs:
                continue
            seen_procs.add(proc)
        for name, m in st.get("monitors", {}).items():
            by_name.setdefault(name, []).append(m)
    rec["monitors"] = {n: merge_hist_dicts(ds)
                       for n, ds in sorted(by_name.items())}

    # tables: per-shard scalars keyed by rank, cluster sums, merged
    # apply histogram, skew, merged hot-key sketch. The apply histogram
    # is the shard's ps[<table>].apply Dashboard monitor — PROCESS-
    # global like every monitor, so same-named shards served from one
    # OS process report the SAME pooled distribution: merge it once per
    # (process, table), or the in-process fixtures/bench would record
    # apply.count at 2x the 'applies' scalar beside it. Scalars and
    # sketches are per-shard objects and never dedupe.
    tables: Dict[str, Dict] = {}
    applies_h: Dict[str, List] = {}
    hot: Dict[str, List] = {}
    seen_apply: set = set()
    for r in sorted(stats_by_rank):
        st = stats_by_rank[r]
        if not isinstance(st, dict):
            continue
        proc = _proc_key(st, r)
        for tname, sh in st.get("shards", {}).items():
            if not isinstance(sh, dict) or "error" in sh:
                tables.setdefault(tname, {"shards": {}})["shards"][
                    str(r)] = dict(sh or {})
                continue
            t = tables.setdefault(tname, {"shards": {}})
            t["shards"][str(r)] = {k: sh[k] for k in _SHARD_SCALARS
                                   if k in sh}
            if (proc, tname) not in seen_apply:
                seen_apply.add((proc, tname))
                applies_h.setdefault(tname, []).append(sh.get("apply"))
            if sh.get("hotkeys"):
                hot.setdefault(tname, []).append(sh["hotkeys"])
    for tname, t in tables.items():
        shards = [s for s in t["shards"].values() if "error" not in s]
        for k in _TABLE_SUMS:
            t[k] = sum(int(s.get(k) or 0) for s in shards)
        t["apply"] = merge_hist_dicts(applies_h.get(tname, []))
        t["skew"] = round(_skew(
            [int(s.get("adds") or 0) + int(s.get("gets") or 0)
             for s in shards]), 3)
    rec["tables"] = tables

    # serving plane (read replicas + admission, docs/SERVING.md): the
    # MSG_STATS "serving" block is PROCESS-global like the monitors
    # (serving/replica.stats_snapshot walks a per-process registry), so
    # in-process multi-rank worlds dedupe by (host, pid) the same way;
    # per-replica detail stays keyed by the reporting rank, counters
    # sum across replica processes.
    serving: Dict[str, Dict] = {}
    seen_srv: set = set()
    for r in sorted(stats_by_rank):
        st = stats_by_rank[r]
        if not isinstance(st, dict):
            continue
        srv = st.get("serving")
        if not isinstance(srv, dict):
            continue
        proc = _proc_key(st, r)
        if proc in seen_srv:
            continue
        seen_srv.add(proc)
        for tname, rep in srv.items():
            if not isinstance(rep, dict):
                continue
            ent = serving.setdefault(tname, {
                "replicas": {}, "served": 0, "shed": 0, "deferred": 0,
                "cache_hits": 0, "cache_misses": 0})
            ent["replicas"][str(r)] = {
                k: rep.get(k) for k in
                ("epoch", "age_s", "bound_s", "refresh_ms",
                 "cache_rows", "cache_hit_rate")}
            # ReplicaPool detail (serving/pool.py): passed through per
            # reporting process — per-member route share / staleness
            # lag / degraded flag feed mvtop's pool panel
            if isinstance(rep.get("pool"), dict):
                ent["replicas"][str(r)]["pool"] = rep["pool"]
                ent.setdefault("pools", {})[str(r)] = rep["pool"]
            for k in ("served", "shed", "deferred", "cache_hits",
                      "cache_misses"):
                ent[k] += int(rep.get(k) or 0)
    if serving:
        for ent in serving.values():
            tot = ent["cache_hits"] + ent["cache_misses"]
            ent["cache_hit_rate"] = (round(ent["cache_hits"] / tot, 4)
                                     if tot else None)
            dem = ent["served"] + ent["shed"]
            ent["shed_rate"] = (round(ent["shed"] / dem, 4)
                                if dem else None)
        rec["serving"] = serving
    # step-profiler blocks (flag step_profile; PR 9): passed through
    # per reporting rank like the serving block, plus two at-a-glance
    # fields folded into the rank entries (mvtop's stall%/recompiles
    # columns). Process-global like the monitors — in-process
    # multi-rank worlds report one process's summary under each of its
    # ranks, the same documented collapse.
    profile: Dict[str, Dict] = {}
    for r in sorted(stats_by_rank):
        st = stats_by_rank[r]
        if not isinstance(st, dict):
            continue
        p = st.get("profile")
        if not isinstance(p, dict):
            continue
        profile[str(r)] = p
        ent = ranks.get(str(r))
        if ent is not None:
            sf = p.get("stall_fraction")
            ent["stall_pct"] = (round(100.0 * sf, 1)
                                if isinstance(sf, (int, float)) else None)
            ent["recompiles"] = p.get("steady_recompiles")
    if profile:
        rec["profile"] = profile
    # memory plane (telemetry/memstats.py): per-rank ledger digests +
    # cluster totals. The block is PROCESS-global like the monitors
    # (one ledger per OS process), so totals dedupe by (host, pid) —
    # an in-process multi-rank world reports the same process under
    # each of its ranks but is summed once.
    memory: Dict[str, Dict] = {}
    mem_totals: Dict[str, float] = {}
    seen_mem: set = set()
    for r in sorted(stats_by_rank):
        st = stats_by_rank[r]
        if not isinstance(st, dict):
            continue
        m = st.get("memory")
        if not isinstance(m, dict):
            continue
        t = m.get("totals") or {}
        ent = {
            "rss_mb": m.get("rss_mb"), "hwm_mb": m.get("hwm_mb"),
            "device_bytes": m.get("device_bytes"),
            "table_bytes": t.get("table_bytes"),
            "retained_bytes": t.get("retained_bytes"),
            "pending_bytes": t.get("pending_bytes"),
            "pinned_epochs": t.get("pinned_epochs"),
            "retired_bytes": t.get("retired_bytes"),
            "samples": m.get("samples"),
            "verdicts": [v.get("kind") for v in (m.get("verdicts") or [])
                         if isinstance(v, dict)][-4:],
        }
        memory[str(r)] = ent
        proc = _proc_key(st, r)
        if proc in seen_mem:
            continue
        seen_mem.add(proc)
        for k in ("rss_mb", "device_bytes", "table_bytes",
                  "retained_bytes", "pending_bytes", "retired_bytes",
                  "pinned_epochs"):
            v = ent.get(k)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                mem_totals[k] = mem_totals.get(k, 0) + v
    if memory:
        rec["memory"] = {
            "ranks": memory,
            "totals": {k: (round(v, 3) if k == "rss_mb" else int(v))
                       for k, v in sorted(mem_totals.items())},
        }
    # device plane (telemetry/devstats.py): per-rank "devices" blocks
    # passed through + cluster totals. PROCESS-global like the monitors
    # (one DevStats per OS process), so totals dedupe by (host, pid).
    # The block is ADDITIVE: a payload without it (an older peer in a
    # mixed-version cluster, or a rank with no device activity) simply
    # contributes nothing — no consumer may require it.
    devices: Dict[str, Dict] = {}
    dev_totals: Dict[str, float] = {}
    seen_dev: set = set()
    for r in sorted(stats_by_rank):
        st = stats_by_rank[r]
        if not isinstance(st, dict):
            continue
        d = st.get("devices")
        if not isinstance(d, dict):
            continue
        devices[str(r)] = d
        proc = _proc_key(st, r)
        if proc in seen_dev:
            continue
        seen_dev.add(proc)
        for direction, g in (d.get("transfers") or {}).items():
            if isinstance(g, dict):
                dev_totals[f"{direction}_bytes"] = (
                    dev_totals.get(f"{direction}_bytes", 0)
                    + int(g.get("bytes") or 0))
        for c in (d.get("collectives") or {}).values():
            if isinstance(c, dict):
                dev_totals["coll_calls"] = (
                    dev_totals.get("coll_calls", 0)
                    + int(c.get("calls") or 0))
                dev_totals["coll_bytes"] = (
                    dev_totals.get("coll_bytes", 0)
                    + int(c.get("bytes") or 0))
        for c in (d.get("compiles_by_mesh") or {}).values():
            if isinstance(c, dict):
                dev_totals["compiles"] = (
                    dev_totals.get("compiles", 0)
                    + int(c.get("compiles") or 0))
                dev_totals["compile_s"] = round(
                    dev_totals.get("compile_s", 0.0)
                    + float(c.get("compile_s") or 0.0), 3)
        for g in (d.get("per_device") or {}).values():
            if isinstance(g, dict):
                dev_totals["device_bytes"] = (
                    dev_totals.get("device_bytes", 0)
                    + int(g.get("bytes") or 0))
        if d.get("hygiene_findings"):
            dev_totals["hygiene_findings"] = (
                dev_totals.get("hygiene_findings", 0)
                + int(d["hygiene_findings"]))
    if devices:
        rec["devices"] = {"ranks": devices, "totals": dev_totals}
    # tenant attribution plane (telemetry/tenants.py): the MSG_STATS
    # "tenants" block is PROCESS-global like serving (one ledger per OS
    # process), so serve counters/episodes dedupe by (host, pid);
    # latency histograms merge exactly (shared bucket table). Shard-side
    # meters (shards[<table>]["tenants"]) are per-shard objects like the
    # hot-key sketches: summed per rank, never deduped. ADDITIVE — a
    # payload without the block contributes nothing.
    ten_tables: Dict[str, Dict] = {}
    ten_hists: Dict[tuple, List] = {}
    ten_adm: Dict[str, Dict] = {}
    ten_episodes = 0
    ten_active = False
    ten_verdict: Optional[Dict] = None
    seen_ten: set = set()
    wire_tenants: Dict[str, Dict[str, int]] = {}
    wire_sketches: List[Dict] = []
    for r in sorted(stats_by_rank):
        st = stats_by_rank[r]
        if not isinstance(st, dict):
            continue
        # shard meters: per-shard, per-rank — no proc dedupe
        for tname, sh in st.get("shards", {}).items():
            tm = sh.get("tenants") if isinstance(sh, dict) else None
            if not isinstance(tm, dict):
                continue
            for tn, c in tm.items():
                if tn == "~sketch":
                    if isinstance(c, dict):
                        wire_sketches.append(c)
                    continue
                if not isinstance(c, dict):
                    continue
                w = wire_tenants.setdefault(
                    tn, {"ops": 0, "add_bytes": 0, "get_bytes": 0})
                for k in ("ops", "add_bytes", "get_bytes"):
                    w[k] += int(c.get(k) or 0)
        ten = st.get("tenants")
        if not isinstance(ten, dict):
            continue
        proc = _proc_key(st, r)
        if proc in seen_ten:
            continue
        seen_ten.add(proc)
        ten_episodes += int(ten.get("episodes") or 0)
        ten_active = ten_active or bool(ten.get("active"))
        v = ten.get("verdict")
        if isinstance(v, dict) and (ten_verdict is None
                                    or (v.get("ts") or 0)
                                    > (ten_verdict.get("ts") or 0)):
            ten_verdict = v
        for tname, tens in (ten.get("tables") or {}).items():
            if not isinstance(tens, dict):
                continue
            tt = ten_tables.setdefault(tname, {})
            for tn, e in tens.items():
                if not isinstance(e, dict):
                    continue
                ent = tt.setdefault(tn, {"served": 0, "shed": 0,
                                         "deferred": 0, "max_age_s": 0.0})
                for k in ("served", "shed", "deferred"):
                    ent[k] += int(e.get(k) or 0)
                age = float(e.get("max_age_s") or 0.0)
                if age > ent["max_age_s"]:
                    ent["max_age_s"] = age
                ten_hists.setdefault((tname, tn), []).append(
                    e.get("infer"))
        for k, a in (ten.get("admission") or {}).items():
            if not isinstance(a, dict):
                continue
            e = ten_adm.get(k)
            if e is None:
                ten_adm[k] = dict(a)
            else:
                e["admitted"] += int(a.get("admitted") or 0)
                e["shed"] += int(a.get("shed") or 0)
                if e.get("qps_limit") is None:
                    e["qps_limit"] = a.get("qps_limit")
    if ten_tables or wire_tenants or ten_adm:
        share_ops: Dict[str, int] = {}
        for tname, tt in ten_tables.items():
            for tn, ent in tt.items():
                ent["infer"] = merge_hist_dicts(
                    ten_hists.get((tname, tn), []))
                dem = ent["served"] + ent["shed"]
                ent["shed_rate"] = (round(ent["shed"] / dem, 4)
                                    if dem else None)
                share_ops[tn] = share_ops.get(tn, 0) + dem
        tot_ops = sum(share_ops.values())
        tblock: Dict[str, Any] = {
            "tables": ten_tables,
            "shares": ({tn: round(d / tot_ops, 4)
                        for tn, d in sorted(share_ops.items())}
                       if tot_ops else {}),
            "episodes": ten_episodes,
            "active": ten_active,
        }
        if ten_verdict is not None:
            tblock["verdict"] = ten_verdict
        if ten_adm:
            tblock["admission"] = ten_adm
        if wire_tenants:
            tblock["wire"] = wire_tenants
        if wire_sketches:
            merged = _hotkeys.merge_sketches(wire_sketches, key=str)
            tblock["sketch"] = {"total": merged["total"],
                                "observed": merged["observed"],
                                "top": merged["items"][:32]}
        rec["tenants"] = tblock
    if hot:
        rec["hotkeys"] = {}
        for tname, sketches in hot.items():
            merged = _hotkeys.merge_sketches(sketches)
            rec["hotkeys"][tname] = {
                "total": merged["total"],
                "observed": merged["observed"],
                "top": merged["items"][:32],
                "hit_rate_curve": _hotkeys.hit_rate_curve(merged),
            }
    # SLO sentinel passthrough (telemetry/slo.py): the block is judged
    # by ONE sentinel (rank 0's process) and identical wherever it
    # appears — first answering rank wins. A locally-armed sentinel
    # overwrites this with a fresher snapshot right after the merge
    # (poll_once), so the passthrough is what remote pollers (mvtop
    # against another process's cluster) render.
    for r in sorted(stats_by_rank):
        st = stats_by_rank[r]
        if isinstance(st, dict) and isinstance(st.get("slo"), dict):
            rec["slo"] = st["slo"]
            break
    return rec


def derive_rates(prev: Optional[Dict], cur: Dict) -> Optional[Dict]:
    """Windowed view between two consecutive cluster records, written
    into ``cur["rates"]``: per-table applies/s, gets/s, adds/s, wire
    bytes/s, the queue-depth delta, and ``skew_window`` — the imbalance
    of JUST this interval's traffic (the cumulative ``skew`` forgives a
    workload that went skewed after a long even warmup; the windowed one
    does not).

    All deltas are computed PER SHARD over the ranks present (and
    error-free) in BOTH records, then summed — never from the table
    totals. A rank whose stats probe failed in one poll and answered
    the next would otherwise dump its entire cumulative counter history
    into one interval: a phantom rate/skew burst in the time series at
    exactly the degraded moment the plane exists to observe. Such a
    rank simply sits the interval out and rejoins on the next pair of
    clean polls."""
    if not prev or prev.get("kind") != "cluster":
        return None
    dt = float(cur.get("ts", 0)) - float(prev.get("ts", 0))
    if dt <= 0:
        return None
    rates: Dict[str, Any] = {"_interval_s": round(dt, 3)}
    for tname, t in cur.get("tables", {}).items():
        pt = prev.get("tables", {}).get(tname)
        if not pt:
            continue
        # shards observed cleanly at BOTH ends of the interval
        pairs = []
        for r, s in t.get("shards", {}).items():
            ps_ = pt.get("shards", {}).get(r)
            if (ps_ is not None and "error" not in s
                    and "error" not in ps_):
                pairs.append((s, ps_))
        if not pairs:
            continue

        def delta(key):
            return sum(max(int(s.get(key) or 0) - int(ps_.get(key) or 0),
                           0) for s, ps_ in pairs)

        d = {"adds_per_s": round(delta("adds") / dt, 2),
             "gets_per_s": round(delta("gets") / dt, 2),
             "applies_per_s": round(delta("applies") / dt, 2),
             "wire_bytes_per_s": round(
                 (delta("add_bytes") + delta("get_bytes")) / dt, 1),
             "queue_depth_delta": sum(
                 int(s.get("queue_depth") or 0)
                 - int(ps_.get("queue_depth") or 0)
                 for s, ps_ in pairs),
             "skew_window": round(_skew(
                 [max((int(s.get("adds") or 0) + int(s.get("gets") or 0))
                      - (int(ps_.get("adds") or 0)
                         + int(ps_.get("gets") or 0)), 0)
                  for s, ps_ in pairs]), 3)}
        rates[tname] = d
    # serving plane: per-table replica-served / shed rates over the
    # interval, written INTO the serving entries (not the shard-rate
    # block — a serving-only table must not fabricate shard rates)
    prev_srv = prev.get("serving") or {}
    for tname, ent in (cur.get("serving") or {}).items():
        p = prev_srv.get(tname)
        if not isinstance(p, dict):
            continue
        ent["rates"] = {
            "served_per_s": round(
                max(ent.get("served", 0) - p.get("served", 0), 0) / dt,
                2),
            "shed_per_s": round(
                max(ent.get("shed", 0) - p.get("shed", 0), 0) / dt, 2),
        }
    # tenant plane: per-(table, tenant) interval rates, written INTO
    # the merged tenant entries (same discipline as serving — counters
    # absent from either end of the interval sit it out)
    prev_ten = (prev.get("tenants") or {}).get("tables") or {}
    for tname, tt in ((cur.get("tenants") or {}).get("tables")
                      or {}).items():
        pt = prev_ten.get(tname)
        if not isinstance(pt, dict):
            continue
        for tn, ent in tt.items():
            p = pt.get(tn)
            if not isinstance(p, dict):
                continue
            ent["rates"] = {
                "served_per_s": round(
                    max(ent.get("served", 0)
                        - p.get("served", 0), 0) / dt, 2),
                "shed_per_s": round(
                    max(ent.get("shed", 0)
                        - p.get("shed", 0), 0) / dt, 2),
            }
    cur["rates"] = rates
    return rates


def compact_record(rec: Dict, top: int = 8,
                   max_monitors: int = 64) -> Dict:
    """Bench-extra-sized digest of a cluster record: per-table op
    counts/skew/apply percentiles, hot-key heads + hit-rate curves, and
    the merged monitor histograms in brief form — what ``bench.py``
    records as ``extra.cluster`` and ``tools/run_bench.py`` compares
    run-over-run."""
    out: Dict[str, Any] = {
        "ts": rec.get("ts"), "world": rec.get("world"),
        "polled": rec.get("polled"),
        "ranks": {r: e.get("status")
                  for r, e in rec.get("ranks", {}).items()},
        "tables": {},
    }
    for tname, t in rec.get("tables", {}).items():
        a = t.get("apply") or {}
        out["tables"][tname] = {
            "shards": len(t.get("shards", {})),
            "adds": t.get("adds"), "gets": t.get("gets"),
            "applies": t.get("applies"),
            "queue_depth": t.get("queue_depth"), "skew": t.get("skew"),
            "apply_p50_ms": a.get("p50_ms"),
            "apply_p99_ms": a.get("p99_ms"),
        }
    if rec.get("hotkeys"):
        out["hotkeys"] = {
            tname: {"total": h.get("total"),
                    "top": list(h.get("top", []))[:top],
                    "hit_rate_curve": h.get("hit_rate_curve")}
            for tname, h in rec["hotkeys"].items()}
    if rec.get("rates"):
        out["rates"] = rec["rates"]
    if rec.get("serving"):
        # replica lag/hit-rate/shed summary (already compact)
        out["serving"] = rec["serving"]
    if rec.get("profile"):
        # per-rank step-profiler summaries (already compact)
        out["profile"] = rec["profile"]
    if rec.get("memory"):
        # per-rank RSS/device/ledger digests + cluster totals (already
        # compact) — run_bench compares peak figures run-over-run
        out["memory"] = rec["memory"]
    if rec.get("tenants"):
        # per-tenant serve/shed/share digest + verdict state (already
        # merged compact) — run_bench compares victim-tenant p99/shed
        out["tenants"] = rec["tenants"]
    if rec.get("slo"):
        # sentinel verdict block (already compact): per-objective burn
        # rates + firing state, episode totals, the named straggler
        out["slo"] = rec["slo"]
    mons: Dict[str, Any] = {}
    for n, m in sorted(rec.get("monitors", {}).items()):
        if not m.get("timed"):
            continue
        if len(mons) >= max_monitors:
            mons["_truncated"] = True
            break
        mons[n] = {k: m.get(k)
                   for k in ("count", "p50_ms", "p90_ms", "p99_ms",
                             "max_ms")}
    out["monitors"] = mons
    return out


# ---------------------------------------------------------------------- #
# the poller
# ---------------------------------------------------------------------- #
def probe_all(ranks, probe_one, deadline_s: float):
    """Run ``probe_one(rank, stats, health)`` for every rank
    CONCURRENTLY (one short-lived thread each) under ONE poll-wide
    deadline, returning frozen ``(stats, health)`` dict copies. A rank
    whose probe overruns the deadline gets TimeoutError placeholders
    and its daemon thread is abandoned (it writes into the originals,
    which are no longer read). Shared by :meth:`ClusterAggregator.
    poll_once` and ``tools/mvtop.py``: a degraded cluster — several
    frozen ranks each costing the full probe timeout — is exactly when
    the poll matters, and a serial sweep would take world x 2 timeouts
    there (and hold PSService.close's final poll just as long)."""
    stats: Dict[int, Any] = {}
    health: Dict[int, Any] = {}
    threads = []
    for r in ranks:
        th = threading.Thread(target=probe_one, args=(r, stats, health),
                              name=f"mv-probe-{r}", daemon=True)
        th.start()
        threads.append((r, th))
    deadline = time.monotonic() + deadline_s
    for _, th in threads:
        th.join(max(deadline - time.monotonic(), 0.0))
    for r, th in threads:
        if th.is_alive():
            err = TimeoutError("probe exceeded the poll deadline")
            health.setdefault(r, err)
            stats.setdefault(r, err)
    return dict(stats), dict(health)


class ClusterAggregator:
    """Background cluster poller bound to one PSService (rank 0's). See
    module docstring; ``poll_once()`` is the synchronous unit (tests,
    bench, and the final flush use it directly)."""

    def __init__(self, service, interval_s: float = 0.0,
                 directory: str = "", history: int = 720):
        self.service = service
        self.interval_s = float(interval_s)
        self.directory = directory
        self._history: collections.deque = collections.deque(
            maxlen=history)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # serializes poll_once: the interval thread and a final flush /
        # bench pull share the history's prev-record chaining and the
        # cluster.jsonl append
        self._poll_lock = threading.Lock()

    # ------------------------------------------------------------------ #
    def start(self) -> "ClusterAggregator":
        if self.interval_s > 0 and self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="mv-cluster-agg", daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — telemetry must not
                log.error("cluster stats poll failed: %s", e)  # kill runs

    def stop(self, final: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final:
            try:
                # short-timeout final poll: teardown must not hang a
                # ps_health_timeout per unreachable rank
                self.poll_once(timeout=1.0)
            except Exception as e:  # noqa: BLE001
                log.error("final cluster poll failed: %s", e)

    # ------------------------------------------------------------------ #
    def poll_once(self, timeout: Optional[float] = None) -> Dict:
        """Probe every rank (one-shot conns, CONCURRENT via
        :func:`probe_all` — errors/overruns become per-rank entries),
        merge, derive rates vs the previous record, append to the
        rolling history, and write the JSONL/.prom files. Bounded by
        one poll-wide deadline of ~2 probe timeouts regardless of how
        many ranks are frozen."""
        t = timeout or config.get_flag("ps_health_timeout")

        def probe_one(r, stats, health):
            try:
                health[r] = self.service.health(r, timeout=timeout)
            except Exception as e:  # noqa: BLE001 — per-rank entry
                health[r] = e
            try:
                stats[r] = self.service.stats_oneshot(r, timeout=timeout)
            except Exception as e:  # noqa: BLE001
                stats[r] = e

        stats, health = probe_all(range(self.service.world), probe_one,
                                  deadline_s=2.0 * t + 1.0)
        with self._poll_lock:
            rec = merge_cluster(stats, health, world=self.service.world)
            derive_rates(self.last(), rec)
            self._history.append(rec)
            # SLO sentinel + signal bus ride every poll (telemetry/slo.py,
            # telemetry/signals.py): judge the fresh record against the
            # rolling history, refresh rec["slo"], publish the typed
            # autoscaling signals. Telemetry never breaks the poll.
            try:
                snap = _slo.SENTINEL.on_poll(rec, list(self._history),
                                             self.directory)
                if snap is not None:
                    rec["slo"] = snap
            except Exception as e:   # noqa: BLE001
                log.error("SLO sentinel poll failed: %s", e)
            try:
                _signals.publish_record(rec)
            except Exception as e:   # noqa: BLE001
                log.error("signal bus publish failed: %s", e)
            try:
                self._write(rec)
            except OSError as e:
                log.error("cluster record write failed: %s", e)
        return rec

    def last(self) -> Optional[Dict]:
        return self._history[-1] if self._history else None

    def history(self) -> List[Dict]:
        return list(self._history)

    # ------------------------------------------------------------------ #
    def _write(self, rec: Dict) -> None:
        if not self.directory:
            return
        from multiverso_tpu.telemetry.exporter import prometheus_text
        os.makedirs(self.directory, exist_ok=True)
        with open(os.path.join(self.directory, "cluster.jsonl"),
                  "a") as f:
            f.write(json.dumps(rec) + "\n")
        # Prometheus view reuses the exporter's exact label scheme with
        # rank="cluster": merged monitors render as mv_monitor_* lines,
        # per-table cluster sums + skew (+ the windowed rates, flattened
        # in) as mv_shard_*{table=...}; one scrape config covers the
        # per-rank files AND this one
        shards: Dict[str, Dict] = {}
        for tname, t in rec.get("tables", {}).items():
            flat = {k: v for k, v in t.items()
                    if isinstance(v, (int, float))
                    and not isinstance(v, bool)}
            for k, v in (rec.get("rates", {}).get(tname) or {}).items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    flat[k] = v
            shards[tname] = flat
        payload = {"rank": "cluster", "monitors": rec.get("monitors", {}),
                   "shards": shards}
        if isinstance(rec.get("slo"), dict):
            payload["slo"] = rec["slo"]    # mv_slo_* gauges
        ppath = os.path.join(self.directory, "cluster.prom")
        tmp = ppath + ".tmp"
        with open(tmp, "w") as f:
            f.write(prometheus_text(payload))
        os.replace(tmp, ppath)


# ---------------------------------------------------------------------- #
# process-global lifecycle (controller rank only; idempotent stop)
# ---------------------------------------------------------------------- #
_global: Optional[ClusterAggregator] = None
_global_lock = threading.Lock()


def ensure_started(service) -> Optional[ClusterAggregator]:
    """Start the global aggregator when flags enable it and ``service``
    is the controller rank (PS rank 0 — the rank that already owns
    registration/barrier duties). Idempotent; returns the live
    aggregator or None."""
    global _global
    interval = config.get_flag("stats_poll_interval_s")
    if interval <= 0 or service.rank != 0:
        return None
    with _global_lock:
        if _global is None:
            _global = ClusterAggregator(
                service, interval,
                config.get_flag("metrics_dir")).start()
        return _global


def global_aggregator() -> Optional[ClusterAggregator]:
    with _global_lock:
        return _global


def stop_if_bound(service) -> None:
    """Stop the global aggregator iff it polls THROUGH ``service`` —
    called from PSService.close so the final poll runs while the
    service's probe path is still alive (a poll through a closed service
    would just record every rank unreachable)."""
    global _global
    with _global_lock:
        if _global is None or _global.service is not service:
            return
        agg, _global = _global, None
    agg.stop()


def stop_global(final: bool = True) -> None:
    """``final=False`` skips the last flush poll — for teardown paths
    (test isolation) where the bound service may already be gone and
    waiting out probe timeouts buys nothing."""
    global _global
    with _global_lock:
        agg, _global = _global, None
    if agg is not None:
        agg.stop(final=final)
