"""Typed autoscaling signal bus — the programmatic seam ROADMAP 5b
named as missing: every signal an autoscaler needs (shed rate, hot-key
mass, replica lag, queue depth, burn rates, warm-spare counts) was
already surfaced by mvtop's panels, but only as rendered text. This
module derives them from the SAME merged cluster record as typed
:class:`Signal` values and publishes them on a subscribable bus, so a
policy loop (``tools/mvautoscale.py``) consumes exactly what the
operator sees — no second measurement path to drift.

* :func:`from_record` is pure: one aggregator record -> the signal
  list (tested directly, like mvtop's ``render``);
* :class:`SignalBus` keeps the latest value per (name, table) and
  fans each publish out to subscribers (exceptions swallowed + logged
  — telemetry never takes the poller down);
* the aggregator publishes every poll through :func:`publish_record`,
  so ``BUS.snapshot()`` is always one poll fresh.

Signal names are a closed set (:data:`SIGNAL_NAMES`):
``tools/check_obs_surface.py`` lint 7 reads the tuple by ast and
requires every name to render in mvtop/dump_metrics — a signal the
bus carries but no pane of glass shows is an autoscaler input nobody
can audit.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, NamedTuple, Optional

from multiverso_tpu.utils import log

# the closed signal vocabulary (ast-read by check_obs_surface lint 7)
SIGNAL_NAMES = (
    "shed_rate",            # windowed shed fraction of serve demand
    "hot_key_mass",         # top-8 sketched rows' share of served ops
    "replica_lag_epochs",   # max shard version - min replica epoch
    "replica_lag_s",        # worst replica/member staleness seconds
    "queue_depth",          # server apply backlog per table
    "burn_rate",            # worst fast-window SLO burn (slo block)
    "spares_left",          # warm spares a pool could still promote
    "active_replicas",      # pool members currently serving
    "stall_fraction",       # worst profiled rank's unattributed wall
)


class Signal(NamedTuple):
    name: str
    table: Optional[str]
    value: float
    ts: float
    detail: Dict[str, Any]


def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


def from_record(rec: Dict[str, Any]) -> List[Signal]:
    """One merged cluster record -> every derivable signal (pure).
    Absent blocks contribute nothing — the bus carries evidence, not
    placeholders."""
    ts = float(rec.get("ts") or 0.0)
    out: List[Signal] = []

    def emit(name, table, value, **detail):
        v = _num(value)
        if v is not None:
            out.append(Signal(name, table, v, ts, detail))

    tables = rec.get("tables") or {}
    for t, tb in tables.items():
        if isinstance(tb, dict):
            emit("queue_depth", t, tb.get("queue_depth"))
    for t, s in (rec.get("serving") or {}).items():
        if not isinstance(s, dict):
            continue
        r = s.get("rates") or {}
        served, shed = _num(r.get("served_per_s")), _num(r.get("shed_per_s"))
        if served is not None and shed is not None and served + shed > 0:
            emit("shed_rate", t, shed / (served + shed),
                 served_per_s=served, shed_per_s=shed)
        ages = [_num(e.get("age_s"))
                for e in (s.get("replicas") or {}).values()
                if isinstance(e, dict)]
        epochs = [_num(e.get("epoch"))
                  for e in (s.get("replicas") or {}).values()
                  if isinstance(e, dict)]
        spares = active = 0
        have_pool = False
        for p in (s.get("pools") or {}).values():
            if not isinstance(p, dict):
                continue
            have_pool = True
            spares += int(p.get("spares_left") or 0)
            active += int(p.get("active") or 0)
            for m in p.get("members", []):
                if isinstance(m, dict) and m.get("active"):
                    ages.append(_num(m.get("age_s")))
                    epochs.append(_num(m.get("epoch")))
        ages = [a for a in ages if a is not None]
        if ages:
            emit("replica_lag_s", t, max(ages))
        epochs = [e for e in epochs if e is not None]
        versions = [_num(sh.get("version"))
                    for sh in (tables.get(t, {}).get("shards")
                               or {}).values() if isinstance(sh, dict)]
        versions = [v for v in versions if v is not None]
        if epochs and versions:
            emit("replica_lag_epochs", t,
                 max(0.0, max(versions) - min(epochs)),
                 head_version=max(versions), min_epoch=min(epochs))
        if have_pool:
            emit("spares_left", t, spares)
            emit("active_replicas", t, active)
    for t, h in (rec.get("hotkeys") or {}).items():
        if not isinstance(h, dict):
            continue
        total = _num(h.get("total"))
        top = h.get("top") or []
        if total and top:
            mass = sum(c for _k, c, *_ in top[:8]
                       if isinstance(c, (int, float))) / total
            emit("hot_key_mass", t, mass, top_k=min(len(top), 8))
    stalls = [_num(p.get("stall_fraction"))
              for p in (rec.get("profile") or {}).values()
              if isinstance(p, dict)]
    stalls = [s for s in stalls if s is not None]
    if stalls:
        emit("stall_fraction", None, max(stalls))
    slo = rec.get("slo")
    if isinstance(slo, dict):
        burns = {name: _num(o.get("burn_fast"))
                 for name, o in (slo.get("objectives") or {}).items()
                 if isinstance(o, dict)}
        burns = {n: b for n, b in burns.items() if b is not None}
        if burns:
            worst = max(burns, key=burns.get)
            emit("burn_rate", None, burns[worst],
                 objective=worst, firing=list(slo.get("firing") or []))
    return out


class SignalBus:
    """Latest-value store + subscriber fan-out. ``subscribe(fn)`` gets
    every signal; ``subscribe(fn, name=...)`` filters. Subscriber
    exceptions are logged and swallowed — a broken policy loop must
    not stall the aggregator's poll."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._latest: Dict[tuple, Signal] = {}
        self._subs: List[tuple] = []   # (fn, name-or-None)

    def subscribe(self, fn: Callable[[Signal], None],
                  name: Optional[str] = None) -> Callable[[], None]:
        """Register; returns the unsubscribe callable."""
        entry = (fn, name)
        with self._lock:
            self._subs.append(entry)

        def unsubscribe() -> None:
            with self._lock:
                if entry in self._subs:
                    self._subs.remove(entry)
        return unsubscribe

    def publish(self, signals: List[Signal]) -> None:
        with self._lock:
            for s in signals:
                self._latest[(s.name, s.table)] = s
            subs = list(self._subs)
        for s in signals:
            for fn, name in subs:
                if name is not None and name != s.name:
                    continue
                try:
                    fn(s)
                except Exception as e:   # noqa: BLE001
                    log.error("signal subscriber failed on %s: %s",
                              s.name, e)

    def latest(self, name: str,
               table: Optional[str] = None) -> Optional[Signal]:
        with self._lock:
            return self._latest.get((name, table))

    def snapshot(self) -> Dict[str, Dict[str, Dict[str, Any]]]:
        """{name: {table-or-"": {"value", "ts", "detail"}}} — the
        shape ``tools/mvautoscale.py`` recommends from."""
        with self._lock:
            out: Dict[str, Dict[str, Dict[str, Any]]] = {}
            for (name, table), s in self._latest.items():
                out.setdefault(name, {})[table or ""] = {
                    "value": s.value, "ts": s.ts,
                    "detail": dict(s.detail)}
            return out

    def reset(self) -> None:
        with self._lock:
            self._latest = {}
            self._subs = []


BUS = SignalBus()


def publish_record(rec: Dict[str, Any]) -> List[Signal]:
    """Derive + publish one record's signals on the process bus (the
    aggregator calls this every poll)."""
    signals = from_record(rec)
    BUS.publish(signals)
    return signals


def snapshot() -> Dict[str, Dict[str, Dict[str, Any]]]:
    return BUS.snapshot()


def reset() -> None:
    BUS.reset()
