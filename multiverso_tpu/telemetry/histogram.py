"""Fixed-bucket log-scale histogram for latency (and other positive)
samples.

Design constraints, in order:

1. ``observe`` must stay cheap enough for the PS hot path (a windowed
   1-row ``add_rows_async`` completes in ~30 us; the whole Monitor
   update budget is well under a microsecond): one ``math.log2``, one
   list increment, no allocation. The histogram itself takes NO lock —
   the embedding :class:`~multiverso_tpu.utils.dashboard.Monitor`
   already holds one for its count/sum fields and the histogram update
   rides inside that same critical section.
2. Fixed memory: bucket boundaries are powers of ``2**(1/LOG2_SUB)``
   over a hard-coded range, so every histogram is one flat int list and
   two histograms (e.g. a remote shard's and a local one) merge by
   elementwise addition — no rebucketing, ever.
3. Quantiles reconstruct from buckets with bounded relative error
   (one bucket width, ~19% at ``LOG2_SUB=4``), tightened at the edges
   by the tracked exact min/max.

The range [2**-14, 2**22) ms spans ~61 ns to ~70 min — below the
cheapest monitored op and above any sane request timeout; out-of-range
samples clamp into the edge buckets (their mass is never lost, only
their resolution).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

# sub-buckets per octave (power of two): 4 -> bucket ratio 2**0.25 ~ 1.19
LOG2_SUB = 4
_MIN_EXP = -14          # lowest bucket lower bound: 2**-14 ms (~61 ns)
_MAX_EXP = 22           # highest bucket upper bound: 2**22 ms (~70 min)
NBUCKETS = (_MAX_EXP - _MIN_EXP) * LOG2_SUB
# bucket i covers [2**(_MIN_EXP + i/SUB), 2**(_MIN_EXP + (i+1)/SUB)) ms
BOUNDS: Tuple[float, ...] = tuple(
    2.0 ** (_MIN_EXP + (i + 1) / LOG2_SUB) for i in range(NBUCKETS))


def bucket_index(ms: float) -> int:
    """Bucket index of a sample (clamped into [0, NBUCKETS-1); <= 0
    samples land in bucket 0 — a zero-duration observe must count, not
    raise on log2)."""
    if ms <= 0.0:
        return 0
    i = int((math.log2(ms) - _MIN_EXP) * LOG2_SUB)
    if i < 0:
        return 0
    if i >= NBUCKETS:
        return NBUCKETS - 1
    return i


class Histogram:
    """Log2-bucket histogram. NOT thread-safe on its own: the caller
    (Monitor) synchronizes; snapshots are taken under that same lock."""

    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self) -> None:
        self.counts: List[int] = [0] * NBUCKETS
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = 0.0

    def observe(self, ms: float) -> None:
        self.counts[bucket_index(ms)] += 1
        self.count += 1
        self.sum += ms
        if ms < self.min:
            self.min = ms
        if ms > self.max:
            self.max = ms

    def merge(self, other: "Histogram") -> None:
        """Elementwise merge (cross-shard / cross-rank aggregation);
        identical fixed buckets make this exact."""
        for i, c in enumerate(other.counts):
            if c:
                self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        if other.min < self.min:
            self.min = other.min
        if other.max > self.max:
            self.max = other.max

    # ------------------------------------------------------------------ #
    def percentile(self, q: float) -> float:
        """Quantile estimate (``q`` in [0, 100]) by linear interpolation
        inside the covering bucket, clamped to the exact observed
        min/max so p0/p100 are never a bucket-width off."""
        if self.count == 0:
            return 0.0
        if q <= 0:
            return self.min
        if q >= 100:
            return self.max
        target = self.count * q / 100.0
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = BOUNDS[i] / (2.0 ** (1.0 / LOG2_SUB))
                hi = BOUNDS[i]
                frac = (target - cum) / c
                est = lo + (hi - lo) * frac
                return min(max(est, self.min), self.max)
            cum += c
        return self.max

    def percentiles(self, qs: Sequence[float] = (50, 90, 99)
                    ) -> Tuple[float, ...]:
        return tuple(self.percentile(q) for q in qs)

    # ------------------------------------------------------------------ #
    def nonzero(self) -> List[Tuple[float, int]]:
        """Sparse view: (bucket upper bound ms, count) for occupied
        buckets — the export/merge wire format (a full 144-bucket dump
        per monitor per interval would be mostly zeros)."""
        return [(BOUNDS[i], c) for i, c in enumerate(self.counts) if c]

    @classmethod
    def from_nonzero(cls, items: Sequence[Tuple[float, int]],
                     count: Optional[int] = None, total: float = 0.0,
                     min_ms: Optional[float] = None,
                     max_ms: Optional[float] = None) -> "Histogram":
        """Rebuild from the sparse view (bound values are matched to the
        fixed bucket table by index; a bound that no longer matches —
        e.g. from a future layout — clamps like an ordinary sample)."""
        h = cls()
        for bound, c in items:
            # the bound is a bucket UPPER bound: nudge just below it so
            # bucket_index maps it back to the originating bucket
            h.counts[bucket_index(float(bound) * 0.999)] += int(c)
        h.count = sum(h.counts) if count is None else int(count)
        h.sum = float(total)
        occupied = [float(b) for b, c in items if c]
        # an incr-only monitor's record has count > 0 with NO buckets —
        # min/max only reconstruct when there is bucket mass to infer
        # them from (or the caller passed them explicitly)
        if min_ms is not None:
            h.min = float(min_ms)
        elif occupied:
            h.min = min(occupied) / (2 ** (1 / LOG2_SUB))
        if max_ms is not None:
            h.max = float(max_ms)
        elif occupied:
            h.max = max(occupied)
        return h

    def as_dict(self) -> Dict:
        """JSON-safe snapshot — SAME key set as
        ``dashboard.MonitorSnapshot.hist_dict()`` (the exporter /
        MSG_STATS wire shape; keep the two in lockstep). A bare
        histogram has no ``incr``-style untimed events, so here
        ``timed`` == ``count``."""
        p50, p90, p99 = self.percentiles((50, 90, 99))
        return {
            "count": self.count,
            "sum_ms": round(self.sum, 6),
            "min_ms": round(self.min, 6) if self.count else 0.0,
            "max_ms": round(self.max, 6),
            "p50_ms": round(p50, 6),
            "p90_ms": round(p90, 6),
            "p99_ms": round(p99, 6),
            "timed": self.count,
            "buckets": [[b, c] for b, c in self.nonzero()],
        }
