"""Typed flag registry.

TPU-native re-design of the reference's configure system
(ref: include/multiverso/util/configure.h:65-112, src/util/configure.cpp:9-54):
``define_*`` registers a typed flag with a default and help string,
``parse_cmd_flags`` consumes ``-key=value`` argv entries (compacting argv, as the
reference does), and ``set_flag`` is the programmatic override used by bindings
and apps (ref: binding/python/multiverso/api.py:31, ps_model.cpp:24).

Unlike the reference there is no static-initialization dance: the registry is a
plain module-level dict, and flags may be (re)defined at import time by any
subsystem. Types: bool, int, float, str.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

_TRUE_STRINGS = frozenset({"true", "1", "yes", "on"})
_FALSE_STRINGS = frozenset({"false", "0", "no", "off"})


@dataclass
class _Flag:
    name: str
    value: Any
    default: Any
    type: type
    help: str


_registry: Dict[str, _Flag] = {}
_lock = threading.RLock()


class FlagError(KeyError):
    """Raised for unknown flags or bad flag values."""


def _define(name: str, default: Any, ftype: type, help: str) -> None:
    with _lock:
        if name in _registry and _registry[name].type is not ftype:
            raise FlagError(
                f"flag {name!r} redefined with different type "
                f"({_registry[name].type.__name__} -> {ftype.__name__})"
            )
        _registry[name] = _Flag(name, default, default, ftype, help)


def define_bool(name: str, default: bool, help: str = "") -> None:
    _define(name, bool(default), bool, help)


def define_int(name: str, default: int, help: str = "") -> None:
    _define(name, int(default), int, help)


def define_float(name: str, default: float, help: str = "") -> None:
    _define(name, float(default), float, help)


def define_string(name: str, default: str, help: str = "") -> None:
    _define(name, str(default), str, help)


def _coerce(flag: _Flag, value: Any) -> Any:
    if flag.type is bool:
        if isinstance(value, bool):
            return value
        s = str(value).strip().lower()
        if s in _TRUE_STRINGS:
            return True
        if s in _FALSE_STRINGS:
            return False
        raise FlagError(f"bad boolean value {value!r} for flag {flag.name!r}")
    try:
        return flag.type(value)
    except (TypeError, ValueError) as e:
        raise FlagError(
            f"bad {flag.type.__name__} value {value!r} for flag {flag.name!r}"
        ) from e


def get_flag(name: str) -> Any:
    with _lock:
        try:
            return _registry[name].value
        except KeyError:
            raise FlagError(f"unknown flag {name!r}") from None


def set_flag(name: str, value: Any) -> None:
    """Programmatic override (ref SetCMDFlag, src/util/configure.cpp)."""
    with _lock:
        try:
            flag = _registry[name]
        except KeyError:
            raise FlagError(f"unknown flag {name!r}") from None
        flag.value = _coerce(flag, value)


def has_flag(name: str) -> bool:
    with _lock:
        return name in _registry


def reset_flags() -> None:
    """Reset every flag to its default (test isolation helper)."""
    with _lock:
        for flag in _registry.values():
            flag.value = flag.default


def flags() -> Dict[str, Any]:
    """Snapshot of the current flag values."""
    with _lock:
        return {name: f.value for name, f in _registry.items()}


def parse_cmd_flags(argv: Optional[List[str]] = None) -> List[str]:
    """Consume ``-key=value`` entries from ``argv``; return the remainder.

    Mirrors the reference's argv compaction (src/util/configure.cpp:9-54):
    recognized flags are removed, everything else is kept in order. Unknown
    ``-key=value`` entries are kept (the reference warns and keeps them too).
    """
    if argv is None:
        return []
    remainder: List[str] = []
    for arg in argv:
        matched = False
        if arg.startswith("-") and "=" in arg:
            body = arg.lstrip("-")
            key, _, value = body.partition("=")
            with _lock:
                if key in _registry:
                    flag = _registry[key]
                    flag.value = _coerce(flag, value)
                    matched = True
        if not matched:
            remainder.append(arg)
    return remainder


def consume_runtime_flags(argv: Optional[List[str]]) -> List[str]:
    """App-CLI preamble: ``-key=value`` entries are runtime flags — parsed
    into the registry, unknown ones warned about (the reference 'warns and
    keeps', src/util/configure.cpp:9-54) — and everything else (the app's
    own ``-key value`` pairs / positionals) is returned. One definition of
    the MV_Init argv contract for every app entry point."""
    argv = list(argv or [])
    flags = [a for a in argv if a.startswith("-") and "=" in a]
    rest = [a for a in argv if not (a.startswith("-") and "=" in a)]
    for a in parse_cmd_flags(flags):
        from multiverso_tpu.utils import log   # lazy: log reads flags
        log.error("unknown runtime flag %s (ignored; app keys use "
                  "'-key value' or config-file form)", a)
    return rest


def parse_config_file(path: str) -> Dict[str, str]:
    """Parse a ``key=value`` config file (LR-app style, ref configure.cpp).

    Lines starting with ``#`` and blank lines are skipped. Known flags are set;
    all pairs are returned for app-level consumption.
    """
    out: Dict[str, str] = {}
    with open(path, "r") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            key, _, value = line.partition("=")
            key, value = key.strip(), value.strip()
            if not key:
                continue
            out[key] = value
            with _lock:
                if key in _registry:
                    flag = _registry[key]
                    flag.value = _coerce(flag, value)
    return out


# ---------------------------------------------------------------------------
# Core framework flags (inventory mirrors the reference's MV_DEFINE_* set).
# Flags whose mechanism has no TPU meaning (OpenMP thread pools, host
# allocator tuning, ZMQ membership files) are still ACCEPTED so reference
# command lines parse unchanged, and documented as no-ops here.
# ---------------------------------------------------------------------------
define_string("ps_role", "default", "role of this process: none|worker|server|default")
# Client-side send window for the sparse async-PS plane (ps/tables.py):
# add_rows_async calls buffer per (owner, table) and flush as ONE frame —
# one round-trip and one batched shard apply per window instead of one
# per call. Off by default: flush()-exact callers (and anything relying
# on an add being on the wire when add_rows_async returns) see no change
# unless they opt in. Windowed results are BIT-IDENTICAL to window-off
# (exact concat merging only; conflicting ops apply in order).
define_float("batch_window_ms", 0.0,
             "send-window age bound in ms for async add_rows batching; "
             "0 disables the window (every add ships immediately). "
             "1-2 ms is the bench-derived sweet spot for ~1-row adds "
             "(docs/TUNING.md)")
define_int("batch_window_bytes", 1 << 20,
           "flush an owner's send window early once its pending add "
           "payloads reach this many bytes")
define_int("batch_window_ops", 64,
           "flush an owner's send window early once this many logical "
           "adds are queued for it")
# Client-side GET coalescer + chunk-streamed replies (the read-path
# mirror of the send window, ps/tables._GetWindow + ps/wire.ChunkedReply)
define_float("get_window_ms", 0.0,
             "enable the client get coalescer for async tables: > 0 "
             "turns on single-flight per-owner fetches — a get to an "
             "idle owner dispatches immediately (no added latency); "
             "gets arriving while that owner's fetch is outstanding "
             "dedupe into ONE follow-up frame, dispatched when the "
             "outstanding reply lands or when the oldest queued get is "
             "this many ms old (so a small get is never starved behind "
             "a long chunked fetch). 0 disables (every get is its own "
             "frame). Per-table override: get_window_ms= on the table")
# Exactly-once send-window replay (the elastic-failover client half,
# ps/tables._SendWindow + ps/shard dedupe; docs/FAILOVER.md): windowed
# frames carry a per-(client, table) monotonic sequence, the owning
# shard dedupes by high-water mark, and the client RETAINS frames past
# their ack until the shard reports them durable (checkpointed) — on a
# shard death the retained tail re-flushes to the restored incarnation,
# so no acked op is lost and no frame applies twice.
define_bool("ps_replay", False,
            "stamp windowed async-table frames with (client, seq), "
            "retain them until the owning shard reports them durable, "
            "and replay the unacked/non-durable tail to a restarted "
            "shard incarnation (dedup by per-client high-water mark on "
            "the shard). Requires a send window (batch_window_ms / "
            "send_window_ms=); the failover supervisor's checkpointer "
            "advances the durable mark (docs/FAILOVER.md)")
define_float("ps_replay_timeout", 120.0,
             "seconds a replayed frame keeps retrying against a dead "
             "owner before its futures fail with PSPeerError (bounds "
             "how long a failover may take before clients give up)")
define_float("ps_replay_backoff", 0.5,
             "BASE seconds between replay attempts against an owner "
             "that is still unreachable; each failed attempt within an "
             "episode doubles the delay (jittered) up to "
             "ps_replay_backoff_cap — the shared capped-exponential "
             "retry policy (utils/retry.py)")
define_float("ps_replay_backoff_cap", 4.0,
             "cap seconds for the replay plane's exponential backoff: "
             "a long owner respawn decays to this poll rate instead "
             "of hammering the restarting rank at the base rate")
define_int("ps_replay_max_frames", 4096,
           "retained-frame cap per owner: past it the oldest ACKED "
           "frames are dropped (with a warning) — durability degrades "
           "to ack-time instead of checkpoint-time rather than memory "
           "growing without bound when no checkpointer is advancing "
           "the durable mark")
define_int("get_chunk_rows", 0,
           "chunk-stream get replies above this many rows: the server "
           "ships N self-describing sub-frames instead of one "
           "mega-frame, so the client's decode + out= scatter overlaps "
           "the network receive. 0 disables. Only requested over python "
           "conns; a native C++ server punts chunk-requesting gets to "
           "its python handlers (slower than its zero-Python fast "
           "path — leave 0 when the hot gets are natively served)")
define_bool("ma", False, "model-average (allreduce) mode: no parameter tables")
define_bool("sync", False, "BSP semantics (reference SyncServer). On TPU sync is "
            "the hardware-native mode; async emulated via sync_frequency")
define_float("backup_worker_ratio", 0.0, "straggler backup ratio (reference "
             "declared-but-dead flag; wired here to worker_map redundancy)")
define_string("updater_type", "default", "server-side updater: "
              "default|sgd|momentum_sgd|adagrad|adam")
define_int("num_workers", 0, "logical workers; 0 = one per JAX process")
define_int("num_servers", 0, "logical server shards; 0 = one per device")
define_string("mesh_axis", "mv", "name of the table-sharding mesh axis")
define_string("log_level", "info", "debug|info|error|fatal")
define_string("log_file", "", "optional log file path ('' = stdout only)")
define_bool("log_jsonl", False,
            "write the log FILE as structured JSONL (ts/mono/level/rank/"
            "name/msg) so tools/postmortem.py can interleave log lines "
            "with flight-recorder dumps; console output stays text")
define_bool("dashboard", True, "collect Monitor timings and display at shutdown")
# Reference CLI-parity no-ops (mechanism owned by XLA / the JAX runtime):
define_int("omp_threads", 4, "no-op: shard updates are VPU-parallel under XLA "
           "(reference OpenMP server loop)")
define_string("allocator_type", "smart", "no-op: device memory is XLA's BFC "
              "arena (reference SmartAllocator)")
define_int("allocator_alignment", 16, "no-op: XLA controls buffer alignment")
define_string("machine_file", "", "no-op: pod topology comes from the JAX "
              "runtime (reference ZMQ membership file)")
define_int("port", 55555, "no-op: see machine_file; DCN endpoints come from "
           "net_init(coordinator_address)")
