"""File-based rendezvous barrier for coordinating plain OS processes.

Used by the multi-process test/bench workers (the async-PS plane itself
has NO barriers — this is harness-side coordination, the moral equivalent
of mpirun's world bring-up around the reference's Test/main.cpp battery).
Each rank publishes ``<dir>/<tag>.<rank>`` and polls for all ranks.
"""

from __future__ import annotations

import os
import time


def file_barrier(directory: str, world: int, rank: int, tag: str,
                 timeout: float = 120.0, poll: float = 0.01) -> None:
    open(os.path.join(directory, f"{tag}.{rank}"), "w").close()
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(os.path.exists(os.path.join(directory, f"{tag}.{r}"))
               for r in range(world)):
            return
        time.sleep(poll)
    raise TimeoutError(f"file_barrier {tag!r}: not all of {world} ranks "
                       f"arrived within {timeout}s")
