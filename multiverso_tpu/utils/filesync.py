"""File-based rendezvous barrier for coordinating plain OS processes.

Used by the multi-process test/bench workers (the async-PS plane itself
has NO barriers — this is harness-side coordination, the moral equivalent
of mpirun's world bring-up around the reference's Test/main.cpp battery).
Each rank publishes ``<dir>/<tag>.<rank>`` and polls for all ranks.

Observability (PR 4): enter/exit/timeout ride the flight recorder, and a
timeout names WHO arrived and who is missing — "not all ranks arrived"
localized to the absent ranks without grepping N logs.
"""

from __future__ import annotations

import os
import time


def file_barrier(directory: str, world: int, rank: int, tag: str,
                 timeout: float = 120.0, poll: float = 0.01) -> None:
    from multiverso_tpu.telemetry import flightrec
    flightrec.record(flightrec.EV_BARRIER_ENTER, note=tag)
    open(os.path.join(directory, f"{tag}.{rank}"), "w").close()
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(os.path.exists(os.path.join(directory, f"{tag}.{r}"))
               for r in range(world)):
            flightrec.record(flightrec.EV_BARRIER_EXIT, note=tag)
            return
        time.sleep(poll)
    # arrival snapshot: the missing ranks ARE the diagnosis, so they
    # belong in the exception (and on the black box before the raise —
    # a rank that dies on this timeout still leaves the evidence)
    arrived = [r for r in range(world)
               if os.path.exists(os.path.join(directory, f"{tag}.{r}"))]
    missing = [r for r in range(world) if r not in arrived]
    if not missing:
        # the last marker landed between the loop's final check and the
        # deadline: the barrier IS satisfied — raising with its own
        # evidence saying "missing []" would be a spurious failure
        flightrec.record(flightrec.EV_BARRIER_EXIT, note=tag)
        return
    flightrec.record(flightrec.EV_BARRIER_TIMEOUT,
                     note=f"{tag}: missing {missing}"[:200])
    raise TimeoutError(
        f"file_barrier {tag!r}: rank {rank} waited {timeout}s; "
        f"arrived {arrived}, missing {missing} of world {world}")
