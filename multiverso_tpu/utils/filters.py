"""Wire compression filters (numpy REFERENCE implementations).

TPU-native equivalent of the reference filter layer
(ref: include/multiverso/util/quantization_util.h:37-154 — ``SparseFilter``
rewrites a blob as (index, value) pairs when >50% of entries fall under a
clip threshold; ``OneBitsFilter`` (:160-161) was declared and never
implemented). On TPU the intra-pod wire is ICI managed by XLA, so these
filters matter on the *host/DCN* seams: compressing deltas before
cross-process aggregation or before a tunneled host<->device transfer.

``OneBitsFilter`` is actually implemented here — 1-bit sign quantization with
per-block scale and error-feedback residual (the 1-bit SGD recipe the
reference planned): finishing what the reference left as a stub.
``TopKFilter`` adds the sparse top-magnitude encode (QSGD-style
sparsification) with the same error-feedback contract.

These numpy implementations are the SOURCE OF TRUTH the jitted device
kernels in ``ops/wire_codec.py`` are property-tested against, bit-for-bit
on bits and scales. That parity is engineered: per-block sums use the
explicit pairwise fold in :func:`_fold_sum` (the identical f32 addition
sequence the device kernel performs — a naive ``.sum(1)`` would differ in
the last ulp from XLA's reduction order), masking uses ``where`` (never
multiply, which XLA could fuse into an FMA), and the scale division is a
single f32/f32 divide. Change one side only in lockstep with the other.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np


# Codec property: SUB-NORMAL inputs are flushed to zero before encoding.
# XLA's CPU/TPU arithmetic flushes denormals (FTZ) the moment the residual
# add runs, so the device kernel cannot see them; the numpy side flushes
# EXPLICITLY at the same point so bits/scales/residuals stay bit-identical.
# Denormal gradient entries (< ~1.18e-38) are far below any useful signal.
_TINY = np.float32(np.finfo(np.float32).tiny)


def canon_f32(x: np.ndarray) -> np.ndarray:
    """Flush sub-normals to zero (mirrors ``wire_codec.canon_f32``)."""
    return np.where(np.abs(x) < _TINY, np.float32(0), x)


def _fold_sum(x: np.ndarray) -> np.ndarray:
    """Pairwise-fold sum over axis 1 (width must be a power of two):
    mirrors ``wire_codec.fold_sum`` addition-for-addition."""
    while x.shape[1] > 1:
        x = x[:, 0::2] + x[:, 1::2]
    return x[:, 0]


def _pow2_pad(width: int) -> int:
    return 1 << max(width - 1, 0).bit_length() if width > 1 else 1


def _block_scales(blocks: np.ndarray, n: Optional[int] = None
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(pos mask, pos_scale, neg_scale) for (nb, block) f32 blocks —
    mean of positives / mean magnitude of non-positives per block.
    ``n`` (logical element count): the block-padding tail beyond it is
    EXCLUDED from the negative-side mean — pad zeros are not data, and
    counting them dilutes the last block's neg scale toward 0 (for a
    small payload in a big block that dilution destabilizes error
    feedback: negatives decode near-zero forever)."""
    nb, block = blocks.shape
    pos = blocks > 0
    neg = ~pos
    if n is not None and n < nb * block:
        valid = (np.arange(nb * block) < n).reshape(nb, block)
        neg = neg & valid
    m = _pow2_pad(block)

    def _mean(vals: np.ndarray, mask: np.ndarray) -> np.ndarray:
        picked = np.where(mask, vals, np.float32(0))
        if m != block:
            picked = np.pad(picked, ((0, 0), (0, m - block)))
        s = _fold_sum(picked)
        cnt = np.maximum(mask.sum(1), 1).astype(np.float32)
        return np.where(mask.any(1), s / cnt, np.float32(0))

    return pos, _mean(blocks, pos), _mean(-blocks, neg)


def onebit_encode_np(flat: np.ndarray, block: int = 1024
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Stateless 1-bit encode of a flat f32 array -> (bits, scales) —
    the payload half of :class:`OneBitsFilter` without the residual, and
    the numpy reference of ``wire_codec.onebit_encode``. Used where the
    stream has no owner to carry error feedback (the PS wire's
    :func:`~multiverso_tpu.ps.wire.encode_payload`) and as the shared
    core of the filter above."""
    if block % 8:
        raise ValueError(f"block must be a multiple of 8, got {block}")
    flat = canon_f32(np.asarray(flat, np.float32).reshape(-1))
    n = flat.size
    nb = (n + block - 1) // block
    padded = np.zeros(nb * block, np.float32)
    padded[:n] = flat
    pos, pos_scale, neg_scale = _block_scales(padded.reshape(nb, block),
                                              n=n)
    return np.packbits(pos, axis=None), np.stack([pos_scale, neg_scale],
                                                 axis=1)


def onebit_decode_np(bits: np.ndarray, scales: np.ndarray, n: int,
                     block: int = 1024) -> np.ndarray:
    """Inverse of :func:`onebit_encode_np` (f32[n] out)."""
    nb = (n + block - 1) // block
    pos = np.unpackbits(np.asarray(bits), count=nb * block
                        ).astype(bool).reshape(nb, block)
    scales = np.asarray(scales)
    out = np.where(pos, scales[:, 0][:, None], -scales[:, 1][:, None])
    return out.reshape(-1)[:n].astype(np.float32)


def default_topk(n: int) -> int:
    """Default top-k support: ~3% of entries, at least one (MUST stay in
    sync with ``wire_codec.default_topk`` — the two codecs are parallel
    implementations of the same wire)."""
    return max(n // 32, 1)


def topk_encode_np(flat: np.ndarray, k: Optional[int] = None
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Stateless top-k encode of a flat f32 array -> (idx i32, vals f32)
    — the payload half of :class:`TopKFilter` without the residual (same
    selection rule: stable descending |x|, ties to the lower index, like
    ``jax.lax.top_k``). Used where the stream has no owner to carry
    error feedback (row-batch adds on the PS wire: the row set changes
    between batches, so a positional residual has no stable meaning)."""
    flat = canon_f32(np.asarray(flat, np.float32).reshape(-1))
    k = min(default_topk(flat.size) if k is None else k, flat.size)
    idx = np.argsort(-np.abs(flat), kind="stable")[:k].astype(np.int32)
    return idx, flat[idx]


def topk_decode_np(idx: np.ndarray, vals: np.ndarray, n: int) -> np.ndarray:
    """Inverse of :func:`topk_encode_np` (zeros off-support)."""
    out = np.zeros(n, np.float32)
    out[np.asarray(idx)] = np.asarray(vals, np.float32)
    return out


class SparseFilter:
    """(index, value) sparse encoding under a clip threshold
    (ref quantization_util.h SparseFilter: FilterIn/FilterOut)."""

    def __init__(self, clip: float = 0.0):
        self.clip = clip

    def filter_in(self, data: np.ndarray) -> Tuple[Dict, np.ndarray]:
        """Returns (header, payload). Sparse iff >50% of entries are clipped
        (the reference's worthwhile-to-compress rule)."""
        flat = np.asarray(data, dtype=np.float32).reshape(-1)
        keep = np.abs(flat) > self.clip
        nnz = int(keep.sum())
        if nnz * 2 < flat.size:
            idx = np.nonzero(keep)[0].astype(np.int32)
            vals = flat[keep]
            payload = np.concatenate([idx.view(np.float32), vals])
            return ({"sparse": True, "size": flat.size, "nnz": nnz},
                    payload)
        return {"sparse": False, "size": flat.size}, flat

    def filter_out(self, header: Dict, payload: np.ndarray) -> np.ndarray:
        if not header["sparse"]:
            return payload.copy()
        nnz = header["nnz"]
        idx = payload[:nnz].view(np.int32)
        vals = payload[nnz:]
        out = np.zeros(header["size"], dtype=np.float32)
        out[idx] = vals
        return out


class OneBitsFilter:
    """1-bit quantization with error feedback (declared but empty in the
    reference, quantization_util.h:160-161 — implemented here).

    Encode: per-block mean magnitude of positives/negatives + sign bitmap.
    The quantization error is kept as a residual and added to the next
    payload, so the compressed stream is unbiased over time (1-bit SGD)."""

    def __init__(self, block: int = 1024):
        if block % 8:
            raise ValueError(f"block must be a multiple of 8, got {block}")
        self.block = block
        self._residual: Optional[np.ndarray] = None

    def filter_in(self, data: np.ndarray) -> Tuple[Dict, np.ndarray, np.ndarray]:
        flat = np.asarray(data, dtype=np.float32).reshape(-1)
        if self._residual is None or self._residual.size != flat.size:
            self._residual = np.zeros_like(flat)
        flat = canon_f32(flat + self._residual)
        n = flat.size
        bits, scales = onebit_encode_np(flat, self.block)
        self._residual = flat - onebit_decode_np(bits, scales, n, self.block)
        return {"size": n, "block": self.block}, bits, scales

    def filter_out(self, header: Dict, bits: np.ndarray,
                   scales: np.ndarray) -> np.ndarray:
        return onebit_decode_np(bits, scales, header["size"],
                                header["block"])

    def compression_ratio(self, n: int) -> float:
        """bytes(original float32) / bytes(bits + scales)."""
        nb = (n + self.block - 1) // self.block
        return (4.0 * n) / (n / 8.0 + 8.0 * nb)


class TopKFilter:
    """Sparse top-magnitude encode with error feedback: the k largest-|x|
    entries travel exactly as (i32 index, f32 value) pairs; everything
    else accumulates in the residual for later payloads (QSGD-style
    sparsification — the ``wire_codec.topk_encode`` numpy reference).

    Ties break toward the lower index (stable descending sort), matching
    ``jax.lax.top_k``."""

    def __init__(self, k: int):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.k = k
        self._residual: Optional[np.ndarray] = None

    def filter_in(self, data: np.ndarray
                  ) -> Tuple[Dict, np.ndarray, np.ndarray]:
        flat = np.asarray(data, dtype=np.float32).reshape(-1)
        if self._residual is None or self._residual.size != flat.size:
            self._residual = np.zeros_like(flat)
        flat = canon_f32(flat + self._residual)
        k = min(self.k, flat.size)
        idx = np.argsort(-np.abs(flat), kind="stable")[:k].astype(np.int32)
        vals = flat[idx]
        self._residual = flat.copy()
        self._residual[idx] = np.float32(0)
        return {"size": flat.size, "k": k}, idx, vals

    def filter_out(self, header: Dict, idx: np.ndarray,
                   vals: np.ndarray) -> np.ndarray:
        out = np.zeros(header["size"], np.float32)
        out[idx] = vals
        return out

    def compression_ratio(self, n: int) -> float:
        """bytes(original float32) / bytes(idx + vals)."""
        return (4.0 * n) / (8.0 * min(self.k, n))
