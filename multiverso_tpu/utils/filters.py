"""Wire compression filters.

TPU-native equivalent of the reference filter layer
(ref: include/multiverso/util/quantization_util.h:37-154 — ``SparseFilter``
rewrites a blob as (index, value) pairs when >50% of entries fall under a
clip threshold; ``OneBitsFilter`` (:160-161) was declared and never
implemented). On TPU the intra-pod wire is ICI managed by XLA, so these
filters matter on the *host/DCN* seams: compressing deltas before
cross-process aggregation or before a tunneled host<->device transfer.

``OneBitsFilter`` is actually implemented here — 1-bit sign quantization with
per-block scale and error-feedback residual (the 1-bit SGD recipe the
reference planned): finishing what the reference left as a stub.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np


class SparseFilter:
    """(index, value) sparse encoding under a clip threshold
    (ref quantization_util.h SparseFilter: FilterIn/FilterOut)."""

    def __init__(self, clip: float = 0.0):
        self.clip = clip

    def filter_in(self, data: np.ndarray) -> Tuple[Dict, np.ndarray]:
        """Returns (header, payload). Sparse iff >50% of entries are clipped
        (the reference's worthwhile-to-compress rule)."""
        flat = np.asarray(data, dtype=np.float32).reshape(-1)
        keep = np.abs(flat) > self.clip
        nnz = int(keep.sum())
        if nnz * 2 < flat.size:
            idx = np.nonzero(keep)[0].astype(np.int32)
            vals = flat[keep]
            payload = np.concatenate([idx.view(np.float32), vals])
            return ({"sparse": True, "size": flat.size, "nnz": nnz},
                    payload)
        return {"sparse": False, "size": flat.size}, flat

    def filter_out(self, header: Dict, payload: np.ndarray) -> np.ndarray:
        if not header["sparse"]:
            return payload.copy()
        nnz = header["nnz"]
        idx = payload[:nnz].view(np.int32)
        vals = payload[nnz:]
        out = np.zeros(header["size"], dtype=np.float32)
        out[idx] = vals
        return out


class OneBitsFilter:
    """1-bit quantization with error feedback (declared but empty in the
    reference, quantization_util.h:160-161 — implemented here).

    Encode: per-block mean magnitude of positives/negatives + sign bitmap.
    The quantization error is kept as a residual and added to the next
    payload, so the compressed stream is unbiased over time (1-bit SGD)."""

    def __init__(self, block: int = 1024):
        self.block = block
        self._residual: Optional[np.ndarray] = None

    def filter_in(self, data: np.ndarray) -> Tuple[Dict, np.ndarray, np.ndarray]:
        flat = np.asarray(data, dtype=np.float32).reshape(-1)
        if self._residual is None or self._residual.size != flat.size:
            self._residual = np.zeros_like(flat)
        flat = flat + self._residual
        n = flat.size
        nb = (n + self.block - 1) // self.block
        padded = np.zeros(nb * self.block, np.float32)
        padded[:n] = flat
        blocks = padded.reshape(nb, self.block)
        pos = blocks > 0
        # per-block scales: mean of positives / mean magnitude of negatives
        pos_scale = np.where(pos.any(1),
                             (blocks * pos).sum(1) / np.maximum(pos.sum(1), 1),
                             0.0).astype(np.float32)
        neg = ~pos
        neg_scale = np.where(neg.any(1),
                             (-blocks * neg).sum(1) / np.maximum(neg.sum(1), 1),
                             0.0).astype(np.float32)
        bits = np.packbits(pos, axis=None)
        decoded = np.where(pos, pos_scale[:, None],
                           -neg_scale[:, None]).reshape(-1)[:n]
        self._residual = flat - decoded
        scales = np.stack([pos_scale, neg_scale], axis=1)
        return {"size": n, "block": self.block}, bits, scales

    def filter_out(self, header: Dict, bits: np.ndarray,
                   scales: np.ndarray) -> np.ndarray:
        n, block = header["size"], header["block"]
        nb = (n + block - 1) // block
        pos = np.unpackbits(bits, count=nb * block).astype(bool).reshape(
            nb, block)
        out = np.where(pos, scales[:, 0][:, None], -scales[:, 1][:, None])
        return out.reshape(-1)[:n].astype(np.float32)

    def compression_ratio(self, n: int) -> float:
        """bytes(original float32) / bytes(bits + scales)."""
        nb = (n + self.block - 1) // self.block
        return (4.0 * n) / (n / 8.0 + 8.0 * nb)
