"""Monitor / Dashboard metrics aggregation.

TPU-native equivalent of the reference observability layer
(ref: include/multiverso/dashboard.h:16-73, src/dashboard.cpp): named
``Monitor``s accumulate call counts and cumulative elapsed milliseconds in a
process-global ``Dashboard`` registry; ``display()`` prints the aggregate
report at shutdown (ref src/zoo.cpp:109). The MONITOR_BEGIN/END macro pair
becomes the ``monitor(name)`` context manager / decorator.

On TPU, device work is asynchronously dispatched, so wall-clock monitors around
jitted calls measure *dispatch* unless the caller blocks; monitors that need
device time should wrap ``block_until_ready`` (the table layer does this for
its sync ops, matching the reference's blocking Add/Get semantics).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterator, Optional


class Monitor:
    """Count + cumulative-ms accumulator (ref dashboard.h Monitor)."""

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total_ms = 0.0
        self._begin: Optional[float] = None
        self._lock = threading.Lock()

    def begin(self) -> None:
        self._begin = time.perf_counter()

    def end(self) -> None:
        if self._begin is None:
            return
        elapsed = (time.perf_counter() - self._begin) * 1e3
        self._begin = None
        with self._lock:
            self.count += 1
            self.total_ms += elapsed

    def observe_ms(self, ms: float) -> None:
        with self._lock:
            self.count += 1
            self.total_ms += ms

    def incr(self, n: int = 1) -> None:
        """Pure event counter: bump ``count`` by ``n`` without touching
        the timing sum (window flushes, merged rows — events with no
        meaningful per-event duration)."""
        with self._lock:
            self.count += n

    @property
    def average_ms(self) -> float:
        return self.total_ms / self.count if self.count else 0.0

    def info_string(self) -> str:
        return (f"[{self.name}] count = {self.count}, "
                f"total = {self.total_ms:.3f} ms, "
                f"average = {self.average_ms:.3f} ms")


class Dashboard:
    """Process-global registry of Monitors (ref dashboard.h Dashboard)."""

    _monitors: Dict[str, Monitor] = {}
    _notes: Dict[str, str] = {}
    _lock = threading.Lock()

    @classmethod
    def get(cls, name: str) -> Monitor:
        with cls._lock:
            mon = cls._monitors.get(name)
            if mon is None:
                mon = cls._monitors[name] = Monitor(name)
            return mon

    @classmethod
    def note(cls, name: str, text: str) -> None:
        """Free-form counter line for work the Monitor timers never see
        (e.g. ops served inside the native transport)."""
        with cls._lock:
            cls._notes[name] = text

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._monitors.clear()
            cls._notes.clear()

    @classmethod
    def snapshot(cls) -> Dict[str, Monitor]:
        with cls._lock:
            return dict(cls._monitors)

    @classmethod
    def display(cls, print_fn=print) -> None:
        with cls._lock:   # one hold: monitors+notes are an atomic view
            mons = dict(cls._monitors)
            notes = dict(cls._notes)
        if not mons and not notes:
            return
        print_fn("--------------Dashboard--------------------")
        for name in sorted(mons):
            print_fn(mons[name].info_string())
        for name in sorted(notes):
            print_fn(f"[{name}] {notes[name]}")
        print_fn("-------------------------------------------")


@contextmanager
def monitor(name: str) -> Iterator[Monitor]:
    """MONITOR_BEGIN/END pair as a context manager."""
    mon = Dashboard.get(name)
    start = time.perf_counter()
    try:
        yield mon
    finally:
        mon.observe_ms((time.perf_counter() - start) * 1e3)


def monitored(name: str):
    """Decorator form of :func:`monitor`."""
    def wrap(fn):
        def inner(*args, **kwargs):
            with monitor(name):
                return fn(*args, **kwargs)
        inner.__name__ = getattr(fn, "__name__", name)
        return inner
    return wrap
