"""Monitor / Dashboard metrics aggregation.

TPU-native equivalent of the reference observability layer
(ref: include/multiverso/dashboard.h:16-73, src/dashboard.cpp): named
``Monitor``s accumulate call counts and cumulative elapsed milliseconds in a
process-global ``Dashboard`` registry; ``display()`` prints the aggregate
report at shutdown (ref src/zoo.cpp:109). The MONITOR_BEGIN/END macro pair
becomes the ``monitor(name)`` context manager / decorator.

Beyond the reference (which stopped at count/total/mean), every Monitor
embeds a fixed-bucket log-scale latency histogram
(:class:`multiverso_tpu.telemetry.histogram.Histogram`): ``info_string``
and snapshots report p50/p90/p99/max, so the multi-threaded, batched PS
plane's tail behavior is visible where a mean would hide it. count and
total_ms keep their reference semantics exactly (``incr`` bumps count
without a timing sample, so counter-style monitors never pollute the
histogram).

Thread-safety: ``observe_ms``/``incr`` serialize on a per-monitor lock
with a histogram update inside the same critical section (~0.3 us total).
The legacy paired ``begin()/end()`` API stores its start stamp in a
``threading.local`` slot — two threads interleaving begin/end each time
their OWN sample instead of corrupting a shared one (the reference's
single ``start_time_`` slot had the same race).

On TPU, device work is asynchronously dispatched, so wall-clock monitors
around jitted calls measure *dispatch* unless the caller blocks; monitors
that need device time should wrap ``block_until_ready`` (the table layer
does this for its sync ops, matching the reference's blocking Add/Get
semantics).
"""

from __future__ import annotations

import functools
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, Iterator, Tuple

from multiverso_tpu.telemetry.histogram import Histogram


@dataclass(frozen=True)
class MonitorSnapshot:
    """Immutable point-in-time view of one Monitor. Exporters, tests,
    and the MSG_STATS reply consume THIS — never the live Monitor, whose
    fields keep mutating under them (``Dashboard.snapshot()`` used to
    hand out live objects; an exporter iterating one raced the hot
    path)."""

    name: str
    count: int
    total_ms: float
    min_ms: float
    max_ms: float
    p50_ms: float
    p90_ms: float
    p99_ms: float
    timed: int                       # samples with a duration (not incr)
    buckets: Tuple[Tuple[float, int], ...] = field(default=())

    @property
    def average_ms(self) -> float:
        return self.total_ms / self.count if self.count else 0.0

    def info_string(self) -> str:
        s = (f"[{self.name}] count = {self.count}, "
             f"total = {self.total_ms:.3f} ms, "
             f"average = {self.average_ms:.3f} ms")
        if self.timed:
            s += (f", p50 = {self.p50_ms:.3f} ms, "
                  f"p90 = {self.p90_ms:.3f} ms, "
                  f"p99 = {self.p99_ms:.3f} ms, "
                  f"max = {self.max_ms:.3f} ms")
        return s

    def brief_dict(self, digits: int = 5) -> Dict:
        """Compact count + p50/p90/p99/max summary — THE shape bench
        records and worker RESULT lines share (one definition instead
        of hand-built literals at every call site)."""
        return {"count": self.count,
                "p50_ms": round(self.p50_ms, digits),
                "p90_ms": round(self.p90_ms, digits),
                "p99_ms": round(self.p99_ms, digits),
                "max_ms": round(self.max_ms, digits)}

    def hist_dict(self) -> Dict:
        """JSON-safe dict (exporter / MSG_STATS wire shape) — SAME key
        set as ``telemetry.histogram.Histogram.as_dict()``; keep the two
        in lockstep."""
        return {
            "count": self.count,
            "sum_ms": round(self.total_ms, 6),
            "min_ms": round(self.min_ms, 6) if self.timed else 0.0,
            "max_ms": round(self.max_ms, 6),
            "p50_ms": round(self.p50_ms, 6),
            "p90_ms": round(self.p90_ms, 6),
            "p99_ms": round(self.p99_ms, 6),
            "timed": self.timed,
            "buckets": [[b, c] for b, c in self.buckets],
        }


class Monitor:
    """Count + cumulative-ms accumulator with a latency histogram
    (ref dashboard.h Monitor, upgraded — see module docstring)."""

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total_ms = 0.0
        self._hist = Histogram()
        # per-thread begin stamp: the paired begin/end API must not share
        # one slot across threads (satellite fix; prefer monitor())
        self._tls = threading.local()
        self._lock = threading.Lock()

    def begin(self) -> None:
        self._tls.begin = time.perf_counter()

    def end(self) -> None:
        begin = getattr(self._tls, "begin", None)
        if begin is None:
            return
        self._tls.begin = None
        self.observe_ms((time.perf_counter() - begin) * 1e3)

    def observe_ms(self, ms: float) -> None:
        with self._lock:
            self.count += 1
            self.total_ms += ms
            self._hist.observe(ms)

    def incr(self, n: int = 1) -> None:
        """Pure event counter: bump ``count`` by ``n`` without touching
        the timing sum or histogram (window flushes, merged rows —
        events with no meaningful per-event duration)."""
        with self._lock:
            self.count += n

    @property
    def average_ms(self) -> float:
        return self.total_ms / self.count if self.count else 0.0

    def percentile(self, q: float) -> float:
        """Latency quantile estimate over the timed samples (bucket
        interpolation; ~one bucket width of relative error)."""
        with self._lock:
            return self._hist.percentile(q)

    @property
    def p50_ms(self) -> float:
        return self.percentile(50)

    @property
    def p99_ms(self) -> float:
        return self.percentile(99)

    @property
    def max_ms(self) -> float:
        with self._lock:
            return self._hist.max

    def snapshot(self) -> MonitorSnapshot:
        """Consistent immutable view (one lock hold)."""
        with self._lock:
            h = self._hist
            p50, p90, p99 = h.percentiles((50, 90, 99))
            return MonitorSnapshot(
                name=self.name, count=self.count, total_ms=self.total_ms,
                min_ms=h.min if h.count else 0.0, max_ms=h.max,
                p50_ms=p50, p90_ms=p90, p99_ms=p99, timed=h.count,
                buckets=tuple(h.nonzero()))

    def info_string(self) -> str:
        return self.snapshot().info_string()


class Dashboard:
    """Process-global registry of Monitors (ref dashboard.h Dashboard)."""

    _monitors: Dict[str, Monitor] = {}
    _notes: Dict[str, str] = {}
    _lock = threading.Lock()

    @classmethod
    def get(cls, name: str) -> Monitor:
        with cls._lock:
            mon = cls._monitors.get(name)
            if mon is None:
                mon = cls._monitors[name] = Monitor(name)
            return mon

    @classmethod
    def note(cls, name: str, text: str) -> None:
        """Free-form counter line for work the Monitor timers never see
        (e.g. ops served inside the native transport)."""
        with cls._lock:
            cls._notes[name] = text

    @classmethod
    def notes(cls) -> Dict[str, str]:
        with cls._lock:
            return dict(cls._notes)

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._monitors.clear()
            cls._notes.clear()

    @classmethod
    def snapshot(cls) -> Dict[str, MonitorSnapshot]:
        """Immutable per-monitor snapshots (safe to hold across the hot
        path; see MonitorSnapshot)."""
        with cls._lock:
            mons = list(cls._monitors.values())
        return {m.name: m.snapshot() for m in mons}

    @classmethod
    def display(cls, print_fn=print) -> None:
        with cls._lock:   # one hold: monitors+notes are an atomic view
            mons = list(cls._monitors.values())
            notes = dict(cls._notes)
        if not mons and not notes:
            return
        print_fn("--------------Dashboard--------------------")
        for m in sorted(mons, key=lambda m: m.name):
            print_fn(m.info_string())
        for name in sorted(notes):
            print_fn(f"[{name}] {notes[name]}")
        print_fn("-------------------------------------------")


@contextmanager
def monitor(name: str) -> Iterator[Monitor]:
    """MONITOR_BEGIN/END pair as a context manager."""
    mon = Dashboard.get(name)
    start = time.perf_counter()
    try:
        yield mon
    finally:
        mon.observe_ms((time.perf_counter() - start) * 1e3)


def monitored(name: str):
    """Decorator form of :func:`monitor` (``functools.wraps`` so the
    instrumented function keeps its docstring/signature/module)."""
    def wrap(fn):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with monitor(name):
                return fn(*args, **kwargs)
        return inner
    return wrap
