"""One-shot host<->device link speed probe.

The host-plane wire filters (bf16/1bit) trade encode CPU for wire bytes —
a win on a slow link (tunneled/remote PJRT device: ~100 ms/MB), a loss on
a fast one (local PCIe/ICI: the 1bit filter measured ~10x SLOWER than
plain off-tunnel, BENCH_EXTRA array_table_cpu_nontunnel). The probe lets
table creation warn when a configured filter contradicts the measured
link (VERDICT r3 item 8's guard).

Sync discipline: host READBACK, not ``block_until_ready`` — the tunneled
PJRT plugin can return from block_until_ready with the transfer still in
flight (see bench.py / memory: differential timing only).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

_CACHED_MS: Optional[float] = None

# above this, a 1 MB upload is "slow wire" territory where payload
# compression pays for itself (tunnel uploads measure 100+ ms; local
# CPU/PCIe measure ~1 ms)
FAST_LINK_MS = 20.0


def device_link_ms(refresh: bool = False) -> float:
    """Median warm latency (ms) of a 1 MB host->device upload + readback,
    cached for the process (the wire doesn't change under one run; link
    WEATHER does, so treat this as an order-of-magnitude signal)."""
    global _CACHED_MS
    if _CACHED_MS is not None and not refresh:
        return _CACHED_MS
    import jax
    buf = np.zeros(1 << 20, np.uint8)
    float(jax.device_put(buf)[0])          # warm the transfer path
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        x = jax.device_put(buf)
        float(x[0])                        # readback = real sync point
        times.append(time.perf_counter() - t0)
    _CACHED_MS = float(np.median(times) * 1e3)
    return _CACHED_MS


def link_is_fast() -> bool:
    return device_link_ms() < FAST_LINK_MS
