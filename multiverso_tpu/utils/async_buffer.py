"""AsyncBuffer: double-buffered prefetch.

TPU-native equivalent of the reference ASyncBuffer
(ref: include/multiverso/util/async_buffer.h:11-116), which overlaps a
parameter pull with compute by keeping two buffers and a background fill
thread — the mechanism behind the LR app's pipeline mode
(ref Applications/LogisticRegression/src/model/ps_model.cpp:236-271).

On TPU the same overlap usually comes for free from JAX async dispatch, but
the host-side pattern is still needed when the fill function does blocking
host work (data loading, host-plane table Gets). The API mirrors the
reference: ``get()`` returns the ready buffer and kicks off the next fill.

``version_fn`` pairs with the table get-cache (``Table.version``): when the
source's version is unchanged since the last completed fill, the next fill
is skipped entirely and ``get()`` re-serves the previous result — a
prefetch loop over a quiet table then costs one integer compare per
iteration instead of one device->host pull.
"""

from __future__ import annotations

import threading
from typing import Callable, Generic, Optional, TypeVar

T = TypeVar("T")


class AsyncBuffer(Generic[T]):
    def __init__(self, fill_fn: Callable[[], T],
                 version_fn: Optional[Callable[[], int]] = None):
        self._fill_fn = fill_fn
        self._version_fn = version_fn
        self._result: Optional[T] = None
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        # version OBSERVED BEFORE the fill ran (a mutation landing mid-fill
        # bumps the source version past this, so the next get() refills)
        self._filled_version: Optional[int] = None
        self.skipped_fills = 0   # diagnostic: fills avoided by version_fn
        self._start_fill()

    def _start_fill(self) -> None:
        pre = self._version_fn() if self._version_fn is not None else None

        def run():
            try:
                self._result = self._fill_fn()
                self._filled_version = pre
            except BaseException as e:  # surfaced on next get()
                self._error = e
        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def _fresh(self) -> bool:
        """True when the last completed fill is still current (version
        unchanged), so the next fill may be skipped."""
        return (self._version_fn is not None
                and self._error is None
                and self._filled_version is not None
                and self._version_fn() == self._filled_version)

    def get(self, start_next: bool = True) -> T:
        """Block for the in-flight fill, return it, start the next one.

        On a fill error the exception is re-raised here; a new fill is still
        started (when ``start_next``) so the buffer recovers from transient
        failures instead of serving stale results forever."""
        assert self._thread is not None
        self._thread.join()
        err, self._error = self._error, None
        result = self._result
        if start_next:
            if err is None and self._fresh():
                self.skipped_fills += 1
            else:
                self._start_fill()
        if err is not None:
            raise err
        return result

    def stop(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
