from multiverso_tpu.utils import config, dashboard, log
from multiverso_tpu.utils.timer import Timer

__all__ = ["config", "dashboard", "log", "Timer"]
