"""Leveled logger + CHECK assertions.

TPU-native equivalent of the reference logging layer
(ref: include/multiverso/util/log.h:9-142, src/util/log.cpp): timestamped
leveled messages (DEBUG/INFO/ERROR/FATAL) to stdout and an optional file, a
``is_kill_fatal`` toggle deciding whether FATAL raises, and ``CHECK`` /
``CHECK_NOTNULL`` assertion helpers.
"""

from __future__ import annotations

import datetime
import enum
import sys
import threading
from typing import Any, IO, Optional

from multiverso_tpu.utils import config


class LogLevel(enum.IntEnum):
    DEBUG = 0
    INFO = 1
    ERROR = 2
    FATAL = 3


_LEVEL_NAMES = {
    LogLevel.DEBUG: "DEBUG",
    LogLevel.INFO: "INFO",
    LogLevel.ERROR: "ERROR",
    LogLevel.FATAL: "FATAL",
}

_LEVEL_FROM_STRING = {name.lower(): lvl for lvl, name in _LEVEL_NAMES.items()}


class FatalError(RuntimeError):
    """Raised on FATAL logs / failed CHECKs when kill-on-fatal is enabled."""


class Logger:
    """Instance logger (ref log.h Logger). Module-level helpers use a default one."""

    def __init__(self, level: LogLevel = LogLevel.INFO,
                 file: Optional[IO[str]] = None, name: str = "multiverso_tpu",
                 kill_fatal: bool = True):
        self.level = level
        self.name = name
        self.kill_fatal = kill_fatal
        self._file = file
        self._lock = threading.Lock()

    def reset_log_file(self, path: str) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
            self._file = open(path, "a") if path else None

    def write(self, level: LogLevel, msg: str, *args: Any) -> None:
        if level < self.level:
            return
        if args:
            msg = msg % args
        ts = datetime.datetime.now().strftime("%Y-%m-%d %H:%M:%S.%f")[:-3]
        line = f"[{_LEVEL_NAMES[level]}] [{ts}] [{self.name}] {msg}"
        with self._lock:
            print(line, file=sys.stderr if level >= LogLevel.ERROR else sys.stdout)
            if self._file is not None:
                self._file.write(line + "\n")
                self._file.flush()
        if level == LogLevel.FATAL and self.kill_fatal:
            raise FatalError(msg)

    def debug(self, msg: str, *args: Any) -> None:
        self.write(LogLevel.DEBUG, msg, *args)

    def info(self, msg: str, *args: Any) -> None:
        self.write(LogLevel.INFO, msg, *args)

    def error(self, msg: str, *args: Any) -> None:
        self.write(LogLevel.ERROR, msg, *args)

    def fatal(self, msg: str, *args: Any) -> None:
        self.write(LogLevel.FATAL, msg, *args)


_default = Logger()


def configure_from_flags() -> None:
    """Apply the log_level / log_file flags to the default logger."""
    level = _LEVEL_FROM_STRING.get(config.get_flag("log_level").lower())
    if level is not None:
        _default.level = level
    path = config.get_flag("log_file")
    if path:
        _default.reset_log_file(path)


def set_level(level: LogLevel) -> None:
    _default.level = level


def debug(msg: str, *args: Any) -> None:
    _default.debug(msg, *args)


def info(msg: str, *args: Any) -> None:
    _default.info(msg, *args)


def error(msg: str, *args: Any) -> None:
    _default.error(msg, *args)


def fatal(msg: str, *args: Any) -> None:
    _default.fatal(msg, *args)


def check(condition: Any, msg: str = "CHECK failed") -> None:
    """ref log.h CHECK macro: fatal-log on false condition."""
    if not condition:
        _default.fatal(msg)


def check_notnull(value: Any, name: str = "value") -> Any:
    """ref log.h CHECK_NOTNULL: returns the value for chaining."""
    if value is None:
        _default.fatal(f"CHECK_NOTNULL failed: {name} is None")
    return value
