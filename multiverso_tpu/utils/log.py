"""Leveled logger + CHECK assertions.

TPU-native equivalent of the reference logging layer
(ref: include/multiverso/util/log.h:9-142, src/util/log.cpp): timestamped
leveled messages (DEBUG/INFO/ERROR/FATAL) to stdout and an optional file, a
``is_kill_fatal`` toggle deciding whether FATAL raises, and ``CHECK`` /
``CHECK_NOTNULL`` assertion helpers.

Beyond the reference (PR 4, observability):

* ``reset_log_file(path, jsonl=True)`` makes the file sink STRUCTURED —
  one JSON object per line with ``ts`` (wall), ``mono`` (monotonic),
  ``level``, ``rank``, ``name``, ``msg`` — so log lines interleave with
  flight-recorder dumps on one timeline in ``tools/postmortem.py``. The
  text format stays the default (and stdout/stderr always stay text).
* ``Logger.fatal`` dumps the flight recorder (best-effort, no-op unless
  a dump directory resolves) BEFORE raising: a FATAL is exactly the
  moment the black box must reach disk.
"""

from __future__ import annotations

import datetime
import enum
import json
import sys
import threading
import time
from typing import Any, IO, Optional

from multiverso_tpu.utils import config


class LogLevel(enum.IntEnum):
    DEBUG = 0
    INFO = 1
    ERROR = 2
    FATAL = 3


_LEVEL_NAMES = {
    LogLevel.DEBUG: "DEBUG",
    LogLevel.INFO: "INFO",
    LogLevel.ERROR: "ERROR",
    LogLevel.FATAL: "FATAL",
}

_LEVEL_FROM_STRING = {name.lower(): lvl for lvl, name in _LEVEL_NAMES.items()}


class FatalError(RuntimeError):
    """Raised on FATAL logs / failed CHECKs when kill-on-fatal is enabled."""


class Logger:
    """Instance logger (ref log.h Logger). Module-level helpers use a default one."""

    def __init__(self, level: LogLevel = LogLevel.INFO,
                 file: Optional[IO[str]] = None, name: str = "multiverso_tpu",
                 kill_fatal: bool = True):
        self.level = level
        self.name = name
        self.kill_fatal = kill_fatal
        self.rank = 0              # stamped into jsonl records (set_rank)
        self._file = file
        self._jsonl = False
        self._lock = threading.Lock()

    def reset_log_file(self, path: str, jsonl: bool = False) -> None:
        """Point the file sink at ``path`` (empty = none). ``jsonl=True``
        switches the FILE format to one JSON object per line
        (ts/mono/level/rank/name/msg) for postmortem interleaving; the
        console stays text either way."""
        with self._lock:
            if self._file is not None:
                self._file.close()
            self._file = open(path, "a") if path else None
            self._jsonl = bool(jsonl)

    def write(self, level: LogLevel, msg: str, *args: Any) -> None:
        if level < self.level:
            return
        if args:
            msg = msg % args
        ts = datetime.datetime.now().strftime("%Y-%m-%d %H:%M:%S.%f")[:-3]
        line = f"[{_LEVEL_NAMES[level]}] [{ts}] [{self.name}] {msg}"
        with self._lock:
            print(line, file=sys.stderr if level >= LogLevel.ERROR else sys.stdout)
            if self._file is not None:
                if self._jsonl:
                    self._file.write(json.dumps({
                        "ts": round(time.time(), 6),
                        "mono": round(time.monotonic(), 6),
                        "level": _LEVEL_NAMES[level], "rank": self.rank,
                        "name": self.name, "msg": msg}) + "\n")
                else:
                    self._file.write(line + "\n")
                self._file.flush()
        if level == LogLevel.FATAL and self.kill_fatal:
            # black box before the raise: a FATAL is a fault-time event,
            # and the dump must not depend on anyone catching FatalError
            # (best-effort; no-op unless a dump directory resolves)
            try:
                from multiverso_tpu.telemetry import flightrec
                flightrec.record(flightrec.EV_FATAL, note=msg[:200])
                flightrec.dump_global(f"fatal: {msg[:120]}", stacks=True)
            except Exception:   # noqa: BLE001 — never mask the FATAL
                pass
            raise FatalError(msg)

    def debug(self, msg: str, *args: Any) -> None:
        self.write(LogLevel.DEBUG, msg, *args)

    def info(self, msg: str, *args: Any) -> None:
        self.write(LogLevel.INFO, msg, *args)

    def error(self, msg: str, *args: Any) -> None:
        self.write(LogLevel.ERROR, msg, *args)

    def fatal(self, msg: str, *args: Any) -> None:
        self.write(LogLevel.FATAL, msg, *args)


_default = Logger()


def configure_from_flags() -> None:
    """Apply the log_level / log_file / log_jsonl flags to the default
    logger."""
    level = _LEVEL_FROM_STRING.get(config.get_flag("log_level").lower())
    if level is not None:
        _default.level = level
    path = config.get_flag("log_file")
    if path:
        _default.reset_log_file(path, jsonl=config.get_flag("log_jsonl"))


def set_level(level: LogLevel) -> None:
    _default.level = level


_rank_pinned = False


def set_rank(rank: int) -> None:
    """Stamp this process's PS rank into structured log records (called
    from Zoo.start / PSService init; first caller wins like the tracer,
    so in-process multi-rank tests keep one attribution)."""
    global _rank_pinned
    if not _rank_pinned:
        _default.rank = int(rank)
        _rank_pinned = True


def reset_rank() -> None:
    """Unpin the rank stamp (test isolation — the public counterpart of
    flightrec.reset()/Tracer.reset(), which unpin their ranks too)."""
    global _rank_pinned
    _rank_pinned = False
    _default.rank = 0


def debug(msg: str, *args: Any) -> None:
    _default.debug(msg, *args)


def info(msg: str, *args: Any) -> None:
    _default.info(msg, *args)


def error(msg: str, *args: Any) -> None:
    _default.error(msg, *args)


def fatal(msg: str, *args: Any) -> None:
    _default.fatal(msg, *args)


def check(condition: Any, msg: str = "CHECK failed") -> None:
    """ref log.h CHECK macro: fatal-log on false condition."""
    if not condition:
        _default.fatal(msg)


def check_notnull(value: Any, name: str = "value") -> Any:
    """ref log.h CHECK_NOTNULL: returns the value for chaining."""
    if value is None:
        _default.fatal(f"CHECK_NOTNULL failed: {name} is None")
    return value
