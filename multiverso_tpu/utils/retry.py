"""Shared retry policy: capped exponential backoff + jitter + deadlines.

Before ISSUE 14 every retry spot in the plane rolled its own schedule:
``_Peer.__init__`` slept a flat 50 ms against a connect refusal, the
send-window replay plane re-flushed on a flat ``ps_replay_backoff``,
one-shot probes never retried at all, and a replica snapshot pull
surfaced the first transient shard error straight to its refresh
caller. Under injected chaos (ps/faults.py) those differences matter:
flat schedules synchronize retry storms against a recovering rank, and
a retry loop without a deadline turns a bounded triage budget into an
unbounded one.

This module is the one policy they all share:

* **capped exponential**: attempt ``k`` waits ``base * factor**k``,
  capped at ``cap`` — early retries are cheap, a long outage decays to
  a bounded poll rate instead of hammering the respawning owner;
* **jitter**: each delay is scaled by a uniform factor in
  ``[1 - jitter, 1 + jitter]`` so a fleet of clients re-arming off the
  same death event spreads out instead of arriving as one thundering
  herd (deterministic when a ``seed`` is given — the chaos bench's
  reproducibility rule);
* **deadline propagation**: every sleep is clamped to the remaining
  deadline and :meth:`Backoff.sleep` returns False once it is
  exhausted, so a caller's total budget means the total — including
  the waits — not per-attempt.

Used by: ``ps/service._Peer`` connect retries and one-shot probe
retries (``ps_probe_attempts``), ``ps/tables`` replay re-flush
scheduling (``ps_replay_backoff`` base / ``ps_replay_backoff_cap``
cap), and ``serving/replica`` snapshot-pull retries
(``serving_pull_retries``). Knob rows live in docs/TUNING.md.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple

DEFAULT_BASE_S = 0.05
DEFAULT_CAP_S = 2.0
DEFAULT_FACTOR = 2.0
DEFAULT_JITTER = 0.25


class Backoff:
    """One retry schedule. Stateless per attempt — callers pass the
    attempt index, so several frames/owners can share one policy
    object while each tracks its own episode."""

    def __init__(self, base_s: float = DEFAULT_BASE_S,
                 cap_s: float = DEFAULT_CAP_S,
                 factor: float = DEFAULT_FACTOR,
                 jitter: float = DEFAULT_JITTER,
                 seed: Optional[int] = None):
        self.base_s = max(float(base_s), 0.0)
        self.cap_s = max(float(cap_s), self.base_s)
        self.factor = max(float(factor), 1.0)
        self.jitter = min(max(float(jitter), 0.0), 1.0)
        # a seeded stream makes the schedule reproducible (chaos runs);
        # the default shares the process-global RNG — jitter quality
        # matters, sequence identity does not
        self._rng = random.Random(seed) if seed is not None else random

    def delay_s(self, attempt: int,
                deadline: Optional[float] = None) -> float:
        """Delay before retry number ``attempt`` (0-based), jittered,
        capped, and clamped to the remaining ``deadline``
        (``time.monotonic()`` timestamp). Returns 0.0 when the deadline
        has passed — the caller's loop should treat that together with
        :meth:`expired`."""
        d = min(self.base_s * (self.factor ** max(int(attempt), 0)),
                self.cap_s)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        if deadline is not None:
            d = min(d, max(deadline - time.monotonic(), 0.0))
        return d

    @staticmethod
    def expired(deadline: Optional[float]) -> bool:
        return deadline is not None and time.monotonic() >= deadline

    def sleep(self, attempt: int,
              deadline: Optional[float] = None) -> bool:
        """Sleep the attempt's delay; False when the deadline is
        already exhausted (nothing slept) — the retry loop's stop
        signal."""
        if self.expired(deadline):
            return False
        time.sleep(self.delay_s(attempt, deadline))
        return True


def deadline_in(seconds: Optional[float]) -> Optional[float]:
    """Monotonic deadline ``seconds`` from now (None = unbounded) —
    the propagation unit every retrying call passes down."""
    return None if seconds is None else time.monotonic() + float(seconds)


def remaining_s(deadline: Optional[float],
                default: float = 0.0) -> float:
    """Seconds left until ``deadline`` (never negative); ``default``
    when unbounded — lets a per-attempt socket timeout inherit the
    caller's overall budget."""
    if deadline is None:
        return default
    return max(deadline - time.monotonic(), 0.0)


def call_with_retries(fn: Callable, *, attempts: int,
                      deadline: Optional[float] = None,
                      retry_on: Tuple = (OSError, TimeoutError),
                      backoff: Optional[Backoff] = None,
                      on_retry: Optional[Callable] = None):
    """Run ``fn()`` up to ``attempts`` times, sleeping the shared
    backoff between failures, never past ``deadline``. The LAST error
    re-raises unchanged (callers wrap in their own typed errors);
    ``on_retry(attempt, exc)`` observes each retry (telemetry)."""
    backoff = backoff or Backoff()
    attempts = max(int(attempts), 1)
    last: Optional[BaseException] = None
    for k in range(attempts):
        try:
            return fn()
        except retry_on as e:   # noqa: PERF203 — retry loop
            last = e
            if k + 1 >= attempts or not backoff.sleep(k, deadline):
                raise
            if on_retry is not None:
                try:
                    on_retry(k, e)
                except Exception:   # noqa: BLE001 — telemetry only
                    pass
    raise last  # pragma: no cover — unreachable (loop raises)
