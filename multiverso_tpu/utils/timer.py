"""Timer (ref: include/multiverso/util/timer.h:9, src/timer.cpp)."""

from __future__ import annotations

import time


class Timer:
    """Monotonic stopwatch; elapsed time in milliseconds like the reference."""

    def __init__(self) -> None:
        self._start = time.perf_counter()

    def start(self) -> None:
        self._start = time.perf_counter()

    def elapse(self) -> float:
        """Milliseconds since the last start()."""
        return (time.perf_counter() - self._start) * 1e3
