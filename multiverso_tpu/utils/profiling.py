"""Profiler integration.

The reference's tracing story is Monitor/Dashboard timestamps (SURVEY §5);
on TPU the equivalent deep tool is an XLA trace. This wraps ``jax.profiler``
with the framework's flag/config conventions so any region can be captured
and opened in XProf/TensorBoard or Perfetto.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Iterator, Optional

import jax

from multiverso_tpu.utils import config, log

config.define_string("trace_dir", "", "when set, trace() regions write a "
                     "jax.profiler trace under this directory")


@contextmanager
def trace(name: str = "trace", trace_dir: Optional[str] = None) -> Iterator[None]:
    """Capture a device+host profile of the enclosed region (no-op when no
    directory is configured)."""
    directory = trace_dir or config.get_flag("trace_dir")
    if not directory:
        yield
        return
    path = f"{directory.rstrip('/')}/{name}"
    log.info("profiler trace -> %s", path)
    with jax.profiler.trace(path):
        yield


def annotate(name: str):
    """Named region inside a trace (ref MONITOR_BEGIN/END analogue at the
    XLA timeline level)."""
    return jax.profiler.TraceAnnotation(name)
