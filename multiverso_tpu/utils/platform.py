"""Make JAX platform env vars effective under a pre-registered plugin.

In some deployments a site hook imports jax at interpreter startup and
force-registers an accelerator plugin, which wins over ``JAX_PLATFORMS`` /
``XLA_FLAGS`` environment variables.  ``jax.config`` updates still take
effect as long as no backend has been initialized, so subprocess entry
points (the tier-2 battery, spawned cluster processes) call this first to
restore the env vars' intent.  No-op when the env vars are unset — a bench
run on real TPU hardware is untouched.
"""

from __future__ import annotations

import os
import re


def force_cpu_mesh(n_devices: int = 8) -> bool:
    """Point JAX at an n-device virtual CPU mesh (the test/dryrun fixture:
    SURVEY §4's "mpirun -np N on one host" analogue). Returns False (instead
    of raising) if a backend is already live — callers honoring an explicit
    user request should surface that."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", n_devices)
        os.environ["JAX_PLATFORMS"] = "cpu"
        return True
    except (RuntimeError, AttributeError):
        return False


def apply_platform_env() -> None:
    """Re-apply JAX_PLATFORMS / host-device-count env intent via jax.config."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if not platforms:
        return
    import jax

    try:
        jax.config.update("jax_platforms", platforms)
        if platforms.split(",")[0] == "cpu":
            m = re.search(r"xla_force_host_platform_device_count=(\d+)",
                          os.environ.get("XLA_FLAGS", ""))
            if m:
                jax.config.update("jax_num_cpu_devices", int(m.group(1)))
    except (RuntimeError, AttributeError):
        pass  # backend already live; keep whatever it is
