"""Make JAX platform env vars effective under a pre-registered plugin,
and paper over cross-version JAX API moves the mesh plane depends on.

In some deployments a site hook imports jax at interpreter startup and
force-registers an accelerator plugin, which wins over ``JAX_PLATFORMS`` /
``XLA_FLAGS`` environment variables.  ``jax.config`` updates still take
effect as long as no backend has been initialized, so subprocess entry
points (the tier-2 battery, spawned cluster processes) call this first to
restore the env vars' intent.  No-op when the env vars are unset — a bench
run on real TPU hardware is untouched.

:func:`shard_map` is the version-portable entry every ``parallel/`` and
model module routes through: newer jax exposes ``jax.shard_map`` with a
``check_vma`` kwarg, older releases only
``jax.experimental.shard_map.shard_map`` with the same knob spelled
``check_rep``. Without the shim every mesh collective (and the whole
1->2->4->8 scale harness judging them) import-errors on the older
runtime this box ships.
"""

from __future__ import annotations

import os
import re


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
    """``jax.shard_map`` where it exists, else the ``jax.experimental``
    spelling with ``check_vma`` translated to ``check_rep``. Positional
    ``f`` first, everything else keyword — the exact call shape every
    in-repo site (and ``functools.partial`` decorator use) relies on."""
    import jax

    native = getattr(jax, "shard_map", None)
    if native is not None:
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return native(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as legacy
    if check_vma is not None:
        kw["check_rep"] = check_vma
    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)


def axis_size(name) -> int:
    """Size of a named mesh axis from inside ``shard_map`` —
    ``jax.lax.axis_size`` where it exists; on older releases
    ``jax.core.axis_frame(name)`` already resolves to the bound size.
    Always a Python int (static), so shard-local chunk math stays
    shape-stable."""
    import jax

    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(name)
    frame = jax.core.axis_frame(name)
    return int(getattr(frame, "size", frame))


def force_cpu_mesh(n_devices: int = 8) -> bool:
    """Point JAX at an n-device virtual CPU mesh (the test/dryrun fixture:
    SURVEY §4's "mpirun -np N on one host" analogue). Returns False (instead
    of raising) if a backend is already live — callers honoring an explicit
    user request should surface that.

    Two spellings: the ``jax_num_cpu_devices`` config option where it
    exists, else ``XLA_FLAGS --xla_force_host_platform_device_count``
    (the one every jax release honors — skipping it silently left a
    1-device mesh under every 8-shard test). The XLA_FLAGS spelling is
    applied to ``os.environ`` only long enough to initialize THIS
    process's backend, then restored: a leaked export turned every
    test-spawned bench worker into an unasked-for 8-virtual-device
    process, silently flipping their big shards into multi-device
    local sharding (whose concurrent collective applies can wedge the
    XLA-CPU rendezvous — see tools/bench_scale.py)."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except (RuntimeError, AttributeError):
        return False
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
        return True
    except (RuntimeError, AttributeError):
        pass   # old jax: the XLA_FLAGS spelling below carries the intent
    prior = os.environ.get("XLA_FLAGS")
    flags = prior or ""
    want = f"--xla_force_host_platform_device_count={n_devices}"
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       want, flags)
    else:
        flags = (flags + " " + want).strip()
    os.environ["XLA_FLAGS"] = flags
    try:
        # touching the device list initializes the backend NOW, while
        # the flag is visible; after this the env can be restored
        return len(jax.devices()) >= n_devices
    except RuntimeError:
        return False
    finally:
        if prior is None:
            os.environ.pop("XLA_FLAGS", None)
        else:
            os.environ["XLA_FLAGS"] = prior


def enable_cpu_collectives() -> bool:
    """Make cross-process computations work on the CPU backend.

    jaxlib's XLA:CPU client ships a cross-host collectives
    implementation (Gloo) but does NOT select it by default: with N
    coordinated CPU processes, ``jax.distributed.initialize`` succeeds
    (the coordination service is separate) and then EVERY cross-process
    computation — ``process_allgather``, ``psum``, the process_sum
    reducer — fails with ``INVALID_ARGUMENT: Multiprocess computations
    aren't implemented on the CPU backend``. This was the seed's last
    standing tier-1 failure (``bench_aggregate`` at np=2; the other 15
    mesh-env failures fell to the shard_map/axis_size shims in PR 12).

    Selecting gloo via ``jax_cpu_collectives_implementation`` BEFORE
    the backend initializes fixes it for real. Call this before
    ``jax.distributed.initialize`` in any multi-process CPU entry
    point. Returns False (never raises) when the option does not exist
    (older jax) or the backend is already live — and is a no-op by
    construction on TPU/GPU paths, where the option is irrelevant.
    """
    import jax

    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        return True
    except Exception:   # noqa: BLE001 — option missing / backend live
        return False


def apply_platform_env() -> None:
    """Re-apply JAX_PLATFORMS / host-device-count env intent via jax.config."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if not platforms:
        return
    import jax

    try:
        jax.config.update("jax_platforms", platforms)
        if platforms.split(",")[0] == "cpu":
            m = re.search(r"xla_force_host_platform_device_count=(\d+)",
                          os.environ.get("XLA_FLAGS", ""))
            if m:
                jax.config.update("jax_num_cpu_devices", int(m.group(1)))
    except (RuntimeError, AttributeError):
        pass  # backend already live; keep whatever it is
