"""Stale-synchronous parallelism: bounded-staleness clocks between workers.

The reference offers only the two extremes — pure async (its default
server, ref src/server.cpp:36-58) or strict BSP (SyncServer vector clocks,
ref src/server.cpp:68-222); its `backup_worker_ratio` flag for anything in
between is declared but dead (ref src/server.cpp:21). This module completes
the spectrum: an :class:`SSPClock` lets each worker run ahead of the slowest
peer by at most ``staleness`` steps.

* ``staleness=0`` — lockstep, the SyncServer BSP guarantee.
* ``staleness=s`` — classic SSP: a fast worker blocks only when it would be
  more than ``s`` clocks ahead; stragglers never block anyone.
* large ``staleness`` — effectively the async default server.

Mechanism: one clock beacon file per worker on shared storage (same
substrate as elastic.Heartbeat — atomic rename, readable by any process),
polled on advance. This is the *host/DCN* plane: inside one jitted mesh
step BSP is hardware-native and needs no clock; SSP governs uncoordinated
per-process training loops, where the reference's SyncServer would sit.
Compose with :func:`multiverso_tpu.elastic.failed` to stop waiting on dead
workers (the reference's abandoned straggler story, actually wired).
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Optional

from multiverso_tpu.utils import log
from multiverso_tpu.zoo import Zoo


class SSPTimeout(TimeoutError):
    """A worker waited longer than ``timeout`` for stragglers to catch up."""


class SSPClock:
    """Bounded-staleness clock over a shared directory.

    Call :meth:`tick` once per training step. It publishes this worker's
    new clock, then blocks until ``min(peer clocks) >= clock - staleness``.
    """

    def __init__(self, directory: str, staleness: int = 1,
                 num_workers: Optional[int] = None,
                 worker_id: Optional[int] = None,
                 poll: float = 0.02, timeout: Optional[float] = 600.0,
                 ignore: Optional[Callable[[], List[int]]] = None):
        """``timeout`` (seconds, None = forever) bounds every wait — the
        default keeps a dead/never-launched peer (e.g. ``num_workers``
        larger than the processes actually started) from hanging the fleet
        silently. ``ignore`` returns worker ids to exclude from the bound
        (pass ``lambda: elastic.failed(hb_dir)`` for heartbeat-driven
        exclusion). A restarted worker resumes from its existing beacon
        rather than re-publishing clock 0 (which would stall every peer at
        the staleness bound until it caught back up)."""
        if staleness < 0:
            raise ValueError(f"staleness must be >= 0, got {staleness}")
        zoo = Zoo.get()
        self.directory = directory
        self.staleness = int(staleness)
        self.num_workers = (zoo.num_workers() if num_workers is None
                            else int(num_workers))
        self.worker_id = (zoo.worker_id() if worker_id is None
                          else int(worker_id))
        self.poll = poll
        self.timeout = timeout
        self._ignore = ignore
        os.makedirs(directory, exist_ok=True)
        try:  # resume: pick up this worker's beacon from a previous run
            with open(self._path(self.worker_id)) as f:
                self._clock = int(json.load(f).get("clock", 0))
        except (OSError, ValueError):
            self._clock = 0
        self._publish()

    def _path(self, worker_id: int) -> str:
        return os.path.join(self.directory, f"sspclock.{worker_id}.json")

    def _publish(self) -> None:
        tmp = self._path(self.worker_id) + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"worker": self.worker_id, "clock": self._clock}, f)
        os.replace(tmp, self._path(self.worker_id))

    @property
    def clock(self) -> int:
        return self._clock

    def peer_clocks(self) -> Dict[int, int]:
        """Latest published clock per worker (absent file = clock 0,
        a worker that has not started yet)."""
        clocks = {}
        for w in range(self.num_workers):
            try:
                with open(self._path(w)) as f:
                    clocks[w] = int(json.load(f).get("clock", 0))
            except (OSError, ValueError):
                clocks[w] = 0
        return clocks

    def _min_live_clock(self) -> int:
        clocks = self.peer_clocks()
        dead = set(self._ignore()) if self._ignore is not None else ()
        live = [c for w, c in clocks.items() if w not in dead]
        return min(live) if live else self._clock

    def tick(self) -> int:
        """Advance this worker's clock by one and enforce the bound.
        Returns the new clock value."""
        self._clock += 1
        self._publish()
        self.wait()
        return self._clock

    def wait(self) -> None:
        """Block until the slowest live worker is within ``staleness`` of
        this worker's clock. Raises :class:`SSPTimeout` after ``timeout``
        seconds (None = wait forever) — the exception message carries the
        full per-worker clock snapshot (and which workers were excluded
        as dead) so a fleet-wide stall is attributable from the error
        alone, and the flight recorder gets the same snapshot before the
        raise (a worker that dies ON this exception still leaves the
        evidence in its dump)."""
        from multiverso_tpu.telemetry import flightrec
        deadline = (None if self.timeout is None
                    else time.monotonic() + self.timeout)
        warned = False
        while self._min_live_clock() < self._clock - self.staleness:
            if deadline is not None and time.monotonic() > deadline:
                clocks = self.peer_clocks()
                dead = sorted(self._ignore()) if self._ignore else []
                snapshot = (f"clock {self._clock}, staleness "
                            f"{self.staleness}, peer clocks {clocks}, "
                            f"ignored-dead {dead}")
                flightrec.record(flightrec.EV_SSP_TIMEOUT,
                                 note=snapshot[:200])
                raise SSPTimeout(
                    f"worker {self.worker_id} waited >{self.timeout}s "
                    f"for stragglers ({snapshot})")
            if not warned:
                log.debug(f"[ssp] worker {self.worker_id} clock "
                          f"{self._clock} waiting on stragglers")
                flightrec.record(flightrec.EV_SSP_WAIT,
                                 msg_id=self._clock)
                warned = True
            time.sleep(self.poll)
        if warned:   # the blocked wait resolved: close the edge (its
            # own kind — a barrier.exit here would read as an unmatched
            # barrier edge in postmortem timelines)
            flightrec.record(flightrec.EV_SSP_RESOLVED,
                             msg_id=self._clock)
