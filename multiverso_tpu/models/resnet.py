"""ResNet (CIFAR variants) in pure JAX, parameter-server trained.

Capability parity with the reference's binding benchmarks: Lasagne ResNet-32
(ref: binding/python/examples/theano/lasagne/*, docs/BENCHMARK.md) and Torch
fb.resnet ResNet-18 data-parallel with a Multiverso ArrayTable holding all
parameters (ref: binding/lua/docs/BENCHMARK.md, BASELINE config 5 "ResNet-18
CIFAR-10 data-parallel, Adam updater, 8->64 chips").

TPU-first shape: NHWC, convolutions via ``lax.conv_general_dilated`` (XLA
maps them to the MXU), BatchNorm with running stats carried functionally, the
whole flattened parameter vector living in one ArrayTable (the reference
Lasagne param_manager recipe) updated by the server-side Adam updater, and
the batch axis sharded over a ``dp`` mesh axis so gradients meet in one psum.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _bn_apply(x, scale, bias, mean, var, eps=1e-5):
    inv = jax.lax.rsqrt(var + eps)
    return (x - mean) * inv * scale + bias


def _bn_train(x, scale, bias, mean, var, momentum=0.9):
    axes = (0, 1, 2)
    m = jnp.mean(x, axes)
    v = jnp.var(x, axes)
    out = _bn_apply(x, scale, bias, m, v)
    new_mean = momentum * mean + (1 - momentum) * m
    new_var = momentum * var + (1 - momentum) * v
    return out, new_mean, new_var


def init_resnet(key, depth: int = 20, num_classes: int = 10,
                width: int = 16, in_channels: int = 3
                ) -> Tuple[Dict, Dict]:
    """CIFAR ResNet (6n+2 layout: depth 20/32/44...; ref benchmarks use 32).
    Returns (params, bn_state)."""
    if (depth - 2) % 6:
        raise ValueError("CIFAR resnet depth must be 6n+2 (20, 32, 44, ...)")
    n = (depth - 2) // 6
    keys = iter(jax.random.split(key, 4 + 6 * n * 3))

    def conv_init(k, kh, kw, cin, cout):
        fan_in = kh * kw * cin
        return (jax.random.normal(k, (kh, kw, cin, cout), jnp.float32)
                * np.sqrt(2.0 / fan_in))

    params: Dict[str, Any] = {"stem": conv_init(next(keys), 3, 3,
                                                in_channels, width)}
    bn: Dict[str, Any] = {"stem": _bn_init(width)}
    chans = [width, 2 * width, 4 * width]
    blocks: List[Dict] = []
    bn_blocks: List[Dict] = []
    cin = width
    for stage, cout in enumerate(chans):
        for b in range(n):
            stride = 2 if (stage > 0 and b == 0) else 1
            blk = {
                "conv1": conv_init(next(keys), 3, 3, cin, cout),
                "conv2": conv_init(next(keys), 3, 3, cout, cout),
            }
            if stride != 1 or cin != cout:
                blk["proj"] = conv_init(next(keys), 1, 1, cin, cout)
            blocks.append(blk)
            bn_blocks.append({"bn1": _bn_init(cout), "bn2": _bn_init(cout)})
            cin = cout
    params["blocks"] = blocks
    bn["blocks"] = bn_blocks
    params["head_w"] = (jax.random.normal(next(keys),
                                          (chans[-1], num_classes),
                                          jnp.float32)
                        * np.sqrt(1.0 / chans[-1]))
    params["head_b"] = jnp.zeros((num_classes,), jnp.float32)
    return params, bn


def _bn_init(c):
    return {"scale": jnp.ones((c,), jnp.float32),
            "bias": jnp.zeros((c,), jnp.float32),
            "mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


def apply_resnet(params: Dict, bn: Dict, x: jax.Array, train: bool = True
                 ) -> Tuple[jax.Array, Dict]:
    """Forward pass; returns (logits, new_bn_state)."""
    new_bn = {"stem": dict(bn["stem"]), "blocks": []}

    def run_bn(h, st, store: Dict):
        if train:
            out, m, v = _bn_train(h, st["scale"], st["bias"], st["mean"],
                                  st["var"])
            store.update({"scale": st["scale"], "bias": st["bias"],
                          "mean": m, "var": v})
            return out
        store.update(st)
        return _bn_apply(h, st["scale"], st["bias"], st["mean"], st["var"])

    h = _conv(x, params["stem"])
    h = jax.nn.relu(run_bn(h, bn["stem"], new_bn["stem"]))
    n = len(params["blocks"]) // 3  # blocks per stage (6n+2 layout)
    for i, (blk, bst) in enumerate(zip(params["blocks"], bn["blocks"])):
        # stage boundaries downsample (except the first stage)
        stride = 2 if (i in (n, 2 * n)) else 1
        store = {"bn1": {}, "bn2": {}}
        out = _conv(h, blk["conv1"], stride)
        out = jax.nn.relu(run_bn(out, bst["bn1"], store["bn1"]))
        out = _conv(out, blk["conv2"])
        out = run_bn(out, bst["bn2"], store["bn2"])
        shortcut = _conv(h, blk["proj"], stride) if "proj" in blk else h
        h = jax.nn.relu(out + shortcut)
        new_bn["blocks"].append(store)
    h = jnp.mean(h, axis=(1, 2))
    logits = h @ params["head_w"] + params["head_b"]
    return logits, new_bn


def loss_fn(params, bn, x, y, train=True):
    logits, new_bn = apply_resnet(params, bn, x, train)
    onehot = jax.nn.one_hot(y, logits.shape[-1], dtype=logits.dtype)
    loss = -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1))
    return loss, new_bn


def flatten_params(params) -> Tuple[np.ndarray, Any]:
    leaves, treedef = jax.tree.flatten(params)
    flat = np.concatenate([np.asarray(l).reshape(-1) for l in leaves])
    meta = (treedef, [np.shape(l) for l in leaves])
    return flat.astype(np.float32), meta


def unflatten_params(flat, meta):
    treedef, shapes = meta
    leaves, off = [], 0
    for s in shapes:
        size = int(np.prod(s)) if s else 1
        leaves.append(jnp.asarray(flat[off:off + size]).reshape(s))
        off += size
    return jax.tree.unflatten(treedef, leaves)


def synthetic_cifar(n: int, size: int = 32, classes: int = 10, seed: int = 0):
    """CIFAR-shaped synthetic data with class-dependent structure (zero-egress
    stand-in; each class gets a distinct low-frequency pattern + noise)."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, classes, n).astype(np.int32)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / size
    patterns = np.stack([
        np.sin(2 * np.pi * ((c % 5 + 1) * xx + (c // 5 + 1) * yy))
        for c in range(classes)]).astype(np.float32)
    x = (patterns[y][..., None].repeat(3, axis=-1) * 0.5
         + rng.normal(size=(n, size, size, 3)).astype(np.float32) * 0.3)
    return x.astype(np.float32), y
