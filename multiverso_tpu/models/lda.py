"""lightLDA-style topic model on the sparse parameter-server table.

BASELINE config 4's workload class ("lightLDA-style sparse topic table
(SparseMatrixTable) — sparse push/pull path"): the word-topic count matrix
lives in a :class:`SparseMatrixTable` (lightLDA shards exactly this table
across Multiverso servers; ref README's related-projects list and the
sparse dirty-row protocol of src/table/matrix.cpp:432-572). Workers
process document batches: PULL only the batch's active vocabulary rows
(the per-chunk key-set pull, ref SparseBlock<bool>), run a few on-device
EM steps, and PUSH expected-count deltas for those rows — the sparse
push/pull loop that is the parameter server's reason to exist for topic
models (V x K is huge; a batch touches a sliver of V).

TPU-first math: instead of per-token collapsed Gibbs (word2vec.c-era
scalar sampling — latency-bound on a TPU), batches run **online EM** on
dense [B, K] responsibilities: two MXU matmuls per iteration, duplicate
word counts accumulated by scatter-add. The planted-topic recovery test
(tests/test_lda.py) pins that the statistics this computes are the right
ones.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class LDAConfig(NamedTuple):
    vocab_size: int = 1000
    num_topics: int = 8
    doc_len: int = 64        # tokens per document (static shape; pad/trim)
    em_iters: int = 5        # per-batch EM iterations on the pulled shard
    alpha: float = 0.1       # document-topic prior
    beta: float = 0.01       # topic-word prior


def make_batch_step(cfg: LDAConfig):
    """Jittable per-batch EM: ``(phi_rows, docs_local) ->
    (delta_rows, theta, ll)``.

    ``phi_rows`` [U, K]: pulled word-topic counts for the batch's U unique
    words; ``docs_local`` [D, L] int32 indices INTO those U rows (the
    caller maps global word ids -> local row slots, exactly the worker's
    local-cache indirection in the reference sparse protocol).
    Returns the expected-count delta for the same U rows, the per-doc
    topic mixtures, and the batch mean log-likelihood.
    """
    K, a, b = cfg.num_topics, cfg.alpha, cfg.beta

    def step(phi_rows, docs_local):
        # topic-word distribution from counts (beta-smoothed); the
        # normalizer over the FULL vocab is approximated by the pulled
        # shard plus the prior mass — adequate for EM ascent and keeps the
        # step independent of unpulled rows
        phi = phi_rows + b
        phi = phi / jnp.sum(phi, axis=0, keepdims=True)        # [U, K]
        d, l = docs_local.shape
        theta = jnp.full((d, K), 1.0 / K, jnp.float32)

        def em(theta, _):
            pw = jnp.take(phi, docs_local.reshape(-1), axis=0)  # [D*L, K]
            pw = pw.reshape(d, l, K)
            r = pw * theta[:, None, :]                          # [D, L, K]
            norm = jnp.sum(r, axis=-1, keepdims=True)
            r = r / jnp.maximum(norm, 1e-30)
            theta = (jnp.sum(r, axis=1) + a)
            theta = theta / jnp.sum(theta, axis=-1, keepdims=True)
            return theta, jnp.mean(jnp.log(jnp.maximum(norm[..., 0],
                                                       1e-30)))

        theta, lls = jax.lax.scan(em, theta, None, length=cfg.em_iters)
        # final responsibilities -> expected word-topic counts, scattered
        # back onto the pulled rows (duplicates accumulate)
        pw = jnp.take(phi, docs_local.reshape(-1), axis=0).reshape(d, l, K)
        r = pw * theta[:, None, :]
        r = r / jnp.maximum(jnp.sum(r, axis=-1, keepdims=True), 1e-30)
        delta = jnp.zeros_like(phi_rows).at[docs_local.reshape(-1)].add(
            r.reshape(d * l, K))
        return delta, theta, lls[-1]

    return jax.jit(step)


class LDATrainer:
    """Sparse push/pull training loop over a SparseMatrixTable.

    Per batch: unique word ids -> ``get_rows_sparse`` (stale rows only
    travel) -> on-device EM (:func:`make_batch_step`) -> ``add_rows`` of
    the expected-count delta. The table's default ``+=`` updater is the
    count accumulator, like lightLDA's servers.
    """

    def __init__(self, cfg: LDAConfig, table, worker_id: int = 0):
        self.cfg = cfg
        self.table = table
        self.worker_id = worker_id
        self._step = make_batch_step(cfg)

    def train_batch(self, docs: np.ndarray) -> float:
        """docs [D, L] int32 global word ids; returns batch mean ll."""
        uids, local = np.unique(docs.reshape(-1), return_inverse=True)
        rows = self.table.get_rows_sparse(uids, worker_id=self.worker_id)
        delta, _, ll = self._step(jnp.asarray(rows),
                                  jnp.asarray(local.reshape(docs.shape)
                                              .astype(np.int32)))
        self.table.add_rows(uids, np.asarray(delta))
        return float(ll)

    def word_topics(self) -> np.ndarray:
        """argmax topic per word from the (pulled) full table."""
        counts = self.table.get()
        return np.argmax(counts + self.cfg.beta, axis=1)


def synthetic_corpus(cfg: LDAConfig, n_docs: int, seed: int = 0
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Planted-topic corpus: topic k owns vocab block k; each doc mixes 1-2
    topics. Returns (docs [n_docs, doc_len], true word->topic labels)."""
    rng = np.random.default_rng(seed)
    K, V, L = cfg.num_topics, cfg.vocab_size, cfg.doc_len
    block = V // K
    labels = np.repeat(np.arange(K), block)
    labels = np.pad(labels, (0, V - labels.size), constant_values=K - 1)
    docs = np.empty((n_docs, L), np.int32)
    for d in range(n_docs):
        ks = rng.choice(K, size=2, replace=False)
        mix = rng.dirichlet([1.0, 1.0])
        topic_of_tok = ks[(rng.uniform(size=L) > mix[0]).astype(int)]
        offs = rng.integers(0, block, L)
        docs[d] = topic_of_tok * block + offs
    return docs, labels
