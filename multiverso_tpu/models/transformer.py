"""Decoder-only transformer LM with context-parallel (long-context) training.

The reference framework predates transformers (SURVEY §5: long-context
absent), but long context is first-class here: this model family trains with
**ring attention** or **Ulysses all-to-all** sequence parallelism
(parallel/ring.py) over a ``(dp, sp)`` mesh — batch data-parallel on ``dp``,
sequence context-parallel on ``sp`` — so sequence length scales with the
number of chips. Everything is a pure function designed for one jitted SPMD
step: params replicated (psum'd grads on dp = the BSP merge the reference's
SyncServer provided, ref src/server.cpp:68-222), activations sharded
``P(dp, sp)``, attention collectives riding ICI.

TPU notes: matmuls are einsum-batched for the MXU; ``cfg.dtype=bfloat16``
keeps activations in bf16 while the loss/softmax runs in f32; no
data-dependent Python control flow — the layer stack is a ``lax.scan`` over
stacked per-layer params so XLA compiles ONE layer body regardless of depth.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from multiverso_tpu.parallel import ring
from multiverso_tpu.utils.platform import shard_map as _shard_map


class TransformerConfig(NamedTuple):
    vocab_size: int = 256
    dim: int = 128
    num_heads: int = 4
    num_layers: int = 2
    max_seq: int = 512
    mlp_ratio: int = 4
    dtype: Any = jnp.float32
    attn: str = "ring"   # "ring" | "zigzag" | "ulysses" | "local" | "flash"
    seq_axis: Optional[str] = None   # mesh axis for sequence parallelism
    batch_axis: Optional[str] = None  # mesh axis for data parallelism
    tp_axis: Optional[str] = None    # mesh axis for tensor parallelism
    # rematerialize each layer in backward (jax.checkpoint on the scanned
    # layer body): stores only the L layer-boundary activations and
    # recomputes one layer's internals at a time — trades ~1/3 more FLOPs
    # for the dominant per-layer activation memory; the HBM lever for deep
    # stacks / long sequences
    remat: bool = False
    # interleaved pipeline schedule: virtual chunks per pp device (see
    # parallel/pipeline.pipeline_apply_interleaved); 1 = plain GPipe
    pp_chunks: int = 1
    # expert-parallel MoE MLPs (parallel/moe.py): 0 = dense MLP
    moe_experts: int = 0
    moe_axis: str = "ep"             # mesh axis the experts shard over
    moe_top_k: int = 1
    moe_capacity_factor: float = 2.0
    moe_aux_coef: float = 0.01


def init_params(cfg: TransformerConfig, seed: int = 0) -> Dict[str, Any]:
    """Stacked-per-layer parameter pytree (leading dim = layer, for scan)."""
    rng = np.random.default_rng(seed)
    d, h, L = cfg.dim, cfg.num_heads, cfg.num_layers
    m = cfg.mlp_ratio * d

    def norm(*shape, scale):
        return jnp.asarray(rng.normal(0, scale, shape), cfg.dtype)

    s = 1.0 / np.sqrt(d)
    layers = {
        "wqkv": norm(L, d, 3 * d, scale=s),
        "wo": norm(L, d, d, scale=s / np.sqrt(2 * L)),
        "ln1": jnp.ones((L, d), cfg.dtype),
        "ln2": jnp.ones((L, d), cfg.dtype),
    }
    if cfg.moe_experts:
        e = cfg.moe_experts
        layers["moe_w1"] = norm(L, e, d, m, scale=s)
        layers["moe_w2"] = norm(L, e, m, d,
                                scale=np.sqrt(1.0 / m) / np.sqrt(2 * L))
        layers["moe_router"] = norm(L, d, e, scale=s)
    else:
        layers["w1"] = norm(L, d, m, scale=s)
        layers["w2"] = norm(L, m, d,
                            scale=np.sqrt(1.0 / m) / np.sqrt(2 * L))
    return {
        "embed": norm(cfg.vocab_size, d, scale=0.02),
        "pos": norm(cfg.max_seq, d, scale=0.02),
        "layers": layers,
        "ln_f": jnp.ones((d,), cfg.dtype),
    }


def _rmsnorm(x, g):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6).astype(x.dtype)) * g


def _attention(cfg: TransformerConfig, q, k, v):
    if cfg.attn == "local":
        # global-level attention; with tp_axis set GSPMD shards the
        # (embarrassingly parallel) head dim itself
        return ring.reference_attention(q, k, v, causal=True)
    if cfg.attn == "flash":
        # fused Pallas kernel (ops/attention_kernels.py); the sequence stays
        # whole per chip — use attn='ring' to shard S. With dp/tp axes set
        # the kernel is shard_mapped so each chip runs it on its own
        # batch/head slice (a bare pallas_call has no GSPMD partitioning
        # rule, so jit alone would replicate the global batch per chip).
        if cfg.seq_axis is not None:
            raise ValueError("attn='flash' is the single-chip fused kernel; "
                             "use attn='ring' for sequence parallelism")
        from multiverso_tpu.ops.attention_kernels import flash_attention
        # block size: biggest divisor of S up to 512 — measured on the
        # 472M LM bench, 512x512 blocks cut the whole-model step ~25-45%
        # vs 128x128 (fewer grid sweeps re-streaming K/V through VMEM)
        blk = next((bsz for bsz in (512, 256, 128)
                    if q.shape[2] % bsz == 0), 128)
        if cfg.batch_axis is None and cfg.tp_axis is None:
            return flash_attention(q, k, v, True, blk, blk)
        from jax.sharding import PartitionSpec as P

        from multiverso_tpu.zoo import Zoo
        spec = P(cfg.batch_axis, cfg.tp_axis, None, None)
        return _shard_map(
            lambda q, k, v: flash_attention(q, k, v, True, blk, blk),
            mesh=Zoo.get().mesh(), in_specs=(spec, spec, spec),
            out_specs=spec, check_vma=False)(q, k, v)
    if cfg.attn == "ring":
        return ring.ring_attention(q, k, v, axis_name=cfg.seq_axis,
                                   causal=True, batch_axis=cfg.batch_axis,
                                   head_axis=cfg.tp_axis)
    if cfg.attn == "zigzag":
        # balanced causal ring; activations are in zigzag sequence order
        # end to end (shard_batch permutes tokens, forward permutes pos)
        return ring.zigzag_ring_attention(
            q, k, v, axis_name=cfg.seq_axis, batch_axis=cfg.batch_axis,
            head_axis=cfg.tp_axis)
    if cfg.tp_axis is not None:
        raise ValueError("ulysses attention reshards heads itself; combine "
                         "tp_axis with attn='ring' or 'local' instead")
    return ring.ulysses_attention(q, k, v, axis_name=cfg.seq_axis,
                                  causal=True, batch_axis=cfg.batch_axis)


def shard_params_moe(params: Dict[str, Any], cfg: TransformerConfig,
                     mesh=None) -> Dict[str, Any]:
    """Place params with expert weights sharded over ``cfg.moe_axis`` (the
    [L, E, ...] stacks split on E) and everything else replicated."""
    from jax.sharding import PartitionSpec as P

    from multiverso_tpu.parallel import tp as tp_lib
    if not cfg.moe_experts:
        raise ValueError("shard_params_moe needs cfg.moe_experts > 0")
    ax = cfg.moe_axis
    rules = {
        "embed": P(), "pos": P(),
        "layers": {
            "wqkv": P(), "wo": P(), "ln1": P(), "ln2": P(),
            "moe_w1": P(None, ax, None, None),
            "moe_w2": P(None, ax, None, None),
            "moe_router": P(),
        },
        "ln_f": P(),
    }
    return tp_lib.shard_params(params, rules, mesh)


def shard_params_fsdp(params: Dict[str, Any], cfg: TransformerConfig,
                      mesh=None, axis: str = "fsdp") -> Dict[str, Any]:
    """Place params FSDP-sharded over ``axis`` (see
    parallel/tp.transformer_fsdp_rules): each chip stores 1/n of every
    large tensor; combine with ``batch_axis=axis`` on the config so the
    same chips compute data-parallel. Works for dense and MoE param trees
    (the signature matches shard_params_tp/shard_params_moe)."""
    from multiverso_tpu.parallel import tp as tp_lib
    return tp_lib.shard_params(
        params, tp_lib.transformer_fsdp_rules(axis,
                                              moe=bool(cfg.moe_experts)),
        mesh)


def shard_params_tp(params: Dict[str, Any], cfg: TransformerConfig,
                    mesh=None) -> Dict[str, Any]:
    """Place params Megatron-sharded over ``cfg.tp_axis`` (see parallel/tp)."""
    from multiverso_tpu.parallel import tp as tp_lib
    if cfg.tp_axis is None:
        raise ValueError("shard_params_tp needs cfg.tp_axis set; with no "
                         "tensor-parallel axis it would silently replicate "
                         "every parameter")
    return tp_lib.shard_params(
        params, tp_lib.transformer_tp_rules(cfg.tp_axis), mesh)


def _make_layer_fn(cfg: TransformerConfig, tp_hint, heads_spec, hidden_spec,
                   mcfg):
    """One transformer block as a scan body ``(x, aux_sum), p -> ...``.

    Shared by :func:`forward_with_aux` (scan over the whole stack) and
    :func:`make_pp_train_step` (scan over one pipeline stage's slice of the
    stack). Shapes are taken from the activation so the same body serves
    full batches and pipeline microbatches.
    """
    h, d = cfg.num_heads, cfg.dim
    hd = d // h
    if cfg.moe_experts:
        from multiverso_tpu.parallel import moe as moe_lib

    def layer(carry, p):
        x, aux_sum = carry
        b, s = x.shape[0], x.shape[1]
        y = _rmsnorm(x, p["ln1"])
        qkv = jnp.einsum("bsd,de->bse", y, p["wqkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        # [B, S, D] -> [B, H, S, hd]; tp shards the head dim
        split = lambda t: tp_hint(
            t.reshape(b, s, h, hd).transpose(0, 2, 1, 3), heads_spec)
        o = _attention(cfg, split(q), split(k), split(v))
        o = o.transpose(0, 2, 1, 3).reshape(b, s, d)
        x = x + jnp.einsum("bsd,de->bse", o, p["wo"])
        y = _rmsnorm(x, p["ln2"])
        if cfg.moe_experts:
            mlp, aux, _ = moe_lib.moe_layer(
                y, {"w1": p["moe_w1"], "w2": p["moe_w2"],
                    "router": p["moe_router"]},
                mcfg, batch_axis=cfg.batch_axis)
            return (x + mlp, aux_sum + aux), None
        # tp shards the MLP hidden dim (column-parallel w1, row-parallel w2)
        y = tp_hint(jnp.einsum("bsd,dm->bsm", y, p["w1"]), hidden_spec)
        y = jax.nn.gelu(y)
        return (x + jnp.einsum("bsm,md->bsd", y, p["w2"]), aux_sum), None

    return layer


def forward(params: Dict[str, Any], tokens: jax.Array,
            cfg: TransformerConfig) -> jax.Array:
    """tokens [B, S] -> logits [B, S, V] (MoE aux loss discarded; training
    uses :func:`loss_fn`, which keeps it)."""
    return forward_with_aux(params, tokens, cfg)[0]


def forward_with_aux(params: Dict[str, Any], tokens: jax.Array,
                     cfg: TransformerConfig):
    """tokens [B, S] -> (logits [B, S, V], moe aux-loss scalar). Written at
    the global-logical level; the attention call shard_maps over the
    sequence axis and MoE MLPs all_to_all tokens over ``moe_axis``."""
    s = tokens.shape[1]
    d = cfg.dim

    if cfg.moe_experts:
        if cfg.seq_axis is not None or cfg.tp_axis is not None:
            raise ValueError(
                "MoE MLPs shard tokens over moe_axis; combine with "
                "batch_axis only (seq_axis/tp_axis are not supported "
                "together with moe_experts yet)")
        from multiverso_tpu.parallel import moe as moe_lib
        mcfg = moe_lib.MoEConfig(
            num_experts=cfg.moe_experts, dim=d, hidden=cfg.mlp_ratio * d,
            capacity_factor=cfg.moe_capacity_factor,
            axis=cfg.moe_axis, top_k=cfg.moe_top_k)

    if cfg.tp_axis is not None or cfg.batch_axis is not None:
        # Constrain activations whenever ANY mesh axis is in play — not
        # just tp. Without the batch-axis pin, the scan-over-layers
        # backward lets GSPMD invent hybrid layouts for the saved
        # attention residuals and fall back to "involuntary full
        # rematerialization" (replicate-then-reshard) on the dp/fsdp
        # mesh — a silent cross-chip perf tax on every layer.
        from jax.sharding import PartitionSpec as P

        from multiverso_tpu.parallel import tp as tp_lib
        heads_spec = P(cfg.batch_axis, cfg.tp_axis, cfg.seq_axis, None)
        hidden_spec = P(cfg.batch_axis, cfg.seq_axis, cfg.tp_axis)
        tp_hint = lambda t, spec: tp_lib.constrain(t, spec)
    else:
        tp_hint = lambda t, spec: t
        heads_spec = hidden_spec = None

    if cfg.attn == "zigzag":
        # tokens arrive zigzag-permuted (shard_batch); position embeddings
        # must follow the same permutation so each token keeps its true
        # global position
        from multiverso_tpu.zoo import Zoo as _Zoo
        zmesh = _Zoo.get().mesh()
        zax = cfg.seq_axis or _Zoo.get().shard_axis()
        zperm = ring.zigzag_shard_ids(s, zmesh.shape[zax])
        pos = params["pos"][zperm]
    else:
        pos = params["pos"][:s]
    x = params["embed"][tokens] + pos[None]

    layer = _make_layer_fn(cfg, tp_hint, heads_spec, hidden_spec,
                           mcfg if cfg.moe_experts else None)

    if cfg.remat:
        # prevent_cse=False: safe (and recommended) under lax.scan, avoids
        # optimization barriers that would inhibit in-layer fusion
        layer = jax.checkpoint(layer, prevent_cse=False)
    (x, aux), _ = jax.lax.scan(layer, (x, jnp.zeros((), jnp.float32)),
                               params["layers"])
    return _lm_head(x, params["ln_f"], params["embed"]), aux


def _lm_head(x, ln_f, embed):
    """Final norm + tied-embedding projection: [B, S, D] -> [B, S, V]."""
    return jnp.einsum("bsd,vd->bsv", _rmsnorm(x, ln_f), embed)


def _nll(logits, targets, mask=None):
    """Mean next-token cross-entropy in f32; ``mask`` weights positions.

    Written as logsumexp - target_logit rather than log_softmax + gather:
    the casts fuse into the reductions so the [B, S, V] f32 log-prob
    tensor (256 MB at the 472M bench config) is never materialized —
    measured ~1 ms/step off the 472M LM train step, loss equal to f32
    association order. The max shift is a constant offset of both terms,
    so it carries no gradient (stop_gradient skips its backward)."""
    lg32 = logits.astype(jnp.float32)
    m = jax.lax.stop_gradient(jnp.max(lg32, -1, keepdims=True))
    lse = jnp.log(jnp.sum(jnp.exp(lg32 - m), -1)) + m[..., 0]
    tl = jnp.take_along_axis(lg32, targets[..., None], -1)[..., 0]
    nll = lse - tl
    if mask is not None:
        return (nll * mask).sum() / jnp.maximum(mask.sum(), 1)
    return nll.mean()


def loss_fn(params, tokens, targets, cfg: TransformerConfig,
            mask: Optional[jax.Array] = None) -> jax.Array:
    """Mean next-token cross-entropy (f32) plus ``moe_aux_coef`` times the
    MoE load-balance loss when MoE layers are enabled. ``targets`` is
    tokens shifted by one on the host, so sequence shards never need a halo
    exchange; ``mask`` zeroes padding/terminal positions and is given in
    the ORIGINAL sequence order — with ``attn="zigzag"`` it is permuted
    here to match the zigzag-ordered nll."""
    if mask is not None and cfg.attn == "zigzag":
        from multiverso_tpu.zoo import Zoo as _Zoo
        ax = cfg.seq_axis or _Zoo.get().shard_axis()
        perm = ring.zigzag_shard_ids(mask.shape[1],
                                     _Zoo.get().mesh().shape[ax])
        mask = mask[:, perm]
    logits, aux = forward_with_aux(params, tokens, cfg)
    nll = _nll(logits, targets, mask)
    if cfg.moe_experts:
        nll = nll + cfg.moe_aux_coef * aux
    return nll


def make_train_step(cfg: TransformerConfig, learning_rate: float = 1e-2):
    """Plain-SGD jittable step (params, tokens, targets) -> (params, loss).

    For the parameter-server training mode, keep params in a table instead:
    compute ``grads`` with ``jax.grad(loss_fn)`` and push ``-lr * grads``
    through ``sharedvar.SharedPytree.sync`` (the delta-sync ASGD surface) or
    ``Table.functional_add`` inside your own step. For stateful optimizers
    use :func:`make_optax_train_step`.

    Jit with ``donate_argnums=(0,)`` when your loop rebinds ``params``
    every step: the update then writes the weight buffers in place
    (measured ~0.6 ms/step on the 472M bench config) — but the ORIGINAL
    params object is consumed, so leave donation off if you keep it.
    """

    def step(params, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets,
                                                  cfg)
        params = jax.tree.map(
            lambda p, g: p - jnp.asarray(learning_rate, p.dtype) * g,
            params, grads)
        return params, loss

    return step


def make_optax_train_step(cfg: TransformerConfig, optimizer):
    """Jittable step for any optax GradientTransformation:
    ``(params, opt_state, tokens, targets) -> (params, opt_state, loss)``.
    Initialize with ``optimizer.init(params)`` — under FSDP/TP the
    optimizer state inherits each param's sharding (ZeRO for free)."""
    import optax

    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets,
                                                  cfg)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return step


def _qkv_head_perm(d: int, h: int) -> np.ndarray:
    """Column permutation taking wqkv's [q_all | k_all | v_all] layout to
    head-grouped [(q,k,v) of head 0 | (q,k,v) of head 1 | ...].

    Needed for tensor parallelism inside pipeline stages: sharding the
    3d output dim contiguously must hand each tp member whole heads (the
    Megatron interleaved-qkv trick)."""
    hd = d // h
    return np.asarray([c * d + g * hd + i
                       for g in range(h) for c in range(3)
                       for i in range(hd)], dtype=np.int64)


def stack_pp_params(params: Dict[str, Any], cfg: TransformerConfig,
                    n_stages: int, tp: Optional[bool] = None,
                    pp_chunks: Optional[int] = None) -> Dict[str, Any]:
    """Regroup the [L, ...] layer stack as [n_stages, L/n_stages, ...].

    The pipeline places stage s's slice on device s of the ``pp`` axis
    (parallel/pipeline.py contract: leading dim = n_stages); each stage
    scans its local L/n_stages layers per tick. When the config has a
    ``tp_axis`` (default ``tp=None`` reads it from ``cfg``, so the same
    config drives stacking, sharding and the step consistently) the wqkv
    columns are permuted head-grouped (see :func:`_qkv_head_perm`) so a
    contiguous tp shard owns whole heads. ``pp_chunks > 1`` produces the
    [n_stages, pp_chunks, per, ...] layout of the interleaved schedule
    (pipeline.pipeline_apply_interleaved).
    """
    if tp is None:
        tp = cfg.tp_axis is not None
    if pp_chunks is None:
        pp_chunks = cfg.pp_chunks
    L = cfg.num_layers
    groups = n_stages * pp_chunks
    if L % groups:
        raise ValueError(f"num_layers={L} not divisible by "
                         f"n_stages*pp_chunks={groups}")
    per = L // groups
    layers = dict(params["layers"])
    if tp:
        layers["wqkv"] = layers["wqkv"][
            ..., _qkv_head_perm(cfg.dim, cfg.num_heads)]
    out = {k: v for k, v in params.items() if k != "layers"}
    if pp_chunks > 1:
        # interleaved layout: global group g -> (device g % S, chunk g // S)
        out["stages"] = jax.tree.map(
            lambda p: p.reshape(pp_chunks, n_stages, per, *p.shape[1:])
                       .swapaxes(0, 1), layers)
    else:
        out["stages"] = jax.tree.map(
            lambda p: p.reshape(n_stages, per, *p.shape[1:]), layers)
    return out


def unstack_pp_params(stacked: Dict[str, Any],
                      cfg: Optional[TransformerConfig] = None,
                      tp: Optional[bool] = None,
                      pp_chunks: Optional[int] = None) -> Dict[str, Any]:
    """Inverse of :func:`stack_pp_params` (for eval/decode/checkpoint
    interop with the plain [L, ...] layout). Pass the same ``cfg`` (and
    ``pp_chunks``) used at stack time so the head-grouped qkv layout and
    the interleaved chunk layout are undone (``tp`` defaults from
    ``cfg.tp_axis`` exactly like :func:`stack_pp_params`)."""
    if tp is None:
        tp = cfg is not None and cfg.tp_axis is not None
    if pp_chunks is None:
        pp_chunks = cfg.pp_chunks if cfg is not None else 1
    out = {k: v for k, v in stacked.items() if k != "stages"}
    if pp_chunks > 1:
        layers = jax.tree.map(
            lambda p: np.asarray(p).swapaxes(0, 1).reshape(
                p.shape[0] * p.shape[1] * p.shape[2], *p.shape[3:]),
            stacked["stages"])
    else:
        layers = jax.tree.map(
            lambda p: np.asarray(p).reshape(p.shape[0] * p.shape[1],
                                            *p.shape[2:]),
            stacked["stages"])
    if tp:
        if cfg is None:
            raise ValueError("unstack_pp_params(tp=True) needs cfg to "
                             "invert the head-grouped qkv layout")
        perm = _qkv_head_perm(cfg.dim, cfg.num_heads)
        inv = np.empty_like(perm)
        inv[perm] = np.arange(perm.size)
        layers["wqkv"] = layers["wqkv"][..., inv]
    out["layers"] = layers
    return out


def _pp_stage_specs(cfg: TransformerConfig, axis: str,
                    chunked: bool = False):
    """PartitionSpecs for the stages subtree under pp x tp: weights split
    over ``cfg.tp_axis`` on the Megatron dims (qkv/w1 output-sharded,
    wo/w2 input-sharded), norms pp-only. ``chunked``: leaves carry the
    interleaved schedule's extra [n_chunks] dim after the stage dim."""
    from jax.sharding import PartitionSpec as P
    t = cfg.tp_axis
    c = (None,) if chunked else ()
    return {
        "wqkv": P(axis, *c, None, None, t),
        "wo": P(axis, *c, None, t, None),
        "ln1": P(axis), "ln2": P(axis),
        "w1": P(axis, *c, None, None, t),
        "w2": P(axis, *c, None, t, None),
    }


def shard_params_pp(stacked: Dict[str, Any], mesh=None,
                    axis: str = "pp",
                    cfg: Optional[TransformerConfig] = None
                    ) -> Dict[str, Any]:
    """Place a :func:`stack_pp_params` tree: stages split over ``axis``
    (one stage's layers per device, via pipeline.shard_stages),
    embeddings/final-norm replicated. Pass ``cfg`` with ``tp_axis`` set to
    additionally shard each stage's weights tensor-parallel
    (:func:`_pp_stage_specs`)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from multiverso_tpu.parallel import pipeline as pp_lib
    from multiverso_tpu.zoo import Zoo
    mesh = mesh or Zoo.get().mesh()
    out = {k: jax.tree.map(
        lambda p: jax.device_put(p, NamedSharding(mesh, P())), v)
        for k, v in stacked.items() if k != "stages"}
    if cfg is not None and cfg.tp_axis is not None:
        # derive the chunked layout from the actual leaf rank (a too-short
        # spec against a [S, V, ...] leaf would silently shard the wrong
        # dim over tp; rank is the ground truth, not cfg.pp_chunks)
        chunked = stacked["stages"]["wqkv"].ndim == 5
        specs = _pp_stage_specs(cfg, axis, chunked=chunked)
        out["stages"] = {
            k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in stacked["stages"].items()}
    else:
        out["stages"] = pp_lib.shard_stages(stacked["stages"], axis=axis,
                                            mesh=mesh)
    return out


def _make_tp_layer_fn(cfg: TransformerConfig, tp_axis: str, n_tp: int):
    """Transformer block with EXPLICIT Megatron tensor parallelism, for use
    inside an enclosing shard_map (the pipeline body): weights arrive as
    tp-local shards (head-grouped qkv — whole heads per member; w1
    column-, wo/w2 row-sharded) and each sublayer ends in ONE
    ``lax.psum`` over ``tp_axis`` — the column->row pairing of
    parallel/tp.py spelled out at the collective level because GSPMD hints
    cannot cross a manual shard_map boundary."""
    h, d = cfg.num_heads, cfg.dim
    hd = d // h
    h_loc = h // n_tp

    def layer(carry, p):
        x, aux_sum = carry
        b, s = x.shape[0], x.shape[1]
        y = _rmsnorm(x, p["ln1"])
        qkv = jnp.einsum("bsd,de->bse", y, p["wqkv"])  # [b,s,3d/t] by head
        qkv = qkv.reshape(b, s, h_loc, 3, hd).transpose(0, 2, 3, 1, 4)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        o = _attention(cfg, q, k, v)                   # local heads
        o = o.transpose(0, 2, 1, 3).reshape(b, s, h_loc * hd)
        x = x + jax.lax.psum(
            jnp.einsum("bsd,de->bse", o, p["wo"]), tp_axis)
        y = _rmsnorm(x, p["ln2"])
        y = jax.nn.gelu(jnp.einsum("bsd,dm->bsm", y, p["w1"]))
        x = x + jax.lax.psum(
            jnp.einsum("bsm,md->bsd", y, p["w2"]), tp_axis)
        return (x, aux_sum), None

    return layer


def make_pp_loss_fn(cfg: TransformerConfig, n_micro: int, axis: str = "pp",
                    mesh=None, pp_chunks: Optional[int] = None):
    """Pipelined LM loss ``loss(stacked, tokens, targets, mask=None)``
    (``mask`` weights positions like :func:`loss_fn`) over the
    ``axis`` mesh dimension (GPipe microbatch ring, parallel/pipeline.py).

    The reference's "pipeline" is communication/compute double-buffering
    (SURVEY §2.10 — `async_buffer.h`, ps_model.cpp GetPipelineTable); layer
    pipelining is the strategy the PS design could not express. Here the
    stack runs through parallel/pipeline.py's single-scan microbatch ring
    and ``jax.grad`` differentiates through the ppermute ring, which
    reverses the schedule automatically: forward fills stage s at tick t,
    backward drains it in the transposed order — the GPipe fill/drain
    schedule without a hand-written backward pass.

    Composition: combine with ``cfg.batch_axis`` on a ``(dp, pp)`` mesh for
    data-parallel pipelines; set ``cfg.tp_axis`` on a ``(dp, pp, tp)`` mesh
    to additionally run Megatron tensor parallelism INSIDE each stage
    (explicit psum layer, :func:`_make_tp_layer_fn`; stack with ``tp=True``
    and shard with ``cfg=`` so qkv is head-grouped); ``cfg.remat=True``
    recomputes each layer in backward (the standard GPipe memory trade).
    Params must be :func:`stack_pp_params` + :func:`shard_params_pp`.
    """
    from multiverso_tpu.parallel import pipeline as pp_lib
    from multiverso_tpu.zoo import Zoo
    mesh = mesh or Zoo.get().mesh()
    if pp_chunks is None:
        pp_chunks = cfg.pp_chunks
    if cfg.moe_experts or cfg.seq_axis is not None:
        raise ValueError("the pp step pipelines the dense stack; sp/moe "
                         "combinations are separate strategies (see "
                         "seq_axis / moe_experts)")
    if cfg.attn not in ("local", "flash"):
        raise ValueError("pipeline stages attend within a microbatch that "
                         "is fully local to the stage; use attn='local' "
                         "(or 'flash' for the fused per-chip kernel)")
    n_stages = mesh.shape[axis]
    if cfg.num_layers % (n_stages * pp_chunks):
        raise ValueError(f"num_layers={cfg.num_layers} not divisible by "
                         f"pp={n_stages} x pp_chunks={pp_chunks}")
    if pp_chunks > 1 and n_micro != n_stages:
        raise ValueError(f"the interleaved schedule runs a fixed "
                         f"n_micro == pp ({n_stages}); got "
                         f"n_micro={n_micro}")
    # inside the pipeline body activations are stage-local, so the layer is
    # built without global sharding hints (flash lowers to the direct
    # kernel call rather than its own shard_map)
    pcfg = cfg._replace(batch_axis=None, tp_axis=None, seq_axis=None)
    param_specs = None
    if cfg.tp_axis is not None:
        n_tp = mesh.shape[cfg.tp_axis]
        if cfg.num_heads % n_tp or (cfg.mlp_ratio * cfg.dim) % n_tp:
            raise ValueError(
                f"num_heads={cfg.num_heads} and mlp hidden "
                f"{cfg.mlp_ratio * cfg.dim} must both be divisible by "
                f"tp={n_tp}")
        layer = _make_tp_layer_fn(pcfg, cfg.tp_axis, n_tp)
        param_specs = _pp_stage_specs(cfg, axis, chunked=pp_chunks > 1)
    else:
        layer = _make_layer_fn(pcfg, lambda t, spec: t, None, None, None)
    if cfg.remat:
        layer = jax.checkpoint(layer, prevent_cse=False)

    def stage_fn(p, x):
        (x, _), _ = jax.lax.scan(layer, (x, jnp.zeros((), jnp.float32)), p)
        return x

    def loss(stacked, tokens, targets, mask=None):
        s = tokens.shape[1]
        x = stacked["embed"][tokens] + stacked["pos"][:s][None]
        if pp_chunks > 1:
            x = pp_lib.pipeline_apply_interleaved(
                stage_fn, stacked["stages"], x, axis=axis, mesh=mesh,
                batch_axis=cfg.batch_axis, param_specs=param_specs)
        else:
            x = pp_lib.pipeline_apply(stage_fn, stacked["stages"], x,
                                      n_micro, axis=axis, mesh=mesh,
                                      batch_axis=cfg.batch_axis,
                                      param_specs=param_specs)
        return _nll(_lm_head(x, stacked["ln_f"], stacked["embed"]),
                    targets, mask)

    return loss


def make_pp_train_step(cfg: TransformerConfig, n_micro: int,
                       learning_rate: float = 1e-2, axis: str = "pp",
                       mesh=None, pp_chunks: Optional[int] = None):
    """Plain-SGD pipeline-parallel LM train step (see
    :func:`make_pp_loss_fn` for the pipelining semantics).
    Returns ``step(stacked, tokens, targets, mask=None) ->
    (stacked, loss)``; ``mask`` weights positions like :func:`loss_fn`."""
    loss = make_pp_loss_fn(cfg, n_micro, axis, mesh, pp_chunks)

    def step(stacked, tokens, targets, mask=None):
        loss_v, grads = jax.value_and_grad(loss)(stacked, tokens, targets,
                                                 mask)
        stacked = jax.tree.map(
            lambda p, g: p - jnp.asarray(learning_rate, p.dtype) * g,
            stacked, grads)
        return stacked, loss_v

    return step


def make_pp_optax_train_step(cfg: TransformerConfig, n_micro: int,
                             optimizer, axis: str = "pp", mesh=None,
                             pp_chunks: Optional[int] = None):
    """Pipelined step for any optax GradientTransformation:
    ``(stacked, opt_state, tokens, targets, mask=None) ->
    (stacked, opt_state, loss)``.
    Initialize with ``optimizer.init(stacked)`` — optimizer moments inherit
    each stage's placement, so Adam state for stage s lives only on device
    s of the ``pp`` axis (the reference pays per-shard updater state the
    same way, ref adagrad_updater.h:19)."""
    import optax

    loss = make_pp_loss_fn(cfg, n_micro, axis, mesh, pp_chunks)

    def step(stacked, opt_state, tokens, targets, mask=None):
        loss_v, grads = jax.value_and_grad(loss)(stacked, tokens, targets,
                                                 mask)
        updates, opt_state = optimizer.update(grads, opt_state, stacked)
        return optax.apply_updates(stacked, updates), opt_state, loss_v

    return step


def _is_q(x):
    from multiverso_tpu.ops.quantization import QuantizedTensor
    return isinstance(x, QuantizedTensor)


def _emb_rows(e, idx):
    """Embedding-row lookup without materializing the full table."""
    if _is_q(e):
        want = (e.q.shape[0],) + (1,) * (e.q.ndim - 1)
        if e.scale.shape != want:
            # out-of-bounds gathers clamp silently, so a wrong scale
            # layout would corrupt decoding without any error
            raise ValueError(
                f"embedding QuantizedTensor needs per-row scales "
                f"{want}, got {e.scale.shape}; quantize embeddings "
                "with keep_axes=(0,) (quantize_lm_params does)")
        return e.q[idx].astype(jnp.float32) * e.scale[idx]
    return e[idx]


def _tied_logits(x, e):
    """[.., D] @ tied embedding -> [.., V] f32 logits. For int8 embeddings
    the int8 operand feeds the dot directly (the convert fuses) and the
    per-row scale lands on the small logits output — the [V, D] f32 table
    is never materialized."""
    if _is_q(e):
        logits = jnp.einsum("bd,vd->bv", x, e.q.astype(x.dtype),
                            preferred_element_type=jnp.float32)
        return logits * e.scale[:, 0][None]
    return jnp.einsum("bd,vd->bv", x, e,
                      preferred_element_type=jnp.float32)


def _moe_exact(y2d, pl, cfg: TransformerConfig, chunk: int = 64):
    """Exact top-k MoE for [T, D] tokens, position-chunked so the per-token
    expert-weight gather stays O(chunk * K * D * M) instead of
    O(T * K * D * M) (a long prompt would otherwise materialize a private
    copy of its experts' weights per position)."""
    from multiverso_tpu.parallel.moe import top_k_gates
    t, d = y2d.shape
    c = min(t, chunk)
    pad = (-t) % c
    if pad:
        y2d = jnp.concatenate(
            [y2d, jnp.zeros((pad, d), y2d.dtype)])

    def one_chunk(yc):
        probs = jax.nn.softmax(
            (yc @ pl["moe_router"]).astype(jnp.float32), -1)
        gates, topi = top_k_gates(probs, cfg.moe_top_k)
        w1_sel = pl["moe_w1"][topi]                  # [C, K, D, M]
        w2_sel = pl["moe_w2"][topi]
        hmid = jax.nn.gelu(jnp.einsum("td,tkdm->tkm", yc, w1_sel))
        out = jnp.einsum("tkm,tkmd->tkd", hmid, w2_sel)
        return (out * gates[..., None].astype(out.dtype)).sum(1)

    mlp = jax.lax.map(one_chunk, y2d.reshape(-1, c, d)).reshape(-1, d)
    return mlp[:t]


def _decode_step(params, caches, tok, t, cfg: TransformerConfig):
    """One token through all layers, reading/updating the KV cache.
    caches: dict of [L, B, H, max_seq, hd]; tok [B]; t scalar position.
    Returns (caches, logits [B, V] f32). Accepts int8 quantized trees
    (weights dequantize one layer at a time)."""
    from multiverso_tpu.ops.quantization import maybe_dequantize

    b = tok.shape[0]
    h, d = cfg.num_heads, cfg.dim
    hd = d // h
    neg_inf = jnp.asarray(-1e30, jnp.float32)
    x = (_emb_rows(params["embed"], tok)
         + _emb_rows(params["pos"], t)).astype(cfg.dtype)    # [B, D]

    def layer(carry, inputs):
        x, = carry
        pl, ck, cv = inputs
        pl = jax.tree.map(lambda l: maybe_dequantize(l, cfg.dtype),
                          pl, is_leaf=_is_q)
        y = _rmsnorm(x, pl["ln1"])
        qkv = y @ pl["wqkv"]                             # [B, 3D]
        q, kk, vv = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, h, hd)
        kk = kk.reshape(b, h, hd)
        vv = vv.reshape(b, h, hd)
        ck = jax.lax.dynamic_update_slice_in_dim(
            ck, kk[:, :, None], t, axis=2)               # [B,H,max,hd]
        cv = jax.lax.dynamic_update_slice_in_dim(
            cv, vv[:, :, None], t, axis=2)
        # f32 score/output accumulation, matching reference_attention's
        # preferred_element_type so bf16 greedy decode agrees with
        # forward()
        s = jnp.einsum("bhd,bhkd->bhk", q, ck,
                       preferred_element_type=jnp.float32)
        s = s / (hd ** 0.5)
        live = jnp.arange(cfg.max_seq)[None, None] <= t
        s = jnp.where(live, s, neg_inf)
        pattn = jax.nn.softmax(s, -1).astype(cv.dtype)
        o = jnp.einsum("bhk,bhkd->bhd", pattn, cv).reshape(b, d)
        x = x + o @ pl["wo"]
        y = _rmsnorm(x, pl["ln2"])
        if cfg.moe_experts:
            # exact top-k routing: each token gathers only its chosen
            # experts' weights (no capacity/dropping at decode time)
            return (x + _moe_exact(y, pl, cfg),), (ck, cv)
        y = jax.nn.gelu(y @ pl["w1"])
        return (x + y @ pl["w2"],), (ck, cv)

    (x,), (ck, cv) = jax.lax.scan(
        layer, (x,), (params["layers"], caches["k"], caches["v"]))
    x = _rmsnorm(x, params["ln_f"])
    return {"k": ck, "v": cv}, _tied_logits(x, params["embed"])


def _prefill(params, prompt, cfg: TransformerConfig, total: int,
             batched: bool = True):
    """Validate a decode request, build the KV caches from the prompt, and
    return (caches, next-token logits).

    ``batched=True`` (default) runs ONE causal pass over all prompt
    positions — the whole prompt hits the MXU as [B, P] matmuls instead
    of P sequential single-token layer scans; ``batched=False`` keeps the
    token-by-token path (the decode step itself, so the two must agree —
    tested)."""
    b, p = prompt.shape
    if p < 1:
        raise ValueError("prompt must contain at least one token (an "
                         "empty prompt would decode from placeholder "
                         "logits)")
    if total <= p:
        raise ValueError("max_new_tokens must be >= 1")
    if total > cfg.max_seq:
        raise ValueError(f"prompt + new tokens = {total} exceeds "
                         f"max_seq={cfg.max_seq}")
    if cfg.moe_experts and not 1 <= cfg.moe_top_k <= cfg.moe_experts:
        raise ValueError(f"top_k={cfg.moe_top_k} out of range for "
                         f"{cfg.moe_experts} experts")
    h, d = cfg.num_heads, cfg.dim
    caches = {
        "k": jnp.zeros((cfg.num_layers, b, h, cfg.max_seq, d // h),
                       cfg.dtype),
        "v": jnp.zeros((cfg.num_layers, b, h, cfg.max_seq, d // h),
                       cfg.dtype),
    }
    if batched:
        ks, vs, logits = _prefill_pass(params, prompt, cfg)
        caches = {
            "k": caches["k"].at[:, :, :, :p].set(ks),
            "v": caches["v"].at[:, :, :, :p].set(vs),
        }
        return caches, logits

    def prefill(carry, i):
        caches, last = carry
        caches, logits = _decode_step(params, caches, prompt[:, i], i, cfg)
        return (caches, logits), None

    (caches, logits), _ = jax.lax.scan(
        prefill, (caches, jnp.zeros((b, cfg.vocab_size), jnp.float32)),
        jnp.arange(p))
    return caches, logits


def _prefill_pass(params, prompt, cfg: TransformerConfig):
    """One causal pass over the prompt, capturing per-layer K/V.
    Returns (ks [L,B,H,P,hd], vs [L,B,H,P,hd], last-position logits
    [B, V] f32). Mirrors _decode_step's math (incl. quantized trees and
    exact MoE routing) batched over positions."""
    from multiverso_tpu.ops.quantization import maybe_dequantize

    b, p = prompt.shape
    h, d = cfg.num_heads, cfg.dim
    hd = d // h
    neg_inf = jnp.asarray(-1e30, jnp.float32)
    x = (_emb_rows(params["embed"], prompt)
         + _emb_rows(params["pos"], jnp.arange(p))[None]
         ).astype(cfg.dtype)                                 # [B, P, D]
    causal = jnp.tril(jnp.ones((p, p), bool))

    def layer(carry, pl):
        x, = carry
        pl = jax.tree.map(lambda l: maybe_dequantize(l, cfg.dtype),
                          pl, is_leaf=_is_q)
        y = _rmsnorm(x, pl["ln1"])
        qkv = jnp.einsum("bpd,de->bpe", y, pl["wqkv"])
        q, kk, vv = jnp.split(qkv, 3, axis=-1)
        split = lambda t: t.reshape(b, p, h, hd).transpose(0, 2, 1, 3)
        q, kk, vv = split(q), split(kk), split(vv)           # [B,H,P,hd]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kk,
                       preferred_element_type=jnp.float32) / (hd ** 0.5)
        s = jnp.where(causal[None, None], s, neg_inf)
        pattn = jax.nn.softmax(s, -1).astype(vv.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", pattn, vv)
        o = o.transpose(0, 2, 1, 3).reshape(b, p, d)
        x = x + jnp.einsum("bpd,de->bpe", o, pl["wo"])
        y = _rmsnorm(x, pl["ln2"])
        if cfg.moe_experts:
            mlp = _moe_exact(y.reshape(b * p, d), pl, cfg)
            return (x + mlp.reshape(b, p, d),), (kk, vv)
        y = jax.nn.gelu(jnp.einsum("bpd,dm->bpm", y, pl["w1"]))
        return (x + jnp.einsum("bpm,md->bpd", y, pl["w2"]),), (kk, vv)

    (x,), (ks, vs) = jax.lax.scan(layer, (x,), params["layers"])
    xl = _rmsnorm(x[:, -1], params["ln_f"])                  # [B, D]
    return ks, vs, _tied_logits(xl, params["embed"])


def generate(params: Dict[str, Any], prompt: jax.Array,
             cfg: TransformerConfig, max_new_tokens: int,
             temperature: float = 0.0,
             key: Optional[jax.Array] = None,
             top_p: float = 1.0,
             eos_id: Optional[int] = None) -> jax.Array:
    """Autoregressive decode with a static KV cache: one ``lax.scan`` over
    decode steps, each step one fused single-token pass (no recompute of
    the prefix). Greedy at ``temperature=0.0``, else samples with ``key``;
    ``top_p < 1.0`` restricts sampling to the nucleus (smallest probability
    mass >= top_p); with ``eos_id`` set, a sequence that emits it keeps
    emitting it (shapes stay static — trim on the host).

    prompt: [B, P] int32 -> returns [B, P + max_new_tokens]. Decoding is
    inherently sequential so there is no sequence axis here (dense and MoE
    configs; attn is ignored); run it data-parallel by sharding B. MoE
    layers decode with exact top-k routing — each token gathers only its
    chosen experts' weights.

    ``params`` may be an int8 weight-only tree from
    ``ops.quantization.quantize_lm_params`` — weights stay int8 in HBM and
    are dequantized one layer at a time inside the decode scan.
    """
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if eos_id is not None and not 0 <= eos_id < cfg.vocab_size:
        raise ValueError(f"eos_id={eos_id} outside vocab of "
                         f"{cfg.vocab_size} (the latch could never fire)")
    if temperature > 0.0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    b, p = prompt.shape
    caches, logits = _prefill(params, prompt, cfg, p + max_new_tokens)
    neg_inf = jnp.asarray(-1e30, jnp.float32)

    def pick(logits, k):
        if temperature <= 0.0:
            return jnp.argmax(logits, -1).astype(prompt.dtype)
        logits = logits / temperature
        if top_p < 1.0:
            # nucleus filter: drop tokens outside the smallest set whose
            # probability mass reaches top_p (the top token always stays)
            sorted_logits = jnp.sort(logits, -1)[:, ::-1]
            csum = jnp.cumsum(jax.nn.softmax(sorted_logits, -1), -1)
            cutoff_idx = jnp.sum(csum < top_p, -1)  # first idx reaching p
            cutoff = jnp.take_along_axis(sorted_logits,
                                         cutoff_idx[:, None], -1)
            logits = jnp.where(logits >= cutoff, logits, neg_inf)
        return jax.random.categorical(k, logits).astype(prompt.dtype)

    def finish(tok, done):
        """Latch eos: once a row emits it, it keeps emitting it."""
        if eos_id is None:
            return tok, done
        tok = jnp.where(done, jnp.asarray(eos_id, tok.dtype), tok)
        return tok, done | (tok == eos_id)

    def decode(carry, i):
        caches, logits, k, done = carry
        k, sub = jax.random.split(k)
        tok, done = finish(pick(logits, sub), done)
        caches, logits = _decode_step(params, caches, tok, p + i, cfg)
        return (caches, logits, k, done), tok

    # scan max_new_tokens - 1 steps; the final token needs only the last
    # logits, not another forward pass
    k0 = key if key is not None else jax.random.key(0)
    done0 = jnp.zeros((b,), bool)
    (_, logits, kf, done), new = jax.lax.scan(
        decode, (caches, logits, k0, done0), jnp.arange(max_new_tokens - 1))
    _, sub = jax.random.split(kf)
    last, _ = finish(pick(logits, sub), done)
    new = (jnp.concatenate([new.T, last[:, None]], axis=1)
           if max_new_tokens > 1 else last[:, None])
    return jnp.concatenate([prompt, new], axis=1)


def generate_beam(params: Dict[str, Any], prompt: jax.Array,
                  cfg: TransformerConfig, max_new_tokens: int,
                  num_beams: int = 4, return_score: bool = False):
    """Beam-search decode: keep the ``num_beams`` highest-logprob
    continuations per sequence, return the best [B, P + max_new_tokens]
    (with its total continuation log-prob [B] when ``return_score``).

    Built on the same KV-cache machinery as :func:`generate` by running
    the batch expanded to B*W rows; each step reorders the caches along
    the beam dim (one gather) after the top-k over (beam, token) pairs.
    ``num_beams=1`` reduces exactly to greedy decoding. Note beam search
    maximizes over the searched set — the greedy path itself can be
    pruned, so the result is not pointwise >= greedy in log-prob.
    """
    if num_beams < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    b, p = prompt.shape
    w = num_beams
    v = cfg.vocab_size

    # prefill once per sequence, then fan the caches out to the W beams
    # (each batch row's beams start identical); scores start [0, -inf, ...]
    # so the first expansion step picks W distinct tokens from beam 0
    caches, logits = _prefill(params, prompt, cfg, p + max_new_tokens)
    caches = jax.tree.map(lambda c: jnp.repeat(c, w, axis=1), caches)
    logits = jnp.repeat(logits, w, axis=0)                   # [B*W, V]
    scores = jnp.tile(jnp.asarray([0.0] + [-1e30] * (w - 1), jnp.float32),
                      (b, 1))                                # [B, W]

    def step(carry, i):
        caches, logits, scores, toks = carry
        logp = jax.nn.log_softmax(logits, -1).reshape(b, w, v)
        cand = scores[..., None] + logp                      # [B, W, V]
        scores, flat = jax.lax.top_k(cand.reshape(b, w * v), w)
        origin = flat // v                                   # [B, W]
        tok = (flat % v).astype(prompt.dtype)
        # reorder beam state to follow the surviving beams
        gather = (jnp.arange(b)[:, None] * w + origin).reshape(-1)
        caches = jax.tree.map(lambda c: c[:, gather], caches)
        toks = toks[jnp.arange(b)[:, None], origin]          # [B, W, T]
        toks = toks.at[:, :, i].set(tok)
        caches, logits = _decode_step(params, caches, tok.reshape(-1),
                                      p + i, cfg)
        return (caches, logits, scores, toks), None

    toks0 = jnp.zeros((b, w, max_new_tokens), prompt.dtype)
    (caches, logits, scores, toks), _ = jax.lax.scan(
        step, (caches, logits, scores, toks0),
        jnp.arange(max_new_tokens - 1))
    # final token from the last logits, no further forward pass
    logp = jax.nn.log_softmax(logits, -1).reshape(b, w, v)
    cand = scores[..., None] + logp
    scores, flat = jax.lax.top_k(cand.reshape(b, w * v), w)
    origin, tok = flat // v, (flat % v).astype(prompt.dtype)
    toks = toks[jnp.arange(b)[:, None], origin]
    toks = toks.at[:, :, max_new_tokens - 1].set(tok)
    best = jnp.argmax(scores, -1)                            # [B]
    new = toks[jnp.arange(b), best]                          # [B, T]
    out = jnp.concatenate([prompt, new], axis=1)
    if return_score:
        return out, scores[jnp.arange(b), best]
    return out


def shard_batch(tokens: np.ndarray, cfg: TransformerConfig,
                mesh=None) -> jax.Array:
    """device_put a [B, S] token batch sharded P(batch_axis, seq_axis).
    With ``attn="zigzag"`` the sequence is permuted into zigzag order first
    (apply to tokens AND targets; logits/losses come back in the same
    order, which leaves any position-mean loss unchanged)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from multiverso_tpu.zoo import Zoo
    zoo_mesh = Zoo.get().mesh()
    mesh = mesh or zoo_mesh
    tokens = jnp.asarray(tokens)
    if cfg.attn == "zigzag":
        ax = cfg.seq_axis or Zoo.get().shard_axis()
        if mesh.shape[ax] != zoo_mesh.shape[ax]:
            # forward_with_aux derives the zigzag layout from the Zoo mesh;
            # permuting with a different shard count would silently corrupt
            # the causal masking
            raise ValueError(
                f"mesh axis {ax!r} has {mesh.shape[ax]} shards but the "
                f"active Zoo mesh has {zoo_mesh.shape[ax]}; zigzag layout "
                "must be computed against the mesh the model runs on")
        perm = ring.zigzag_shard_ids(tokens.shape[1], mesh.shape[ax])
        tokens = tokens[:, perm]
    spec = P(cfg.batch_axis, cfg.seq_axis)
    return jax.device_put(tokens, NamedSharding(mesh, spec))
