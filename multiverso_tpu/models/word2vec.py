"""word2vec model math (skipgram / CBOW, negative sampling / hierarchical
softmax), pure JAX.

TPU-native re-design of the reference WordEmbedding trainer math
(ref: Applications/WordEmbedding/src/wordembedding.cpp:57-160 — per-pair
scalar FeedForward/BPOutputLayer loops, Hogwild-racy within a node). Here a
whole minibatch of (center, context) pairs trains as batched gathers + a
(B, K+1, D) einsum on the MXU, and the scatter-add of gradients replaces the
racy writes with deterministic duplicate accumulation — same algorithm, no
races, hardware-shaped.

Negative sampling uses a device-resident precomputed slot table (the
word2vec.c / reference design, sized 2^20 instead of 1e8): one uniform draw +
one gather per negative. (The inverse-CDF ``searchsorted`` variant is kept
for reference but its binary search is ~3x the whole step's cost on the VPU.)

All step functions are functional: they take and return the embedding arrays,
so the caller can run them under ``lax.scan``/``jit`` and commit to the
parameter tables at block boundaries (the PS Add/Get shows up only at the
block seam, exactly like the reference's RequestParameter/AddDeltaParameter
block pipeline, src/communicator.cpp:104-236).
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class W2VConfig(NamedTuple):
    vocab_size: int
    embedding_dim: int = 128
    negatives: int = 5
    window: int = 5
    learning_rate: float = 0.025
    cbow: bool = False
    hierarchical_softmax: bool = False
    shared_negatives: int = 0  # >0: batch-shared negative pool (TPU-first)


def init_embeddings(cfg: W2VConfig, seed: int = 0
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Input: uniform ±0.5/dim (ref communicator.cpp:20 server random init);
    output: zeros."""
    rng = np.random.default_rng(seed)
    win = ((rng.random((cfg.vocab_size, cfg.embedding_dim)) - 0.5)
           / cfg.embedding_dim).astype(np.float32)
    wout = np.zeros((cfg.vocab_size, cfg.embedding_dim), dtype=np.float32)
    return win, wout


def sample_negatives(key: jax.Array, cdf: jax.Array, batch: int,
                     k: int) -> jax.Array:
    """Inverse-CDF draw from the unigram^0.75 table. NOTE: searchsorted's
    binary search is slow on the TPU VPU (~3x the whole training step);
    prefer :func:`build_negative_table` + :func:`sample_negatives_table`,
    which is the word2vec.c design and costs one gather."""
    u = jax.random.uniform(key, (batch, k))
    return jnp.searchsorted(cdf, u).astype(jnp.int32)


def build_negative_table(unigram: np.ndarray, size: int = 1 << 20
                         ) -> np.ndarray:
    """Precomputed sampling table: word w occupies ~unigram[w]*size slots
    (the reference/word2vec.c 1e8-slot table, sized for accelerator memory).
    Sampling = uniform int + one gather — no binary search."""
    p = np.asarray(unigram, dtype=np.float64)
    p = p / p.sum()
    counts = np.maximum(np.round(p * size).astype(np.int64), 1)
    table = np.repeat(np.arange(p.size, dtype=np.int32), counts)
    if table.size >= size:
        return table[:size]
    pad = np.random.default_rng(0).choice(
        p.size, size - table.size, p=p).astype(np.int32)
    return np.concatenate([table, pad])


def sample_negatives_table(key: jax.Array, neg_table: jax.Array, batch: int,
                           k: int) -> jax.Array:
    idx = jax.random.randint(key, (batch, k), 0, neg_table.shape[0])
    return jnp.take(neg_table, idx, axis=0)


def splitmix32(x):
    """Counter-based hash (splitmix64's finalizer, 32-bit constants) that is
    BIT-IDENTICAL between numpy and jnp uint32 arrays. The PS block path
    uses it to draw the same negative-sample stream twice: once on the host
    (to know which rows to pull) and once in-graph (so the sampled ids never
    have to cross the host->device wire)."""
    x = (x ^ (x >> np.uint32(16))) * np.uint32(0x7FEB352D)
    x = (x ^ (x >> np.uint32(15))) * np.uint32(0x846CA68B)
    return x ^ (x >> np.uint32(16))


def counter_negs(base, count: int, table_mask: int):
    """Slot indices into a pow2-sized negative table for counters
    [base, base+count): works on host (numpy) and in-graph (jnp, ``base``
    traced) with identical results. ``table_mask`` = table_size - 1."""
    mod = jnp if isinstance(base, jax.Array) else np
    ctr = mod.arange(count, dtype=mod.uint32) + base
    return splitmix32(ctr) & mod.uint32(table_mask)


def _ns_forward_backward(v: jax.Array, u: jax.Array, labels: jax.Array,
                         lr: float) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Shared NS math. v: (B, D); u: (B, T, D); labels: (T,) or (B, T).

    Returns (loss, dv, du) where dv/du are *ascent* deltas pre-scaled by lr
    (ref BPOutputLayer sigmoid ± label, wordembedding.cpp:100-140).
    """
    scores = jnp.einsum("bd,btd->bt", v, u)
    sig = jax.nn.sigmoid(scores)
    g = (labels - sig) * lr                     # (B, T)
    dv = jnp.einsum("bt,btd->bd", g, u)
    du = g[..., None] * v[:, None, :]
    # loss: -log sigmoid(pos) - log sigmoid(-neg)
    logsig = jax.nn.log_sigmoid(jnp.where(labels > 0, scores, -scores))
    loss = -jnp.mean(jnp.sum(logsig, axis=-1))
    return loss, dv, du


def skipgram_ns_step(win: jax.Array, wout: jax.Array, centers: jax.Array,
                     contexts: jax.Array, negatives: jax.Array,
                     lr: float) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One skipgram negative-sampling minibatch.

    centers/contexts: (B,) int32; negatives: (B, K) int32.
    """
    b, k = negatives.shape
    v = jnp.take(win, centers, axis=0)                       # (B, D)
    targets = jnp.concatenate([contexts[:, None], negatives], axis=1)
    u = jnp.take(wout, targets, axis=0)                      # (B, K+1, D)
    labels = jnp.concatenate(
        [jnp.ones((b, 1), v.dtype), jnp.zeros((b, k), v.dtype)], axis=1)
    loss, dv, du = _ns_forward_backward(v, u, labels, lr)
    win = win.at[centers].add(dv)
    wout = wout.at[targets.reshape(-1)].add(
        du.reshape(-1, du.shape[-1]))
    return win, wout, loss


def _cbow_mean(win, windows, window_mask):
    """Masked mean of the window's input vectors (ref FeedForward average,
    wordembedding.cpp:57-80). Returns (v, denom, m) for the backward."""
    ctx = jnp.take(win, windows, axis=0)                     # (B, W, D)
    m = window_mask.astype(ctx.dtype)[..., None]
    denom = jnp.maximum(m.sum(axis=1), 1.0)
    return (ctx * m).sum(axis=1) / denom, denom, m


def _cbow_spread(win, windows, dv, denom, m):
    """Scatter dv back over the (masked) window, divided like the forward
    mean."""
    dctx = (dv[:, None, :] / denom[:, None, :]) * m          # (B, W, D)
    return win.at[windows.reshape(-1)].add(
        dctx.reshape(-1, dctx.shape[-1]))


def cbow_ns_step(win: jax.Array, wout: jax.Array, windows: jax.Array,
                 window_mask: jax.Array, targets_pos: jax.Array,
                 negatives: jax.Array, lr: float
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One CBOW minibatch: windows (B, W) context ids with bool mask,
    averaged input vectors predict targets_pos (B,)."""
    b, k = negatives.shape
    v, denom, m = _cbow_mean(win, windows, window_mask)
    tgt = jnp.concatenate([targets_pos[:, None], negatives], axis=1)
    u = jnp.take(wout, tgt, axis=0)
    labels = jnp.concatenate(
        [jnp.ones((b, 1), v.dtype), jnp.zeros((b, k), v.dtype)], axis=1)
    loss, dv, du = _ns_forward_backward(v, u, labels, lr)
    win = _cbow_spread(win, windows, dv, denom, m)
    wout = wout.at[tgt.reshape(-1)].add(du.reshape(-1, du.shape[-1]))
    return win, wout, loss


def _hs_forward_backward(v: jax.Array, u: jax.Array, codes: jax.Array,
                         path_mask: jax.Array, lr: float
                         ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Shared hierarchical-softmax math. v: (B, D) predictor vectors;
    u: (B, L, D) inner-node vectors along each word's Huffman path.
    Returns (loss, dv, du), ascent deltas pre-scaled by lr."""
    scores = jnp.einsum("bd,bld->bl", v, u)
    sig = jax.nn.sigmoid(scores)
    # label for Huffman: predict 1 - code (word2vec.c convention)
    labels = (1.0 - codes.astype(v.dtype))
    g = (labels - sig) * path_mask.astype(v.dtype) * lr
    dv = jnp.einsum("bl,bld->bd", g, u)
    du = g[..., None] * v[:, None, :]
    masked = jnp.where(path_mask, scores * (1 - 2 * codes), 0.0)
    loss = -jnp.mean(jnp.sum(jax.nn.log_sigmoid(masked)
                             * path_mask.astype(v.dtype), axis=-1))
    return loss, dv, du


def skipgram_hs_step(win: jax.Array, hs_out: jax.Array, centers: jax.Array,
                     codes: jax.Array, points: jax.Array,
                     path_mask: jax.Array, lr: float
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Hierarchical-softmax skipgram minibatch.

    codes/points/path_mask: (B, L) — the context word's Huffman path
    (ref huffman_encoder.cpp output consumed at wordembedding.cpp HS branch).
    hs_out has V-1 inner-node rows.
    """
    v = jnp.take(win, centers, axis=0)                       # (B, D)
    u = jnp.take(hs_out, points, axis=0)                     # (B, L, D)
    loss, dv, du = _hs_forward_backward(v, u, codes, path_mask, lr)
    win = win.at[centers].add(dv)
    hs_out = hs_out.at[points.reshape(-1)].add(
        du.reshape(-1, du.shape[-1]))
    return win, hs_out, loss


def cbow_hs_step(win: jax.Array, hs_out: jax.Array, windows: jax.Array,
                 window_mask: jax.Array, codes: jax.Array,
                 points: jax.Array, path_mask: jax.Array, lr: float
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """CBOW x hierarchical softmax: the averaged window context predicts
    the target word's Huffman path (ref wordembedding.cpp CBOW+HS branch).

    windows/window_mask: (B, W); codes/points/path_mask: (B, L), the
    TARGET word's path.
    """
    v, denom, m = _cbow_mean(win, windows, window_mask)
    u = jnp.take(hs_out, points, axis=0)                     # (B, L, D)
    loss, dv, du = _hs_forward_backward(v, u, codes, path_mask, lr)
    win = _cbow_spread(win, windows, dv, denom, m)
    hs_out = hs_out.at[points.reshape(-1)].add(
        du.reshape(-1, du.shape[-1]))
    return win, hs_out, loss


def make_fused_epoch(cfg: W2VConfig, unigram: np.ndarray):
    """Build a jitted scan over skipgram-NS pair minibatches: the whole block
    trains on device; negatives are drawn in-graph. Returns
    ``epoch_fn(win, wout, centers, contexts, key) -> (win, wout, mean_loss)``
    where centers/contexts are (num_batches, B)."""
    neg_table = jnp.asarray(build_negative_table(unigram))

    @jax.jit
    def epoch_fn(win, wout, centers, contexts, key):
        def body(carry, batch):
            win, wout, key = carry
            c, ctx = batch
            key, sub = jax.random.split(key)
            neg = sample_negatives_table(sub, neg_table, c.shape[0],
                                         cfg.negatives)
            win, wout, loss = skipgram_ns_step(
                win, wout, c, ctx, neg, cfg.learning_rate)
            return (win, wout, key), loss

        (win, wout, _), losses = jax.lax.scan(
            body, (win, wout, key), (centers, contexts))
        return win, wout, jnp.mean(losses)

    return epoch_fn


_LCG_A = np.uint32(1664525)
_LCG_C = np.uint32(1013904223)


@functools.lru_cache(maxsize=8)
def _lcg_jump_consts(n: int) -> Tuple[np.ndarray, np.ndarray]:
    """Closed-form LCG jump constants: ``s_t = A^t * s_0 + C_t (mod 2^32)``
    for t = 1..n, so a whole epoch's negative-sampler states come from one
    vectorized [n, K'] expression instead of n sequential in-scan steps
    (which profiled at ~17% of the epoch). Bit-identical to stepping the
    recurrence n times."""
    At = np.empty(n, np.uint32)
    Ct = np.empty(n, np.uint32)
    # python ints masked to 32 bits: np.uint32 scalar arithmetic would wrap
    # correctly too but spews RuntimeWarnings on every overflow
    mask, A, C = 0xFFFFFFFF, int(_LCG_A), int(_LCG_C)
    a, c = A, C
    for t in range(n):
        At[t], Ct[t] = a, c
        a = (a * A) & mask
        c = (c * A + C) & mask
    return At, Ct


def shared_neg_step(win: jax.Array, wout: jax.Array, centers: jax.Array,
                    contexts: jax.Array, neg_ids: jax.Array, lr: float,
                    neg_weight: float = 1.0,
                    compute_dtype=jnp.bfloat16
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Skipgram-NS minibatch with a batch-SHARED negative pool.

    The reference draws ``k`` fresh negatives per pair
    (wordembedding.cpp:100-140 per-pair loop). Per-pair draws on TPU cost a
    (B, K) scalar gather + a (B, K, D) row gather + a duplicate-heavy scatter
    — all latency-bound VPU work. Sharing one pool of ``K'`` negatives across
    the minibatch turns the entire negative half into two (B,D)x(D,K') MXU
    matmuls and a K'-row scatter, ~5x faster end-to-end. ``neg_weight``
    (typically k/K') rescales the negative gradient so the expected objective
    matches the reference's k-negatives-per-pair loss.

    centers/contexts: (B,) int32; neg_ids: (K',) int32.
    Tables stay in their storage dtype (f32); compute runs in
    ``compute_dtype`` (bf16 on the MXU).
    """
    cd = compute_dtype
    v = jnp.take(win, centers, axis=0).astype(cd)              # (B, D)
    up = jnp.take(wout, contexts, axis=0).astype(cd)           # (B, D)
    un = jnp.take(wout, neg_ids, axis=0).astype(cd)            # (K', D)
    pos = jnp.sum(v * up, axis=-1).astype(jnp.float32)         # (B,)
    negs = jnp.dot(v, un.T).astype(jnp.float32)                # (B, K') MXU
    gp = ((1.0 - jax.nn.sigmoid(pos)) * lr).astype(cd)
    gn = (-jax.nn.sigmoid(negs) * (lr * neg_weight)).astype(cd)
    dv = gp[:, None] * up + jnp.dot(gn, un)                    # (B, D) MXU
    dup = gp[:, None] * v
    dun = jnp.dot(gn.T, v)                                     # (K', D) MXU
    loss = (-jnp.mean(jax.nn.log_sigmoid(pos))
            - neg_weight * jnp.mean(
                jnp.sum(jax.nn.log_sigmoid(-negs), axis=-1)))
    win = win.at[centers].add(dv.astype(win.dtype))
    # two scatters, NOT one concat'd scatter: the K'-row pool scatter is
    # nearly free while concatenation forces an extra [B+K', D]
    # materialization (measured ~30% slower per batch on-chip)
    wout = wout.at[contexts].add(dup.astype(wout.dtype))
    wout = wout.at[neg_ids].add(dun.astype(wout.dtype))
    return win, wout, loss


def make_fused_shared_epoch(cfg: W2VConfig, unigram: np.ndarray,
                            compute_dtype=jnp.bfloat16, table_bits: int = 20):
    """Fused epoch with batch-shared negatives and an in-graph LCG sampler.

    The negative draw uses the reference's own RNG design — word2vec.c's
    ``next_random = next_random * A + C`` linear congruential stream (the
    reference inherits it at wordembedding.cpp SampleNegative). The whole
    epoch's (K',)-lane states come from closed-form jumps
    (:func:`_lcg_jump_consts`) + one batched table gather before the scan,
    replacing both a threefry invocation (profiled at ~55% of the epoch)
    and the earlier per-batch in-scan LCG step (~17%).
    Returns ``epoch_fn(win, wout, centers, contexts, lcg_state) ->
    (win, wout, mean_loss, lcg_state)``.
    """
    k_shared = cfg.shared_negatives
    if k_shared <= 0:
        raise ValueError("cfg.shared_negatives must be > 0")
    neg_table = jnp.asarray(build_negative_table(unigram, 1 << table_bits))
    neg_weight = cfg.negatives / k_shared
    shift = jnp.uint32(32 - table_bits)  # top bits: LCG low bits are weak

    # donate the tables: epochs chain win/wout through, and without donation
    # every call pays a full-table copy before the first scatter
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def epoch_fn(win, wout, centers, contexts, lcg_state):
        # the whole epoch's sampler states in one closed-form jump + ONE
        # batched table gather (bit-identical to stepping the LCG per
        # batch, which serialized ~17% of the epoch on small VPU ops)
        At, Ct = _lcg_jump_consts(centers.shape[0])
        s_all = (lcg_state[None, :] * jnp.asarray(At)[:, None]
                 + jnp.asarray(Ct)[:, None])
        nids = jnp.take(neg_table, (s_all >> shift).astype(jnp.int32),
                        axis=0)

        def body(carry, batch):
            win, wout, = carry
            c, x, nid = batch
            win, wout, loss = shared_neg_step(
                win, wout, c, x, nid, cfg.learning_rate, neg_weight,
                compute_dtype)
            return (win, wout), loss

        (win, wout), losses = jax.lax.scan(
            body, (win, wout), (centers, contexts, nids))
        return win, wout, jnp.mean(losses), s_all[-1]

    return epoch_fn


def init_lcg_state(k_shared: int, seed: int = 0) -> np.ndarray:
    """Independent per-lane LCG seeds for :func:`make_fused_shared_epoch`."""
    return np.random.default_rng(seed).integers(
        0, np.iinfo(np.uint32).max, size=(k_shared,), dtype=np.uint32)


def make_fused_cbow_epoch(cfg: W2VConfig, unigram: np.ndarray):
    """CBOW-NS variant: scans (windows, masks, targets) batches."""
    neg_table = jnp.asarray(build_negative_table(unigram))

    @jax.jit
    def epoch_fn(win, wout, windows, masks, targets, key):
        def body(carry, batch):
            win, wout, key = carry
            w, m, t = batch
            key, sub = jax.random.split(key)
            neg = sample_negatives_table(sub, neg_table, t.shape[0],
                                         cfg.negatives)
            win, wout, loss = cbow_ns_step(win, wout, w, m, t, neg,
                                           cfg.learning_rate)
            return (win, wout, key), loss

        (win, wout, _), losses = jax.lax.scan(
            body, (win, wout, key), (windows, masks, targets))
        return win, wout, jnp.mean(losses)

    return epoch_fn


def _make_path_gather(codes: np.ndarray, points: np.ndarray,
                      lengths: np.ndarray):
    """Closure gathering words' Huffman paths in-graph: the path tables
    live on device once; ``gather(ids) -> (code, point, mask)``."""
    codes_d = jnp.asarray(codes)
    points_d = jnp.asarray(points)
    lengths_d = jnp.asarray(lengths)
    max_len = codes.shape[1]

    def gather(ids):
        code = jnp.take(codes_d, ids, axis=0)
        point = jnp.take(points_d, ids, axis=0)
        mask = (jnp.arange(max_len)[None, :]
                < jnp.take(lengths_d, ids)[:, None])
        return code, point, mask

    return gather


def make_fused_hs_epoch(cfg: W2VConfig, codes: np.ndarray, points: np.ndarray,
                        lengths: np.ndarray):
    """Hierarchical-softmax skipgram variant: each batch gathers its
    contexts' Huffman paths in-graph."""
    path = _make_path_gather(codes, points, lengths)

    @jax.jit
    def epoch_fn(win, hs_out, centers, contexts, key):
        def body(carry, batch):
            win, hs_out = carry
            c, ctx = batch
            code, point, mask = path(ctx)
            win, hs_out, loss = skipgram_hs_step(
                win, hs_out, c, code, point, mask, cfg.learning_rate)
            return (win, hs_out), loss

        (win, hs_out), losses = jax.lax.scan(
            body, (win, hs_out), (centers, contexts))
        return win, hs_out, jnp.mean(losses)

    return epoch_fn


def make_fused_cbow_hs_epoch(cfg: W2VConfig, codes: np.ndarray,
                             points: np.ndarray, lengths: np.ndarray):
    """CBOW x HS variant: scans (windows, masks, targets) batches; each
    batch gathers its TARGETS' Huffman paths in-graph."""
    path = _make_path_gather(codes, points, lengths)

    @jax.jit
    def epoch_fn(win, hs_out, windows, masks, targets, key):
        del key  # HS draws no negatives; kept for dispatch uniformity

        def body(carry, batch):
            win, hs_out = carry
            w, m, t = batch
            code, point, pmask = path(t)
            win, hs_out, loss = cbow_hs_step(
                win, hs_out, w, m, code, point, pmask, cfg.learning_rate)
            return (win, hs_out), loss

        (win, hs_out), losses = jax.lax.scan(
            body, (win, hs_out), (windows, masks, targets))
        return win, hs_out, jnp.mean(losses)

    return epoch_fn


def generate_cbow_batches(ids: np.ndarray, window: int
                          ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(windows, mask, targets) for CBOW: each position is a target predicted
    from its masked +-window context."""
    n = ids.size
    pad = np.concatenate([np.full(window, -1, ids.dtype), ids,
                          np.full(window, -1, ids.dtype)])
    view = np.lib.stride_tricks.sliding_window_view(pad, 2 * window + 1)
    ctx = np.delete(view, window, axis=1)        # (n, 2*window)
    mask = ctx >= 0
    windows = np.where(mask, ctx, 0).astype(np.int32)
    return windows, mask, ids.astype(np.int32)


def nearest_neighbors(win: np.ndarray, word_id: int, k: int = 10) -> np.ndarray:
    """Cosine-similarity neighbors (analogy/eval helper)."""
    w = win / (np.linalg.norm(win, axis=1, keepdims=True) + 1e-8)
    sims = w @ w[word_id]
    return np.argsort(-sims)[1: k + 1]


def generate_pairs(ids: np.ndarray, window: int, seed: int = 0,
                   dynamic: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Sliding-window (center, context) pairs with the reference's random
    window shrink (word2vec 'b = rand % window'). Vectorized: one pass per
    offset instead of a Python loop per token."""
    n = ids.size
    if n < 2:
        return (np.zeros(0, np.int32), np.zeros(0, np.int32))
    rng = np.random.default_rng(seed)
    win_sizes = (rng.integers(1, window + 1, size=n) if dynamic
                 else np.full(n, window))
    centers_parts, contexts_parts = [], []
    idx = np.arange(n)
    for d in range(1, window + 1):
        ok = win_sizes >= d
        fwd = ok & (idx + d < n)
        bwd = ok & (idx - d >= 0)
        i_f = idx[fwd]
        i_b = idx[bwd]
        centers_parts.append(ids[i_f])
        contexts_parts.append(ids[i_f + d])
        centers_parts.append(ids[i_b])
        contexts_parts.append(ids[i_b - d])
    centers = np.concatenate(centers_parts).astype(np.int32)
    contexts = np.concatenate(contexts_parts).astype(np.int32)
    # shuffle so minibatches mix offsets (the per-token order of the scalar
    # version isn't load-bearing; SGD prefers shuffled pairs)
    perm = rng.permutation(centers.size)
    return centers[perm], contexts[perm]
