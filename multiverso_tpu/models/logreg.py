"""Logistic-regression / softmax model math, pure JAX.

TPU-native equivalent of the reference LR model + objectives
(ref: Applications/LogisticRegression/src/model/model.cpp:64-111 minibatch
gradient accumulation; src/objective/objective.cpp sigmoid/softmax Predict /
Diff / Gradient; src/regular/{l1,l2}_regular.h). The per-sample scalar loops
of the reference become one batched matmul on the MXU; the minibatch-average
gradient is a second matmul.

Parameters are a single (num_classes, input_dim + 1) matrix with the bias
folded in, stored flattened in an ArrayTable (the reference's dense PS layout,
ps_model.cpp:24-41).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from multiverso_tpu.updaters import AddOption


def param_count(input_dim: int, num_classes: int) -> int:
    return num_classes * (input_dim + 1)


def unflatten(params: jax.Array, input_dim: int, num_classes: int) -> jax.Array:
    return params[: param_count(input_dim, num_classes)].reshape(
        num_classes, input_dim + 1)


def _augment(x: jax.Array) -> jax.Array:
    """Append the bias column."""
    return jnp.concatenate(
        [x, jnp.ones((*x.shape[:-1], 1), x.dtype)], axis=-1)


def predict_logits(w: jax.Array, x: jax.Array) -> jax.Array:
    """(B, D) x (C, D+1) -> (B, C) on the MXU."""
    return _augment(x) @ w.T


def predict_proba(w: jax.Array, x: jax.Array, objective: str) -> jax.Array:
    logits = predict_logits(w, x)
    if objective == "sigmoid":
        return jax.nn.sigmoid(logits)
    return jax.nn.softmax(logits, axis=-1)


def loss_and_grad(w: jax.Array, x: jax.Array, y: jax.Array, objective: str,
                  regular: str = "none", reg_coef: float = 0.0
                  ) -> Tuple[jax.Array, jax.Array]:
    """Minibatch loss and average gradient (ref objective.cpp Diff = p - onehot
    then Gradient accumulation; regularizer added per element like
    regular.cpp Calculate)."""
    xb = _augment(x)
    logits = xb @ w.T
    num_classes = w.shape[0]
    if objective == "sigmoid":
        onehot = jax.nn.one_hot(y, num_classes, dtype=w.dtype)
        p = jax.nn.sigmoid(logits)
        eps = 1e-7
        loss = -jnp.mean(jnp.sum(
            onehot * jnp.log(p + eps) + (1 - onehot) * jnp.log(1 - p + eps),
            axis=-1))
        diff = p - onehot
    else:  # softmax cross-entropy
        onehot = jax.nn.one_hot(y, num_classes, dtype=w.dtype)
        logp = jax.nn.log_softmax(logits, axis=-1)
        loss = -jnp.mean(jnp.sum(onehot * logp, axis=-1))
        diff = jax.nn.softmax(logits, axis=-1) - onehot
    grad = diff.T @ xb / x.shape[0]
    if regular == "l2":
        grad = grad + reg_coef * w
        loss = loss + 0.5 * reg_coef * jnp.sum(jnp.square(w))
    elif regular == "l1":
        grad = grad + reg_coef * jnp.sign(w)
        loss = loss + reg_coef * jnp.sum(jnp.abs(w))
    return loss, grad


def accuracy(w: jax.Array, x: jax.Array, y: jax.Array) -> jax.Array:
    return jnp.mean((jnp.argmax(predict_logits(w, x), axis=-1) == y)
                    .astype(jnp.float32))


def make_train_step(table, input_dim: int, num_classes: int, objective: str,
                    regular: str = "none", reg_coef: float = 0.0,
                    learning_rate: float = 0.1) -> Callable:
    """Build the in-graph PS train step: grad -> lr-premultiplied delta ->
    ``table.functional_add`` (the reference worker premultiplies the LR and the
    server's SGD updater subtracts, ref app updater.cpp:52-71). Suitable for
    ``lax.scan`` over a device-resident epoch."""

    def step(state: Dict, batch) -> Tuple[Dict, jax.Array]:
        x, y = batch
        w = unflatten(state["data"], input_dim, num_classes)
        loss, grad = loss_and_grad(w, x, y, objective, regular, reg_coef)
        delta = learning_rate * grad
        flat = jnp.zeros(table.padded_shape, table.dtype
                         ).at[: delta.size].set(delta.reshape(-1))
        state = table.functional_add(
            state, flat, AddOption(learning_rate=learning_rate))
        return state, loss

    return step


def synthetic_dataset(num_samples: int, input_dim: int, num_classes: int,
                      seed: int = 0, noise: float = 0.6,
                      centers_seed: int = 1234
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Gaussian-blob classification set (test/bench fixture; the reference
    pulls MNIST from the network, which a zero-egress environment cannot).
    ``centers_seed`` fixes the class centers independently of the sample seed
    so train/test splits share one task."""
    rng = np.random.default_rng(seed)
    centers = (np.random.default_rng(centers_seed)
               .normal(size=(num_classes, input_dim)).astype(np.float32))
    y = rng.integers(0, num_classes, size=num_samples).astype(np.int32)
    x = centers[y] + noise * rng.normal(size=(num_samples, input_dim)
                                        ).astype(np.float32)
    return x.astype(np.float32), y
