"""DLRM-style recommender: sharded embedding tables + dot-interaction MLP.

The workload class the parameter-server design exists for (ref: the
LogisticRegression app's sparse-FTRL CTR path, Applications/
LogisticRegression/src/util/sparse_table.h, and WordEmbedding's claim of
21M-vocab embedding tables, Applications/WordEmbedding/README.md "Why") —
modernized: categorical fields hit row-sharded embedding tables
(`MatrixTable`), the dense side is a small MLP, and second-order feature
interactions are pairwise dots (the DLRM architecture).

TPU-first training shape: ONE jitted step — gather embedding rows, forward
+ backward, scatter the row gradients into a dense table delta
(duplicate-accumulating, like the word2vec fused path), then apply the
table's server-side updater via ``functional_add``. Gradient aggregation
followed by one updater application per step = the BSP parameter-server
semantics with zero wire hops. All tables stay row-sharded over the mesh;
XLA inserts the collectives.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from multiverso_tpu.updaters import AddOption


class DLRMConfig(NamedTuple):
    vocab_sizes: Tuple[int, ...] = (100, 100, 100)  # rows per categorical field
    embed_dim: int = 16
    dense_dim: int = 8                  # continuous-feature width
    bottom_mlp: Tuple[int, ...] = (32, 16)  # last entry must equal embed_dim
    top_mlp: Tuple[int, ...] = (32, 1)      # last entry must be 1 (logit)
    dtype: Any = jnp.float32


def field_offsets(cfg: DLRMConfig) -> np.ndarray:
    """Row offset of each field inside the single concatenated table (the
    standard multi-table-in-one-table layout, so ONE sharded MatrixTable
    serves every field)."""
    return np.concatenate([[0], np.cumsum(cfg.vocab_sizes)[:-1]]).astype(
        np.int32)


def total_rows(cfg: DLRMConfig) -> int:
    return int(sum(cfg.vocab_sizes))


def _mlp_shapes(cfg: DLRMConfig):
    f = len(cfg.vocab_sizes)
    n_inter = (f + 1) * f // 2          # upper-triangle pairwise dots
    bottom, top = [], []
    d_in = cfg.dense_dim
    for d_out in cfg.bottom_mlp:
        bottom.append((d_in, d_out))
        d_in = d_out
    if cfg.bottom_mlp[-1] != cfg.embed_dim:
        raise ValueError(f"bottom_mlp must end at embed_dim="
                         f"{cfg.embed_dim}, got {cfg.bottom_mlp}")
    d_in = cfg.embed_dim + n_inter
    for d_out in cfg.top_mlp:
        top.append((d_in, d_out))
        d_in = d_out
    if cfg.top_mlp[-1] != 1:
        raise ValueError(f"top_mlp must end at 1 (logit), got {cfg.top_mlp}")
    return bottom, top


def init_mlp_params(cfg: DLRMConfig, seed: int = 0) -> Dict[str, Any]:
    rng = np.random.default_rng(seed)
    bottom, top = _mlp_shapes(cfg)

    def glorot(shape):
        s = np.sqrt(2.0 / (shape[0] + shape[1]))
        return jnp.asarray(rng.normal(0, s, shape), cfg.dtype)

    return {
        "bottom_w": [glorot(s) for s in bottom],
        "bottom_b": [jnp.zeros((s[1],), cfg.dtype) for s in bottom],
        "top_w": [glorot(s) for s in top],
        "top_b": [jnp.zeros((s[1],), cfg.dtype) for s in top],
    }


def flatten_mlp(params: Dict[str, Any]) -> Tuple[np.ndarray, Any]:
    """[flat f32 vector, treedef] — the MLP side lives in ONE ArrayTable
    (the ref bindings' flatten-the-net-into-one-table convention,
    ref theano_ext/lasagne_ext/param_manager.py:9-64)."""
    leaves, treedef = jax.tree.flatten(params)
    flat = np.concatenate([np.asarray(l).reshape(-1) for l in leaves])
    meta = (treedef, [l.shape for l in leaves],
            [int(np.prod(l.shape)) for l in leaves])
    return flat.astype(np.float32), meta


def unflatten_mlp(flat: jax.Array, meta) -> Dict[str, Any]:
    treedef, shapes, sizes = meta
    leaves, off = [], 0
    for shape, size in zip(shapes, sizes):
        leaves.append(flat[off: off + size].reshape(shape))
        off += size
    return jax.tree.unflatten(treedef, leaves)


def _mlp(x, ws, bs, final_linear=True):
    for i, (w, b) in enumerate(zip(ws, bs)):
        x = x @ w + b
        if not (final_linear and i == len(ws) - 1):
            x = jax.nn.relu(x)
    return x


def forward(mlp: Dict[str, Any], emb_rows: jax.Array, dense: jax.Array,
            cfg: DLRMConfig) -> jax.Array:
    """emb_rows [B, F, D], dense [B, dense_dim] -> logits [B].

    DLRM dot interaction: the bottom-MLP output joins the F embeddings,
    all (F+1 choose 2) pairwise dots concat with the bottom output feed
    the top MLP.
    """
    f = len(cfg.vocab_sizes)
    x = _mlp(dense, mlp["bottom_w"], mlp["bottom_b"], final_linear=False)
    z = jnp.concatenate([x[:, None, :], emb_rows], axis=1)   # [B, F+1, D]
    dots = jnp.einsum("bfd,bgd->bfg", z, z)                  # [B, F+1, F+1]
    iu, ju = np.triu_indices(f + 1, k=1)
    inter = dots[:, iu, ju]                                  # [B, (F+1)F/2]
    top_in = jnp.concatenate([x, inter], axis=-1)
    return _mlp(top_in, mlp["top_w"], mlp["top_b"])[:, 0]


def loss_fn(mlp: Dict[str, Any], emb_rows: jax.Array, dense: jax.Array,
            labels: jax.Array, cfg: DLRMConfig) -> jax.Array:
    """Mean binary cross-entropy on the click logit (f32)."""
    logits = forward(mlp, emb_rows, dense, cfg).astype(jnp.float32)
    y = labels.astype(jnp.float32)
    return jnp.mean(jnp.maximum(logits, 0) - logits * y
                    + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def make_train_step(cfg: DLRMConfig, emb_table, mlp_table, mlp_meta,
                    emb_opt: Optional[AddOption] = None,
                    mlp_opt: Optional[AddOption] = None):
    """One jitted PS step over the sharded tables.

    ``step(emb_state, mlp_state, cat_ids [B, F], dense, labels) ->
    (emb_state, mlp_state, loss)`` — gather rows, grad, scatter row grads
    into a dense delta (duplicate ids accumulate), apply each table's
    server-side updater via ``functional_add``. Donate both states when
    jitting to recycle the table buffers:
    ``jax.jit(step, donate_argnums=(0, 1))``.
    """
    offsets = jnp.asarray(field_offsets(cfg))
    n_mlp = int(mlp_table.shape[0])
    emb_opt = emb_opt or AddOption(learning_rate=0.05, rho=0.1)
    mlp_opt = mlp_opt or AddOption(learning_rate=0.05, rho=0.1)
    # The MLP params sliced out of the mesh-sharded ArrayTable state must
    # be pinned REPLICATED: on a multi-device mesh the SPMD partitioner
    # otherwise propagates the state's row-sharding through the slice
    # into the tiny parameter tensors and miscompiles the fused
    # fwd+bwd+two-updates graph — wrong LOSS, wrong deltas (first seen
    # when the 8-virtual-device conftest mesh became real; both updates
    # must be live outputs to trigger it). Replicated is also simply the
    # correct layout for a few-KB parameter vector every device reads.
    try:
        from jax.sharding import NamedSharding, PartitionSpec
        from multiverso_tpu.zoo import Zoo
        _replicated = NamedSharding(Zoo.get().mesh(), PartitionSpec())
    except Exception:   # noqa: BLE001 — no Zoo/mesh: single-device use
        _replicated = None

    def step(emb_state, mlp_state, cat_ids, dense, labels):
        ids = (cat_ids + offsets[None, :]).reshape(-1)        # [B*F] global
        rows = jnp.take(emb_state["data"], ids, axis=0)
        b, f = cat_ids.shape
        rows = rows.reshape(b, f, cfg.embed_dim)
        flat_params = mlp_state["data"][:n_mlp]
        if _replicated is not None:
            flat_params = jax.lax.with_sharding_constraint(
                flat_params, _replicated)
        mlp = unflatten_mlp(flat_params, mlp_meta)
        loss, (g_mlp, g_rows) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(mlp, rows, dense, labels, cfg)
        # PS push: duplicate-accumulating scatter of row grads into a dense
        # table-shaped delta, then ONE updater application (grad aggregation
        # before update = BSP server semantics)
        emb_delta = jnp.zeros_like(emb_state["data"]).at[ids].add(
            g_rows.reshape(b * f, cfg.embed_dim))
        emb_state = emb_table.functional_add(emb_state, emb_delta, emb_opt)
        flat_g = jnp.concatenate(
            [g.reshape(-1) for g in jax.tree.leaves(g_mlp)])
        mlp_state = mlp_table.functional_add(
            mlp_state, mlp_table.pad_delta(flat_g), mlp_opt)
        return emb_state, mlp_state, loss

    return step


def synthetic_ctr(cfg: DLRMConfig, n: int, seed: int = 0):
    """Click data with planted structure: certain (field-0, field-1) row
    pairs interact positively — learnable only through the embedding
    tables + dot interaction."""
    rng = np.random.default_rng(seed)
    f = len(cfg.vocab_sizes)
    cat = np.stack([rng.integers(0, v, n) for v in cfg.vocab_sizes],
                   axis=1).astype(np.int32)
    dense = rng.normal(size=(n, cfg.dense_dim)).astype(np.float32)
    w = rng.normal(size=cfg.dense_dim)
    affinity = rng.normal(0, 1.5, (cfg.vocab_sizes[0], cfg.vocab_sizes[1]))
    logits = dense @ w + affinity[cat[:, 0], cat[:, 1] % cfg.vocab_sizes[1]]
    labels = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(
        np.float32)
    return cat, dense, labels
