"""Table core: device-sharded parameter tables with Add/Get semantics.

TPU-native re-design of the reference table stack
(ref: include/multiverso/table_interface.h:24-75, src/table.cpp,
src/worker.cpp, src/server.cpp). The reference splits a table into a
WorkerTable (client: partitions requests per server, tracks msg_id Waiters)
and a ServerTable (storage shard + updater), connected by an actor/MPI message
path. On TPU both halves collapse into ONE object:

* storage     -> a single ``jax.Array`` sharded over the mesh's table axis;
                 each device shard IS the reference's "server shard".
* Add         -> a jitted, donated update: delta is scattered shard-wise over
                 ICI and the updater runs element-wise on every shard in
                 parallel (the Worker->Communicator->Server hop disappears
                 into XLA's sharding machinery).
* Get         -> device->host gather of the sharded array (XLA all-gather /
                 per-shard DMA instead of per-server reply messages).
* AddAsync /
  GetAsync    -> JAX async dispatch. Every op returns a msg-id; ``wait(id)``
                 blocks on the underlying arrays (the reference's msg_id ->
                 Waiter bookkeeping, src/table.cpp:27-97, maps onto XLA's
                 future machinery).
* updater     -> a pure function applied in-graph (see updaters/__init__.py).

Sync (BSP) semantics are *free*: program order on a single stream of donated
arrays gives every Get the state after all previously issued Adds — exactly
what the reference's SyncServer vector-clock machinery enforces
(src/server.cpp:68-222). Async mode is the JAX dispatch queue itself.

Tables also expose a **functional plane** for in-graph use: ``state`` /
``functional_add`` / ``adopt`` let a jitted training loop thread the table
through ``lax.scan`` at full speed, which is how the bundled apps hit the
hardware roofline rather than paying a host round-trip per step.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from multiverso_tpu import updaters as updaters_lib
from multiverso_tpu.ops import wire_codec
from multiverso_tpu.telemetry import memstats as _memstats
from multiverso_tpu.updaters import AddOption
from multiverso_tpu.utils import config, log
from multiverso_tpu.utils.dashboard import Dashboard, monitor
from multiverso_tpu.zoo import Zoo

config.define_bool(
    "table_get_cache", True,
    "version-stamped host cache for whole-table Get: each applied Add "
    "bumps a table version, and a Get at an unchanged version returns "
    "the cached host array instead of dispatching a snapshot + "
    "device->host transfer (a repeated Get with no intervening Add "
    "costs one memcpy, not one wire round-trip). Safe multi-controller: "
    "host-plane ops are collective and identical on every process, so "
    "versions advance in lockstep and all ranks hit or miss together")

config.define_bool(
    "table_get_prefetch", True,
    "write-triggered snapshot prefetch for whole-table Get on a "
    "tunneled/remote device: once a Get-after-Add pattern is observed, "
    "each whole-table Add also dispatches a non-donating snapshot of "
    "the post-update data and starts its device->host copy "
    "IMMEDIATELY, so the transfer streams while the caller is still "
    "waiting out the Add's own round-trip — the next Get at that "
    "version waits only the residual instead of paying the full "
    "dispatch RTT + transfer (BENCH_r05: ~226 ms blocking get on a "
    "~105 ms-RTT tunnel). Bit-exact: the snapshot is the same bytes a "
    "blocking Get would pull at that version; a version mismatch "
    "(another mutation landed first) discards it. Costs one extra "
    "table-sized device buffer + one background transfer per "
    "prefetching Add, so it self-disarms when two Adds pass with no "
    "Get consuming the snapshot. Single-controller only (multi-host "
    "pulls stay collective)")


class _HostAdd:
    """One queued client-side add awaiting the coalescing applier."""

    __slots__ = ("arr", "opt", "event", "error", "token")

    def __init__(self, arr: np.ndarray, opt: AddOption):
        self.arr, self.opt = arr, opt
        self.event = threading.Event()
        self.error: Optional[Exception] = None
        self.token: Optional[jax.Array] = None

    def ready(self) -> bool:
        """Sweepable: applied and the completion token is device-ready."""
        return self.event.is_set() and (
            self.error is not None
            or (self.token is not None and self.token.is_ready()))

    def result(self):
        self.event.wait()
        if self.error is not None:
            raise self.error
        return self.token.block_until_ready()

ArrayLike = Union[np.ndarray, jax.Array, Sequence]


def _ceil_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


class Table:
    """Base sharded table. Subclasses fix dimensionality and op surface."""

    def __init__(self, shape: Tuple[int, ...], dtype=jnp.float32,
                 updater: Union[str, updaters_lib.Updater, None] = None,
                 name: str = "table",
                 init: Optional[ArrayLike] = None,
                 seed: Optional[int] = None,
                 init_scale: float = 0.0,
                 wire_filter: str = "none"):
        """``wire_filter`` compresses the host<->device wire of whole-table
        Add/Get (the reference compressed its MPI wire the same way,
        quantization_util.h SparseFilter; OneBitsFilter was declared there
        and implemented here): "bf16" halves both directions (near-lossless
        for SGD traffic); "1bit" sends sign bits + per-block scales with
        error feedback (1-bit SGD) on Add and bf16 on Get; "topk" sends
        the ~3% largest-|x| delta entries exactly (QSGD-style
        sparsification) with error feedback on Add and bf16 on Get.
        Encoding runs through the jitted ops/wire_codec kernels (on the
        host-side CPU backend, so the f32 payload never crosses the
        accelerator wire just to be compressed); decode runs in-graph,
        fused into the updater apply. Row ops are unaffected (their
        payloads are already small)."""
        zoo = Zoo.get()
        self._zoo = zoo
        self.name = name
        self.dtype = jnp.dtype(dtype)
        self.shape = tuple(int(s) for s in shape)
        mesh = zoo.mesh()
        self._mesh = mesh
        self._axis = zoo.shard_axis()
        self._num_shards = mesh.shape[self._axis]

        # Row-padding so the leading dim splits evenly across shards; at least
        # one spare row is kept as scatter scratch space for masked row ops.
        self._padded_rows = _ceil_to(self.shape[0] + 1, self._num_shards)
        self._padded_shape = (self._padded_rows,) + self.shape[1:]

        self._data_spec = P(self._axis, *([None] * (len(self.shape) - 1)))
        self._sharding = NamedSharding(mesh, self._data_spec)
        self._replicated = NamedSharding(mesh, P())

        if updater is None:
            updater = config.get_flag("updater_type")
        if isinstance(updater, str):
            updater = updaters_lib.get_updater(
                updater, num_workers=zoo.num_workers(), dtype=self.dtype)
        self.updater = updater

        host_init = self._build_init(init, seed, init_scale)
        self._data = jax.device_put(host_init, self._sharding)
        self._ustate = jax.tree.map(self._place_state,
                                    updater.init_state(self._padded_shape,
                                                       self.dtype))
        self.table_id = zoo.register_table(self)

        if wire_filter not in ("none", "bf16", "1bit", "topk"):
            raise ValueError(f"unknown wire_filter {wire_filter!r}")
        self._wire = wire_filter
        if wire_filter == "1bit":
            from multiverso_tpu.utils.filters import OneBitsFilter
            self._one_bit = OneBitsFilter(block=1024)
        elif wire_filter == "topk":
            from multiverso_tpu.utils.filters import TopKFilter
            self._topk_k = wire_codec.default_topk(int(np.prod(self.shape)))
            self._topk = TopKFilter(self._topk_k)
        if wire_filter in ("1bit", "topk"):
            # jitted encode runs on the host-side CPU backend (numpy
            # reference filter when unavailable); the error-feedback
            # residual stays resident there as table state — it never
            # round-trips through a host pull
            self._codec_dev = wire_codec.host_codec_device()
            self._wire_residual: Optional[jax.Array] = None
        if wire_filter != "none":
            # filters trade encode CPU for wire bytes; on a FAST link that
            # trade loses (1bit measured ~10x slower than plain off-tunnel)
            # — warn at creation, when the user can still change the flag
            from multiverso_tpu.utils import linkprobe
            ms = linkprobe.device_link_ms()
            if ms < linkprobe.FAST_LINK_MS:
                log.error(
                    "table[%s]: wire_filter=%r but the host<->device link "
                    "is fast (1 MB upload ~%.1f ms): the filter's encode "
                    "cost will likely exceed its wire savings — use "
                    "wire_filter='none' unless this process feeds a slow "
                    "(tunneled/remote) device", name, wire_filter, ms)

        self._pending: Dict[int, Any] = {}
        self._next_msg_id = 0
        self._lock = threading.Lock()
        # version-stamped get cache: every applied mutation bumps
        # _version (see _mark_mutated); a whole-table Get at an unchanged
        # version returns the cached host array and skips the snapshot
        # dispatch + device->host transfer entirely (flag table_get_cache)
        self._version = 0
        self._get_cache: Optional[Tuple[int, np.ndarray]] = None
        # write-triggered snapshot prefetch (flag table_get_prefetch):
        # (version, in-flight device snapshot) dispatched by the LAST
        # whole-table add, consumed by the next Get at that version.
        # _prefetch_armed latches on the first Get and drops when a
        # prefetch goes unconsumed (two adds, no get), so add-only
        # workloads never pay the extra snapshot. All under the
        # dispatch lock.
        self._get_prefetch: Optional[Tuple[int, jax.Array]] = None
        self._prefetch_armed = False
        # unconsumed-prefetch backoff: each wasted snapshot doubles how
        # many arming opportunities are skipped (capped), and one
        # CONSUMED prefetch resets it — a mixed add,add,get cadence
        # decays to ~no wasted transfers instead of burning one
        # table-sized device->host copy per cycle
        self._prefetch_backoff = 0
        self._prefetch_skip = 0
        # Serializes op *dispatch* (not device execution): a donating add on
        # one thread must not delete the data buffer while another thread
        # (e.g. an AsyncBuffer prefetch pull) is snapshotting it.
        self._dispatch_lock = threading.RLock()
        self._jit_cache: Dict[Any, Any] = {}
        # client-side add coalescing (stateless linear updaters, single
        # controller, uncompressed wire): async host adds queue here and a
        # background applier merges everything queued into ONE summed
        # upload — the host->device transfer is the dominant cost on a
        # tunneled link and transfers do NOT overlap (measured: 4 threaded
        # 4 MB uploads take ~4x one), so N-deep pipelining must become
        # 1 upload, not N concurrent ones
        self._addq: list = []
        self._addq_cv = threading.Condition()
        self._addq_inflight = 0
        self._add_applier: Optional[threading.Thread] = None
        # hot-row training cache (serving/hotcache; row-table subclasses
        # create it behind the train_cache_rows flag — base ops only need
        # to INVALIDATE on coarse mutations)
        self._train_cache = None
        # memory ledger (telemetry/memstats.py): the PR-1 get cache and
        # the write-triggered prefetch staging buffer are the sync
        # plane's two table-sized hoards; gauges are pull-only
        _memstats.register(f"table[{name}]", self)

    def memory_stats(self) -> Dict[str, Any]:
        """Byte-ledger gauges: cached whole-table Get host copy +
        in-flight prefetch snapshot (device) bytes. Lock-free reads of
        the two tuple refs — benign vs the dispatch lock, and the
        ledger tolerates a one-sample-stale figure."""
        cache = self._get_cache
        pf = self._get_prefetch
        return {
            "cache_bytes": (int(cache[1].nbytes)
                            if cache is not None else 0),
            "prefetch_bytes": (int(getattr(pf[1], "nbytes", 0))
                               if pf is not None else 0),
        }

    # ------------------------------------------------------------------ #
    # construction helpers
    # ------------------------------------------------------------------ #
    def _build_init(self, init, seed, init_scale) -> np.ndarray:
        if init is not None:
            arr = np.asarray(init, dtype=self.dtype)
            if arr.shape != self.shape:
                raise ValueError(
                    f"init shape {arr.shape} != table shape {self.shape}")
            out = np.zeros(self._padded_shape, dtype=self.dtype)
            out[: self.shape[0]] = arr
            return out
        if seed is not None and init_scale != 0.0:
            # Uniform(-scale, scale) random init — the reference's word2vec
            # input-embedding server init (ref src/table/matrix_table.cpp:372-384
            # and Applications/WordEmbedding/src/communicator.cpp:20).
            rng = np.random.default_rng(seed)
            out = rng.uniform(-init_scale, init_scale,
                              self._padded_shape).astype(self.dtype)
            out[self.shape[0]:] = 0
            return out
        return np.zeros(self._padded_shape, dtype=self.dtype)

    def _place_state(self, x: jax.Array) -> jax.Array:
        """Shard updater state like the data where shapes line up, else replicate."""
        nd, pd = np.ndim(x), len(self._padded_shape)
        if nd >= pd and tuple(np.shape(x)[nd - pd:]) == self._padded_shape:
            spec = P(*([None] * (nd - pd)), self._axis, *([None] * (pd - 1)))
            return jax.device_put(x, NamedSharding(self._mesh, spec))
        return jax.device_put(x, self._replicated)

    # ------------------------------------------------------------------ #
    # mutation bookkeeping (Zoo dirty fence + get-cache version)
    # ------------------------------------------------------------------ #
    def _mark_mutated(self) -> None:
        """Entry of every table mutation path: dirty-mark for the Zoo
        barrier fence and bump the get-cache version CONSERVATIVELY (so a
        ``version`` poll — e.g. an AsyncBuffer ``version_fn`` — already
        sees a queued-but-unapplied coalesced add as a change). This
        entry bump alone cannot make the cache correct: it happens
        outside the dispatch lock, so a concurrent Get could stamp
        pre-mutation data with the post-bump version. The guarantee
        comes from :meth:`_version_applied`, which bumps AGAIN at the
        point the mutation is dispatched while the dispatch lock is
        held — any mutation applying after a Get's snapshot therefore
        always moves the version past that Get's stamp."""
        self._zoo.mark_dirty(self.table_id)
        self._version += 1

    def _version_applied(self) -> None:
        """Apply-side version bump (see :meth:`_mark_mutated`). Called at
        every site that actually mutates ``_data``/``_ustate``, while the
        dispatch lock is held (or, for adopt/load, after the state
        assignment) — the Get cache's correctness anchor."""
        self._version += 1

    @property
    def version(self) -> int:
        """Monotonic mutation counter (the get-cache stamp). Cheap enough
        to poll — e.g. as an AsyncBuffer ``version_fn`` so a prefetch pull
        of an unchanged table is skipped entirely."""
        return self._version

    def _cached_get(self, into: Optional[np.ndarray] = None
                    ) -> Optional[np.ndarray]:
        """Cached host array when the version is unchanged, else None.
        Caller holds the dispatch lock. The cache owns a private copy
        (callers may mutate what get() hands them), so hits pay one
        memcpy instead of a dispatch + transfer — straight into ``into``
        when the caller supplied a reusable output buffer (one memcpy,
        not copy-then-copyto)."""
        if not config.get_flag("table_get_cache"):
            return None
        cache = self._get_cache
        if cache is None or cache[0] != self._version:
            return None
        # incr, not observe_ms(0.0): a hit COUNTER must not feed fake
        # 0-ms samples into the monitor's latency histogram
        Dashboard.get(f"table[{self.name}].get.cached").incr()
        if into is not None:
            np.copyto(into.reshape(self.shape), cache[1])
            return into
        return cache[1].copy()

    def _maybe_prefetch(self) -> None:
        """Write-triggered snapshot prefetch (caller holds the dispatch
        lock, right after a whole-table update dispatched): snapshot the
        post-update data (non-donating) and start its device->host copy
        NOW, so the bytes stream back concurrently with the caller's own
        wait on the add — the read path's half of the off-lock snapshot
        theme, applied to the tunneled-device seam. Armed only while a
        Get-after-Add pattern holds: an unconsumed prefetch (two adds,
        no get between) disarms it, so add-only workloads pay nothing."""
        if self._get_prefetch is not None:
            # the previous prefetch was never consumed: this workload is
            # not in a clean get-after-add regime — drop it, disarm, and
            # back off exponentially (a Get re-arms, but a thrashing
            # add,add,get cadence must not buy one wasted table-sized
            # transfer per cycle forever)
            self._prefetch_armed = False
            self._get_prefetch = None
            self._prefetch_backoff = min(self._prefetch_backoff * 2 + 1,
                                         16)
            self._prefetch_skip = self._prefetch_backoff
            return
        if (not self._prefetch_armed
                or not config.get_flag("table_get_prefetch")
                or self._zoo.size() > 1):
            return
        if self._prefetch_skip > 0:
            self._prefetch_skip -= 1
            return
        snap = (self._bf16_cast_fn()(self._data) if self._wire != "none"
                else self._snapshot_fn()(self._data))
        try:
            snap.copy_to_host_async()
        except AttributeError:
            pass
        self._get_prefetch = (self._version, snap)

    def _take_prefetch(self) -> Optional[jax.Array]:
        """The in-flight prefetched snapshot for the CURRENT version, or
        None (caller holds the dispatch lock). A stale snapshot (another
        mutation landed after it) is dropped — its bytes are not the
        bytes a Get at this version must return."""
        self._prefetch_armed = True
        pf = self._get_prefetch
        if pf is None:
            return None
        self._get_prefetch = None
        if pf[0] != self._version:
            return None
        self._prefetch_backoff = 0   # consumed: the regime is real
        Dashboard.get(f"table[{self.name}].get.prefetched").incr()
        return pf[1]

    def _store_get_cache(self, version: int, host: np.ndarray) -> None:
        """Caller holds the dispatch lock. An older-version store (a slow
        get_async finalize racing a sync get that already cached fresher
        data) is dropped instead of clobbering the fresher entry — it
        could never match a future version check anyway, and replacing
        the fresh entry would just turn the next Get into a miss."""
        if not config.get_flag("table_get_cache"):
            return
        cache = self._get_cache
        if cache is not None and cache[0] > version:
            return
        self._get_cache = (version, host.copy())

    # ------------------------------------------------------------------ #
    # msg-id / Waiter bookkeeping (ref src/table.cpp:27-97)
    # ------------------------------------------------------------------ #
    def _track(self, arrays: Any, finalize=None) -> int:
        with self._lock:
            # opportunistic sweep of completed fire-and-forget adds: an
            # add whose msg id is never wait()ed (finalize is None and the
            # completion token is already ready) would otherwise pin its
            # device buffer in _pending forever. Swept ids behave exactly
            # like already-waited ones (wait returns None). Coalesced-add
            # entries sweep once applied + token-ready.
            done = [mid for mid, (arrs, fin) in self._pending.items()
                    if (isinstance(arrs, _HostAdd) and arrs.ready())
                    or (fin is None and not isinstance(arrs, _HostAdd)
                        and all(
                        hasattr(a, "is_ready") and a.is_ready()
                        for a in jax.tree.leaves(arrs)
                        if isinstance(a, jax.Array)))]
            for mid in done:
                arrs, _ = self._pending.pop(mid)
                if isinstance(arrs, _HostAdd) and arrs.error is not None:
                    log.error("table[%s]: fire-and-forget add %d failed: "
                              "%s", self.name, mid, arrs.error)
            msg_id = self._next_msg_id
            self._next_msg_id += 1
            self._pending[msg_id] = (arrays, finalize)
            return msg_id

    def wait(self, msg_id: int) -> Any:
        """Block until the op behind ``msg_id`` is complete; return its result.

        For get-style ops the result is the materialized host array (the ref's
        Wait(GetAsync) leaves the data in the user buffer, src/table.cpp:27-97);
        for adds it is the completion token — or ``None`` when the add already
        completed (its token may have been swept by :meth:`_track`, which is
        indistinguishable from waiting on an already-waited id).
        """
        with self._lock:
            entry = self._pending.pop(msg_id, None)
        if entry is None:
            return None
        arrays, finalize = entry
        if isinstance(arrays, _HostAdd):
            return arrays.result()
        arrays = jax.tree.map(
            lambda a: a.block_until_ready() if isinstance(a, jax.Array) else a,
            arrays)
        return finalize(arrays) if finalize is not None else arrays

    # ------------------------------------------------------------------ #
    # functional plane (in-graph use)
    # ------------------------------------------------------------------ #
    @property
    def state(self) -> Dict[str, Any]:
        """Current table pytree {data, ustate}; safe to close over in jit."""
        self._flush_host_adds()
        return {"data": self._data, "ustate": self._ustate}

    def functional_add(self, state: Dict[str, Any], delta: jax.Array,
                       opt: Optional[AddOption] = None) -> Dict[str, Any]:
        """Pure add for use inside a user's jitted step. ``delta`` must be
        padded-shape (use :meth:`pad_delta`)."""
        opt = opt or AddOption()
        data, ustate = self.updater.apply(state["data"], state["ustate"],
                                          delta, opt)
        return {"data": data, "ustate": ustate}

    def adopt(self, state: Dict[str, Any]) -> None:
        """Commit an externally-advanced table state (end of in-graph loop)."""
        self._mark_mutated()
        self._flush_host_adds()   # a late-applying add must not overwrite
        self._data = state["data"]
        self._ustate = state["ustate"]
        self._version_applied()
        if self._train_cache is not None:
            # wholesale rewrite: all rows stale. AFTER the rebind — a
            # clear logged before the mutation is visible lets a racing
            # get re-fill pre-adopt rows under a current fill token,
            # and nothing would ever invalidate them again
            self._train_cache.clear()

    def pad_delta(self, delta: jax.Array) -> jax.Array:
        pad = self._padded_rows - self.shape[0]
        if pad == 0:
            return delta
        widths = [(0, pad)] + [(0, 0)] * (len(self.shape) - 1)
        return jnp.pad(delta, widths)

    @property
    def sharding(self) -> NamedSharding:
        return self._sharding

    @property
    def padded_shape(self) -> Tuple[int, ...]:
        return self._padded_shape

    def raw(self) -> jax.Array:
        """The live padded, sharded data array (graph-plane read)."""
        self._flush_host_adds()   # reads see every prior async add
        return self._data

    # ------------------------------------------------------------------ #
    # whole-table ops (host plane)
    # ------------------------------------------------------------------ #
    def _full_update_fn(self):
        key = "full"
        fn = self._jit_cache.get(key)
        if fn is None:
            updater = self.updater

            def _update(data, ustate, delta, opt):
                data, ustate = updater.apply(data, ustate, delta, opt)
                # Tiny completion token: later adds donate (and delete) the
                # data buffer, so pending waits block on this instead.
                token = jnp.ravel(data)[0]
                return data, ustate, token

            fn = jax.jit(_update, donate_argnums=(0, 1))
            self._jit_cache[key] = fn
        return fn

    def _snapshot_fn(self):
        key = "snapshot"
        fn = self._jit_cache.get(key)
        if fn is None:
            # Non-donating identity: the output is a fresh buffer that stays
            # valid when subsequent adds donate the live data array.
            fn = jax.jit(jnp.copy)
            self._jit_cache[key] = fn
        return fn

    @staticmethod
    def _to_host(data: jax.Array) -> np.ndarray:
        """Device -> host, including multi-controller arrays whose shards
        live on other processes (ICI/DCN allgather instead of local DMA)."""
        if getattr(data, "is_fully_addressable", True):
            return np.asarray(data)
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(data, tiled=True))

    def _host_delta(self, delta: ArrayLike) -> jax.Array:
        """Pad + shard-place a host/device delta of logical table shape.

        Multi-controller: host-plane Add is a *collective* — every process
        calls it with its own worker's delta, and the effective delta is the
        SUM over processes (reference semantics: N workers each pushed
        theirs). A plain global device_put would instead mosaic each
        process's rows into its local shards, silently dropping the other
        workers' contributions.
        """
        if isinstance(delta, jax.Array) and delta.shape == self._padded_shape:
            return delta
        if isinstance(delta, jax.Array):
            return jax.device_put(self.pad_delta(delta), self._sharding)
        arr = np.asarray(delta, dtype=self.dtype).reshape(self.shape)
        if self._zoo.size() > 1:
            # device AllReduce, not allgather+numpy-sum: per-host transfer
            # stays O(size) as the world grows (VERDICT r3 item 7)
            from multiverso_tpu.parallel.collectives import process_sum
            arr = process_sum(arr)
        padded = np.zeros(self._padded_shape, dtype=self.dtype)
        padded[: self.shape[0]] = arr
        return jax.device_put(padded, self._sharding)

    # ------------------------------------------------------------------ #
    # wire-compressed upload path (ref quantization_util.h filters, applied
    # to the host->device seam: the tunnel/PCIe wire is the analogue of the
    # reference's MPI wire)
    # ------------------------------------------------------------------ #
    def _bf16_update_fn(self):
        fn = self._jit_cache.get("full_bf16")
        if fn is None:
            updater = self.updater

            def _update(data, ustate, delta_bf16, opt):
                data, ustate = updater.apply(
                    data, ustate, delta_bf16.astype(data.dtype), opt)
                return data, ustate, jnp.ravel(data)[0]

            fn = self._jit_cache["full_bf16"] = jax.jit(
                _update, donate_argnums=(0, 1))
        return fn

    def _pad_flat_delta(self, flat: jax.Array, dtype) -> jax.Array:
        """Raveled logical-size delta -> padded table shape (in-graph)."""
        n = int(np.prod(self.shape))
        return jnp.zeros(self._padded_shape, dtype).reshape(-1).at[:n].set(
            flat.astype(dtype)).reshape(self._padded_shape)

    def _onebit_update_fn(self):
        fn = self._jit_cache.get("full_1bit")
        if fn is None:
            updater = self.updater
            n = int(np.prod(self.shape))
            block = self._one_bit.block

            def _update(data, ustate, bits, scales, opt):
                # in-graph decode of the 1-bit payload (ops/wire_codec),
                # fused into the updater apply
                flat = wire_codec.onebit_decode(bits, scales, n=n,
                                                block=block)
                delta = self._pad_flat_delta(flat, data.dtype)
                data, ustate = updater.apply(data, ustate, delta, opt)
                return data, ustate, jnp.ravel(data)[0]

            fn = self._jit_cache["full_1bit"] = jax.jit(
                _update, donate_argnums=(0, 1))
        return fn

    def _topk_update_fn(self):
        fn = self._jit_cache.get("full_topk")
        if fn is None:
            updater = self.updater
            n = int(np.prod(self.shape))

            def _update(data, ustate, idx, vals, opt):
                flat = wire_codec.topk_decode(idx, vals, n=n)
                delta = self._pad_flat_delta(flat, data.dtype)
                data, ustate = updater.apply(data, ustate, delta, opt)
                return data, ustate, jnp.ravel(data)[0]

            fn = self._jit_cache["full_topk"] = jax.jit(
                _update, donate_argnums=(0, 1))
        return fn

    # ------------------------------------------------------------------ #
    # client-side add coalescing
    # ------------------------------------------------------------------ #
    def _coalescible(self, delta, opt) -> bool:
        """Async host adds coalesce when the merge is EXACT for the
        updater: stateless linear updater (sum of deltas == sequence of
        adds, and opt is never read), single controller (a collective
        process_sum must keep one per-process issue order). Wire-filtered
        tables coalesce too: the single applier thread preserves encode
        order, and under a linear updater the error-feedback codecs are
        indifferent to whether N deltas are encoded one-by-one or as
        their sum — the residual carries whatever any one payload left
        out. This is also what takes the encode off the caller's
        dispatch path (BENCH_r05: the inline 1bit encode+compile made
        add_async ~1400x the uncompressed dispatch)."""
        return (self._zoo.size() == 1
                and not isinstance(delta, jax.Array)
                and type(self.updater) in updaters_lib.STATELESS_LINEAR)

    _ADDQ_CAP = 16          # backpressure: each entry is a full host copy
    _APPLIER_IDLE_S = 5.0   # idle applier threads exit (no table pinning)

    def _enqueue_host_add(self, delta: ArrayLike, opt: AddOption) -> int:
        entry = _HostAdd(
            np.array(delta, dtype=self.dtype).reshape(self.shape), opt)
        with self._addq_cv:
            while len(self._addq) >= self._ADDQ_CAP:
                self._addq_cv.wait()   # throttle like the old inline path
            self._addq.append(entry)
            self._addq_inflight += 1
            if self._add_applier is None:
                self._add_applier = threading.Thread(
                    target=self._add_applier_loop,
                    name=f"mv-add-{self.name}", daemon=True)
                self._add_applier.start()
            self._addq_cv.notify_all()
        return self._track(entry)

    def _apply_host_batch(self, batch) -> None:
        """Merge + upload + apply one drained batch (caller holds the
        dispatch lock)."""
        try:
            if len(batch) == 1:
                acc = batch[0].arr
            else:   # float64 accumulate, like every other merge seam
                acc = np.zeros(self.shape, np.float64)
                for e in batch:
                    acc += e.arr
                acc = acc.astype(self.dtype)
            if self._wire != "none":
                # compressed upload for the whole merged batch: ONE
                # encode + one small transfer instead of N of either
                token = self._dispatch_wire_add(acc, batch[0].opt)
            else:
                delta_dev = self._host_delta(acc)   # ONE upload for all
                self._data, self._ustate, token = self._full_update_fn()(
                    self._data, self._ustate, delta_dev, batch[0].opt)
                self._version_applied()
                # prefetch BEFORE the waiters wake: the snapshot's
                # device->host copy streams while they block on the token
                self._maybe_prefetch()
            for e in batch:
                e.token = token
            if self._train_cache is not None:
                # the delta is VISIBLE only now (add_async's clear ran
                # at enqueue time, before the apply): a get that won the
                # dispatch lock ahead of this apply filled pre-add rows
                # under a then-current token — drop them, or every later
                # full hit would serve pre-add values forever
                self._train_cache.clear()
        except Exception as err:   # pragma: no cover - device failure
            for e in batch:
                e.error = err
        finally:
            with self._addq_cv:
                for e in batch:
                    e.event.set()
                self._addq_inflight -= len(batch)
                self._addq_cv.notify_all()

    def _add_applier_loop(self) -> None:
        while True:
            with self._addq_cv:
                while not self._addq:
                    if (not self._addq_cv.wait(self._APPLIER_IDLE_S)
                            and not self._addq):
                        # idle exit: a parked thread would pin the table
                        # (and its device buffers) for the process's life
                        self._add_applier = None
                        return
            # dispatch lock FIRST, pop second: entries are only ever held
            # by a thread that already owns the lock, so a lock-holding
            # flusher always finds them still queued and drains inline —
            # no lock-ordering deadlock is possible
            with self._dispatch_lock:
                with self._addq_cv:
                    batch, self._addq = self._addq, []
                    if batch:
                        self._addq_cv.notify_all()   # free throttled adds
                if batch:
                    self._apply_host_batch(batch)

    def _flush_host_adds(self) -> None:
        """Reads must observe every prior async add: drain the queue
        inline. Safe whether or not the caller already holds the dispatch
        lock (it is reentrant). INVARIANT: entries are only ever popped by
        a thread holding the dispatch lock, and the inflight decrement
        happens before that hold is released — so for a dispatch-holder,
        inflight > 0 implies the entries are still in the queue, and a
        holder can always drain them itself (no lock-ordering deadlock)."""
        while self._addq_inflight > 0:
            with self._dispatch_lock:
                with self._addq_cv:
                    batch, self._addq = self._addq, []
                    if batch:
                        self._addq_cv.notify_all()   # free throttled adds
                if batch:
                    self._apply_host_batch(batch)
                    continue
            # empty queue but inflight > 0: another thread is mid-apply
            # (it held the dispatch lock we just cycled through) — wait
            # for its completion signal OUTSIDE the dispatch lock
            with self._addq_cv:
                while self._addq_inflight > 0 and not self._addq:
                    self._addq_cv.wait()

    def add_async(self, delta: ArrayLike,
                  opt: Optional[AddOption] = None) -> int:
        """ref WorkerTable::AddAsync — dispatch the update, return a msg id.

        Stateless-linear host adds ride the coalescing queue: N pipelined
        adds become one summed upload (transfers do not overlap on the
        tunneled link, so fewer transfers is the only lever). Everything
        else applies inline under the dispatch lock."""
        opt = opt or AddOption()
        self._mark_mutated()
        try:
            with monitor(f"table[{self.name}].add"):
                if self._coalescible(delta, opt):
                    return self._enqueue_host_add(delta, opt)
                with self._dispatch_lock:
                    if (self._wire != "none"
                            and not isinstance(delta, jax.Array)):
                        return self._add_async_wire(delta, opt)
                    delta_dev = self._host_delta(delta)
                    self._data, self._ustate, token = \
                        self._full_update_fn()(
                            self._data, self._ustate, delta_dev, opt)
                    self._version_applied()
                    self._maybe_prefetch()
            return self._track(token)
        finally:
            if self._train_cache is not None:
                # whole-table delta: conservative wholesale drop, AFTER
                # the delta is queued/applied (every return path above) —
                # a clear logged before the mutation is visible lets a
                # get racing into the window re-fill pre-add rows under
                # a current fill token, permanently stale
                self._train_cache.clear()

    def _add_async_wire(self, delta: ArrayLike, opt: AddOption) -> int:
        """Compressed upload: the host payload shrinks 2x (bf16) / ~29x
        (1bit) / ~16x (topk) before crossing the wire; decode runs
        in-graph, fused into the updater apply."""
        arr = np.asarray(delta, dtype=self.dtype).reshape(self.shape)
        if self._zoo.size() > 1:
            from multiverso_tpu.parallel.collectives import process_sum
            arr = process_sum(arr)
        return self._track(self._dispatch_wire_add(arr, opt))

    def _encode_residual(self) -> jax.Array:
        """The device-resident error-feedback residual (lazy zeros)."""
        if self._wire_residual is None:
            self._wire_residual = jax.device_put(
                np.zeros(int(np.prod(self.shape)), np.float32),
                self._codec_dev)
        return self._wire_residual

    def _dispatch_wire_add(self, arr: np.ndarray, opt: AddOption):
        """Encode (jitted wire_codec kernel on the host-side CPU backend,
        numpy reference filter when that backend is unavailable) + ship
        only the compressed payload across the host<->device seam + apply
        via the in-graph decode+update program. Caller holds the dispatch
        lock (the codec residual is table state). Returns the completion
        token."""
        if self._wire == "bf16":
            import ml_dtypes
            padded = np.zeros(self._padded_shape, ml_dtypes.bfloat16)
            padded[: self.shape[0]] = arr.astype(ml_dtypes.bfloat16)
            dev = jax.device_put(padded, self._sharding)
            self._data, self._ustate, token = self._bf16_update_fn()(
                self._data, self._ustate, dev, opt)
        elif self._wire == "1bit":
            if self._codec_dev is not None:
                bits, scales, self._wire_residual = wire_codec.onebit_encode(
                    arr.reshape(-1).astype(np.float32, copy=False),
                    self._encode_residual(), block=self._one_bit.block)
                bits, scales = np.asarray(bits), np.asarray(scales)
            else:
                _, bits, scales = self._one_bit.filter_in(arr)
            self._data, self._ustate, token = self._onebit_update_fn()(
                self._data, self._ustate,
                jax.device_put(bits, self._replicated),
                jax.device_put(scales, self._replicated), opt)
        else:  # topk
            if self._codec_dev is not None:
                idx, vals, self._wire_residual = wire_codec.topk_encode(
                    arr.reshape(-1).astype(np.float32, copy=False),
                    self._encode_residual(), k=self._topk_k)
                idx, vals = np.asarray(idx), np.asarray(vals)
            else:
                _, idx, vals = self._topk.filter_in(arr)
            self._data, self._ustate, token = self._topk_update_fn()(
                self._data, self._ustate,
                jax.device_put(idx, self._replicated),
                jax.device_put(vals, self._replicated), opt)
        self._version_applied()
        self._maybe_prefetch()
        return token

    def add(self, delta: ArrayLike, opt: Optional[AddOption] = None) -> None:
        """ref WorkerTable::Add — blocking add (Wait(AddAsync(...)))."""
        self.wait(self.add_async(delta, opt))

    def get_async(self) -> int:
        """ref WorkerTable::GetAsync — start device->host transfer, return
        id. A version-cache hit skips the snapshot dispatch and transfer
        entirely; with a wire filter the snapshot is cast to bf16 on
        device first (half the download bytes — get() always did this,
        the async variant previously pulled full f32)."""
        self._flush_host_adds()   # before the lock: the applier needs it
        with monitor(f"table[{self.name}].get"), self._dispatch_lock:
            cached = self._cached_get()
            if cached is not None:
                return self._track((), lambda _: cached)
            version = self._version
            # a write-triggered prefetch at this version already has its
            # transfer in flight — adopt it instead of dispatching a
            # fresh snapshot (same bytes by construction)
            snap = self._take_prefetch()
            if snap is None:
                snap = (self._bf16_cast_fn()(self._data)
                        if self._wire != "none"
                        else self._snapshot_fn()(self._data))
                try:
                    snap.copy_to_host_async()
                except AttributeError:
                    pass

            def _finalize(s, _v=version):
                host = self._to_host(s)[: self.shape[0]]
                if host.dtype != self.dtype:
                    host = host.astype(self.dtype)
                with self._dispatch_lock:
                    self._store_get_cache(_v, host)
                return host

            return self._track(snap, _finalize)

    def _bf16_cast_fn(self):
        # the non-donating codec kernel: table data stays live
        return wire_codec.bf16_cast

    def get(self, out: Optional[np.ndarray] = None) -> np.ndarray:
        """ref WorkerTable::Get — blocking pull of the whole logical table.

        Fast path: reads the live array directly instead of dispatching a
        snapshot copy — safe because the transfer completes under the
        dispatch lock, before any later donating add can delete the buffer
        (saves one dispatch round-trip per get over a tunneled device;
        get_async keeps the snapshot since its read is deferred). With a
        wire filter the download is cast to bf16 on device first (half the
        bytes; ~3 decimal digits, plenty for parameter traffic)."""
        self._flush_host_adds()   # before the lock: the applier needs it
        with monitor(f"table[{self.name}].get"), self._dispatch_lock:
            hit = self._cached_get(into=out)
            if hit is not None:
                return hit
            version = self._version
            snap = self._take_prefetch()
            if snap is not None:
                # the prefetched transfer has been streaming since the
                # add dispatched it: wait out only the residual
                host = self._to_host(snap)[: self.shape[0]]
                if host.dtype != self.dtype:
                    host = host.astype(self.dtype)
            elif self._wire != "none":
                host = self._to_host(self._bf16_cast_fn()(self._data))
                host = host[: self.shape[0]].astype(self.dtype)
            else:
                host = self._to_host(self._data)[: self.shape[0]]
            self._store_get_cache(version, host)
        if out is not None:
            np.copyto(out.reshape(self.shape), host)
            return out
        return host

    def read(self, msg_id: int, out: Optional[np.ndarray] = None) -> np.ndarray:
        """Materialize the result of a previous :meth:`get_async`."""
        with self._lock:
            entry = self._pending.get(msg_id)
        if entry is not None and entry[1] is None:
            raise TypeError(
                f"msg_id {msg_id} is an add, not a get; use wait()")
        host = self.wait(msg_id)
        if host is None:
            raise KeyError(f"msg_id {msg_id} unknown or already consumed")
        if out is not None:
            np.copyto(out.reshape(self.shape), host)
            return out
        return host

    # ------------------------------------------------------------------ #
    # checkpoint (ref ServerTable Store/Load, table_interface.h:61-75)
    # ------------------------------------------------------------------ #
    def store(self, stream) -> None:
        """Write raw table + updater state (ref array_table.cpp:143-151).
        Multi-controller: fetching sharded state is a collective, so every
        process must call this together (checkpoint.save does)."""
        self._flush_host_adds()
        np.save(stream, self._to_host(self._data), allow_pickle=False)
        flat, _ = jax.tree.flatten(self._ustate)
        np.save(stream, np.asarray(len(flat)), allow_pickle=False)
        for leaf in flat:
            np.save(stream, self._to_host(leaf), allow_pickle=False)

    def load(self, stream) -> None:
        self._mark_mutated()
        self._flush_host_adds()   # a late-applying add must not overwrite
        data = np.load(stream)
        if data.shape != self._padded_shape:
            raise ValueError(
                f"checkpoint shape {data.shape} != table {self._padded_shape}")
        self._data = jax.device_put(data.astype(self.dtype), self._sharding)
        n = int(np.load(stream))
        flat, treedef = jax.tree.flatten(self._ustate)
        if n != len(flat):
            raise ValueError("checkpoint updater state mismatch")
        leaves = [np.load(stream) for _ in range(n)]
        self._ustate = jax.tree.unflatten(
            treedef, [self._place_state(l) for l in leaves])
        self._version_applied()
        if self._train_cache is not None:
            self._train_cache.clear()   # after the load is visible (the
            #  adopt()/add_async() clear-after-mutate ordering rule)
