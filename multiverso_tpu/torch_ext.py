"""PyTorch binding: delta-sync data parallelism for ``torch.nn.Module``s.

The reference shipped framework bindings as its user surface — Theano
``mv_shared``/``mv_sync`` (ref binding/python/multiverso/theano_ext/
sharedvar.py:38-50) and Lasagne's ``MVNetParamManager`` which flattens every
network parameter into one ArrayTable (ref theano_ext/lasagne_ext/
param_manager.py:9-64), plus a Lua/Torch FFI mirror (ref binding/lua/).
Torch-the-framework outlived both hosts, so the modern equivalent binds
PyTorch: ``TorchParamManager`` flattens a module's parameters into one
sharded ArrayTable and ``sync()`` runs the same Add(current − last) → Get
delta-sync ASGD recipe, writing the merged state back into the module
in-place. The table lives on the TPU mesh; torch stays on CPU and only the
flat float32 vector crosses the boundary per sync (the reference moved the
same vector over MPI).

Usage::

    manager = TorchParamManager(model)          # master-init convention
    for batch in loader:
        loss.backward(); opt.step()
        if step % sync_frequency == 0:
            manager.sync()                      # ASGD merge across workers
"""

from __future__ import annotations

from typing import Iterator, List, Tuple

import numpy as np

import multiverso_tpu as mv


def _require_torch():
    try:
        import torch
    except ImportError as e:  # pragma: no cover - torch is in the image
        raise ImportError(
            "torch_ext needs pytorch; `pip install torch` or use "
            "multiverso_tpu.sharedvar for JAX pytrees") from e
    return torch


class TorchParamManager:
    """``MVNetParamManager`` for PyTorch modules (ref param_manager.py:9-64).

    Flattens ``module.parameters()`` into one float32 ArrayTable sharded
    over the mesh. Worker 0 seeds the table with its initial values, other
    workers add zeros, so after the constructor's barrier every worker
    holds worker 0's init (ref param_manager.py:24-31 master-init).
    """

    def __init__(self, module, name: str = "torch_params"):
        torch = _require_torch()
        self._torch = torch
        self._module = module
        self._shapes: List[Tuple[int, ...]] = [
            tuple(p.shape) for p in module.parameters()]
        self._sizes = [int(np.prod(s)) if s else 1 for s in self._shapes]
        # paramless modules still get a 1-slot table so the Add/Get/barrier
        # protocol below stays collective-uniform across workers
        self._width = max(sum(self._sizes), 1)
        self.table = mv.ArrayTable(self._width, dtype=np.float32, name=name)
        flat = self._flatten()
        if mv.is_master_worker():
            self.table.add(flat)
        else:
            self.table.add(np.zeros_like(flat))
        mv.barrier()
        self._last = self.table.get().copy()
        self._write_back(self._last)

    def _flatten(self) -> np.ndarray:
        """Module params as one float32 vector, padded to the table width."""
        ps = [p.detach().cpu().numpy().astype(np.float32).reshape(-1)
              for p in self._module.parameters()]
        flat = np.concatenate(ps) if ps else np.zeros(0, np.float32)
        out = np.zeros(self._width, np.float32)
        out[: flat.size] = flat
        return out

    def _write_back(self, flat: np.ndarray) -> None:
        torch = self._torch
        with torch.no_grad():
            off = 0
            for p, shape, size in zip(self._module.parameters(),
                                      self._shapes, self._sizes):
                # np.array(copy=True): from_numpy on a read-only view
                # (e.g. a jax export) warns about non-writable tensors
                chunk = np.array(flat[off: off + size].reshape(shape))
                p.copy_(torch.from_numpy(chunk).to(p.dtype))
                off += size

    def sync(self) -> None:
        """Add(current − last) then Get, in-place into the module
        (ref sharedvar.py mv_sync :38-50 semantics)."""
        current = self._flatten()
        self.table.add(current - self._last)
        merged = self.table.get()
        self._last = merged.copy()
        self._write_back(merged)

    def pull(self) -> None:
        """Get without pushing (refresh from the global state)."""
        merged = self.table.get()
        self._last = merged.copy()
        self._write_back(merged)

    def numel(self) -> int:
        return int(sum(self._sizes))
