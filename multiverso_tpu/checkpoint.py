"""Checkpoint / resume of the full table state.

The reference defines ``ServerTable::Store/Load`` but no driver ever calls
them on the server path — only apps checkpoint, worker-side
(ref: include/multiverso/table_interface.h:61-75, src/table/array_table.cpp:
143-151, and the abandoned MV_LoadTable plan in Test/main.cpp:302-316).
Here resume is first-class: ``save``/``restore`` walk the Zoo's table
registry and serialize every table's data *and updater state* through the
URI-dispatched stream layer (local file or, gated, gs://).

Format: one stream per table (``<name>.<table_id>.mvt``) containing the
table's own store() payload, plus a ``manifest.json`` with shapes/dtypes for
validation. Multi-host: only process 0 writes (tables are replicated views of
the same sharded arrays); every process reads on restore.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional

import jax
import numpy as np

from multiverso_tpu.io.stream import open_stream
from multiverso_tpu.telemetry import memstats as _memstats
from multiverso_tpu.utils import log
from multiverso_tpu.zoo import Zoo


class _CheckpointGauges:
    """Byte-ledger gauges for the checkpoint plane (telemetry/
    memstats.py): host bytes STAGED by in-progress saves (owned copies
    of shard data + updater-state leaves, nonzero only while a save
    runs) and the on-disk size of the last committed tag per rank
    base. One process-global instance — but NOT one save at a time:
    an in-process multi-rank world runs one ShardCheckpointer thread
    per rank, so staging ACCUMULATES (stage/unstage deltas under a
    lock; one save zeroing a flat field would blank another rank's
    live figure) and committed-tag sizes key by base directory."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._staging = 0
        self._tags: Dict[str, int] = {}

    def stage(self, nbytes: int) -> None:
        with self._lock:
            self._staging += int(nbytes)

    def unstage(self, nbytes: int) -> None:
        with self._lock:
            self._staging -= int(nbytes)

    def note_tag(self, base: str, nbytes: int) -> None:
        with self._lock:
            self._tags[base] = int(nbytes)

    def memory_stats(self) -> Dict[str, int]:
        with self._lock:
            return {"staging_bytes": max(int(self._staging), 0),
                    "disk_tag_bytes": int(sum(self._tags.values()))}


_GAUGES = _CheckpointGauges()
_memstats.register("checkpoint", _GAUGES)


def _dir_bytes(path: str) -> int:
    """Total file bytes under ``path`` (pull-time only; a missing tree
    reads as 0 — the gauge must never fail a save)."""
    total = 0
    try:
        for root, _dirs, files in os.walk(path):
            for fn in files:
                try:
                    total += os.path.getsize(os.path.join(root, fn))
                except OSError:
                    pass
    except OSError:
        pass
    return total


def _join(base: str, *parts: str) -> str:
    """Path join that preserves URI schemes (os.path.join would mangle
    gs://bucket into a local-looking path)."""
    if "://" in base:
        return "/".join([base.rstrip("/"), *parts])
    return os.path.join(base, *parts)


def is_local(path: str) -> bool:
    return "://" not in path or path.startswith("file://")


# Commit marker written LAST into every checkpoint directory: a tag
# whose manifest exists but whose marker does not is a torn/partial
# write (the writer died mid-checkpoint) and is invisible to latest()
# and rejected by restore() — a resume must never load half a save.
COMMIT_MARKER = "COMMIT"


def _write_commit(path: str) -> None:
    with open_stream(_join(path, COMMIT_MARKER), "wb") as s:
        s.write(b"1")


def is_committed(path: str) -> bool:
    """True when ``path`` holds a COMPLETE checkpoint (the commit
    marker was written after everything else)."""
    if is_local(path):
        local = path[len("file://"):] if path.startswith("file://") else path
        return os.path.exists(os.path.join(local, COMMIT_MARKER))
    try:
        with open_stream(_join(path, COMMIT_MARKER), "rb") as s:
            s.read(1)
        return True
    except Exception:   # noqa: BLE001 — missing remote marker
        return False


def _manifest_entry(table) -> Dict:
    entry = {"name": table.name, "type": type(table).__name__}
    if hasattr(table, "shape"):
        entry["shape"] = list(table.shape)
        entry["dtype"] = str(table.dtype)
    return entry


def save(directory: str, tag: str = "checkpoint",
         backend: str = "stream", block: bool = True) -> str:
    """Write every registered table (data + updater state) under
    ``directory/tag/``. Returns the checkpoint path.

    ``backend="stream"`` (default) is the self-contained format above;
    ``backend="orbax"`` delegates the array payloads to Orbax — sharded,
    parallel per-shard IO, the industry-standard TPU checkpoint layout —
    while keeping the same manifest for name/shape validation.
    ``block=False`` (orbax only) returns as soon as the on-device state is
    snapshotted and writes in the background; the checkpoint becomes
    visible (manifest written, ``latest()`` sees it) only when
    :func:`wait_pending` runs — the next save/restore does this
    automatically.
    """
    if backend == "orbax":
        return _save_orbax(directory, tag, block)
    if backend != "stream":
        raise ValueError(f"unknown checkpoint backend {backend!r}")
    if not block:
        raise ValueError("block=False requires backend='orbax' (the stream "
                         "format writes synchronously)")
    # finalize any in-flight async save so manifest mtimes (latest()'s
    # ordering) can't invert across a backend switch
    wait_pending()
    zoo = Zoo.get()
    path = _join(directory, tag)
    manifest = {"tables": {}, "version": 1}

    class _DevNull:
        """Discarding sink: non-zero ranks still run store() because the
        sharded-state fetch inside it is a collective, but nothing is
        buffered or written."""

        def write(self, b):
            return len(b)

    for table_id, table in zoo.tables().items():
        if not hasattr(table, "store"):
            continue
        fname = f"{table.name}.{table_id}.mvt"
        if zoo.rank() == 0:
            with open_stream(_join(path, fname), "wb") as s:
                table.store(s)
        elif getattr(table, "collective_store", True):
            table.store(_DevNull())
        # async (uncoordinated) tables: store() is plain RPC, not a
        # collective — non-zero ranks skip it entirely instead of pulling
        # world-sized state dumps just to discard them
        manifest["tables"][str(table_id)] = dict(
            _manifest_entry(table), file=fname)
    if zoo.rank() == 0:
        # manifest rides the same URI-dispatched stream layer as the table
        # payloads, so gs:// checkpoints stay in one storage system; the
        # commit marker lands LAST — readers ignore marker-less tags
        with open_stream(_join(path, "manifest.json"), "wb") as s:
            s.write(json.dumps(manifest, indent=2).encode())
        _write_commit(path)
        log.info("checkpoint saved: %s (%d tables)", path,
                 len(manifest["tables"]))
    zoo.barrier()
    return path


def restore(directory: str, tag: str = "checkpoint") -> int:
    """Load every registered table from a checkpoint written by :func:`save`.

    Tables are matched by registration id + name; mismatched shapes raise.
    The backend is auto-detected from the manifest, so a loop can switch
    formats and still resume. Returns the number of tables restored.
    """
    wait_pending()  # finalize any in-flight async save first
    zoo = Zoo.get()
    path = _join(directory, tag)
    if not is_committed(path):
        raise ValueError(
            f"checkpoint {path} has no commit marker — the save was "
            "torn/partial (writer died mid-checkpoint); restore the "
            "previous committed tag instead")
    with open_stream(_join(path, "manifest.json"), "rb") as s:
        manifest = json.loads(s.read().decode())
    if manifest.get("backend") == "orbax":
        return _restore_orbax(path, manifest)
    restored = 0
    for table_id, table in zoo.tables().items():
        entry = manifest["tables"].get(str(table_id))
        if entry is None or not hasattr(table, "load"):
            continue
        if entry["name"] != table.name:
            raise ValueError(
                f"checkpoint table {table_id} is {entry['name']!r}, "
                f"registry has {table.name!r} — create tables in the same "
                "order before restoring")
        if (zoo.rank() != 0
                and not getattr(table, "collective_store", True)):
            # async tables: load() pushes the full state to every owner —
            # plain RPC, not a collective; rank 0's push restores everyone
            # (same gate as save(), symmetric)
            restored += 1
            continue
        with open_stream(_join(path, entry["file"]), "rb") as s:
            table.load(s)
        restored += 1
    zoo.barrier()
    log.info("checkpoint restored: %s (%d tables)", path, restored)
    return restored


BACKENDS = ("stream", "orbax")


def _orbax_tree(zoo, only_ids=None) -> Dict[str, Dict]:
    """{table_<id>: state pytree} over checkpointable tables (optionally
    restricted to ``only_ids``)."""
    return {f"table_{tid}": t.state for tid, t in zoo.tables().items()
            if hasattr(t, "state")
            and (only_ids is None or tid in only_ids)}


def _arrays_path(path: str) -> str:
    """Where the orbax array payloads live for a checkpoint path. Orbax
    needs an absolute path for local storage; file:// URIs must be stripped
    BEFORE abspath (abspath of the raw URI would nest a literal 'file:'
    directory under the cwd, and save/restore from different cwds would
    disagree on the location)."""
    if not is_local(path):
        return _join(path, "arrays")
    local = path[len("file://"):] if path.startswith("file://") else path
    return os.path.abspath(os.path.join(local, "arrays"))


_async_ckptr = None                 # lazily-created AsyncCheckpointer
_pending = []                       # [(path, manifest)] awaiting finalize


def _get_async_ckptr():
    global _async_ckptr
    if _async_ckptr is None:
        import orbax.checkpoint as ocp
        _async_ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return _async_ckptr


def wait_pending() -> int:
    """Block until in-flight ``block=False`` saves finish, then finalize
    them (write manifests, making them visible to restore/``latest``).
    Returns the number finalized."""
    global _pending
    if not _pending:
        return 0
    try:
        _get_async_ckptr().wait_until_finished()
    except Exception:
        # a failed background write must not wedge every later call nor
        # ever become visible: discard the unfinalized checkpoints (restore
        # falls back to the previous finalized one) and surface the error
        dropped = [p for p, _ in _pending]
        _pending = []
        log.error("async checkpoint write failed; discarded unfinalized "
                  "checkpoints: %s", dropped)
        raise
    zoo = Zoo.get()
    done = 0
    for path, manifest in _pending:
        if zoo.rank() == 0:
            with open_stream(_join(path, "manifest.json"), "wb") as s:
                s.write(json.dumps(manifest, indent=2).encode())
            _write_commit(path)
            log.info("checkpoint finalized (orbax async): %s", path)
        done += 1
    _pending = []
    zoo.barrier()
    return done


def _save_orbax(directory: str, tag: str, block: bool = True) -> str:
    import orbax.checkpoint as ocp

    wait_pending()  # at most one async save in flight
    zoo = Zoo.get()
    path = _join(directory, tag)
    tree = _orbax_tree(zoo)
    if block:
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(_arrays_path(path), tree, force=True)
    else:
        _get_async_ckptr().save(_arrays_path(path),
                                args=ocp.args.StandardSave(tree),
                                force=True)
    manifest = {"version": 1, "backend": "orbax", "tables": {}}
    for tid, t in zoo.tables().items():
        if hasattr(t, "state"):
            manifest["tables"][str(tid)] = dict(_manifest_entry(t),
                                                kind="orbax")
        elif hasattr(t, "store"):
            # host-side tables (e.g. KVTable) have no device state pytree;
            # they ride the stream format inside the same checkpoint
            # (written synchronously — they are tiny host dicts)
            fname = f"{t.name}.{tid}.mvt"
            if zoo.rank() == 0:
                with open_stream(_join(path, fname), "wb") as s:
                    t.store(s)
            manifest["tables"][str(tid)] = dict(_manifest_entry(t),
                                                kind="stream", file=fname)
    if not block:
        # manifest (the visibility marker) is deferred to wait_pending()
        _pending.append((path, manifest))
        return path
    if zoo.rank() == 0:
        with open_stream(_join(path, "manifest.json"), "wb") as s:
            s.write(json.dumps(manifest, indent=2).encode())
        _write_commit(path)
        log.info("checkpoint saved (orbax): %s (%d tables)", path,
                 len(manifest["tables"]))
    zoo.barrier()
    return path


def _restore_orbax(path: str, manifest: Dict) -> int:
    import orbax.checkpoint as ocp

    zoo = Zoo.get()
    for table_id, entry in manifest["tables"].items():
        table = zoo.tables().get(int(table_id))
        if table is not None and entry["name"] != table.name:
            raise ValueError(
                f"checkpoint table {table_id} is {entry['name']!r}, "
                f"registry has {table.name!r} — create tables in the same "
                "order before restoring")
    # abstract target: same shapes/dtypes/shardings as the live tables, so
    # orbax restores each shard directly onto its device. Restrict to the
    # ids the checkpoint actually holds — like the stream path, tables
    # added since the save are simply left at their current state
    saved_ids = {int(tid) for tid, e in manifest["tables"].items()
                 if e.get("kind") == "orbax"}
    tree = _orbax_tree(zoo, only_ids=saved_ids)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=x.sharding), tree)
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(_arrays_path(path), abstract)
    count = 0
    for key, state in restored.items():
        zoo.table(int(key.removeprefix("table_"))).adopt(state)
        count += 1
    for table_id, entry in manifest["tables"].items():
        if entry.get("kind") != "stream":
            continue
        table = zoo.tables().get(int(table_id))
        if table is None or not hasattr(table, "load"):
            continue
        with open_stream(_join(path, entry["file"]), "rb") as s:
            table.load(s)
        count += 1
    zoo.barrier()
    log.info("checkpoint restored (orbax): %s (%d tables)", path, count)
    return count


def latest(directory: str) -> Optional[str]:
    """Most recent COMMITTED tag under ``directory`` (by manifest
    mtime). Tags without the commit marker — torn/partial saves whose
    writer died mid-checkpoint — are invisible: a resume silently falls
    back to the previous complete save instead of loading half of one.
    Local filesystems only — remote URIs return None (no listing API in
    the gated stream layer)."""
    if not is_local(directory) or not os.path.isdir(directory):
        return None
    best, best_mtime = None, -1.0
    skipped = []
    for tag in os.listdir(directory):
        base = os.path.join(directory, tag)
        m = os.path.join(base, "manifest.json")
        if not os.path.exists(m):
            continue
        if not os.path.exists(os.path.join(base, COMMIT_MARKER)):
            skipped.append(tag)
            continue
        mt = os.path.getmtime(m)
        if mt > best_mtime:
            best, best_mtime = tag, mt
    if skipped:
        # loud, because this is also the legacy-upgrade surface: a tag
        # with a manifest but no marker is EITHER a torn save (skip is
        # the fix) or a pre-marker checkpoint (the operator must
        # `touch COMMIT` to readmit it — docs/FAILOVER.md); silently
        # cold-starting over saved state would be the worst outcome
        log.error("checkpoint latest(%s): skipping %d uncommitted "
                  "tag(s) %s — torn saves, or pre-commit-marker "
                  "checkpoints needing a manual COMMIT file (see "
                  "docs/FAILOVER.md)", directory, len(skipped),
                  sorted(skipped)[:4])
    return best


# ---------------------------------------------------------------------- #
# per-shard incremental checkpoints (elastic failover, ps/failover.py;
# docs/FAILOVER.md). Unlike save()/restore() — which walk every table
# COLLECTIVELY and roll the whole world back — these snapshot ONE
# rank's locally-owned shards (data + updater state + replay sequence
# channels + apply version) so a restarted incarnation restores exactly
# its own rows without touching peers' newer live state. Local
# filesystems only: failover checkpoints are written at ~second cadence
# and read by the replacement process on the same host/NFS plane.
# ---------------------------------------------------------------------- #
def _shard_base(directory: str, rank: int) -> str:
    return os.path.join(directory, f"shard-r{int(rank)}")


def _checkpointable_shards(tables):
    """(name, shard) pairs of the local shards with the failover
    checkpoint surface. Accepts a list of async tables, a
    ``{name: shard}`` dict (the PSService registry shape), or a
    zero-arg callable returning either."""
    if callable(tables):
        tables = tables()
    if isinstance(tables, dict):
        return [(n, s) for n, s in tables.items()
                if hasattr(s, "checkpoint_state")]
    out = []
    for t in tables:
        shard = getattr(t, "_shard", None)
        if shard is None and hasattr(t, "_m"):   # AsyncArrayTable wraps
            shard = getattr(t._m, "_shard", None)
        if shard is not None and hasattr(shard, "checkpoint_state"):
            out.append((t.name, shard))
    return out


def _save_shard_file(path: str, meta: Dict, arrays) -> None:
    header = json.dumps(meta).encode()
    with open(path, "wb") as f:
        np.save(f, np.frombuffer(header, np.uint8), allow_pickle=False)
        np.save(f, np.array([len(arrays)], np.int64), allow_pickle=False)
        for a in arrays:
            np.save(f, np.ascontiguousarray(a), allow_pickle=False)


def _load_shard_file(path: str):
    with open(path, "rb") as f:
        meta = json.loads(np.load(f).tobytes().decode())
        n = int(np.load(f)[0])
        arrays = [np.load(f) for _ in range(n)]
    return meta, arrays


def save_shard_state(directory: str, rank: int, tables) -> str:
    """Write one COMMITTED snapshot of ``rank``'s local shards under
    ``directory/shard-r<rank>/v<N>/`` (monotonic tag; the commit marker
    lands last, so a writer dying mid-save leaves an invisible torn
    tag, never a loadable half-checkpoint). After the commit, each
    shard's durable replay floors advance (``mark_durable``) — from
    here on its stamped acks tell clients the snapshot's sequences
    survive a crash. Returns the tag path."""
    if not is_local(directory):
        raise ValueError("per-shard failover checkpoints require a "
                         f"local/NFS directory, got {directory!r}")
    base = _shard_base(directory, rank)
    os.makedirs(base, exist_ok=True)
    nxt = 0
    for name in os.listdir(base):
        if name.startswith("v") and name[1:].isdigit():
            nxt = max(nxt, int(name[1:]) + 1)
    path = os.path.join(base, f"v{nxt:09d}")
    os.makedirs(path, exist_ok=True)
    manifest: Dict = {"version": 1, "rank": int(rank), "tables": {}}
    shards = _checkpointable_shards(tables)
    metas = []
    for name, shard in shards:
        meta, arrays = shard.checkpoint_state()
        # ledger gauge: this save's owned host copies, released as
        # each shard's file lands (staging peaks at one shard's
        # snapshot per concurrent save, not the whole rank's) —
        # delta-accumulated so concurrent per-rank checkpointers in
        # one process never blank each other's figure
        staged = sum(int(getattr(a, "nbytes", 0)) for a in arrays)
        fname = f"{name}.mvs"
        _GAUGES.stage(staged)
        try:
            _save_shard_file(os.path.join(path, fname), meta, arrays)
        finally:
            _GAUGES.unstage(staged)
        manifest["tables"][name] = {"file": fname,
                                    "kind": meta.get("kind"),
                                    "version": meta.get("version")}
        metas.append((shard, meta))
    with open(os.path.join(path, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1)
    _write_commit(path)
    _GAUGES.note_tag(base, _dir_bytes(path))
    # durable ONLY now: the marks must never run ahead of a commit a
    # replacement could actually restore
    for shard, meta in metas:
        shard.mark_durable({cl: int(chan.get("floor", -1))
                            for cl, chan in
                            (meta.get("replay") or {}).items()})
    log.debug("shard checkpoint saved: %s (%d shards)", path, len(shards))
    return path


def latest_shard_tag(directory: str, rank: int) -> Optional[str]:
    """Newest COMMITTED per-shard tag for ``rank`` (torn tags skipped),
    or None when the rank never completed a save."""
    base = _shard_base(directory, rank)
    if not os.path.isdir(base):
        return None
    tags = sorted((n for n in os.listdir(base)
                   if n.startswith("v") and n[1:].isdigit()
                   and os.path.exists(os.path.join(base, n,
                                                   COMMIT_MARKER))),
                  reverse=True)
    return tags[0] if tags else None


def restore_shard_state(directory: str, rank: int, tables,
                        tag: Optional[str] = None) -> int:
    """Restore ``rank``'s local shards from its newest committed
    per-shard checkpoint (or an explicit ``tag``) — the respawned
    incarnation's first act. Tables absent from the snapshot keep
    their fresh state (they were created after the save); snapshot
    entries without a live table are skipped. Returns the number of
    shards restored (0 when no committed tag exists — a cold start)."""
    tag = tag or latest_shard_tag(directory, rank)
    if tag is None:
        return 0
    path = os.path.join(_shard_base(directory, rank), tag)
    if not is_committed(path):
        raise ValueError(f"shard checkpoint {path} is not committed")
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    by_name = dict(_checkpointable_shards(tables))
    restored = 0
    for name, entry in manifest.get("tables", {}).items():
        shard = by_name.get(name)
        if shard is None:
            continue
        meta, arrays = _load_shard_file(os.path.join(path, entry["file"]))
        shard.restore_checkpoint(meta, arrays)
        restored += 1
    log.info("shard checkpoint restored: %s (%d shards)", path, restored)
    return restored


def prune_shard_tags(directory: str, rank: int, keep: int = 2) -> None:
    """Drop all but the newest ``keep`` committed per-shard tags, plus
    any torn (uncommitted) tag older than the newest committed one —
    a crashed writer's debris must not accumulate forever."""
    import shutil

    base = _shard_base(directory, rank)
    if not os.path.isdir(base):
        return
    tags = sorted(n for n in os.listdir(base)
                  if n.startswith("v") and n[1:].isdigit())
    committed = [n for n in tags
                 if os.path.exists(os.path.join(base, n, COMMIT_MARKER))]
    drop = set(committed[: -max(keep, 1)])
    if committed:
        newest = committed[-1]
        drop.update(n for n in tags
                    if n < newest and n not in committed)
    for n in drop:
        shutil.rmtree(os.path.join(base, n), ignore_errors=True)
