"""Checkpoint / resume of the full table state.

The reference defines ``ServerTable::Store/Load`` but no driver ever calls
them on the server path — only apps checkpoint, worker-side
(ref: include/multiverso/table_interface.h:61-75, src/table/array_table.cpp:
143-151, and the abandoned MV_LoadTable plan in Test/main.cpp:302-316).
Here resume is first-class: ``save``/``restore`` walk the Zoo's table
registry and serialize every table's data *and updater state* through the
URI-dispatched stream layer (local file or, gated, gs://).

Format: one stream per table (``<name>.<table_id>.mvt``) containing the
table's own store() payload, plus a ``manifest.json`` with shapes/dtypes for
validation. Multi-host: only process 0 writes (tables are replicated views of
the same sharded arrays); every process reads on restore.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

from multiverso_tpu.io.stream import open_stream
from multiverso_tpu.utils import log
from multiverso_tpu.zoo import Zoo


def _join(base: str, *parts: str) -> str:
    """Path join that preserves URI schemes (os.path.join would mangle
    gs://bucket into a local-looking path)."""
    if "://" in base:
        return "/".join([base.rstrip("/"), *parts])
    return os.path.join(base, *parts)


def is_local(path: str) -> bool:
    return "://" not in path or path.startswith("file://")


def _manifest_entry(table) -> Dict:
    entry = {"name": table.name, "type": type(table).__name__}
    if hasattr(table, "shape"):
        entry["shape"] = list(table.shape)
        entry["dtype"] = str(table.dtype)
    return entry


def save(directory: str, tag: str = "checkpoint") -> str:
    """Write every registered table (data + updater state) under
    ``directory/tag/``. Returns the checkpoint path."""
    zoo = Zoo.get()
    path = _join(directory, tag)
    manifest = {"tables": {}, "version": 1}

    class _DevNull:
        """Discarding sink: non-zero ranks still run store() because the
        sharded-state fetch inside it is a collective, but nothing is
        buffered or written."""

        def write(self, b):
            return len(b)

    for table_id, table in zoo.tables().items():
        if not hasattr(table, "store"):
            continue
        fname = f"{table.name}.{table_id}.mvt"
        if zoo.rank() == 0:
            with open_stream(_join(path, fname), "wb") as s:
                table.store(s)
        else:
            table.store(_DevNull())
        manifest["tables"][str(table_id)] = dict(
            _manifest_entry(table), file=fname)
    if zoo.rank() == 0:
        # manifest rides the same URI-dispatched stream layer as the table
        # payloads, so gs:// checkpoints stay in one storage system
        with open_stream(_join(path, "manifest.json"), "wb") as s:
            s.write(json.dumps(manifest, indent=2).encode())
        log.info("checkpoint saved: %s (%d tables)", path,
                 len(manifest["tables"]))
    zoo.barrier()
    return path


def restore(directory: str, tag: str = "checkpoint") -> int:
    """Load every registered table from a checkpoint written by :func:`save`.

    Tables are matched by registration id + name; mismatched shapes raise.
    Returns the number of tables restored.
    """
    zoo = Zoo.get()
    path = _join(directory, tag)
    with open_stream(_join(path, "manifest.json"), "rb") as s:
        manifest = json.loads(s.read().decode())
    restored = 0
    for table_id, table in zoo.tables().items():
        entry = manifest["tables"].get(str(table_id))
        if entry is None or not hasattr(table, "load"):
            continue
        if entry["name"] != table.name:
            raise ValueError(
                f"checkpoint table {table_id} is {entry['name']!r}, "
                f"registry has {table.name!r} — create tables in the same "
                "order before restoring")
        with open_stream(_join(path, entry["file"]), "rb") as s:
            table.load(s)
        restored += 1
    zoo.barrier()
    log.info("checkpoint restored: %s (%d tables)", path, restored)
    return restored


def latest(directory: str) -> Optional[str]:
    """Most recent tag under ``directory`` (by manifest mtime).
    Local filesystems only — remote URIs return None (no listing API in the
    gated stream layer)."""
    if not is_local(directory) or not os.path.isdir(directory):
        return None
    best, best_mtime = None, -1.0
    for tag in os.listdir(directory):
        m = os.path.join(directory, tag, "manifest.json")
        if os.path.exists(m):
            mt = os.path.getmtime(m)
            if mt > best_mtime:
                best, best_mtime = tag, mt
    return best
