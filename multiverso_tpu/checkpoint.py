"""Checkpoint / resume of the full table state.

The reference defines ``ServerTable::Store/Load`` but no driver ever calls
them on the server path — only apps checkpoint, worker-side
(ref: include/multiverso/table_interface.h:61-75, src/table/array_table.cpp:
143-151, and the abandoned MV_LoadTable plan in Test/main.cpp:302-316).
Here resume is first-class: ``save``/``restore`` walk the Zoo's table
registry and serialize every table's data *and updater state* through the
URI-dispatched stream layer (local file or, gated, gs://).

Format: one stream per table (``<name>.<table_id>.mvt``) containing the
table's own store() payload, plus a ``manifest.json`` with shapes/dtypes for
validation. Multi-host: only process 0 writes (tables are replicated views of
the same sharded arrays); every process reads on restore.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import jax

from multiverso_tpu.io.stream import open_stream
from multiverso_tpu.utils import log
from multiverso_tpu.zoo import Zoo


def _join(base: str, *parts: str) -> str:
    """Path join that preserves URI schemes (os.path.join would mangle
    gs://bucket into a local-looking path)."""
    if "://" in base:
        return "/".join([base.rstrip("/"), *parts])
    return os.path.join(base, *parts)


def is_local(path: str) -> bool:
    return "://" not in path or path.startswith("file://")


def _manifest_entry(table) -> Dict:
    entry = {"name": table.name, "type": type(table).__name__}
    if hasattr(table, "shape"):
        entry["shape"] = list(table.shape)
        entry["dtype"] = str(table.dtype)
    return entry


def save(directory: str, tag: str = "checkpoint",
         backend: str = "stream", block: bool = True) -> str:
    """Write every registered table (data + updater state) under
    ``directory/tag/``. Returns the checkpoint path.

    ``backend="stream"`` (default) is the self-contained format above;
    ``backend="orbax"`` delegates the array payloads to Orbax — sharded,
    parallel per-shard IO, the industry-standard TPU checkpoint layout —
    while keeping the same manifest for name/shape validation.
    ``block=False`` (orbax only) returns as soon as the on-device state is
    snapshotted and writes in the background; the checkpoint becomes
    visible (manifest written, ``latest()`` sees it) only when
    :func:`wait_pending` runs — the next save/restore does this
    automatically.
    """
    if backend == "orbax":
        return _save_orbax(directory, tag, block)
    if backend != "stream":
        raise ValueError(f"unknown checkpoint backend {backend!r}")
    if not block:
        raise ValueError("block=False requires backend='orbax' (the stream "
                         "format writes synchronously)")
    # finalize any in-flight async save so manifest mtimes (latest()'s
    # ordering) can't invert across a backend switch
    wait_pending()
    zoo = Zoo.get()
    path = _join(directory, tag)
    manifest = {"tables": {}, "version": 1}

    class _DevNull:
        """Discarding sink: non-zero ranks still run store() because the
        sharded-state fetch inside it is a collective, but nothing is
        buffered or written."""

        def write(self, b):
            return len(b)

    for table_id, table in zoo.tables().items():
        if not hasattr(table, "store"):
            continue
        fname = f"{table.name}.{table_id}.mvt"
        if zoo.rank() == 0:
            with open_stream(_join(path, fname), "wb") as s:
                table.store(s)
        elif getattr(table, "collective_store", True):
            table.store(_DevNull())
        # async (uncoordinated) tables: store() is plain RPC, not a
        # collective — non-zero ranks skip it entirely instead of pulling
        # world-sized state dumps just to discard them
        manifest["tables"][str(table_id)] = dict(
            _manifest_entry(table), file=fname)
    if zoo.rank() == 0:
        # manifest rides the same URI-dispatched stream layer as the table
        # payloads, so gs:// checkpoints stay in one storage system
        with open_stream(_join(path, "manifest.json"), "wb") as s:
            s.write(json.dumps(manifest, indent=2).encode())
        log.info("checkpoint saved: %s (%d tables)", path,
                 len(manifest["tables"]))
    zoo.barrier()
    return path


def restore(directory: str, tag: str = "checkpoint") -> int:
    """Load every registered table from a checkpoint written by :func:`save`.

    Tables are matched by registration id + name; mismatched shapes raise.
    The backend is auto-detected from the manifest, so a loop can switch
    formats and still resume. Returns the number of tables restored.
    """
    wait_pending()  # finalize any in-flight async save first
    zoo = Zoo.get()
    path = _join(directory, tag)
    with open_stream(_join(path, "manifest.json"), "rb") as s:
        manifest = json.loads(s.read().decode())
    if manifest.get("backend") == "orbax":
        return _restore_orbax(path, manifest)
    restored = 0
    for table_id, table in zoo.tables().items():
        entry = manifest["tables"].get(str(table_id))
        if entry is None or not hasattr(table, "load"):
            continue
        if entry["name"] != table.name:
            raise ValueError(
                f"checkpoint table {table_id} is {entry['name']!r}, "
                f"registry has {table.name!r} — create tables in the same "
                "order before restoring")
        if (zoo.rank() != 0
                and not getattr(table, "collective_store", True)):
            # async tables: load() pushes the full state to every owner —
            # plain RPC, not a collective; rank 0's push restores everyone
            # (same gate as save(), symmetric)
            restored += 1
            continue
        with open_stream(_join(path, entry["file"]), "rb") as s:
            table.load(s)
        restored += 1
    zoo.barrier()
    log.info("checkpoint restored: %s (%d tables)", path, restored)
    return restored


BACKENDS = ("stream", "orbax")


def _orbax_tree(zoo, only_ids=None) -> Dict[str, Dict]:
    """{table_<id>: state pytree} over checkpointable tables (optionally
    restricted to ``only_ids``)."""
    return {f"table_{tid}": t.state for tid, t in zoo.tables().items()
            if hasattr(t, "state")
            and (only_ids is None or tid in only_ids)}


def _arrays_path(path: str) -> str:
    """Where the orbax array payloads live for a checkpoint path. Orbax
    needs an absolute path for local storage; file:// URIs must be stripped
    BEFORE abspath (abspath of the raw URI would nest a literal 'file:'
    directory under the cwd, and save/restore from different cwds would
    disagree on the location)."""
    if not is_local(path):
        return _join(path, "arrays")
    local = path[len("file://"):] if path.startswith("file://") else path
    return os.path.abspath(os.path.join(local, "arrays"))


_async_ckptr = None                 # lazily-created AsyncCheckpointer
_pending = []                       # [(path, manifest)] awaiting finalize


def _get_async_ckptr():
    global _async_ckptr
    if _async_ckptr is None:
        import orbax.checkpoint as ocp
        _async_ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return _async_ckptr


def wait_pending() -> int:
    """Block until in-flight ``block=False`` saves finish, then finalize
    them (write manifests, making them visible to restore/``latest``).
    Returns the number finalized."""
    global _pending
    if not _pending:
        return 0
    try:
        _get_async_ckptr().wait_until_finished()
    except Exception:
        # a failed background write must not wedge every later call nor
        # ever become visible: discard the unfinalized checkpoints (restore
        # falls back to the previous finalized one) and surface the error
        dropped = [p for p, _ in _pending]
        _pending = []
        log.error("async checkpoint write failed; discarded unfinalized "
                  "checkpoints: %s", dropped)
        raise
    zoo = Zoo.get()
    done = 0
    for path, manifest in _pending:
        if zoo.rank() == 0:
            with open_stream(_join(path, "manifest.json"), "wb") as s:
                s.write(json.dumps(manifest, indent=2).encode())
            log.info("checkpoint finalized (orbax async): %s", path)
        done += 1
    _pending = []
    zoo.barrier()
    return done


def _save_orbax(directory: str, tag: str, block: bool = True) -> str:
    import orbax.checkpoint as ocp

    wait_pending()  # at most one async save in flight
    zoo = Zoo.get()
    path = _join(directory, tag)
    tree = _orbax_tree(zoo)
    if block:
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(_arrays_path(path), tree, force=True)
    else:
        _get_async_ckptr().save(_arrays_path(path),
                                args=ocp.args.StandardSave(tree),
                                force=True)
    manifest = {"version": 1, "backend": "orbax", "tables": {}}
    for tid, t in zoo.tables().items():
        if hasattr(t, "state"):
            manifest["tables"][str(tid)] = dict(_manifest_entry(t),
                                                kind="orbax")
        elif hasattr(t, "store"):
            # host-side tables (e.g. KVTable) have no device state pytree;
            # they ride the stream format inside the same checkpoint
            # (written synchronously — they are tiny host dicts)
            fname = f"{t.name}.{tid}.mvt"
            if zoo.rank() == 0:
                with open_stream(_join(path, fname), "wb") as s:
                    t.store(s)
            manifest["tables"][str(tid)] = dict(_manifest_entry(t),
                                                kind="stream", file=fname)
    if not block:
        # manifest (the visibility marker) is deferred to wait_pending()
        _pending.append((path, manifest))
        return path
    if zoo.rank() == 0:
        with open_stream(_join(path, "manifest.json"), "wb") as s:
            s.write(json.dumps(manifest, indent=2).encode())
        log.info("checkpoint saved (orbax): %s (%d tables)", path,
                 len(manifest["tables"]))
    zoo.barrier()
    return path


def _restore_orbax(path: str, manifest: Dict) -> int:
    import orbax.checkpoint as ocp

    zoo = Zoo.get()
    for table_id, entry in manifest["tables"].items():
        table = zoo.tables().get(int(table_id))
        if table is not None and entry["name"] != table.name:
            raise ValueError(
                f"checkpoint table {table_id} is {entry['name']!r}, "
                f"registry has {table.name!r} — create tables in the same "
                "order before restoring")
    # abstract target: same shapes/dtypes/shardings as the live tables, so
    # orbax restores each shard directly onto its device. Restrict to the
    # ids the checkpoint actually holds — like the stream path, tables
    # added since the save are simply left at their current state
    saved_ids = {int(tid) for tid, e in manifest["tables"].items()
                 if e.get("kind") == "orbax"}
    tree = _orbax_tree(zoo, only_ids=saved_ids)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=x.sharding), tree)
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(_arrays_path(path), abstract)
    count = 0
    for key, state in restored.items():
        zoo.table(int(key.removeprefix("table_"))).adopt(state)
        count += 1
    for table_id, entry in manifest["tables"].items():
        if entry.get("kind") != "stream":
            continue
        table = zoo.tables().get(int(table_id))
        if table is None or not hasattr(table, "load"):
            continue
        with open_stream(_join(path, entry["file"]), "rb") as s:
            table.load(s)
        count += 1
    zoo.barrier()
    log.info("checkpoint restored (orbax): %s (%d tables)", path, count)
    return count


def latest(directory: str) -> Optional[str]:
    """Most recent tag under ``directory`` (by manifest mtime).
    Local filesystems only — remote URIs return None (no listing API in the
    gated stream layer)."""
    if not is_local(directory) or not os.path.isdir(directory):
        return None
    best, best_mtime = None, -1.0
    for tag in os.listdir(directory):
        m = os.path.join(directory, tag, "manifest.json")
        if os.path.exists(m):
            mt = os.path.getmtime(m)
            if mt > best_mtime:
                best, best_mtime = tag, mt
    return best
