"""multiverso_tpu — a TPU-native parameter-server-class training framework.

A ground-up re-design of the capabilities of Microsoft Multiverso
(liming-vie/multiverso) for TPU hardware: parameter tables are device-sharded
``jax.Array``s over a ``jax.sharding.Mesh``, Add/Get lower to XLA collectives
over ICI, server-side updaters are jitted per-shard functions, BSP is the
hardware-native synchronization mode, and the bundled applications
(LogisticRegression, WordEmbedding) train end-to-end with no MPI in the loop.
"""

from multiverso_tpu.api import (
    MV_Aggregate, MV_Barrier, MV_CreateTable, MV_Init, MV_NumServers,
    MV_NumWorkers, MV_Rank, MV_ServerId, MV_ShutDown, MV_Size, MV_WorkerId,
    aggregate, barrier, create_table, init, is_master_worker, mesh,
    num_servers, num_workers, rank, server_id, servers_num, shutdown, size,
    worker_id, workers_num,
)
from multiverso_tpu.ps import (AsyncArrayTable, AsyncKVTable,
                               AsyncMatrixTable, AsyncSparseKVTable,
                               AsyncSparseMatrixTable)
from multiverso_tpu.table import Table
from multiverso_tpu.tables import ArrayTable, KVTable, MatrixTable, SparseMatrixTable
from multiverso_tpu.tables.array_table import ArrayTableOption
from multiverso_tpu.tables.kv_table import KVTableOption
from multiverso_tpu.tables.matrix_table import MatrixTableOption
from multiverso_tpu.tables.sparse_matrix_table import SparseMatrixTableOption
from multiverso_tpu.utils.async_buffer import AsyncBuffer
from multiverso_tpu.updaters import (
    AdaGradUpdater, AdamUpdater, AddOption, MomentumUpdater, SGDUpdater,
    Updater, get_updater, register_updater,
)
from multiverso_tpu import serving, telemetry
from multiverso_tpu.utils import config, dashboard, log
from multiverso_tpu.zoo import Zoo

__version__ = "0.1.0"


def __getattr__(name):
    # Lazy submodule access (checkpoint, parallel, handlers, sharedvar,
    # native import multiverso_tpu themselves, so eager import would cycle).
    import importlib
    if name in ("checkpoint", "parallel", "handlers", "sharedvar", "native",
                "models", "apps", "io", "data", "ssp", "elastic"):
        return importlib.import_module(f"multiverso_tpu.{name}")
    raise AttributeError(f"module 'multiverso_tpu' has no attribute {name!r}")
