"""ReadReplica: a bounded-staleness read copy of one async PS table.

The serving tier's read path (docs/SERVING.md). A replica pulls each
owning shard's committed rows through the ``MSG_SNAPSHOT`` subscription
RPC (ps/service.MSG_SNAPSHOT -> ps/shard.RowShard.export_snapshot) on an
epoch cadence and answers ``get_rows`` from its local copy — zero wire
hops on the hot path, so inference QPS scales with replica processes
instead of loading the shards, and a shard briefly down costs serving
nothing while the snapshot is within bound.

The staleness contract (the part that makes a replica *usable*, not
just fast): every served read's data is at most ``staleness_s`` old,
measured from the moment the adopted snapshot's pull STARTED (the
conservative end — the data is at least that fresh). A background
thread refreshes every ``refresh_s``; a read that still finds the
snapshot over bound (refresh thread stalled, owner briefly down longer
than the cadence) does NOT serve stale — it performs/joins one
synchronous refresh first (single-flight; counted as ``deferred``) and
only serves once back under bound. The advertised bound is therefore
enforced, not just reported, and the serving bench asserts
measured-staleness <= bound in-run.

Snapshot pulls reuse the machinery the write plane already paid for:
the shard serves the copy off-lock under a PR-5 epoch pin (applies keep
flowing during the copy), streams big shards as PR-5 chunked replies
(decode overlaps the receive), and answers ``since``-version probes
with a tiny ``unchanged`` frame when nothing applied since the last
pull — an idle table costs the wire almost nothing per epoch.

Hot-row cache: with ``cache_rows > 0`` the replica keeps the table's
hottest rows — ranked by the PR-6 Space-Saving sketch merged across the
owning shards — as a device-resident array rebuilt atomically with each
snapshot swap (cache and snapshot are always the same epoch, so a
fully-cached request may be served from the device without mixing
versions). Hits/misses are measured per request: the bench compares the
MEASURED hit rate against the sketch's ``hit_rate_curve`` estimate —
closing the loop the sketch promised.

Reads can be gated by an :class:`~multiverso_tpu.serving.admission.
AdmissionController` (``admission=``): class ``"infer"`` reads over
budget shed with :class:`SheddingError` before touching any state.
Counters land on the Dashboard (``table[X].get.replica`` serve
latency/count, ``.shed``, ``.deferred``, ``.cache_hit`` / ``.cache_miss``)
— they ride MSG_STATS and the Zoo shutdown report like every monitor —
and first-class replica stats (lag epochs/seconds, versions, hit rate)
ride the MSG_STATS ``serving`` block via :func:`stats_snapshot`.

Module-import discipline: ps/service.py imports this module at module
level (flag registration before argv parse, the aggregator rule), so
nothing here may import the ps package at module scope — ps imports
stay inside methods.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from multiverso_tpu.serving.admission import (AdmissionController,
                                              SheddingError)
from multiverso_tpu.serving.hotcache import HotRowCache, match_positions
from multiverso_tpu.telemetry import hotkeys as _hotkeys
from multiverso_tpu.telemetry import memstats as _memstats
from multiverso_tpu.telemetry import tenants as _tenants
from multiverso_tpu.utils import config, log
from multiverso_tpu.utils import retry as _retry
from multiverso_tpu.utils.dashboard import Dashboard

config.define_float(
    "serving_refresh_s", 0.5,
    "read-replica snapshot refresh cadence seconds (the epoch "
    "cadence); each cycle pulls MSG_SNAPSHOT from every owning shard "
    "with a since-version, so an idle table costs one tiny "
    "'unchanged' frame per shard per epoch")
config.define_float(
    "serving_staleness_s", 2.0,
    "read-replica advertised staleness bound seconds: a served read's "
    "data is at most this old (age measured from the adopted pull's "
    "start). Reads finding the snapshot over bound refresh "
    "synchronously first (counted as 'deferred') — the bound is "
    "enforced, not just reported")
config.define_int(
    "serving_cache_rows", 0,
    "device-resident hot-row cache capacity per replica (rows), "
    "seeded from the shards' Space-Saving sketch top-K and rebuilt "
    "atomically with every snapshot swap; 0 = off. Hits/misses are "
    "measured per request (table[X].get.cache_hit/_miss)")
config.define_int(
    "serving_snapshot_chunk_rows", 4096,
    "rows per MSG_REPLY_CHUNK sub-frame of a replica snapshot pull; "
    "shards bigger than this stream chunked (decode overlaps the "
    "receive, PR-5 machinery). 0 = never chunk")
config.define_int(
    "serving_pull_retries", 2,
    "attempts per owning shard within one replica snapshot pull "
    "(utils/retry.py shared backoff, deadline = the pull's own "
    "ps_timeout budget): a transient shard blip — an injected reset, "
    "a mid-failover reconnect — retries inside the refresh instead of "
    "failing the whole cycle and burning a staleness epoch. 1 = the "
    "pre-ISSUE-14 fail-fast behavior")


class BoundUnsatisfiableError(RuntimeError):
    """The replica's staleness bound cannot be met: repeated fresh
    pulls each aged past the bound before a read could be served (the
    pull is slower than the advertised staleness, or the owners are
    mid-outage). Typed so a :class:`~multiverso_tpu.serving.pool.
    ReplicaPool` can fail over to a healthy sibling and only surface
    it when the WHOLE pool is over bound."""

# replica registry for the MSG_STATS "serving" block (weak: a replica's
# lifetime belongs to its owner, not to telemetry)
_REPLICAS: "weakref.WeakSet" = weakref.WeakSet()
# pool snapshot providers (serving/pool.py registers one per pool):
# zero-arg callables returning {table: merged-pool entry}. A pool's
# entry REPLACES its member replicas' individual entries — N replicas
# of one table in one process would otherwise last-write-wins each
# other in the block. Registered here (not imported from pool.py) so
# this module never imports pool at module scope.
_POOL_PROVIDERS: List = []


def register_pool_provider(fn) -> None:
    if fn not in _POOL_PROVIDERS:
        _POOL_PROVIDERS.append(fn)

# cache reseed cadence, in refresh epochs: pulling the shards' sketch is
# an extra stats RPC per owner, so it rides every Nth refresh (traffic
# shifts over minutes, snapshots over sub-seconds)
_CACHE_RESEED_EPOCHS = 8


def stats_snapshot() -> Dict[str, Dict]:
    """{table: replica stats} across this process's live replicas —
    the MSG_STATS ``serving`` block (ps/service.stats_payload). Pure
    JSON-safe data; one replica per table expected (the last
    constructed wins a name collision). Tables served by a
    :class:`~multiverso_tpu.serving.pool.ReplicaPool` report the
    pool's MERGED entry instead (summed counters + a ``"pool"``
    detail block — per-member route share, lag, degraded flag — the
    aggregator and mvtop's pool panel consume it)."""
    out: Dict[str, Dict] = {}
    for rep in list(_REPLICAS):
        try:
            s = rep.stats()
            out[s["table"]] = s
        except Exception:   # noqa: BLE001 — telemetry never raises
            pass
    for prov in list(_POOL_PROVIDERS):
        try:
            for tname, ent in (prov() or {}).items():
                out[tname] = ent
        except Exception:   # noqa: BLE001 — telemetry never raises
            pass
    return out


class ReadReplica:
    """Bounded-staleness read copy of one row-partitioned async table.

    Construct from the table object (``ReadReplica(table)``) or
    standalone from a context + spec (a serving sidecar that never
    constructs the table)::

        rep = ReadReplica(ctx=ctx, name="emb", num_row=N, num_col=D)

    ``start=True`` (default) runs the background refresh thread; call
    :meth:`close` to stop it. ``start=False`` = manual mode: the owner
    drives :meth:`refresh` (tests, step-driven serving loops) — the
    staleness bound is still enforced via deferred synchronous
    refreshes on reads.
    """

    def __init__(self, table=None, *, ctx=None, name: Optional[str] = None,
                 num_row: Optional[int] = None,
                 num_col: Optional[int] = None, dtype=np.float32,
                 refresh_s: Optional[float] = None,
                 staleness_s: Optional[float] = None,
                 cache_rows: Optional[int] = None,
                 admission: Optional[AdmissionController] = None,
                 start: bool = True):
        if table is not None:
            ctx = table.ctx
            name = table.name
            num_row, num_col = table.num_row, table.num_col
            dtype = table.dtype
            ranges = list(table._ranges)
        else:
            if ctx is None or name is None or not num_row or not num_col:
                raise ValueError("standalone ReadReplica needs ctx, name, "
                                 "num_row and num_col")
            # identical partition math to AsyncMatrixTable: (rank, lo, hi)
            # of every non-empty shard
            rows_per = -(-int(num_row) // ctx.world)
            ranges = [(r, min(r * rows_per, num_row),
                       min((r + 1) * rows_per, num_row))
                      for r in range(ctx.world)]
            ranges = [(r, a, b) for r, a, b in ranges if b > a]
        self.ctx = ctx
        self.name = str(name)
        self.num_row, self.num_col = int(num_row), int(num_col)
        self.dtype = np.dtype(dtype)
        self._ranges: List[Tuple[int, int, int]] = ranges
        self.refresh_s = (config.get_flag("serving_refresh_s")
                          if refresh_s is None else float(refresh_s))
        self.staleness_s = (config.get_flag("serving_staleness_s")
                            if staleness_s is None else float(staleness_s))
        self.cache_capacity = (config.get_flag("serving_cache_rows")
                               if cache_rows is None else int(cache_rows))
        self.admission = admission

        # snapshot state: (_data, _versions, _pulled_at, _epoch) swap
        # together under _swap_lock; readers take a reference and
        # compute off it (the buffer is never mutated in place — a
        # refresh builds a fresh one, so held references stay
        # epoch-consistent, the PR-5 pin idea without the pin)
        self._swap_lock = threading.Lock()
        self._data: Optional[np.ndarray] = None
        self._versions: Dict[int, int] = {}
        # per-rank shard incarnation generation (failover plane): the
        # since-version dedupe token is (gen, version) — a respawned
        # shard's counter may coincide with a pre-crash version while
        # the content diverged, and the shard only answers "unchanged"
        # when BOTH match
        self._gens: Dict[int, int] = {}
        self._pulled_at = -float("inf")   # monotonic; -inf = never
        self._epoch = 0
        self._last_refresh_ms = 0.0
        self._unchanged_pulls = 0         # shard replies deduped by since=
        # hot-row cache (same epoch as _data by construction): the shared
        # serving/hotcache.HotRowCache under the replica discipline —
        # whole-cache install at each snapshot swap, never mutated between
        self._hot_ids: Optional[np.ndarray] = None
        self._cache = HotRowCache(self.num_col, self.dtype,
                                  capacity=self.cache_capacity,
                                  name=self.name)
        # single-flight refresh
        self._refresh_lock = threading.Lock()
        # serving counters (ints for stats(); Dashboard monitors beside
        # them for MSG_STATS/shutdown-report visibility)
        self._served = 0
        self._shed = 0
        self._deferred = 0
        self._hits = 0
        self._misses = 0
        # pull-health counters (the pool's demotion signal): total
        # failed refresh cycles + the CONSECUTIVE failure streak
        # (reset by any successful pull)
        self._pull_failures = 0
        self._consec_pull_failures = 0
        base = f"table[{self.name}].get"
        self._mon_replica = Dashboard.get(base + ".replica")
        self._mon_shed = Dashboard.get(base + ".shed")
        self._mon_deferred = Dashboard.get(base + ".deferred")
        self._mon_cache_hit = Dashboard.get(base + ".cache_hit")
        self._mon_cache_miss = Dashboard.get(base + ".cache_miss")

        self._closed = False
        self._thread: Optional[threading.Thread] = None
        # in-flight refresh staging copy (bytes; nonzero only while a
        # pull is assembling its fresh buffer) — the memory ledger's
        # view of the transient second table each refresh costs
        self._staging_nb = 0
        _REPLICAS.add(self)
        _memstats.register(f"replica[{self.name}]", self)
        if start:
            self.start()

    def memory_stats(self) -> Dict[str, Any]:
        """Byte-ledger gauges (telemetry/memstats.py, pull-only): the
        adopted snapshot buffer, the device-resident hot-row cache, and
        the transient refresh staging copy."""
        with self._swap_lock:
            data = self._data
        cstats = self._cache.memory_stats()
        return {
            "snapshot_bytes": int(getattr(data, "nbytes", 0) or 0)
            if data is not None else 0,
            "cache_device_bytes": cstats["device_bytes"],
            "cache_rows": cstats["rows"],
            "staging_bytes": int(self._staging_nb),
        }

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    def start(self) -> "ReadReplica":
        if self._thread is None:
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"mv-replica-{self.name}")
            self._thread.start()
        return self

    def close(self) -> None:
        self._closed = True
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=10.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.refresh_s):
            if self._closed:
                return
            try:
                self.refresh()
            except Exception as e:   # noqa: BLE001 — an owner briefly
                # down must not kill the cadence; reads stay served
                # from the in-bound snapshot and the bound turns a
                # LONG outage into refused (deferred-refresh) reads,
                # never silently-stale ones
                log.debug("replica[%s] refresh failed: %s: %s",
                          self.name, type(e).__name__, e)

    # ------------------------------------------------------------------ #
    # refresh (snapshot pull)
    # ------------------------------------------------------------------ #
    def refresh(self, need_from: Optional[float] = None) -> bool:
        """One synchronous snapshot pull, single-flight: concurrent
        callers serialize, and a caller that waited out someone else's
        pull returns without pulling again IF that pull STARTED at or
        after ``need_from`` (default: this call's entry time) — only
        then does the adopted snapshot cover every write acked before
        the caller asked. (Comparing against the previous pull's stamp
        instead would let a background pull that began BEFORE the
        caller's writes satisfy the dedupe and serve a snapshot
        missing them — the read-your-acked-writes contract refresh()
        gives quiescing callers.) Bound-enforcement callers relax
        ``need_from`` to ``now - staleness_s``: they only need SOME
        in-bound pull, and the strict default would turn K readers
        blocked on one stale snapshot into K serialized full-table
        pulls against an already-degraded owner. Returns True when
        THIS call pulled."""
        if self._closed:
            # a killed/closed replica must not quietly resurrect
            # itself through a health probe's refresh — the pool's
            # demotion of it is permanent until a NEW replica exists
            raise RuntimeError(f"replica[{self.name}] is closed")
        if need_from is None:
            need_from = time.monotonic()
        with self._refresh_lock:
            if self._pulled_at >= need_from:
                return False   # a satisfying concurrent refresh landed
            try:
                self._pull_once()
            except Exception:
                # pull-health bookkeeping for the pool's demotion
                # logic: a replica whose pulls keep failing is routed
                # around, not retried into
                self._pull_failures += 1
                self._consec_pull_failures += 1
                raise
            self._consec_pull_failures = 0
            return True

    def pull_health(self) -> Dict[str, Any]:
        """(pool surface) total + consecutive failed refresh cycles."""
        return {"failures": self._pull_failures,
                "consecutive": self._consec_pull_failures}

    def _make_sink(self, buf: np.ndarray):
        """Chunk sink scattering MSG_REPLY_CHUNK sub-frames of one
        shard's snapshot stream into ``buf`` (runs on the peer's recv
        thread; PR-5 contract — failures surface on the final frame)."""
        from multiverso_tpu.ps import wire as wire_mod
        cols, dtype = self.num_col, self.dtype

        def sink(cmeta, arrays):
            r0, n = int(cmeta["row0"]), int(cmeta["rows"])
            buf[r0:r0 + n] = wire_mod.decode_payload(
                arrays, cmeta.get("wire", "none"), (n, cols), dtype)

        return sink

    def _pull_once(self) -> None:
        from multiverso_tpu.ps import service as svc
        from multiverso_tpu.ps import wire as wire_mod
        from multiverso_tpu.telemetry import flightrec as _flight
        from multiverso_tpu.telemetry import trace as _trace
        t_start = time.monotonic()
        # PR-3 trace plumbing (the PR-8 coverage gap): one trace ID per
        # refresh cycle rides every shard's snapshot request meta, so
        # the client-side replica.pull span and each shard's
        # snapshot.serve span stitch on one timeline like gets/adds
        tr = _trace.new_id() if _trace.enabled() else None
        t_wall0 = time.time() if tr is not None else 0.0
        service = self.ctx.service
        chunk = int(config.get_flag("serving_snapshot_chunk_rows"))

        def dispatch(rank, lo, hi):
            # tenant-stamped like add/get frames (the refresh thread has
            # no per-call scope, so this is the process's tenant_id
            # flag): the shard attributes pull bytes to the tenant the
            # replica serves, and the stamp punts the frame exactly as
            # the other modern meta keys do
            meta: Dict[str, Any] = wire_mod.with_tenant(
                wire_mod.with_trace({
                    "table": self.name,
                    "since": int(self._versions.get(rank, -1)),
                    "since_gen": int(self._gens.get(rank, -1))}, tr),
                _tenants.current())
            sink = buf = None
            if chunk > 0 and (hi - lo) > chunk and rank != self.ctx.rank:
                buf = np.empty((hi - lo, self.num_col), self.dtype)
                meta["chunk"] = chunk
                sink = self._make_sink(buf)
            fut = service.request(rank, svc.MSG_SNAPSHOT, meta, (),
                                  chunk_sink=sink)
            return fut, buf

        reqs = []
        for rank, lo, hi in self._ranges:
            fut, buf = dispatch(rank, lo, hi)
            reqs.append((rank, lo, hi, fut, buf))
        timeout = config.get_flag("ps_timeout")
        # shared retry policy (utils/retry.py) with deadline
        # propagation: the whole pull — every shard's attempts AND the
        # backoff sleeps between them — fits one ps_timeout budget, so
        # a transient shard blip (injected reset, mid-failover
        # reconnect) retries inside the refresh instead of burning a
        # staleness epoch, while a real outage still fails in bounded
        # time for _grab_fresh to judge
        attempts = max(int(config.get_flag("serving_pull_retries")), 1)
        deadline = _retry.deadline_in(timeout)
        backoff = _retry.Backoff(base_s=0.05, cap_s=1.0)
        changed: Dict[Tuple[int, int], np.ndarray] = {}
        versions = dict(self._versions)
        gens = dict(self._gens)
        for rank, lo, hi, fut, buf in reqs:
            rmeta = arrays = None
            for k in range(attempts):
                try:
                    rmeta, arrays = svc.await_reply(
                        fut, max(_retry.remaining_s(deadline, timeout),
                                 0.05),
                        f"replica[{self.name}] snapshot from rank "
                        f"{rank}")
                    break
                except svc.PSError:
                    if k + 1 >= attempts or not backoff.sleep(
                            k, deadline):
                        raise
                    log.debug("replica[%s] snapshot pull from rank %d "
                              "failed (attempt %d); retrying",
                              self.name, rank, k + 1)
                    fut, buf = dispatch(rank, lo, hi)   # fresh request
            versions[rank] = int(rmeta.get("version", -1))
            gens[rank] = int(rmeta.get("gen", 0))
            if rmeta.get("unchanged"):
                self._unchanged_pulls += 1
                continue
            if rmeta.get("chunks"):
                rows = buf   # the sinks already scattered the stream
            else:
                rows = np.asarray(arrays[0], self.dtype).reshape(
                    hi - lo, self.num_col)
            changed[(lo, hi)] = rows
        # reseed the hot-id set on a cadence (an extra stats RPC per
        # owner — see _CACHE_RESEED_EPOCHS); BEFORE the swap so the
        # fresh cache is built against the fresh snapshot below
        if (self.cache_capacity > 0
                and self._epoch % _CACHE_RESEED_EPOCHS == 0):
            self._reseed_hot_ids()
        # assemble OFF the reader-facing lock: _refresh_lock already
        # makes pulls single-flight (we are the only mutator of
        # _data), and holding _swap_lock across a production-sized
        # table copy + a device transfer would stall every concurrent
        # get_rows for the duration of each refresh — the same
        # off-lock discipline PR 5 applied to the shard read path.
        # Readers only ever need the lock for a reference grab.
        cur = self._data   # sole-writer read; rebind is swap-locked
        if cur is None:
            staging = np.zeros((self.num_row, self.num_col), self.dtype)
        elif changed:
            staging = cur.copy()
        else:
            staging = cur   # nothing applied anywhere: the epoch
            #                 advances, the buffer stays
        if staging is not cur:
            self._staging_nb = int(staging.nbytes)   # ledger gauge
        for (lo, hi), rows in changed.items():
            staging[lo:hi] = rows
        cache_ids = cache_dev = None
        if self.cache_capacity > 0:
            cache_ids, cache_dev = self._build_cache(staging)
        with self._swap_lock:
            snapshot_moved = staging is not cur
            self._data = staging
            self._versions = versions
            self._gens = gens
            self._pulled_at = t_start   # pull START: conservative age
            self._epoch += 1
            self._last_refresh_ms = (time.monotonic() - t_start) * 1e3
            if cache_ids is not None:
                # atomic whole-cache replace (hotcache install: the
                # replica discipline) — cache and snapshot swap in under
                # the same lock hold, so they are always the same epoch
                self._cache.install(cache_ids, None,
                                    device_rows=cache_dev)
            elif snapshot_moved:
                # the snapshot content moved but no same-epoch cache was
                # built (no hot ids yet / device placement failed): DROP
                # the old cache at the swap commit. Keeping it would (a)
                # pin a full device-resident row block from a RETIRED
                # epoch until whenever the next successful build lands —
                # the same shape as the PR-5 _pin_buf identity-anchor
                # hoard — and (b) let cache_lookup serve rows the
                # adopted snapshot no longer contains, breaking the
                # "cache and snapshot are always the same epoch"
                # contract the class docstring promises.
                self._cache.clear()
            self._staging_nb = 0
        # flight recorder + trace span: one refresh = one event/span, so
        # serving refresh traffic appears on the same timeline as the
        # data plane (nbytes = rows actually re-shipped this cycle)
        _flight.record(
            _flight.EV_REPLICA_PULL,
            nbytes=sum(r.nbytes for r in changed.values()),
            note=f"replica[{self.name}] epoch {self._epoch}")
        if tr is not None:
            _trace.add_span(
                "replica.pull", t_wall0, time.time(), trace=tr,
                cat="serving",
                args={"table": self.name, "epoch": int(self._epoch),
                      "changed": len(changed),
                      "shards": len(self._ranges)})

    # ------------------------------------------------------------------ #
    # hot-row cache (Space-Saving sketch seeded, PR-6 loop closed)
    # ------------------------------------------------------------------ #
    def _reseed_hot_ids(self) -> None:
        """Pull the owning shards' Space-Saving sketches over MSG_STATS,
        merge (shards partition the id space — exact), and keep the
        top-``cache_capacity`` row ids as the cache seed. Telemetry is
        best-effort: a failed stats pull keeps the previous seed."""
        sketches = []
        for rank, _lo, _hi in self._ranges:
            try:
                payload = self.ctx.service.stats(rank)
                sk = (payload.get("shards", {})
                      .get(self.name, {}).get("hotkeys"))
                if sk:
                    sketches.append(sk)
            except Exception as e:   # noqa: BLE001 — best-effort
                log.debug("replica[%s] sketch pull from rank %d failed: "
                          "%s", self.name, rank, e)
        if not sketches:
            return
        merged = _hotkeys.merge_sketches(sketches)
        ids = [k for k, _c, _e in merged.get("items", [])
               if 0 <= k < self.num_row][: self.cache_capacity]
        if ids:
            self._hot_ids = np.asarray(sorted(ids), np.int64)

    def _build_cache(self, data: np.ndarray):
        """Build the device-resident cache arrays for ``data`` — OFF
        the swap lock (the gather + device put may be expensive); the
        caller installs the result under the same lock hold that swaps
        the snapshot in, so cache rows and snapshot rows are always
        the same epoch. Returns ``(ids, device_rows)`` or ``(None,
        None)`` — the swap then DROPS the previous cache when the
        snapshot content moved (an old-epoch device cache must neither
        stay pinned nor serve retired rows) and keeps it only across
        unchanged epochs."""
        ids = self._hot_ids
        if ids is None or ids.size == 0:
            return None, None
        try:
            import jax.numpy as jnp
            return ids, jnp.asarray(data[ids])
        except Exception as e:   # noqa: BLE001 — a device placement
            # failure must not fail the snapshot swap; the swap drops
            # the cache for this epoch (served from host until a build
            # succeeds) rather than serving a retired epoch's rows
            log.debug("replica[%s] cache build failed: %s",
                      self.name, e)
            return None, None

    def cache_lookup(self, row_ids) -> Optional[Any]:
        """Device-resident rows for ``row_ids`` when EVERY id is cached
        (same epoch as the last adopted snapshot), else None. For
        inference pipelines that consume rows on-device; hit/miss
        accounting stays with :meth:`get_rows`. (The membership math
        and the fused serve live in serving/hotcache — shared with the
        training-path cache.)"""
        return self._cache.take_device(row_ids)

    # ------------------------------------------------------------------ #
    # the read path
    # ------------------------------------------------------------------ #
    def age_s(self) -> float:
        """Seconds since the adopted snapshot's pull started (inf =
        never refreshed)."""
        with self._swap_lock:
            return time.monotonic() - self._pulled_at

    def _grab_fresh(self, tn: Optional[str] = None):
        """Enforce the staleness bound and take the serving snapshot in
        ONE atomic step: the age check, the buffer grab, and the served
        age are measured under the same lock hold — a read descheduled
        between a passing check and the grab can never serve (or
        report) an over-bound age. A snapshot found over bound
        refreshes synchronously (single-flight; counted as deferred)
        and re-checks. Raises the pull's error when the owners are
        unreachable AND the snapshot is out of bound: refusing to serve
        beats serving silently-stale. Returns (data, age_s, cache_ids)."""
        for _ in range(3):
            with self._swap_lock:
                age = time.monotonic() - self._pulled_at
                if self._data is not None and age <= self.staleness_s:
                    return self._data, age, self._cache.ids()
            self._deferred += 1
            self._mon_deferred.incr()
            # a deferred serve is per-tenant degradation evidence for
            # the noisy-neighbor sweep (the reader who paid the
            # synchronous refresh is the one the storm displaced)
            _tenants.LEDGER.note_deferred(self.name, tn)
            # any pull started within the bound satisfies this reader —
            # K concurrent over-bound readers then share ONE pull
            # instead of performing K serialized ones
            self.refresh(need_from=time.monotonic() - self.staleness_s)
            # loop: a refresh that lost the single-flight race may have
            # adopted a pull started just before the bound — re-check
        # three fresh pulls each aged past the bound before serving:
        # the pull itself is slower than the advertised staleness, so
        # the bound is unsatisfiable as configured — refuse loudly
        # rather than quietly violate the contract. Typed: a
        # ReplicaPool catches this, fails over to a healthy sibling,
        # and re-raises only when the WHOLE pool is over bound
        raise BoundUnsatisfiableError(
            f"replica[{self.name}]: staleness bound {self.staleness_s}s "
            f"is below the snapshot pull time "
            f"({self._last_refresh_ms:.1f} ms) — raise "
            "serving_staleness_s or shrink the table")

    def get_rows(self, row_ids, cls: str = "infer",
                 out: Optional[np.ndarray] = None,
                 with_age: bool = False,
                 tenant: Optional[str] = None):
        """Serve rows from the bounded-staleness snapshot.

        ``cls`` is the admission class ("infer" reads may shed with
        :class:`SheddingError`; "train" bypasses unless explicitly
        limited). ``out`` takes the reply in place when it is an exact
        (n, cols) C-contiguous buffer of the table dtype.
        ``with_age=True`` returns ``(rows, age_s)`` with the age of the
        served snapshot measured atomically with the buffer grab — the
        bench's staleness evidence. ``tenant`` overrides the caller's
        :func:`tenants.tenant_scope` / ``tenant_id`` attribution for
        this read (``""`` = explicitly the default tenant)."""
        t0 = time.perf_counter()
        if self._closed:
            # serving off a dead member's last snapshot would mask a
            # replica kill exactly where the pool needs to observe it
            raise RuntimeError(f"replica[{self.name}] is closed")
        ids = np.asarray(row_ids, np.int64).reshape(-1)
        if ids.size == 0:
            raise ValueError("empty row_ids")
        if ids.min() < 0 or ids.max() >= self.num_row:
            raise IndexError(f"row id out of range [0, {self.num_row})")
        tn = _tenants.current() if tenant is None else (tenant or None)
        if self.admission is not None and not self.admission.admit(
                self.name, cls, tenant=tn):
            self._shed += 1
            self._mon_shed.incr()
            _tenants.LEDGER.note_shed(self.name, tn)
            raise SheddingError(
                f"replica[{self.name}]: {cls} read shed by admission "
                "control")
        data, age, cids = self._grab_fresh(tn)
        if (out is not None and isinstance(out, np.ndarray)
                and out.shape == (ids.size, self.num_col)
                and out.dtype == self.dtype and out.flags.c_contiguous):
            np.take(data, ids, axis=0, out=out)
            rows = out
        else:
            rows = data[ids]
        if cids is not None and cids.size:
            _pos, ok = match_positions(cids, ids)
            hits = int(np.count_nonzero(ok))
            if hits:
                self._hits += hits
                self._mon_cache_hit.incr(hits)
            if ids.size - hits:
                self._misses += ids.size - hits
                self._mon_cache_miss.incr(ids.size - hits)
        self._served += 1
        ms = (time.perf_counter() - t0) * 1e3
        self._mon_replica.observe_ms(ms)
        # the serve-side tenant ledger: latency + served age per tenant
        # (one entry per read, at the member that actually served — the
        # pool's failover loop reaches exactly one member per read)
        _tenants.LEDGER.note_serve(self.name, tn, ms, age_s=age,
                                   bound_s=self.staleness_s)
        return (rows, age) if with_age else rows

    # ------------------------------------------------------------------ #
    def stats(self) -> Dict[str, Any]:
        """First-class replica stats for the MSG_STATS ``serving``
        block and mvtop's serving panel. JSON-safe."""
        with self._swap_lock:
            age = time.monotonic() - self._pulled_at
            epoch = self._epoch
            versions = {str(r): int(v) for r, v in self._versions.items()}
            cache_rows = len(self._cache)
            refresh_ms = self._last_refresh_ms
        total = self._hits + self._misses
        out: Dict[str, Any] = {
            "table": self.name, "epoch": epoch,
            # replica lag: seconds behind the shards (age of the
            # adopted snapshot) + the epoch count, mvtop's two columns
            "age_s": (None if age == float("inf") else round(age, 3)),
            "bound_s": round(self.staleness_s, 3),
            "refresh_s": round(self.refresh_s, 3),
            "refresh_ms": round(refresh_ms, 3),
            "versions": versions,
            "unchanged_pulls": self._unchanged_pulls,
            "served": self._served, "shed": self._shed,
            "deferred": self._deferred,
            "pull_failures": self._pull_failures,
            "pull_failures_consecutive": self._consec_pull_failures,
            "cache_rows": cache_rows,
            "cache_hits": self._hits, "cache_misses": self._misses,
            "cache_hit_rate": (round(self._hits / total, 4)
                               if total else None),
        }
        if self.admission is not None:
            out["admission"] = self.admission.stats()
        return out
