"""HotRowCache: device-resident hot-row cache shared by serving and training.

PR 8 built a sketch-seeded device row cache INSIDE ``serving/replica.py``
(epoch-swapped with each snapshot, read-only between swaps); PR 11 lifts
the mechanism into this module so the *training* read path
(``ps/tables.AsyncMatrixTable``, flag ``train_cache_rows``) can use the
same machinery with a different consistency discipline:

* **replica discipline** (:meth:`install` / :meth:`take_device`): the
  owner atomically replaces the whole cache at an epoch boundary; rows
  are never mutated in place. The replica keeps its own swap lock — the
  cache is just the (ids, rows) pair + the membership math.
* **training discipline** (:meth:`fill` / :meth:`apply_delta` /
  :meth:`drop`): rows enter when a get reply delivers them, local pushes
  either *write through* (stateless updaters, raw wire — the cached copy
  tracks the server bit-for-bit for a single writer) or *invalidate*
  (drop the pushed ids, the always-safe default), and the device mirror
  is maintained incrementally with the jitted gather/scatter kernels in
  ``ops/row_assemble.py`` instead of rebuilt per mutation.

Thread safety: every public method takes the internal lock; the device
mirror is built lazily outside it and committed under it (the PR-5
off-lock discipline — a device transfer must not stall concurrent
lookups).

Module-import discipline (the serving-package rule): ``ps/service.py``
imports the serving package at module level, so nothing here may import
the ps package at module scope. jax imports stay inside methods — a
cache used host-only never touches the device runtime.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

from multiverso_tpu.telemetry import memstats as _memstats
from multiverso_tpu.utils import config
from multiverso_tpu.utils.dashboard import Dashboard

config.define_int(
    "train_cache_rows", 0,
    "hot-row TRAINING cache capacity per matrix table (rows), the "
    "ISSUE-11 training read path: cached rows serve gets locally (device "
    "block when fully covered) and only the residual cold rows cross the "
    "wire. 0 = off. Hits/misses land on "
    "table[X].get.train_cache_hit/_miss")
config.define_string(
    "train_cache_mode", "auto",
    "training-cache push discipline: 'writethrough' applies local pushes "
    "to the cached copy (bit-identical to the shard for a default-updater "
    "table on a lossless wire — the single-writer WE fast path), "
    "'invalidate' drops pushed rows (always safe), 'auto' picks "
    "writethrough when eligible else invalidate")
config.define_int(
    "train_cache_refresh_gets", 0,
    "drop the whole training cache every N get calls so rows re-fetch "
    "from the shards — bounds how long OTHER workers' pushes stay "
    "invisible to a writethrough cache (SSP-style read staleness of ~N "
    "blocks). 0 = never (exact single-writer mode)")


def match_positions(cached_ids: np.ndarray, ids: np.ndarray
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """(positions, hit_mask) of ``ids`` inside the SORTED ``cached_ids``
    — the one membership predicate behind replica hit accounting,
    cache_lookup and the training-path hit/cold split. ``positions`` is
    only meaningful where ``hit_mask`` is True."""
    ids = np.asarray(ids, np.int64).reshape(-1)
    if cached_ids is None or cached_ids.size == 0:
        return np.zeros(ids.size, np.int64), np.zeros(ids.size, bool)
    pos = np.searchsorted(cached_ids, ids)
    ok = (pos < cached_ids.size) & (
        cached_ids[np.minimum(pos, cached_ids.size - 1)] == ids)
    return pos, ok


class HotRowCache:
    """Sorted-id row cache with a host store and a lazy device mirror."""

    def __init__(self, num_col: int, dtype=np.float32, capacity: int = 0,
                 name: str = ""):
        self.num_col = int(num_col)
        self.dtype = np.dtype(dtype)
        self.capacity = int(capacity)
        self.name = name
        self._lock = threading.RLock()
        self._ids: Optional[np.ndarray] = None      # sorted int64
        self._rows: Optional[np.ndarray] = None     # (n, num_col) host
        self._dev = None                            # lazy device mirror
        self._dev_epoch = -1
        self._epoch = 0   # bumps on every content change

    # ------------------------------------------------------------------ #
    # replica discipline: atomic whole-cache replace
    # ------------------------------------------------------------------ #
    def install(self, ids: Optional[np.ndarray], rows: Optional[Any],
                device_rows: Any = None) -> None:
        """Replace the whole cache: ``ids`` sorted, ``rows`` the host
        rows aligned with them (``device_rows`` optionally pre-built by
        the caller off-lock, the replica's build-then-commit shape).
        ``ids=None`` clears."""
        with self._lock:
            if ids is None or getattr(ids, "size", 0) == 0:
                self._ids = self._rows = self._dev = None
            else:
                self._ids = np.asarray(ids, np.int64).reshape(-1)
                self._rows = (None if rows is None
                              else np.asarray(rows, self.dtype))
                self._dev = device_rows
            self._epoch += 1
            self._dev_epoch = self._epoch if device_rows is not None else -1

    def clear(self) -> None:
        self.install(None, None)

    # ------------------------------------------------------------------ #
    # membership / reads
    # ------------------------------------------------------------------ #
    def __len__(self) -> int:
        with self._lock:
            return 0 if self._ids is None else int(self._ids.size)

    def ids(self) -> Optional[np.ndarray]:
        with self._lock:
            return self._ids

    def lookup(self, ids) -> Tuple[np.ndarray, np.ndarray]:
        """(positions, hit_mask) against the current cache content.
        Test/diagnostic primitive: positions are only stable while the
        caller excludes fills/drops — production serves go through
        ``TrainRowCache.serve_into``/``serve_full`` (atomic)."""
        with self._lock:
            return match_positions(self._ids, ids)

    def covers(self, ids) -> bool:
        """True when EVERY id is currently cached."""
        _, ok = self.lookup(ids)
        return bool(ok.all()) if ok.size else False

    def gather_into(self, buf: np.ndarray, sel: np.ndarray,
                    pos: np.ndarray) -> bool:
        """``buf[sel] = rows[pos]`` under the lock (training hit fill).
        Returns False when the content moved since the caller's lookup
        resolved (caller falls back to the wire)."""
        with self._lock:
            if self._rows is None or (pos.size and
                                      int(pos.max()) >= self._rows.shape[0]):
                return False
            buf[sel] = self._rows[pos]
            return True

    def take_device(self, row_ids) -> Optional[Any]:
        """Device rows for ``row_ids`` when EVERY id is cached and a
        device mirror exists — the replica's ``cache_lookup`` serve
        (same epoch as the install that built the mirror)."""
        with self._lock:
            cids, cdev = self._ids, self._dev
        if cids is None or cdev is None:
            return None
        pos, ok = match_positions(cids, row_ids)
        if not ok.size or not bool(ok.all()):
            return None
        import jax.numpy as jnp
        return jnp.take(cdev, jnp.asarray(pos), axis=0)

    def device_block(self, row_ids, bucket: int) -> Optional[Any]:
        """Fused gather+pad serve: the cached rows for ``row_ids`` as a
        zero-padded ``(bucket, num_col)`` DEVICE block (the training
        consumer's scan layout) — one jitted gather/pad program
        (ops/row_assemble), no host assembly. None unless every id is
        cached with a live device mirror."""
        with self._lock:
            cids = self._ids
            if cids is None:
                return None
            # coverage first (one host searchsorted): a miss block must
            # not pay the whole-cache host copy + device upload it can
            # never use — in invalidate mode every block after a push is
            # such a miss (the push dropped the trained rows and the
            # mirror with them)
            pos, ok = match_positions(cids, row_ids)
            if not ok.size or not bool(ok.all()) or int(ok.size) > bucket:
                return None
            cdev = self._dev
            if cdev is None or self._dev_epoch != self._epoch:
                cdev = self._ensure_device_locked()
                if cdev is None:
                    return None
        from multiverso_tpu.ops import row_assemble
        return row_assemble.gather_pad_rows(cdev, pos, bucket)

    # ------------------------------------------------------------------ #
    # training discipline: incremental fills / pushes
    # ------------------------------------------------------------------ #
    def fill(self, ids: np.ndarray, rows: np.ndarray,
             admit: Optional[np.ndarray] = None) -> int:
        """Merge freshly-fetched rows into the cache. ``ids`` sorted
        unique (the get path's _prep contract); ``admit`` optionally
        restricts which of them may ENTER (hot-set gating) — ids already
        cached always refresh in place. Respects ``capacity``: when the
        merge would overflow, only refreshes survive. Returns rows
        admitted or refreshed. Drops the device mirror (rebuilt lazily);
        refreshing in place keeps it patchable but a membership change
        cannot be patched."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        rows = np.asarray(rows, self.dtype).reshape(-1, self.num_col)
        with self._lock:
            if self._ids is None:
                take = ids if admit is None else ids[admit]
                takerows = rows if admit is None else rows[admit]
                if self.capacity and take.size > self.capacity:
                    take, takerows = (take[: self.capacity],
                                      takerows[: self.capacity])
                if take.size == 0:
                    return 0
                order = np.argsort(take, kind="stable")   # invariant:
                self._ids = take[order]                   # _ids sorted
                self._rows = takerows[order]
                self._dev = None
                self._epoch += 1
                return int(take.size)
            pos, ok = match_positions(self._ids, ids)
            n = 0
            if np.any(ok):
                self._rows[pos[ok]] = rows[ok]
                n += int(np.count_nonzero(ok))
            new = ~ok if admit is None else (~ok & admit)
            room = ((self.capacity - self._ids.size)
                    if self.capacity else int(np.count_nonzero(new)))
            if np.any(new) and room > 0:
                nidx = np.flatnonzero(new)[:room]
                merged_ids = np.concatenate([self._ids, ids[nidx]])
                merged_rows = np.concatenate([self._rows, rows[nidx]])
                order = np.argsort(merged_ids, kind="stable")
                self._ids = merged_ids[order]
                self._rows = merged_rows[order]
                n += int(nidx.size)
            if n:
                self._dev = None
                self._epoch += 1
            return n

    def apply_delta(self, ids: np.ndarray, delta: np.ndarray) -> None:
        """Write-through: add a pushed delta to the cached copies (ids
        unique — the add path's _prep contract; missing ids are
        ignored). Host rows update with the same IEEE f32 add the
        shard's default updater performs; the device mirror is patched
        IN-GRAPH with the jitted scatter-add (ops/row_assemble) instead
        of dropped — the mirror stays warm across every push."""
        with self._lock:
            if self._ids is None:
                return
            pos, ok = match_positions(self._ids, ids)
            if not np.any(ok):
                return
            hit_pos = pos[ok]
            d = np.asarray(delta, self.dtype).reshape(
                -1, self.num_col)[ok]
            self._rows[hit_pos] += d
            if self._dev is not None and self._dev_epoch == self._epoch:
                from multiverso_tpu.ops import row_assemble
                try:
                    self._dev = row_assemble.scatter_add_rows(
                        self._dev, hit_pos, d)
                except Exception:   # noqa: BLE001 — a device failure
                    self._dev = None   # costs the mirror, never the data
            self._epoch += 1
            if self._dev is not None:
                self._dev_epoch = self._epoch

    def drop(self, ids) -> int:
        """Invalidate: remove ``ids`` from the cache (push invalidation,
        the always-safe discipline). Returns rows dropped."""
        with self._lock:
            if self._ids is None:
                return 0
            pos, ok = match_positions(self._ids, ids)
            n = int(np.count_nonzero(ok))
            if n == 0:
                return 0
            if n == self._ids.size:
                self._ids = self._rows = self._dev = None
            else:
                keep = np.ones(self._ids.size, bool)
                keep[pos[ok]] = False
                self._ids = self._ids[keep]
                self._rows = self._rows[keep]
                self._dev = None
            self._epoch += 1
            return n

    # ------------------------------------------------------------------ #
    # device mirror
    # ------------------------------------------------------------------ #
    def _ensure_device_locked(self):
        """Build the device mirror from the host rows (caller holds the
        lock; the put is small enough to hold it — training fills are
        block-cadence, not request-cadence).

        The put MUST copy: jax's CPU backend zero-copy-aliases aligned
        host buffers, and this class mutates ``_rows`` IN PLACE
        (apply_delta's ``+=``, fill's refresh) — a mirror aliasing that
        memory would let a lazy gather dispatched before a push read
        post-push values, an allocator-alignment-dependent bit
        divergence the ISSUE-11 parity suite caught in the wild."""
        if self._rows is None:
            return None
        try:
            import jax.numpy as jnp

            from multiverso_tpu.ops import row_assemble
            # height padded to a power-of-two bucket: the mirror's H is
            # a jit-trace dimension of every gather/scatter program, and
            # an exact H would recompile them each time a fill grows the
            # cache (the bench's zero-steady-recompiles gate); the pad
            # rows are zeros past every valid position, never addressed
            h = self._rows.shape[0]
            hb = row_assemble.bucket_rows(h)
            host = np.zeros((hb, self.num_col), self.dtype)
            host[:h] = self._rows
            self._dev = jnp.asarray(host)
            self._dev_epoch = self._epoch
            return self._dev
        except Exception:   # noqa: BLE001 — host-only environments
            return None

    # ------------------------------------------------------------------ #
    def memory_stats(self) -> Dict[str, Any]:
        """PR-10 byte-ledger gauges (pull-only)."""
        with self._lock:
            rows = 0 if self._ids is None else int(self._ids.size)
            host_nb = (0 if self._rows is None
                       else int(self._rows.nbytes))
            dev_nb = (int(getattr(self._dev, "nbytes", 0) or 0)
                      if self._dev is not None else 0)
        return {"rows": rows, "host_bytes": host_nb,
                "device_bytes": dev_nb, "capacity": self.capacity}


class TrainRowCache(HotRowCache):
    """HotRowCache under the TRAINING discipline, with the table-facing
    policy attached: Dashboard hit/miss counters
    (``table[X].get.train_cache_hit`` / ``_miss`` — they ride MSG_STATS
    and mvtop's monitor table like every counter), the push discipline
    (write-through vs invalidate), and the periodic refresh that bounds
    a multi-writer run's read staleness (``train_cache_refresh_gets``).

    Correctness contract (asserted by tests/test_we_pipeline.py):

    * **writethrough** is bit-exact for a table whose updater is the
      plain adder and whose wire is lossless, because every local push
      lands the same IEEE f32 add on the cached copy the owning shard
      lands on its rows — the table layer gates eligibility.
    * **invalidate** is always safe: a pushed row is dropped and the
      next get re-fetches it from the shard.
    * remote writers are invisible either way until a refresh; for
      multi-writer runs set ``train_cache_refresh_gets`` (the async
      plane's accepted bounded-staleness, now with a knob on it).
    """

    # in-flight-get push log depth: entries are only needed while a get
    # dispatched before the push is still awaiting its reply (the WE
    # pipeline holds 1-2 per table); past this, fills conservatively skip
    _PUSH_LOG_DEPTH = 8

    def __init__(self, table_name: str, num_col: int, dtype=np.float32,
                 capacity: int = 0, writethrough: bool = False,
                 refresh_gets: int = 0):
        super().__init__(num_col, dtype=dtype, capacity=capacity,
                         name=table_name)
        self.writethrough = bool(writethrough)
        self.refresh_gets = int(refresh_gets)
        self._gets = 0
        self.hits = 0
        self.misses = 0
        self.refreshes = 0
        # push log for late fills: a get's reply lands at wait() time,
        # possibly AFTER pushes that were dispatched behind it — filling
        # those rows verbatim would cache pre-push state. Each local push
        # appends (seq, sorted ids, sorted delta|None); fill_since()
        # replays the tail onto the incoming rows (write-through — the
        # same f32 adds the shard applies, in the same order, so the
        # filled copy is bit-identical to the shard) or excludes the
        # pushed ids (invalidate / log overflow: conservative).
        self._push_seq = 0
        self._push_log: list = []   # [(seq, ids_sorted, vals|None)]

    def on_get(self) -> None:
        """Once per table-level get: advances the refresh clock (the
        periodic whole-cache drop for multi-writer staleness bounding)."""
        with self._lock:
            self._gets += 1
            due = (self.refresh_gets > 0
                   and self._gets % self.refresh_gets == 0)
            if due:
                self.refreshes += 1
        if due:
            self.clear()   # takes the lock itself (wildcard mutation)

    def count(self, hits: int, misses: int) -> None:
        # counters under the lock (concurrent gets must not lose
        # increments); the Dashboard monitors are thread-safe themselves
        # and stay OUTSIDE it
        with self._lock:
            self.hits += hits
            self.misses += misses
        if hits:
            self._mon_hit().incr(hits)
        if misses:
            self._mon_miss().incr(misses)

    def device_block_counted(self, row_ids, bucket: int):
        """The table-facing device serve policy, shared by BOTH planes
        (AsyncMatrixTable / MatrixTable): a fully-covered block serves
        from the device mirror and counts its hits + advances the
        refresh clock; a miss counts NOTHING here — the caller falls
        back to the normal get path, which does its own on_get and
        hit/cold accounting (counting here too would double-count the
        block). Clock after serve, deliberately: a refresh falling due
        on this get must not clear the cache mid-decision and then
        double-advance the clock in the fallback path."""
        blk = self.device_block(row_ids, bucket)
        if blk is not None:
            self.count(int(np.asarray(row_ids).size), 0)
            self.on_get()
            # a device-block serve IS a table-level get: count it in the
            # get_rows monitor so mvtop's get totals stay consistent
            # with the hit counters (incr only — no wire latency)
            Dashboard.get(f"table[{self.name}].get_rows").incr()
        return blk

    def _mon_hit(self):
        return Dashboard.get(f"table[{self.name}].get.train_cache_hit")

    def _mon_miss(self):
        return Dashboard.get(f"table[{self.name}].get.train_cache_miss")

    def fill_token(self) -> int:
        """Capture at get DISPATCH; hand back to :meth:`fill_since` when
        the reply lands."""
        with self._lock:
            return self._push_seq

    def serve_full(self, uids: np.ndarray
                   ) -> Tuple[int, Optional[np.ndarray]]:
        """All-or-nothing atomic serve: when EVERY id is cached, gather
        the rows into a fresh buffer and return ``(token, rows)``; else
        ``(token, None)`` with no allocation and no gather — the sync
        plane's serve (its partial path refetches ALL rows from the
        device anyway, so a partial host gather would be wasted work)."""
        with self._lock:
            token = self._push_seq
            pos, ok = match_positions(self._ids, uids)
            if not ok.size or not bool(ok.all()):
                return token, None
            return token, self._rows[pos]   # fancy indexing: a copy

    def serve_into(self, uids: np.ndarray, buf: np.ndarray
                   ) -> Tuple[int, np.ndarray]:
        """Atomic {fill token, membership, gather}: copies every cached
        row of ``uids`` into the matching slot of ``buf`` and returns
        ``(token, hit_mask)`` from ONE lock hold — a concurrent
        fill/drop can neither skew positions between a lookup and the
        gather (which would serve the WRONG row's values, not merely
        stale ones) nor advance the push log between the token capture
        and the membership decision. This (with :meth:`serve_full`) is
        the ONLY serve protocol production callers may use — the split
        :meth:`lookup`/:meth:`gather_into` primitives exist for tests
        and diagnostics and reintroduce the skewed-positions race when
        composed without external exclusion."""
        with self._lock:
            token = self._push_seq
            pos, ok = match_positions(self._ids, uids)
            sel = np.flatnonzero(ok)
            if sel.size:
                buf[sel] = self._rows[pos[sel]]
            return token, ok

    def _note_mutation(self, ids, vals) -> None:
        """Append one push-log entry (``ids=None`` = wildcard: a clear/
        overwrite that poisons every in-flight fill). Caller holds the
        lock or accepts the race (entries are append-only)."""
        with self._lock:
            self._push_seq += 1
            if ids is not None:
                ids = np.asarray(ids, np.int64).reshape(-1)
                order = np.argsort(ids, kind="stable")
                ids = ids[order]
                if vals is not None:
                    vals = np.asarray(vals, self.dtype).reshape(
                        -1, self.num_col)[order].copy()
            self._push_log.append((self._push_seq, ids, vals))
            del self._push_log[: max(
                0, len(self._push_log) - self._PUSH_LOG_DEPTH)]

    def on_push(self, ids, delta=None) -> None:
        """A local push to ``ids``: write through (delta is the exact
        host-side delta the shard will apply) or invalidate.

        The mutation and its log entry commit under ONE lock hold (the
        lock is an RLock): a wait()-thread ``fill_since`` landing between
        them would see ``_push_seq`` still at its token, replay nothing,
        and refresh the just-mutated rows with pre-push reply values —
        permanently losing the delta from the cached copy."""
        with self._lock:
            if self.writethrough and delta is not None:
                self.apply_delta(ids, delta)
                self._note_mutation(ids, delta)
            else:
                self.drop(ids)
                self._note_mutation(ids, None)

    def on_overwrite(self, ids) -> None:
        """set_rows-style overwrite: drop + poison in-flight fills for
        these ids (an overwrite is not replayable as an add)."""
        with self._lock:   # atomic with the log entry, like on_push
            self.drop(ids)
            self._note_mutation(ids, None)

    def clear(self) -> None:
        with self._lock:   # atomic with the log entry, like on_push
            super().clear()
            self._note_mutation(None, None)   # wildcard: poison every fill

    def fill_since(self, ids: np.ndarray, rows: np.ndarray,
                   token: int) -> int:
        """Merge a get reply fetched at ``token`` into the cache,
        reconciled against every local mutation logged since: in
        write-through mode the logged deltas REPLAY onto the incoming
        rows (shard order, same IEEE f32 adds — the filled copy matches
        the shard bit-for-bit); rows touched by a non-replayable
        mutation (invalidate drop, overwrite, wildcard, log overflow)
        are excluded and re-fetch fresh next time."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        rows = np.asarray(rows, self.dtype).reshape(-1, self.num_col)
        with self._lock:
            if self._push_seq != token:
                if token < self._push_seq - len(self._push_log):
                    return 0   # log overflowed past the token: skip
                rows = rows.copy()   # never scribble on the caller's buf
                keep = np.ones(ids.size, bool)
                for seq, pids, pvals in self._push_log:
                    if seq <= token:
                        continue
                    if pids is None:
                        return 0   # wildcard mutation: poison the fill
                    pos, ok = match_positions(pids, ids)
                    if pvals is None:
                        keep &= ~ok
                    elif np.any(ok):
                        rows[ok] += pvals[pos[ok]]
                if not np.all(keep):
                    ids, rows = ids[keep], rows[keep]
                if ids.size == 0:
                    return 0
            return self.fill(ids, rows)

    def memory_stats(self) -> Dict[str, Any]:
        # the push log retains up to _PUSH_LOG_DEPTH full per-push delta
        # copies (write-through) — real retained host bytes that scale
        # with push size, so the PR-10 ledger must see them
        out = super().memory_stats()
        with self._lock:
            log_nb = 0
            for _seq, pids, pvals in self._push_log:
                if pids is not None:
                    log_nb += int(pids.nbytes)
                if pvals is not None:
                    log_nb += int(pvals.nbytes)
            out["push_log_entries"] = len(self._push_log)
        out["push_log_bytes"] = log_nb
        return out

    def stats(self) -> Dict[str, Any]:
        total = self.hits + self.misses
        return {"rows": len(self), "capacity": self.capacity,
                "mode": ("writethrough" if self.writethrough
                         else "invalidate"),
                "refresh_gets": self.refresh_gets,
                "refreshes": self.refreshes,
                "hits": self.hits, "misses": self.misses,
                "hit_rate": (round(self.hits / total, 4) if total
                             else None)}


def make_train_cache(table_name: str, num_col: int, dtype,
                     writethrough_ok: bool) -> Optional[TrainRowCache]:
    """Flag-driven factory for the table layer: None when the
    ``train_cache_rows`` knob is off. ``writethrough_ok`` is the CALLER's
    eligibility verdict (default updater + lossless wire); mode 'auto'
    degrades to invalidate when ineligible, an explicit 'writethrough'
    raises instead of silently diverging from the shard."""
    capacity = int(config.get_flag("train_cache_rows"))
    if capacity <= 0:
        return None
    mode = str(config.get_flag("train_cache_mode"))
    if mode not in ("auto", "writethrough", "invalidate"):
        raise ValueError(f"unknown train_cache_mode {mode!r}")
    if mode == "writethrough" and not writethrough_ok:
        raise ValueError(
            f"train_cache_mode=writethrough: table[{table_name}] is not "
            "eligible (needs the default plain-add updater and a "
            "lossless wire) — use 'auto' or 'invalidate'")
    wt = writethrough_ok if mode == "auto" else (mode == "writethrough")
    cache = TrainRowCache(
        table_name, num_col, dtype, capacity=capacity, writethrough=wt,
        refresh_gets=int(config.get_flag("train_cache_refresh_gets")))
    _memstats.register(f"train_cache[{table_name}]", cache)
    return cache
