"""Online-serving plane for the sparse PS: read replicas + admission.

The write-optimized async PS (ps/) serves a *training* mix — windowed
adds, coalesced applies, read-your-writes gets. A recommender in
production adds the other half: a read-dominated inference tier pulling
embedding rows for millions of users while training keeps writing
(ROADMAP open item 3). Serving those reads from the owning shards
directly couples inference tail latency to the training write path and
lets an inference storm starve the optimizer; classic serving systems
decouple the two with **read replicas** (bounded-staleness copies the
hot path reads instead) and **admission control** (budget the readers,
never the trainer). This package is that layer:

* :mod:`multiverso_tpu.serving.replica` — :class:`ReadReplica`: a
  bounded-staleness copy of one table, refreshed on an epoch cadence
  through the ``MSG_SNAPSHOT`` subscription RPC (epoch-pinned,
  chunk-streamed, since-version deduped at the shard), with a
  device-resident hot-row cache seeded from the PR-6 Space-Saving
  sketch.
* :mod:`multiverso_tpu.serving.admission` — per-(table, class)
  token-bucket QPS limits with priority classes: training traffic is
  never shed by default, inference reads shed fast and loudly
  (``table[X].get.shed`` counters, MSG_STATS ``serving`` block).

The app over it is :mod:`multiverso_tpu.apps.dlrm_serving`; the bench
is ``tools/bench_serving.py``; the operator story is docs/SERVING.md.
Imported module-level by ps/service.py (like the aggregator) so the
``serving_*`` flags are registered before any argv parse — nothing
here imports the ps package at module scope.
"""

from multiverso_tpu.serving.admission import (AdmissionController,
                                              SheddingError, TokenBucket)
from multiverso_tpu.serving.replica import ReadReplica, stats_snapshot

__all__ = ["AdmissionController", "SheddingError", "TokenBucket",
           "ReadReplica", "stats_snapshot"]
