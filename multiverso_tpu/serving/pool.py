"""ReplicaPool: N read replicas per table that survive chaos.

PR 8 proved ONE :class:`~multiverso_tpu.serving.replica.ReadReplica`
with an enforced staleness bound; "millions of users" (ROADMAP item 5)
means a *pool*: several replicas per table behind one read surface,
with routing, health, and spare capacity so that losing a replica — or
the shard it pulls from — degrades QPS briefly instead of zeroing it.

* **Least-staleness routing** — each read goes to the healthy active
  member with the freshest adopted snapshot (ties round-robin via the
  routed counter), so a member mid-refresh or mid-outage naturally
  sheds load to its siblings before any error is raised. Per-member
  route counts ride the stats block (mvtop's pool panel renders the
  share).

* **Health-aware demotion** — a member whose reads fail
  (:class:`~multiverso_tpu.serving.replica.BoundUnsatisfiableError`,
  peer errors) or whose background pulls keep failing
  (``pull_health()["consecutive"] >= serving_pool_demote_after``) is
  DEMOTED: routed around, probed by the health loop, and only
  re-promoted after a successful in-bound refresh — the pool never
  retries into a known-sick replica on the serve path.

* **Warm spares** — ``spares`` extra members are constructed cold (no
  refresh thread, no snapshot) and activated on demotion: one
  synchronous priming pull, then they serve. A killed replica's
  capacity is back within one pull time, not one provisioning time.

* **Bound-unsatisfiable failover** (ISSUE 14 satellite) — a single
  replica raises after 3 over-bound pulls; the pool catches the typed
  error, demotes the member, and tries every sibling (spares
  included). Only when the WHOLE pool is over bound does the caller
  see the error — the contract "refusing to serve beats serving
  silently-stale" now applies to the pool, not the member.

* **Failover wiring** (PR 7) — ``bind_failover(supervisor)`` watches a
  :class:`~multiverso_tpu.ps.failover.FailoverSupervisor`'s event log:
  a shard REJOIN kicks an immediate refresh on every member, so the
  pool re-syncs the moment the restored shard publishes instead of
  waiting out the refresh cadence. The chaos bench kills a replica AND
  a shard mid-storm and asserts served QPS recovers inside the
  staleness bound with the exactly-once ledger intact.

The pool registers a merged per-table stats entry with the serving
block (``serving/replica.register_pool_provider``): summed counters so
the PR-8 aggregator math keeps working, plus a ``"pool"`` detail block
(per-member age/degraded/route share) the aggregator passes through
and ``tools/mvtop.py`` renders as the pool panel.

Module-import discipline: same as replica.py — ps/service.py reaches
this module through serving/replica's provider registry, so nothing
here imports the ps package at module scope.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Dict, List, Optional

import numpy as np

from multiverso_tpu.serving import replica as _replica_mod
from multiverso_tpu.serving.admission import (AdmissionController,
                                              SheddingError)
from multiverso_tpu.serving.replica import (BoundUnsatisfiableError,
                                            ReadReplica)
from multiverso_tpu.telemetry import tenants as _tenants
from multiverso_tpu.utils import config, log

config.define_int(
    "serving_pool_replicas", 2,
    "active ReadReplicas per ReplicaPool (least-staleness routed); "
    "the pool survives N-1 member losses without refusing reads as "
    "long as one member stays within the staleness bound")
config.define_int(
    "serving_pool_spares", 0,
    "warm spare replicas per pool: constructed cold (no refresh "
    "thread, no snapshot) and activated — one priming pull, then "
    "serving — when an active member is demoted")
config.define_int(
    "serving_pool_demote_after", 3,
    "consecutive failed pulls (background refresh or serve-path "
    "failures) before a pool member is demoted — routed around and "
    "probed by the health loop rather than retried into")
config.define_float(
    "serving_pool_probe_s", 1.0,
    "pool health-loop cadence seconds: probes demoted members with a "
    "refresh and re-promotes them after a successful in-bound pull; "
    "also watches a bound FailoverSupervisor's rejoin events to kick "
    "immediate re-syncs after a shard restore")

# pool registry for the serving stats block (weak, like _REPLICAS)
_POOLS: "weakref.WeakSet" = weakref.WeakSet()


def _pools_snapshot() -> Dict[str, Dict]:
    out: Dict[str, Dict] = {}
    for pool in list(_POOLS):
        try:
            out[pool.name] = pool.stats_entry()
        except Exception:   # noqa: BLE001 — telemetry never raises
            pass
    return out


_replica_mod.register_pool_provider(_pools_snapshot)


class _Member:
    """One pool slot: the replica + its routing/health bookkeeping."""

    __slots__ = ("idx", "replica", "active", "degraded", "routed",
                 "serve_failures", "demotions")

    def __init__(self, idx: int, replica: ReadReplica, active: bool):
        self.idx = idx
        self.replica = replica
        self.active = active       # False = cold spare
        self.degraded = False
        self.routed = 0            # reads routed here (share basis)
        self.serve_failures = 0    # consecutive serve-path failures
        self.demotions = 0


class ReplicaPool:
    """N bounded-staleness read replicas of one async table behind a
    single :meth:`get_rows` surface. Construct like a ReadReplica —
    from the table object or standalone from a ctx + spec::

        pool = ReplicaPool(table, replicas=3, spares=1)
        rows = pool.get_rows([1, 2, 3])

    ``start=True`` runs each active member's refresh thread and the
    pool health loop; :meth:`close` stops everything.
    """

    def __init__(self, table=None, *, ctx=None,
                 name: Optional[str] = None,
                 num_row: Optional[int] = None,
                 num_col: Optional[int] = None, dtype=np.float32,
                 replicas: Optional[int] = None,
                 spares: Optional[int] = None,
                 refresh_s: Optional[float] = None,
                 staleness_s: Optional[float] = None,
                 cache_rows: Optional[int] = None,
                 admission: Optional[AdmissionController] = None,
                 demote_after: Optional[int] = None,
                 probe_s: Optional[float] = None,
                 start: bool = True):
        n_active = (config.get_flag("serving_pool_replicas")
                    if replicas is None else int(replicas))
        n_spare = (config.get_flag("serving_pool_spares")
                   if spares is None else int(spares))
        if n_active < 1:
            raise ValueError("a pool needs at least one active replica")
        self.demote_after = max(
            config.get_flag("serving_pool_demote_after")
            if demote_after is None else int(demote_after), 1)
        self.probe_s = (config.get_flag("serving_pool_probe_s")
                        if probe_s is None else float(probe_s))
        # admission is enforced ONCE at the pool surface (member
        # replicas are constructed without it): per-member admission
        # would multiply the budget by however many members a failover
        # sweep tries
        self.admission = admission

        def make(active: bool, i: int) -> _Member:
            rep = ReadReplica(
                table, ctx=ctx, name=name, num_row=num_row,
                num_col=num_col, dtype=dtype, refresh_s=refresh_s,
                staleness_s=staleness_s, cache_rows=cache_rows,
                admission=None, start=False)
            return _Member(i, rep, active)

        self._members: List[_Member] = (
            [make(True, i) for i in range(n_active)]
            + [make(False, n_active + i) for i in range(n_spare)])
        first = self._members[0].replica
        self.name = first.name
        self.num_row, self.num_col = first.num_row, first.num_col
        self.staleness_s = first.staleness_s
        self._lock = threading.Lock()
        self._rr = 0                      # round-robin tie-breaker
        self._shed = 0
        self._failovers = 0               # serve-path sibling failovers
        # FailoverSupervisor-shaped recovery log the chaos bench reads:
        # (wall_ts, phase, member idx), phase in
        # demote|promote|spare_activated
        self.events: List = []
        self._sup = None                  # bound FailoverSupervisor
        self._sup_seen = 0                # its events consumed so far
        self._closed = False
        self._health_thread: Optional[threading.Thread] = None
        _POOLS.add(self)
        if start:
            self.start()

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    def start(self) -> "ReplicaPool":
        for m in self._members:
            if m.active:
                m.replica.start()
        if self._health_thread is None:
            self._stop = threading.Event()
            self._health_thread = threading.Thread(
                target=self._health_loop, daemon=True,
                name=f"mv-pool-{self.name}")
            self._health_thread.start()
        return self

    def close(self) -> None:
        self._closed = True
        if self._health_thread is not None:
            self._stop.set()
            self._health_thread.join(timeout=10.0)
            self._health_thread = None
        for m in self._members:
            m.replica.close()

    def bind_failover(self, supervisor) -> None:
        """Watch a PR-7 :class:`FailoverSupervisor`: each shard REJOIN
        it observes kicks an immediate refresh across the pool, so the
        restored shard's rows re-sync at recovery speed rather than
        refresh-cadence speed."""
        self._sup = supervisor
        self._sup_seen = len(supervisor.events)

    # ------------------------------------------------------------------ #
    # health machinery
    # ------------------------------------------------------------------ #
    def _health_loop(self) -> None:
        while not self._stop.wait(self.probe_s):
            if self._closed:
                return
            try:
                self.check_health()
            except Exception as e:   # noqa: BLE001 — the loop survives
                log.debug("pool[%s] health check failed: %s: %s",
                          self.name, type(e).__name__, e)

    def check_health(self) -> None:
        """One health pass (the loop's body; tests drive it directly):
        demote actives whose background pulls keep failing, probe
        demoted members for re-promotion, and consume any bound
        supervisor's rejoin events."""
        # shard failover rejoin → immediate pool-wide re-sync: force a
        # FRESH pull (need_from=now — an in-bound snapshot does not
        # satisfy it) so the restored shard's replayed rows are served
        # at recovery speed, not refresh-cadence speed
        if self._sup is not None:
            ev = self._sup.events
            fresh, self._sup_seen = ev[self._sup_seen:], len(ev)
            if any(p == "rejoin" for _, p, _ in fresh):
                for m in self._members:
                    if m.active and not m.degraded:
                        try:
                            m.replica.refresh(
                                need_from=time.monotonic())
                        except Exception:   # noqa: BLE001 — probed
                            pass            # again next pass
        for m in list(self._members):
            if m.active and not m.degraded:
                if (m.replica.pull_health()["consecutive"]
                        >= self.demote_after):
                    self._demote(m, "background pulls failing")
            elif m.degraded:
                # probe, never on the serve path: one refresh attempt;
                # an in-bound snapshot re-promotes
                try:
                    m.replica.refresh(need_from=time.monotonic()
                                      - self.staleness_s)
                except Exception:   # noqa: BLE001 — still sick
                    continue
                if m.replica.age_s() <= self.staleness_s:
                    self._promote(m)

    def _demote(self, m: _Member, why: str) -> None:
        with self._lock:
            if m.degraded:
                return
            m.degraded = True
            m.demotions += 1
            self.events.append((time.time(), "demote", m.idx))
        log.info("pool[%s]: replica %d demoted (%s)", self.name,
                 m.idx, why)
        self._activate_spare()

    def _promote(self, m: _Member) -> None:
        with self._lock:
            if not m.degraded:
                return
            m.degraded = False
            m.serve_failures = 0
            self.events.append((time.time(), "promote", m.idx))
        log.info("pool[%s]: replica %d re-promoted", self.name, m.idx)

    def _activate_spare(self) -> None:
        with self._lock:
            spare = next((m for m in self._members if not m.active),
                         None)
            if spare is None:
                return
            spare.active = True
            self.events.append((time.time(), "spare_activated",
                                spare.idx))
        log.info("pool[%s]: spare replica %d activated", self.name,
                 spare.idx)
        spare.replica.start()
        try:
            spare.replica.refresh()   # priming pull: serve immediately
        except Exception as e:   # noqa: BLE001 — the health loop
            # keeps probing; the member serves as soon as a pull lands
            log.debug("pool[%s]: spare %d priming pull failed: %s",
                      self.name, spare.idx, e)

    # ------------------------------------------------------------------ #
    # the read path
    # ------------------------------------------------------------------ #
    def _candidates(self) -> List[_Member]:
        """Serve order: healthy actives by least staleness (ties by
        route count — cheap round-robin), then degraded actives as the
        last resort (a degraded member within bound still beats
        refusing the read), spares never (no snapshot until
        activated)."""
        with self._lock:
            active = [m for m in self._members if m.active]
            healthy = [m for m in active if not m.degraded]
            sick = [m for m in active if m.degraded]
        healthy.sort(key=lambda m: (m.replica.age_s(), m.routed))
        return healthy + sick

    def get_rows(self, row_ids, cls: str = "infer",
                 out: Optional[np.ndarray] = None,
                 with_age: bool = False,
                 tenant: Optional[str] = None):
        """Serve rows from the least-stale healthy member, failing
        over across the pool. Admission (``cls="infer"`` budgets,
        per-tenant budgets first) is enforced once, up front — a shed
        is a policy decision, never a health signal, and must not
        trigger failover. Raises the last member's error only when
        EVERY member refused: the whole pool is over bound (or
        unreachable). ``tenant`` overrides the caller's scope/flag
        attribution (``""`` = explicitly the default tenant)."""
        tn = _tenants.current() if tenant is None else (tenant or None)
        if self.admission is not None and not self.admission.admit(
                self.name, cls, tenant=tn):
            with self._lock:
                self._shed += 1
            _tenants.LEDGER.note_shed(self.name, tn)
            raise SheddingError(
                f"pool[{self.name}]: {cls} read shed by admission "
                "control")
        candidates = self._candidates()
        last: Optional[BaseException] = None
        for i, m in enumerate(candidates):
            try:
                # tenant rides to the member explicitly ("" = default):
                # the member's ledger entry is the serve-side record
                res = m.replica.get_rows(row_ids, cls="train", out=out,
                                         with_age=with_age,
                                         tenant=tn or "")
            except (ValueError, IndexError, TypeError):
                # caller input errors (empty/out-of-range row_ids) are
                # not replica health events: propagate untouched — a
                # buggy caller must not demote healthy members and
                # burn the warm spare
                raise
            except Exception as e:   # noqa: BLE001 — every member
                # HEALTH failure (bound unsatisfiable, peer errors,
                # closed replica) is a failover trigger; the LAST one
                # re-raises
                # health failure: count it, demote at the threshold,
                # try the next sibling. (cls="train" above bypasses
                # the members' own admission — the pool already
                # admitted this read.)
                last = e
                m.serve_failures += 1
                if i + 1 < len(candidates) or self._spare_left():
                    with self._lock:
                        self._failovers += 1
                if m.serve_failures >= self.demote_after or isinstance(
                        e, BoundUnsatisfiableError):
                    self._demote(m, f"serve failed: {type(e).__name__}")
                continue
            m.serve_failures = 0
            with self._lock:
                m.routed += 1
            return res
        # every active member refused; a just-activated spare may
        # still save the read (activation primes synchronously)
        spare = next((m for m in self._members
                      if m.active and m not in candidates), None)
        if spare is not None:
            try:
                res = spare.replica.get_rows(row_ids, cls="train",
                                             out=out, with_age=with_age,
                                             tenant=tn or "")
                with self._lock:
                    spare.routed += 1
                return res
            except Exception as e:   # noqa: BLE001
                last = e
        raise last if last is not None else RuntimeError(
            f"pool[{self.name}]: no active replicas")

    def _spare_left(self) -> bool:
        return any(not m.active for m in self._members)

    # chaos surface (the bench's replica-kill lever): close one member
    # as if its process died — reads fail over, health demotes, a
    # spare activates
    def kill_replica(self, idx: int) -> None:
        m = self._members[idx]
        m.replica.close()
        self._demote(m, "killed")

    # ------------------------------------------------------------------ #
    def min_age_s(self) -> float:
        ages = [m.replica.age_s() for m in self._members if m.active]
        return min(ages) if ages else float("inf")

    def spares_left(self) -> int:
        """Warm spares this pool could still promote — the autoscaling
        signal (telemetry/signals.py ``spares_left``): a grow
        recommendation is only actionable while this is positive."""
        with self._lock:
            return sum(1 for m in self._members if not m.active)

    def stats_entry(self) -> Dict[str, Any]:
        """The merged serving-block entry for this table: summed
        member counters under the PR-8 replica-entry keys (the
        aggregator's serving merge sums them unchanged) + the
        ``"pool"`` detail block mvtop's pool panel renders."""
        members = []
        served = shed = deferred = hits = misses = 0
        unchanged = 0
        total_routed = 0
        with self._lock:
            snap = [(m.idx, m.active, m.degraded, m.routed,
                     m.demotions, m.replica) for m in self._members]
            failovers = self._failovers
            pool_shed = self._shed
        for _idx, _active, _deg, routed, _dem, _rep in snap:
            total_routed += routed
        best_age = None
        epoch = 0
        for idx, active, degraded, routed, demotions, rep in snap:
            s = rep.stats()
            epoch = max(epoch, s["epoch"])
            served += s["served"]
            shed += s["shed"]
            deferred += s["deferred"]
            hits += s["cache_hits"]
            misses += s["cache_misses"]
            unchanged += s["unchanged_pulls"]
            age = s["age_s"]
            if active and age is not None and (best_age is None
                                               or age < best_age):
                best_age = age
            members.append({
                "idx": idx, "active": active, "degraded": degraded,
                "routed": routed,
                "share": (round(routed / total_routed, 4)
                          if total_routed else None),
                "age_s": age,
                "demotions": demotions,
                "pull_failures": s["pull_failures"],
                "pull_failures_consecutive":
                    s["pull_failures_consecutive"],
            })
        total = hits + misses
        ent: Dict[str, Any] = {
            "table": self.name,
            "epoch": epoch,
            "age_s": best_age,
            "bound_s": round(self.staleness_s, 3),
            "served": served, "shed": shed + pool_shed,
            "deferred": deferred,
            "unchanged_pulls": unchanged,
            "cache_hits": hits, "cache_misses": misses,
            "cache_hit_rate": (round(hits / total, 4) if total
                               else None),
            "pool": {
                "members": members,
                "active": sum(1 for m in members if m["active"]),
                "degraded": sum(1 for m in members if m["degraded"]),
                "spares_left": sum(1 for m in members
                                   if not m["active"]),
                "failovers": failovers,
                "demotions": sum(m["demotions"] for m in members),
            },
        }
        if self.admission is not None:
            ent["admission"] = self.admission.stats()
        return ent

    def recovery_spans(self) -> List[Dict]:
        """demote→promote/spare durations per episode (bench extra) —
        the FailoverSupervisor.recovery_spans shape, for pool members."""
        out: List[Dict] = []
        open_at: Dict[int, float] = {}
        for ts, phase, idx in list(self.events):
            if phase == "demote":
                open_at.setdefault(idx, ts)
            elif phase in ("promote", "spare_activated"):
                t0 = open_at.pop(idx, None)
                if phase == "spare_activated" and open_at:
                    # a spare recovers the OLDEST open demotion
                    k = min(open_at, key=open_at.get)
                    t0 = open_at.pop(k)
                if t0 is not None:
                    out.append({"member": idx, "phase": phase,
                                "recovered_in_s": round(ts - t0, 3)})
        return out
