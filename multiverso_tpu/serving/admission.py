"""Admission control for the serving plane: token buckets + priorities.

The overload failure mode this prevents: an inference storm (zipf-hot
users, retry amplification) saturates the process serving reads, and
the *training* write path — the thing that must never stall, or the
model stops improving — degrades behind it. The standard fix is to
shed load at the door, by priority class: a read refused in
microseconds costs one client a retry; a read admitted into an
overloaded plane costs every op behind it.

* :class:`TokenBucket` — the classic rate limiter: ``rate`` tokens/s
  refill up to ``burst``; an acquire that can't be covered fails
  immediately (never blocks — shedding must be cheap precisely when
  the plane is busiest).
* :class:`AdmissionController` — per-``(table, class)`` buckets with
  two priority classes: ``"train"`` (optimizer traffic; admitted
  unconditionally unless an explicit limit is set — training writes
  are never starved by inference reads) and ``"infer"`` (the serving
  tier; limited by ``serving_infer_qps`` or per-table overrides).
  Decisions are counted per (table, class) and surfaced through the
  MSG_STATS ``serving`` block (ps/service.stats_payload) next to the
  replica counters; the reader-facing ``table[X].get.shed`` Dashboard
  counter is bumped by the caller that owns the read path
  (serving/replica.py), so one shed is never double-counted.

Shedding raises :class:`SheddingError` (via the caller) rather than
queueing: bounded-staleness replicas make retries cheap, and a queue
in front of an overloaded server is just a slower way to time out.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Dict, Optional, Tuple

from multiverso_tpu.utils import config

config.define_float(
    "serving_infer_qps", 0.0,
    "default per-table admission rate (queries/s) for the 'infer' "
    "priority class on the serving read plane (serving/replica.py); "
    "reads over the budget are shed immediately with SheddingError. "
    "0 = unlimited. Per-table overrides via "
    "AdmissionController.set_limit")
config.define_float(
    "serving_burst_s", 1.0,
    "token-bucket burst depth, in seconds of the configured rate "
    "(burst = rate * serving_burst_s, floored at 1 token): how big an "
    "instantaneous spike is absorbed before shedding starts")

#: priority classes, highest first. "train" is the optimizer's traffic
#: (writes AND the trainer's own reads): admitted unconditionally
#: unless an explicit limit is installed for it. "infer" is the
#: serving tier: limited, shed first.
CLASSES = ("train", "infer")


class SheddingError(RuntimeError):
    """A read refused by admission control (over the class's QPS
    budget). Deliberately NOT a PSError: the PS plane is healthy —
    the caller asked for more than its class is budgeted, and should
    back off and retry, not fail over."""


class TokenBucket:
    """``rate`` tokens/s refilling up to ``burst``; ``try_acquire``
    never blocks. Thread-safe; refill is computed lazily from the
    monotonic clock on each acquire (no timer thread)."""

    __slots__ = ("rate", "burst", "_tokens", "_at", "_lock")

    def __init__(self, rate: float, burst: Optional[float] = None):
        if rate <= 0:
            raise ValueError("TokenBucket rate must be positive")
        self.rate = float(rate)
        if burst is None:
            burst = max(rate * config.get_flag("serving_burst_s"), 1.0)
        self.burst = float(burst)
        self._tokens = self.burst   # start full: a fresh limiter must
        self._at: Optional[float] = None   # not shed the first burst;
        #                                    anchored on first acquire so
        #                                    an injected clock (tests)
        #                                    needs no epoch agreement
        self._lock = threading.Lock()

    def try_acquire(self, n: float = 1.0,
                    now: Optional[float] = None) -> bool:
        """Take ``n`` tokens if available; False = shed. ``now`` is an
        injectable monotonic timestamp (tests); out-of-order stamps
        never rewind the refill anchor (no negative minting)."""
        with self._lock:
            t = time.monotonic() if now is None else float(now)
            if self._at is None:
                self._at = t
            elif t > self._at:
                self._tokens = min(self.burst,
                                   self._tokens + (t - self._at) * self.rate)
                self._at = t
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def tokens(self) -> float:
        with self._lock:
            return self._tokens


class AdmissionController:
    """Per-(table, class) admission decisions. One controller per
    serving process (the replica holds one); stateless consumers may
    share it across tables."""

    def __init__(self):
        # (table, cls) -> TokenBucket, or None = EXPLICITLY unlimited
        # (an operator's set_limit(..., 0) tombstone — absence means
        # "fall back to the serving_infer_qps flag default", and the
        # two must stay distinguishable or a removal is silently
        # undone by the lazy default on the next admit)
        self._buckets: Dict[Tuple[str, str],
                            Optional[TokenBucket]] = {}
        self._counts: Dict[Tuple[str, str], Dict[str, int]] = {}
        # per-(table, tenant, cls) budgets (telemetry/tenants.py): the
        # noisy-neighbor containment knob — a NAMED tenant's bucket is
        # checked BEFORE the table-wide one, so a storm tenant's shed
        # never burns aggregate tokens the victim needed. Same
        # tombstone discipline as the aggregate buckets; the lazy
        # default comes from the tenant_infer_qps flag.
        self._tbuckets: Dict[Tuple[str, str, str],
                             Optional[TokenBucket]] = {}
        self._tcounts: Dict[Tuple[str, str, str], Dict[str, int]] = {}
        self._lock = threading.Lock()
        _CONTROLLERS.add(self)

    # ------------------------------------------------------------------ #
    def set_limit(self, table: str, cls: str, qps: float,
                  burst: Optional[float] = None) -> None:
        """Install (or with ``qps <= 0`` remove) a QPS limit for
        ``(table, cls)``. Removal is an explicit exemption: it also
        overrides the ``serving_infer_qps`` flag default for this
        table, not just a previously installed limit. Installing a
        limit for ``"train"`` is legal but unusual — the default
        priority contract is that training traffic is never shed."""
        if cls not in CLASSES:
            raise ValueError(f"unknown admission class {cls!r} "
                             f"(one of {CLASSES})")
        with self._lock:
            if qps <= 0:
                self._buckets[(table, cls)] = None   # tombstone
            else:
                self._buckets[(table, cls)] = TokenBucket(qps, burst)

    def _bucket(self, table: str, cls: str) -> Optional[TokenBucket]:
        with self._lock:
            key = (table, cls)
            if key in self._buckets:    # explicit limit OR exemption
                return self._buckets[key]
            if cls == "infer":
                # lazy default from the flag, so a flag set after the
                # controller exists still takes effect on first use
                qps = config.get_flag("serving_infer_qps")
                if qps > 0:
                    b = self._buckets[key] = TokenBucket(qps)
                    return b
            return None

    def set_tenant_limit(self, table: str, tenant: str, cls: str,
                         qps: float,
                         burst: Optional[float] = None) -> None:
        """Install (or with ``qps <= 0`` remove) a QPS budget for
        ``(table, tenant, cls)``. Removal is an explicit exemption
        overriding the ``tenant_infer_qps`` flag default, same
        discipline as :meth:`set_limit`."""
        if cls not in CLASSES:
            raise ValueError(f"unknown admission class {cls!r} "
                             f"(one of {CLASSES})")
        if not tenant:
            raise ValueError("per-tenant limits need a named tenant "
                             "(use set_limit for the table-wide budget)")
        with self._lock:
            key = (table, tenant, cls)
            if qps <= 0:
                self._tbuckets[key] = None   # tombstone
            else:
                self._tbuckets[key] = TokenBucket(qps, burst)

    def _tenant_bucket(self, table: str, tenant: str,
                       cls: str) -> Optional[TokenBucket]:
        with self._lock:
            key = (table, tenant, cls)
            if key in self._tbuckets:   # explicit limit OR exemption
                return self._tbuckets[key]
            if cls == "infer":
                # lazy flag default for NAMED tenants only — the
                # default tenant is governed by the table-wide budget
                qps = (config.get_flag("tenant_infer_qps")
                       if config.has_flag("tenant_infer_qps") else 0.0)
                if qps > 0:
                    b = self._tbuckets[key] = TokenBucket(qps)
                    return b
            return None

    def admit(self, table: str, cls: str = "infer",
              n: float = 1.0, tenant: Optional[str] = None) -> bool:
        """One admission decision (``n`` tokens = usually 1 request —
        QPS budgets queries, not rows). ``"train"`` with no explicit
        limit is always admitted: the priority contract. A NAMED
        tenant's budget is judged first — a tenant-shed request never
        draws down the table-wide bucket. Never raises, never blocks;
        the caller owns what a shed means (raise SheddingError, drop,
        retry-after)."""
        if tenant:
            tb = self._tenant_bucket(table, tenant, cls)
            if tb is not None:
                ok_t = tb.try_acquire(n)
                tkey = (table, tenant, cls)
                with self._lock:
                    c = self._tcounts.setdefault(
                        tkey, {"admitted": 0, "shed": 0})
                    c["admitted" if ok_t else "shed"] += 1
                if not ok_t:
                    return False
        bucket = self._bucket(table, cls)
        ok = bucket is None or bucket.try_acquire(n)
        key = (table, cls)
        with self._lock:
            c = self._counts.setdefault(key, {"admitted": 0, "shed": 0})
            c["admitted" if ok else "shed"] += 1
        return ok

    # ------------------------------------------------------------------ #
    def stats(self) -> Dict[str, Dict]:
        """JSON-safe per-(table, class) decision counters + limits —
        the MSG_STATS ``serving.admission`` shape."""
        out: Dict[str, Dict] = {}
        with self._lock:
            for (table, cls), c in self._counts.items():
                b = self._buckets.get((table, cls))
                out[f"{table}/{cls}"] = {
                    "admitted": c["admitted"], "shed": c["shed"],
                    "qps_limit": (round(b.rate, 3)
                                  if b is not None else None),
                }
            for (table, cls), b in self._buckets.items():
                if b is None:
                    continue   # explicit exemption: no limit to report
                out.setdefault(f"{table}/{cls}", {
                    "admitted": 0, "shed": 0,
                    "qps_limit": round(b.rate, 3)})
        return out

    def tenant_stats(self) -> Dict[str, Dict]:
        """Per-(table, tenant, class) decision counters + limits — the
        MSG_STATS ``tenants.admission`` shape (keys
        ``"<table>/<tenant>/<cls>"``). Empty when no tenant budget was
        ever installed or exercised."""
        out: Dict[str, Dict] = {}
        with self._lock:
            for (table, tn, cls), c in self._tcounts.items():
                b = self._tbuckets.get((table, tn, cls))
                out[f"{table}/{tn}/{cls}"] = {
                    "admitted": c["admitted"], "shed": c["shed"],
                    "qps_limit": (round(b.rate, 3)
                                  if b is not None else None),
                }
            for (table, tn, cls), b in self._tbuckets.items():
                if b is None:
                    continue
                out.setdefault(f"{table}/{tn}/{cls}", {
                    "admitted": 0, "shed": 0,
                    "qps_limit": round(b.rate, 3)})
        return out


# every live controller, so the process-global MSG_STATS "tenants"
# block (telemetry/tenants.py stats_snapshot) can gather tenant budget
# decisions without the ledger holding controller references — a
# replica pool closing drops out of the block automatically
_CONTROLLERS: "weakref.WeakSet[AdmissionController]" = weakref.WeakSet()


def tenant_stats_all() -> Dict[str, Dict]:
    """Merged :meth:`AdmissionController.tenant_stats` across every
    live controller in the process (sums counters for a key two
    controllers share; keeps the first non-None limit)."""
    out: Dict[str, Dict] = {}
    for ctl in list(_CONTROLLERS):
        try:
            for k, v in ctl.tenant_stats().items():
                e = out.get(k)
                if e is None:
                    out[k] = dict(v)
                else:
                    e["admitted"] += v["admitted"]
                    e["shed"] += v["shed"]
                    if e.get("qps_limit") is None:
                        e["qps_limit"] = v.get("qps_limit")
        except Exception:
            continue
    return out
