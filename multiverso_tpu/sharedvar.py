"""Shared-variable delta sync: ASGD over arbitrary parameter pytrees.

Parity surface for the reference Theano/Lasagne extensions
(ref: binding/python/multiverso/theano_ext/sharedvar.py — ``mv_shared``
wrapping a Theano shared variable, ``mv_sync`` = Add(current - last) then Get,
the delta-sync ASGD recipe at :38-50 — and lasagne_ext/param_manager.py's
``MVNetParamManager``, which flattens all network params into one ArrayTable).

The TPU-era equivalent wraps any JAX pytree (flax/haiku/optax params): all
leaves are flattened into a single ArrayTable; ``sync()`` pushes the local
delta since the last sync and pulls the merged global state. Drop this around
an existing training loop and N processes train data-parallel ASGD with no
other changes.
"""

from __future__ import annotations

from typing import Any, List

import jax
import numpy as np

import multiverso_tpu as mv


def _flatten(tree: Any) -> np.ndarray:
    leaves = jax.tree.leaves(tree)
    return np.concatenate([np.asarray(l, dtype=np.float32).reshape(-1)
                           for l in leaves]) if leaves else np.zeros(0, np.float32)


class SharedPytree:
    """``mv_shared`` + ``MVNetParamManager`` equivalent for JAX pytrees."""

    def __init__(self, params: Any, name: str = "shared_params"):
        leaves, self._treedef = jax.tree.flatten(params)
        self._shapes = [np.shape(l) for l in leaves]
        self._dtypes = [np.asarray(l).dtype for l in leaves]
        self._sizes = [int(np.prod(s)) if s else 1 for s in self._shapes]
        flat = _flatten(params)
        self.table = mv.ArrayTable(max(flat.size, 1), dtype=np.float32,
                                   name=name)
        # master-init convention (ref param_manager.py:24-31)
        if mv.is_master_worker():
            self.table.add(flat)
        else:
            self.table.add(np.zeros_like(flat))
        mv.barrier()
        self._last = self.table.get().copy()

    def unflatten(self, flat: np.ndarray) -> Any:
        leaves: List[Any] = []
        off = 0
        for shape, dtype, size in zip(self._shapes, self._dtypes,
                                      self._sizes):
            leaves.append(flat[off: off + size].reshape(shape).astype(dtype))
            off += size
        return jax.tree.unflatten(self._treedef, leaves)

    def sync(self, params: Any) -> Any:
        """Add(current − last), Get, return the merged params
        (ref sharedvar.py mv_sync :38-50)."""
        current = _flatten(params)
        self.table.add(current - self._last)
        merged = self.table.get()
        self._last = merged.copy()
        return self.unflatten(merged)

    def get(self) -> Any:
        flat = self.table.get()
        self._last = flat.copy()
        return self.unflatten(flat)


def mv_shared(value: Any, name: str = "mv_shared") -> SharedPytree:
    """Sugar matching the reference's ``mv_shared(value=...)`` constructor."""
    return SharedPytree(value, name=name)
