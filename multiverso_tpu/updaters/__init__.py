"""Server-side updaters as pure JAX functions.

TPU-native re-design of the reference updater module
(ref: include/multiverso/updater/updater.h:113-132, src/updater/updater.cpp:38-46
and the concrete sgd/momentum/adagrad headers). In the reference an updater is a
stateful C++ object applied by the server actor, OpenMP-parallel over the shard.
Here an updater is a pair of pure functions:

* ``init_state(shape, dtype)``  -> pytree of state arrays (same sharding as data)
* ``apply(data, state, delta, opt)`` -> (new_data, new_state)

applied inside a jitted, donated update whose arrays are device-sharded over
the table mesh axis — XLA parallelizes element-wise work across all chips the
way OpenMP parallelized it across cores (ref src/updater/updater.cpp:14-22).

Semantics parity notes (signs follow the reference):
* default:      data += delta                       (plain Add aggregation)
* sgd:          data -= delta                       (lr pre-multiplied by worker,
                                                     ref sgd_updater.h:14-19)
* momentum_sgd: smooth = m*smooth + (1-m)*delta; data -= smooth
                                                    (ref momentum_updater.h:17-25)
* adagrad:      G += delta**2 / lr**2 ; data -= delta * rho / (sqrt(G)+eps)
                The reference keeps *per-worker* G buffers
                (ref adagrad_updater.h:19); we default to one shared buffer
                (idiomatic, W× less memory) with ``per_worker=True`` opt-in.
* adam:         first-class here (BASELINE config 5 calls for it; the reference
                never shipped one).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class AddOption(NamedTuple):
    """Wire-parity hyperparameter bundle (ref updater.h:10-70 AddOption)."""
    worker_id: int = 0
    momentum: float = 0.0
    learning_rate: float = 0.1
    rho: float = 0.1
    lam: float = 0.0  # "lambda" in the reference


class Updater:
    """Base updater: plain accumulation (ref updater.cpp:14-22 default)."""

    name = "default"

    def __init__(self, num_workers: int = 1):
        self.num_workers = num_workers

    def init_state(self, shape, dtype) -> Any:
        return ()

    def apply(self, data: jax.Array, state: Any, delta: jax.Array,
              opt: AddOption) -> Tuple[jax.Array, Any]:
        return data + delta, state


class SGDUpdater(Updater):
    name = "sgd"

    def apply(self, data, state, delta, opt):
        return data - delta, state


class MomentumUpdater(Updater):
    name = "momentum_sgd"

    def init_state(self, shape, dtype):
        return {"smooth": jnp.zeros(shape, dtype)}

    def apply(self, data, state, delta, opt):
        m = jnp.asarray(opt.momentum, data.dtype)
        smooth = m * state["smooth"] + (1.0 - m) * delta
        return data - smooth, {"smooth": smooth}


class AdaGradUpdater(Updater):
    name = "adagrad"

    def __init__(self, num_workers: int = 1, per_worker: bool = False,
                 eps: float = 1e-10):
        super().__init__(num_workers)
        self.per_worker = per_worker
        self.eps = eps

    def init_state(self, shape, dtype):
        if self.per_worker:
            return {"g_sqr": jnp.zeros((self.num_workers,) + tuple(shape), dtype)}
        return {"g_sqr": jnp.zeros(shape, dtype)}

    def apply(self, data, state, delta, opt):
        lr = jnp.asarray(opt.learning_rate, data.dtype)
        rho = jnp.asarray(opt.rho, data.dtype)
        g2 = jnp.square(delta) / jnp.square(lr)
        if self.per_worker:
            wid = jnp.asarray(opt.worker_id, jnp.int32)
            g_sqr = state["g_sqr"].at[wid].add(g2)
            hist = g_sqr[wid]
        else:
            g_sqr = state["g_sqr"] + g2
            hist = g_sqr
        step = delta * rho / (jnp.sqrt(hist) + self.eps)
        return data - step, {"g_sqr": g_sqr}


class AdamUpdater(Updater):
    name = "adam"

    def __init__(self, num_workers: int = 1, beta1: float = 0.9,
                 beta2: float = 0.999, eps: float = 1e-8):
        super().__init__(num_workers)
        self.beta1, self.beta2, self.eps = beta1, beta2, eps

    def init_state(self, shape, dtype):
        return {
            "m": jnp.zeros(shape, dtype),
            "v": jnp.zeros(shape, dtype),
            "t": jnp.zeros((), jnp.int32),
        }

    def apply(self, data, state, delta, opt):
        lr = jnp.asarray(opt.learning_rate, data.dtype)
        b1 = jnp.asarray(self.beta1, data.dtype)
        b2 = jnp.asarray(self.beta2, data.dtype)
        t = state["t"] + 1
        m = b1 * state["m"] + (1.0 - b1) * delta
        v = b2 * state["v"] + (1.0 - b2) * jnp.square(delta)
        tf = t.astype(data.dtype)
        m_hat = m / (1.0 - jnp.power(b1, tf))
        v_hat = v / (1.0 - jnp.power(b2, tf))
        step = lr * m_hat / (jnp.sqrt(v_hat) + self.eps)
        return data - step, {"m": m, "v": v, "t": t}


class FTRLUpdater(Updater):
    """FTRL-proximal (ref: Applications/LogisticRegression/src/updater/
    updater.cpp:79-101 FTRL branch + util/ftrl_sparse_table.h z/n entries).
    The delta passed to ``apply`` is the raw gradient; the stored data is the
    *weight* vector recomputed from the (z, n) state after each update, so
    Get keeps returning ready-to-use weights like every other table."""

    name = "ftrl"

    def __init__(self, num_workers: int = 1, alpha: float = 0.1,
                 beta: float = 1.0, lambda1: float = 0.1,
                 lambda2: float = 1.0):
        super().__init__(num_workers)
        self.alpha, self.beta = alpha, beta
        self.lambda1, self.lambda2 = lambda1, lambda2

    def init_state(self, shape, dtype):
        return {"z": jnp.zeros(shape, dtype), "n": jnp.zeros(shape, dtype)}

    def apply(self, data, state, delta, opt):
        g = delta
        z, n = state["z"], state["n"]
        alpha = jnp.asarray(self.alpha, data.dtype)
        sigma = (jnp.sqrt(n + jnp.square(g)) - jnp.sqrt(n)) / alpha
        z = z + g - sigma * data
        n = n + jnp.square(g)
        w = jnp.where(
            jnp.abs(z) <= self.lambda1,
            jnp.zeros_like(z),
            -(z - jnp.sign(z) * self.lambda1)
            / ((self.beta + jnp.sqrt(n)) / alpha + self.lambda2))
        return w, {"z": z, "n": n}


# classification used by the serving/coalescing planes (EXACT type match
# everywhere: a user subclass overriding apply() must not inherit either
# property):
# * STATELESS_LINEAR: Add is a signed accumulate with no state — K adds
#   merge into one summed add EXACTLY, and host-backed shards may apply
#   with in-place numpy instead of a jitted program.
# * OPT_INSENSITIVE: apply() never reads AddOption — queued adds coalesce
#   across senders regardless of per-worker opt values.
# * ROW_LOCAL_STATE: apply() is per-row elementwise and every state leaf
#   is row-aligned (gathered/scattered with the touched rows), so applying
#   K DISJOINT-row adds as one merged update is bit-identical to K
#   sequential applies — the invariant the send window's merging (client
#   groups + shard waves) relies on. Adam is excluded: its global step
#   counter t advances once per apply() CALL, so a merge would miscount
#   K-1 steps. Unlisted custom updaters never merge (conservative).
STATELESS_LINEAR: Dict[type, float] = {Updater: 1.0, SGDUpdater: -1.0}
OPT_INSENSITIVE = {Updater, SGDUpdater, FTRLUpdater}
ROW_LOCAL_STATE = {Updater, SGDUpdater, MomentumUpdater, AdaGradUpdater,
                   FTRLUpdater}

_REGISTRY: Dict[str, Callable[..., Updater]] = {
    "default": Updater,
    "sgd": SGDUpdater,
    "momentum_sgd": MomentumUpdater,
    "adagrad": AdaGradUpdater,
    "adam": AdamUpdater,
    "ftrl": FTRLUpdater,
}


def register_updater(name: str, factory: Callable[..., Updater]) -> None:
    """User extension point (the reference's factory is closed; ours is open)."""
    _REGISTRY[name] = factory


def get_updater(name: str, num_workers: int = 1, dtype=None, **kwargs) -> Updater:
    """Factory keyed on the ``updater_type`` flag value
    (ref src/updater/updater.cpp:38-46). Integer tables always get the default
    updater, matching ref updater.cpp:33-36."""
    if dtype is not None and jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
        return Updater(num_workers)
    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown updater_type {name!r}; known: {sorted(_REGISTRY)}"
        ) from None
    return factory(num_workers=num_workers, **kwargs)
