"""Pallas TPU flash attention: fused blockwise softmax-attention kernel.

Single-chip counterpart of the cross-chip schemes in parallel/ring.py (the
reference framework predates attention entirely — SURVEY §5 "long-context:
absent"). The kernel never materializes the [S, S] score matrix: the grid
walks (batch*heads, q_blocks, k_blocks) with the k dimension innermost and
sequential, carrying the online-softmax state (running max ``m``, denominator
``l``, f32 accumulator) in VMEM scratch that persists across the k steps —
the same math as ``ring._ring_attention_local`` with ppermute hops replaced
by grid steps over HBM-resident K/V blocks.

MXU/VPU notes: both matmuls (q@k^T, p@v) run on the MXU in the input dtype
with f32 accumulation (``preferred_element_type``); masking, exp and the
rescale are VPU elementwise ops on (block_q, block_k) tiles. Causal blocks
strictly above the diagonal skip their compute with ``pl.when`` (the
block pipeline still streams those K/V blocks — only the MXU/VPU work is
saved).

The backward pass recomputes attention with plain XLA ops (jax.custom_vjp),
trading the O(S^2) backward memory for not keeping ``p`` alive; use ring
attention when S itself is the memory problem.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  scale: float, causal: bool, block_q: int, block_k: int,
                  nk: int):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # causal: the whole block is masked iff its first k position exceeds
    # the last q position of this q block
    live = (j * block_k <= i * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _step():
        qb = q_ref[0]                                     # (bq, d)
        kb = k_ref[0]                                     # (bk, d)
        vb = v_ref[0]
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bq, bk)
        if causal:
            qpos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) \
                + i * block_q
            kpos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) \
                + j * block_k
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_prev = m_ref[...][:, :1]                        # (bq, 1)
        l_prev = l_ref[...][:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next)
        if causal:
            # rows whose every position is masked would get exp(-inf-(-inf))
            p = jnp.where(s > _NEG_INF / 2, p, 0.0)
        l_next = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # (bq, d)
        acc_ref[...] = acc_ref[...] * corr + pv
        m_ref[...] = jnp.broadcast_to(m_next, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_next, l_ref.shape)

    @pl.when(j == nk - 1)
    def _emit():
        l = l_ref[...][:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    b, h, s, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(f"seq len {s} not divisible by blocks "
                         f"({block_q}, {block_k})")
    bh, nq, nk = b * h, s // block_q, s // block_k
    scale = 1.0 / (d ** 0.5)
    flat = lambda t: t.reshape(bh, s, d)

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nk=nk)
    out = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # denominator
            pltpu.VMEM((block_q, d), jnp.float32),        # output acc
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(flat(q), flat(k), flat(v))
    return out.reshape(b, h, s, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Fused attention over [B, H, S, D]; S must divide by the block sizes
    (blocks auto-clamp to S when S < 128). ``interpret=None`` auto-selects
    interpreter mode off-TPU (tests); pass False to force the compiled path.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    out = flash_attention(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _bwd(causal, block_q, block_k, interpret, res, g):
    from multiverso_tpu.parallel.ring import reference_attention
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: reference_attention(q, k, v, causal=causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
