"""Pallas TPU flash attention: fused blockwise softmax-attention kernel.

Single-chip counterpart of the cross-chip schemes in parallel/ring.py (the
reference framework predates attention entirely — SURVEY §5 "long-context:
absent"). The kernel never materializes the [S, S] score matrix: the grid
walks (batch*heads, q_blocks, k_blocks) with the k dimension innermost and
sequential, carrying the online-softmax state (running max ``m``, denominator
``l``, f32 accumulator) in VMEM scratch that persists across the k steps —
the same math as ``ring._ring_attention_local`` with ppermute hops replaced
by grid steps over HBM-resident K/V blocks.

MXU/VPU notes: both matmuls (q@k^T, p@v) run on the MXU in the input dtype
with f32 accumulation (``preferred_element_type``); masking, exp and the
rescale are VPU elementwise ops on (block_q, block_k) tiles. Causal blocks
strictly above the diagonal skip their compute with ``pl.when`` (the
block pipeline still streams those K/V blocks — only the MXU/VPU work is
saved).

The backward pass is Pallas too (FlashAttention-2 style): the forward
additionally emits the per-row logsumexp, and two blockwise kernels
recompute ``p = exp(s - lse)`` tile by tile — one walking k-blocks
innermost to accumulate dQ, one walking q-blocks innermost to accumulate
dK/dV — so the [S, S] score matrix is never materialized in either
direction. Measured on the 472M LM bench (b=2, s=1024): full-XLA
attention 70 ms/step, Pallas fwd + XLA-recompute bwd ~61 ms, Pallas
fwd+bwd 57.5 ms at the default 128x128 blocks, and 47-54 ms with the
512x512 blocks the transformer model now auto-selects — in total 97 ->
113-124 whole-model TFLOP/s depending on tunnel compute weather.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# cross-version Pallas API move (same class as jax.shard_map /
# jax.lax.axis_size, see utils/platform.py): newer jax spells the
# TPU compiler-params class CompilerParams, older releases
# TPUCompilerParams — without the alias every flash-kernel path
# import-errors on the older runtime
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")

_LANES = 128
_RES_LANES = 8    # lse residual lane width (smallest legal TPU tile)
_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, *refs,
                  scale: float, causal: bool, block_q: int, block_k: int,
                  nk: int, emit_lse: bool):
    if emit_lse:
        o_ref, lse_ref, m_ref, l_ref, acc_ref = refs
    else:   # inference-only call: skip the residual's VPU work + HBM write
        (o_ref, m_ref, l_ref, acc_ref), lse_ref = refs, None
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # causal: the whole block is masked iff its first k position exceeds
    # the last q position of this q block
    live = (j * block_k <= i * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _step():
        qb = q_ref[0]                                     # (bq, d)
        kb = k_ref[0]                                     # (bk, d)
        vb = v_ref[0]
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bq, bk)
        if causal:
            qpos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) \
                + i * block_q
            kpos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) \
                + j * block_k
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_prev = m_ref[...][:, :1]                        # (bq, 1)
        l_prev = l_ref[...][:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next)
        if causal:
            # rows whose every position is masked would get exp(-inf-(-inf))
            p = jnp.where(s > _NEG_INF / 2, p, 0.0)
        l_next = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # (bq, d)
        acc_ref[...] = acc_ref[...] * corr + pv
        m_ref[...] = jnp.broadcast_to(m_next, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_next, l_ref.shape)

    @pl.when(j == nk - 1)
    def _emit():
        l = l_ref[...][:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        if lse_ref is not None:
            # per-row logsumexp, the backward's softmax residual (stored
            # with a tiny 8-lane trailing dim — TPU blocks need their last
            # dim to match the array dim or divide 128)
            lse_ref[0] = jnp.broadcast_to(m_ref[...][:, :1] + jnp.log(l),
                                          lse_ref.shape[1:])


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   interpret: bool, with_lse: bool):
    b, h, s, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(f"seq len {s} not divisible by blocks "
                         f"({block_q}, {block_k})")
    bh, nq, nk = b * h, s // block_q, s // block_k
    scale = 1.0 / (d ** 0.5)
    flat = lambda t: t.reshape(bh, s, d)

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nk=nk, emit_lse=with_lse)
    ospec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    oshape = jax.ShapeDtypeStruct((bh, s, d), q.dtype)
    lspec = pl.BlockSpec((1, block_q, _RES_LANES),
                         lambda b, i, j: (b, i, 0))
    lshape = jax.ShapeDtypeStruct((bh, s, _RES_LANES), jnp.float32)
    res = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[ospec, lspec] if with_lse else [ospec],
        out_shape=[oshape, lshape] if with_lse else [oshape],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # denominator
            pltpu.VMEM((block_q, d), jnp.float32),        # output acc
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(flat(q), flat(k), flat(v))
    out = res[0].reshape(b, h, s, d)
    return (out, res[1]) if with_lse else (out, None)


def _bwd_p_ds(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, i, j, *,
              scale: float, causal: bool, block_q: int, block_k: int):
    """Shared backward recompute for ONE (q-block i, k-block j) tile:
    returns (p, ds) with ds already scale-folded — the one definition of
    the tile math, so the dQ and dK/dV kernels cannot desynchronize.
    D_i = rowsum(dO * O) is recomputed per tile in VPU registers:
    trivially cheap next to the three matmuls, and it saves materializing
    a lane-padded delta array in HBM."""
    qb, kb, vb, dob = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    lse = lse_ref[0][:, :1]
    delta = jnp.sum(dob.astype(jnp.float32) * o_ref[0].astype(jnp.float32),
                    axis=-1, keepdims=True)
    s = jax.lax.dot_general(
        qb, kb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale           # (bq, bk)
    if causal:
        qpos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) \
            + i * block_q
        kpos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) \
            + j * block_k
        s = jnp.where(qpos >= kpos, s, _NEG_INF)
    p = jnp.exp(s - lse)               # masked entries: exp(-inf-..) = 0
    dp = jax.lax.dot_general(
        dob, vb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                   # (bq, bk)
    ds = (p * (dp - delta) * scale).astype(qb.dtype)
    return p, ds


def _bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                   dq_ref, acc_ref, *, scale: float, causal: bool,
                   block_q: int, block_k: int, nk: int):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    live = (j * block_k <= i * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _step():
        _, ds = _bwd_p_ds(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                          i, j, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k)
        acc_ref[...] += jax.lax.dot_general(
            ds, k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bq, d)

    @pl.when(j == nk - 1)
    def _emit():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                    causal: bool, block_q: int, block_k: int, nq: int):
    j = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    live = (i * block_q + block_q - 1 >= j * block_k) if causal else True

    @pl.when(live)
    def _step():
        p, ds = _bwd_p_ds(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                          i, j, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k)
        dob = do_ref[0]
        dv_acc[...] += jax.lax.dot_general(
            p.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bk, d)
        dk_acc[...] += jax.lax.dot_general(
            ds, q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bk, d)

    @pl.when(i == nq - 1)
    def _emit():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, do, causal: bool, block_q: int,
                    block_k: int, interpret: bool):
    b, h, s, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    bh, nq, nk = b * h, s // block_q, s // block_k
    scale = 1.0 / (d ** 0.5)
    flat = lambda t: t.reshape(bh, s, d)
    qf, kf, vf, of, dof = flat(q), flat(k), flat(v), flat(out), flat(do)

    qspec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))
    rspec = pl.BlockSpec((1, block_q, _RES_LANES),
                         lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nk=nk),
        grid=(bh, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, qspec, rspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, of, dof, lse)

    # dK/dV walk q-blocks innermost: grid axis 1 is the K block
    qspec2 = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
    kspec2 = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
    rspec2 = pl.BlockSpec((1, block_q, _RES_LANES),
                          lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nq=nq),
        grid=(bh, nk, nq),
        in_specs=[qspec2, kspec2, kspec2, qspec2, qspec2, rspec2],
        out_specs=[kspec2, kspec2],
        out_shape=[jax.ShapeDtypeStruct((bh, s, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, s, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, of, dof, lse)
    shape = (b, h, s, d)
    return dq.reshape(shape), dk.reshape(shape), dv.reshape(shape)


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    """None = interpreter mode off-TPU (tests); one rule for fwd AND bwd
    (a drift between them would run half the op interpreted)."""
    return (jax.devices()[0].platform != "tpu" if interpret is None
            else interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Fused attention over [B, H, S, D]; S must divide by the block sizes
    (blocks auto-clamp to S when S < 128). ``interpret=None`` auto-selects
    interpreter mode off-TPU (tests); pass False to force the compiled path.
    """
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k,
                            _resolve_interpret(interpret), with_lse=False)
    return out


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k,
                              _resolve_interpret(interpret), with_lse=True)
    return out, (q, k, v, out, lse)


def _bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, causal, block_q, block_k,
                           _resolve_interpret(interpret))


flash_attention.defvjp(_fwd, _bwd)
