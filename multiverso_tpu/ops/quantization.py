"""Weight-only int8 quantization for inference.

Complements the wire-compression filters (utils/filters.py — the
reference's SparseFilter/OneBitsFilter surface, ref
include/multiverso/util/quantization_util.h) with *storage* quantization:
params are held as int8 + per-channel f32 scales — 4x smaller in HBM, the
win for HBM-bandwidth-bound decoding — and dequantized on use (the
matmuls themselves still run in the model dtype; a true int8-MXU dot is a
possible future step).

Symmetric scheme: ``scale = max|w| / 127`` per kept channel and
``w ≈ q.astype(f32) * scale``; error is bounded by scale/2 per element.
:class:`QuantizedTensor` is a plain two-array pytree, so stacked
``[L, ...]`` quantized layers slice transparently under ``lax.scan`` —
``models/transformer.generate`` accepts trees produced by
:func:`quantize_lm_params` and dequantizes one layer at a time.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp


class QuantizedTensor(NamedTuple):
    q: jax.Array          # int8, same shape as the original
    scale: jax.Array      # f32, original shape with reduced dims = 1


def quantize(w: jax.Array, keep_axes: Sequence[int] = (-1,)
             ) -> QuantizedTensor:
    """Symmetric int8 quantization with one scale per index of the
    ``keep_axes`` dims (all other dims share a scale)."""
    keep = {a % w.ndim for a in keep_axes}
    reduce_dims = tuple(d for d in range(w.ndim) if d not in keep)
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_dims,
                   keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return QuantizedTensor(q.astype(jnp.int8), scale)


def dequantize(t: QuantizedTensor, dtype=jnp.float32) -> jax.Array:
    return (t.q.astype(jnp.float32) * t.scale).astype(dtype)


def maybe_dequantize(leaf: Any, dtype=jnp.float32) -> Any:
    return dequantize(leaf, dtype) if isinstance(leaf, QuantizedTensor) \
        else leaf


def quantize_lm_params(params: Any) -> Any:
    """Quantize a models/transformer param tree for decoding: embeddings
    per-row, stacked layer matrices per (layer, out-channel); the tiny
    norm vectors stay exact. The result drops into
    ``transformer.generate`` directly."""
    out = dict(params)
    out["embed"] = quantize(params["embed"], keep_axes=(0,))
    out["pos"] = quantize(params["pos"], keep_axes=(0,))
    layers = dict(params["layers"])
    for k in ("wqkv", "wo", "w1", "w2"):
        if k in layers:
            layers[k] = quantize(layers[k], keep_axes=(0, -1))
    out["layers"] = layers
    return out
