"""Stacked SPMD row kernels for the mesh-sharded PS data plane.

One table's colocated :class:`~multiverso_tpu.ps.shard.RowShard`\\ s pool
their storage into ONE ``(S, R, C)`` device array sharded over a local
``("shards",)`` mesh axis (``ps/spmd.py``). These are the per-dispatch
programs over that layout: every device runs the SAME program on its own
shard slab(s) — the reference's worker-side ``Partition`` fan-out
(PAPER.md layer 5) turned server-side and mesh-placed, per the
``shard_map`` SPMD patterns in SNIPPETS.md rather than MPI-rank-style
one-array-per-process.

Bit-parity contract: each shard's slab update is EXACTLY the body of
``RowShard._row_update_fn`` (gather touched rows -> updater -> scatter),
vmapped over the stacked shard axis and partitioned with ``shard_map``.
The ops are elementwise per row (no cross-row reductions), so the
stacked program's arithmetic is bit-identical to S sequential per-shard
dispatches — asserted by tests/test_spmd_plane.py against the classic
path and by ``tools/bench_scale.py`` against a 1-shard oracle in-run.

Shape discipline: ids are padded to a shared power-of-two bucket with
each shard's OWN scratch row and zero deltas (the same trick every row
path uses, ``tables/matrix_table._bucket_size``), so there is one
compiled program per (bucket, donate) — zero steady-state recompiles.
Shards with no pending work in a wave round ride along as an all-scratch
zero-delta update, which is a no-op for every ROW_LOCAL_STATE updater on
a row that is never served.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from multiverso_tpu.updaters import AddOption
from multiverso_tpu.utils import platform as _platform

AXIS = "shards"


def _one_shard_update(updater, row_axes):
    """Per-shard update body — the exact ``RowShard._row_update_fn``
    program over one ``(R, C)`` slab. ``row_axes`` is the static tree of
    row-axis indices per updater-state leaf (-1 = row-free), computed
    once at plane build from the member shards' padded shape."""

    def _update(data, ustate, ids, vals, opt_leaves):
        opt = AddOption(*opt_leaves)
        rows = jnp.take(data, ids, axis=0)

        def gather(leaf, axis):
            return jnp.take(leaf, ids, axis=axis) if axis >= 0 else leaf

        gstate = jax.tree.map(gather, ustate, row_axes)
        new_rows, new_gstate = updater.apply(rows, gstate, vals, opt)
        data = data.at[ids].set(new_rows)

        def scatter(leaf, new_leaf, axis):
            if axis < 0:
                return new_leaf
            idx = (slice(None),) * axis + (ids,)
            return leaf.at[idx].set(new_leaf)

        ustate = jax.tree.map(scatter, ustate, new_gstate, row_axes)
        return data, ustate

    return _update


def build_apply(updater, row_axes, mesh: Optional[Any]):
    """ONE donated program applying a wave round for EVERY shard of the
    stack: ``(stack(S,R,C), ustate(S,...), ids(S,B), vals(S,B,C),
    opt_leaves((S,) each)) -> (stack, ustate)``. With a mesh, each
    device applies its local slab(s) via ``shard_map`` (no cross-device
    communication — ids are shard-local by construction); without one
    (single device) the vmap alone still makes it one dispatch."""
    inner = jax.vmap(_one_shard_update(updater, row_axes))
    if mesh is not None:
        from jax.sharding import PartitionSpec as P
        spec = P(AXIS)
        inner = _platform.shard_map(
            inner, mesh=mesh,
            in_specs=(spec, spec, spec, spec, spec),
            out_specs=(spec, spec))
    return jax.jit(inner, donate_argnums=(0, 1))


def build_gather(mesh: Optional[Any]):
    """One program serving every shard's row gather in a single
    dispatch: ``(stack(S,R,C), ids(S,B)) -> rows(S,B,C)``."""

    def _take(data, ids):
        return jnp.take(data, ids, axis=0)

    inner = jax.vmap(_take)
    if mesh is not None:
        from jax.sharding import PartitionSpec as P
        spec = P(AXIS)
        inner = _platform.shard_map(inner, mesh=mesh,
                                    in_specs=(spec, spec),
                                    out_specs=spec)
    return jax.jit(inner)


def build_slice():
    """Materialize ONE shard's slab out of a stacked leaf:
    ``(stacked, slot) -> stacked[slot]``. The slot index is a traced
    scalar, so one compile serves every member (no per-slot retrace)."""

    def _slice(stacked, slot):
        return jax.lax.dynamic_index_in_dim(stacked, slot, axis=0,
                                            keepdims=False)

    return jax.jit(_slice)


def opt_leaves(opts, dtype=jnp.float32):
    """Stack a list of per-shard :class:`AddOption`\\ s into per-field
    ``(S,)`` arrays (the vmap-able form). Integer fields stay int32."""
    import numpy as np
    cols = list(zip(*[tuple(o) for o in opts]))
    out = []
    for name, vals in zip(AddOption._fields, cols):
        if name == "worker_id":
            out.append(np.asarray(vals, np.int32))
        else:
            out.append(np.asarray(vals, np.float32))
    return tuple(out)
