"""Pallas TPU kernels for embedding-row traffic.

The hot ops behind MatrixTable row Get/Add and the word2vec inner loop are
row gather and row scatter-add over a large (V, D) table in HBM. These
kernels use the explicit-DMA TPU pattern: the row-id list is scalar-prefetched
into SMEM, the table stays resident in HBM (``memory_space=ANY``), and each
grid step issues 8 row-sized async DMAs HBM<->VMEM driven by the prefetched
ids — only the touched rows ever move, with no V-sized materialization.
(Block-mapped gathers can't do this: BlockSpec blocks need 8-row alignment,
and scattered ids aren't contiguous.)

Constraints (checked; callers fall back to the XLA path otherwise):
* D a multiple of 128 (lane width), B a multiple of 8 (sublane group),
  ids pre-deduplicated for scatter (MatrixTable._prep_ids guarantees all
  three: bucket sizes are powers of two >= 8 and ids are uniqued).
* On non-TPU backends the kernels run in interpreter mode (tests only);
  production fallback is the jnp take / at[].add path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_GROUP = 8  # rows per grid step (float32 sublane count)


def pallas_supported(d: int, b: int = _GROUP) -> bool:
    return (d % 128 == 0 and b % _GROUP == 0
            and jax.devices()[0].platform == "tpu")


# --------------------------------------------------------------------- #
# gather: out[i] = table[ids[i]]
# --------------------------------------------------------------------- #
def _gather_kernel(ids_ref, table_ref, out_ref, sems):
    step = pl.program_id(0)
    copies = []
    for j in range(_GROUP):
        row = ids_ref[step * _GROUP + j]
        copies.append(pltpu.make_async_copy(
            table_ref.at[pl.ds(row, 1), :],
            out_ref.at[pl.ds(j, 1), :],
            sems.at[j]))
    for c in copies:
        c.start()
    for c in copies:
        c.wait()


@functools.partial(jax.jit, static_argnames=("interpret",))
def embedding_gather(table: jax.Array, ids: jax.Array,
                     interpret: bool = False) -> jax.Array:
    """Gather rows of ``table`` (V, D) at ``ids`` (B,) via row-DMA."""
    _, d = table.shape
    b = ids.shape[0]
    assert b % _GROUP == 0, f"batch {b} must be a multiple of {_GROUP}"
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b // _GROUP,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((_GROUP, d), lambda i, ids: (i, 0)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((_GROUP,))],
    )
    return pl.pallas_call(
        _gather_kernel,
        out_shape=jax.ShapeDtypeStruct((b, d), table.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(ids, table)


# --------------------------------------------------------------------- #
# scatter-add: table[ids[i]] += deltas[i]   (in place, table donated)
# --------------------------------------------------------------------- #
def _scatter_kernel(ids_ref, table_ref, delta_ref, out_ref, scratch, sems):
    step = pl.program_id(0)
    # pull the 8 target rows into VMEM
    pulls = []
    for j in range(_GROUP):
        row = ids_ref[step * _GROUP + j]
        pulls.append(pltpu.make_async_copy(
            table_ref.at[pl.ds(row, 1), :],
            scratch.at[pl.ds(j, 1), :],
            sems.at[j]))
    for c in pulls:
        c.start()
    for c in pulls:
        c.wait()
    scratch[:] = scratch[:] + delta_ref[:]
    # push them back (out aliases table)
    pushes = []
    for j in range(_GROUP):
        row = ids_ref[step * _GROUP + j]
        pushes.append(pltpu.make_async_copy(
            scratch.at[pl.ds(j, 1), :],
            out_ref.at[pl.ds(row, 1), :],
            sems.at[j]))
    for c in pushes:
        c.start()
    for c in pushes:
        c.wait()


@functools.partial(jax.jit, static_argnames=("interpret",),
                   donate_argnums=(0,))
def embedding_scatter_add(table: jax.Array, ids: jax.Array,
                          deltas: jax.Array,
                          interpret: bool = False) -> jax.Array:
    """``table[ids] += deltas`` with the table updated in place (aliased).
    ``ids`` must be unique within the call (duplicates would race the
    read-modify-write across grid steps)."""
    v, d = table.shape
    b = ids.shape[0]
    assert b % _GROUP == 0, f"batch {b} must be a multiple of {_GROUP}"
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b // _GROUP,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),                 # table
            pl.BlockSpec((_GROUP, d), lambda i, ids: (i, 0)),     # deltas
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),           # table out
        scratch_shapes=[
            pltpu.VMEM((_GROUP, d), table.dtype),
            pltpu.SemaphoreType.DMA((_GROUP,)),
        ],
    )
    return pl.pallas_call(
        _scatter_kernel,
        out_shape=jax.ShapeDtypeStruct((v, d), table.dtype),
        grid_spec=grid_spec,
        input_output_aliases={1: 0},  # args: (ids, table, deltas) -> table
        interpret=interpret,
    )(ids, table, deltas)


def gather_reference(table, ids):
    return jnp.take(table, ids, axis=0)


def scatter_add_reference(table, ids, deltas):
    return table.at[ids].add(deltas)
