"""Jitted sparse-row assemble/apply kernels for the training read path.

The PS block pipeline moves row blocks between three homes — the wire
(host numpy), the hot-row cache's device mirror, and the padded
``(bucket, D)`` scan layout the block trainer consumes — and each move
used to be a host-side ``np.pad``/copy followed by a full
``device_put``.  These kernels keep the moves on device:

* :func:`pad_rows` — zero-pad a host row block straight into the scan
  bucket: ONE transfer of the real rows, the padding materializes
  in-graph (the old ``np.pad`` + ``jnp.asarray`` paid a full host copy
  of the padded block first).
* :func:`gather_pad_rows` — serve a block from the cache's device
  mirror: gather the requested positions and pad to the bucket in one
  program; nothing crosses the host boundary.
* :func:`scatter_add_rows` — write-through maintenance of the device
  mirror: scatter-add a pushed delta into the cached rows in-graph, so
  a push costs one small fused program instead of a full mirror
  rebuild.

All three are bucketed like every other row-batch program in the repo
(matrix_table's static-shape rule): one compiled program per (bucket,
dim, dtype), position arrays padded by the caller-facing wrappers so
retraces never key on the batch's exact size. Bit-parity with the
numpy equivalents is asserted by tests/test_we_pipeline.py — the
write-through cache's correctness story rests on the scatter-add
landing the IEEE-identical f32 sums the shard's updater lands.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnums=1, donate_argnums=())
def _pad_rows(rows: jax.Array, bucket: int) -> jax.Array:
    return jnp.pad(rows, ((0, bucket - rows.shape[0]), (0, 0)))


def pad_rows(rows, bucket: int) -> jax.Array:
    """Host (n, D) rows -> device (bucket, D) zero-padded block."""
    rows = jnp.asarray(rows)
    if rows.shape[0] == bucket:
        return rows
    if rows.shape[0] > bucket:
        raise ValueError(f"pad_rows: {rows.shape[0]} rows > bucket "
                         f"{bucket}")
    return _pad_rows(rows, bucket)


@partial(jax.jit, static_argnums=2)
def _gather_pad(rows: jax.Array, pos: jax.Array, bucket: int) -> jax.Array:
    """pos is padded to a stable length with an out-of-range sentinel;
    jnp.take in 'fill' mode lands zeros there — the pad rows of the
    output block, produced by the same gather that serves the real
    rows."""
    return jnp.take(rows, pos, axis=0, mode="fill", fill_value=0)


def gather_pad_rows(rows_dev, positions, bucket: int) -> jax.Array:
    """Device (H, D) cache mirror + host positions -> (bucket, D) padded
    block: one fused gather, no host assembly. ``positions`` may be any
    length <= bucket; the tail pads with zero rows (sentinel = H, PAST
    the last row — 'fill' mode wraps NEGATIVE indices like plain numpy,
    so -1 would gather the last real row instead of filling)."""
    pos = np.asarray(positions, np.int64).reshape(-1)
    if pos.size > bucket:
        raise ValueError(f"gather_pad_rows: {pos.size} positions > "
                         f"bucket {bucket}")
    full = np.full(bucket, rows_dev.shape[0], np.int64)   # -> fill 0
    full[: pos.size] = pos
    return _gather_pad(rows_dev, jnp.asarray(full), bucket)


def bucket_rows(n: int, floor: int = 8) -> int:
    """Next power of two >= n (>= floor): the static-shape rule every
    row-batch program in the repo follows — one compiled program per
    (bucket, dim, dtype), never one per exact batch size. Without it the
    scatter-add retraced on every new (mirror height, push size) pair,
    which the bench's zero-steady-recompiles gate caught in the wild."""
    b = floor
    while b < n:
        b <<= 1
    return b


@jax.jit
def _scatter_add(rows: jax.Array, pos: jax.Array,
                 delta: jax.Array) -> jax.Array:
    return rows.at[pos].add(delta, mode="drop")


def scatter_add_rows(rows_dev, positions, delta) -> jax.Array:
    """Device (H, D) mirror + pushed (n, D) delta -> updated mirror,
    scatter-add in-graph. Positions must be unique (the add path's
    _prep dedupe contract) so each row sees exactly ONE f32 add — the
    same operand order the shard's default updater applies, hence the
    bit-identical write-through guarantee. The batch is padded to a
    power-of-two bucket (sentinel position H = out of range, dropped by
    ``mode="drop"``; zero delta rows ride along dead) so steady-state
    pushes of varying size reuse ONE compiled program."""
    pos = np.asarray(positions, np.int64).reshape(-1)
    delta = np.asarray(delta)
    b = bucket_rows(pos.size)
    if b != pos.size:
        full = np.full(b, rows_dev.shape[0], np.int64)   # dropped
        full[: pos.size] = pos
        pad = np.zeros((b - pos.size,) + delta.shape[1:], delta.dtype)
        pos, delta = full, np.concatenate([delta, pad])
    return _scatter_add(rows_dev, jnp.asarray(pos), jnp.asarray(delta))
