"""Device-resident wire-compression kernels.

Jitted JAX implementations of the filter layer in ``utils/filters.py``
(the reference's quantization_util.h surface — SparseFilter and the
1-bit SGD OneBitsFilter recipe, Seide et al. 2014 / Alistarh et al.
2017).  The numpy filters remain the REFERENCE implementation; the
kernels here are property-tested to match them **bit-for-bit** on the
encoded bits and per-block scales (tests/test_wire_codec.py), so a
payload encoded on one side always decodes identically on the other.

Bit-for-bit parity is engineered, not hoped for:

* per-block sums use an explicit pairwise fold (:func:`fold_sum` here,
  ``filters._fold_sum`` on the numpy side) — the identical sequence of
  f32 additions on both sides, where a naive ``sum()`` would differ in
  the last ulp between numpy's pairwise reduction and XLA's;
* masking uses ``where`` (select), never multiply, so XLA cannot fuse a
  multiply-add into an FMA with different rounding;
* the scale division is a single f32/f32 divide on both sides;
* bit packing is ``jnp.packbits``/``np.packbits`` (MSB-first), exact.

Who runs where: encode kernels run on whatever device their inputs live
on.  For host-resident payloads :func:`host_codec_device` supplies a CPU
device so the f32 payload never crosses the accelerator wire just to be
compressed (the whole point is to ship FEWER bytes over that seam);
decode runs in-graph on the table's devices, fused into the updater
apply (table.py builds those programs from :func:`onebit_decode` /
:func:`topk_decode`).

Error feedback (1bit / topk): the quantization error is returned as a
new residual to be added to the next payload, carried as device state by
the caller — it never round-trips through the host.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


_TINY = np.float32(np.finfo(np.float32).tiny)


def canon_f32(x: jnp.ndarray) -> jnp.ndarray:
    """Flush sub-normals to zero — codec property shared with the numpy
    reference (``filters.canon_f32``). XLA flushes denormals (FTZ) in any
    case the moment arithmetic touches them; making the flush explicit on
    BOTH sides is what keeps bits/scales/residuals bit-identical when the
    input contains them."""
    return jnp.where(jnp.abs(x) < _TINY, jnp.float32(0), x)


def fold_sum(x: jnp.ndarray) -> jnp.ndarray:
    """Pairwise-fold sum over axis 1. Width must be a power of two (pad
    with zeros first); mirrors ``filters._fold_sum`` addition-for-addition."""
    while x.shape[1] > 1:
        x = x[:, 0::2] + x[:, 1::2]
    return x[:, 0]


def _pow2_pad(width: int) -> int:
    return 1 << max(width - 1, 0).bit_length() if width > 1 else 1


def block_scales(blocks: jnp.ndarray, n: Optional[int] = None
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(pos mask, pos_scale, neg_scale) for (nb, block) f32 blocks —
    mean of positives / mean magnitude of non-positives per block.
    ``n`` (logical element count, static): the block-padding tail beyond
    it is EXCLUDED from the negative-side mean, mirroring
    ``filters._block_scales`` (pad zeros are not data; counting them
    dilutes the last block's neg scale and destabilizes error
    feedback)."""
    nb, block = blocks.shape
    pos = blocks > 0
    neg = ~pos
    if n is not None and n < nb * block:
        valid = (jnp.arange(nb * block) < n).reshape(nb, block)
        neg = neg & valid
    m = _pow2_pad(block)

    def _mean(vals, mask):
        picked = jnp.where(mask, vals, jnp.float32(0))
        if m != block:
            picked = jnp.pad(picked, ((0, 0), (0, m - block)))
        s = fold_sum(picked)
        cnt = jnp.maximum(mask.sum(1), 1).astype(jnp.float32)
        return jnp.where(mask.any(1), s / cnt, jnp.float32(0))

    return pos, _mean(blocks, pos), _mean(-blocks, neg)


@partial(jax.jit, static_argnames=("block",))
def onebit_encode(flat: jnp.ndarray, residual: jnp.ndarray,
                  block: int = 1024
                  ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """1-bit sign-pack with error feedback (filters.OneBitsFilter.filter_in).

    Returns ``(bits u8[ceil(n/block)*block/8], scales f32[nb, 2],
    new_residual f32[n])``. ``block`` must be a multiple of 8."""
    if block % 8:
        raise ValueError(f"block must be a multiple of 8, got {block}")
    flat = canon_f32(flat.reshape(-1).astype(jnp.float32) + residual)
    n = flat.shape[0]
    nb = -(-n // block)
    padded = jnp.zeros(nb * block, jnp.float32).at[:n].set(flat)
    pos, pos_scale, neg_scale = block_scales(padded.reshape(nb, block),
                                             n=n)
    bits = jnp.packbits(pos.reshape(-1))
    decoded = jnp.where(pos, pos_scale[:, None],
                        -neg_scale[:, None]).reshape(-1)[:n]
    return bits, jnp.stack([pos_scale, neg_scale], axis=1), flat - decoded


@partial(jax.jit, static_argnames=("n", "block"))
def onebit_decode(bits: jnp.ndarray, scales: jnp.ndarray, n: int,
                  block: int = 1024) -> jnp.ndarray:
    """Inverse of :func:`onebit_encode` (filters.OneBitsFilter.filter_out)."""
    nb = -(-n // block)
    pos = jnp.unpackbits(bits, count=nb * block).reshape(nb, block) > 0
    flat = jnp.where(pos, scales[:, 0:1], -scales[:, 1:2])
    return flat.reshape(-1)[:n]


@partial(jax.jit, static_argnames=("k",))
def topk_encode(flat: jnp.ndarray, residual: jnp.ndarray, k: int
                ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sparse top-magnitude encode with error feedback (QSGD-style
    sparsification): keep the k largest-|x| entries exactly, accumulate
    the rest into the residual. Ties break toward the lower index, same
    as the numpy reference (``filters.TopKFilter``).

    Returns ``(idx i32[k], vals f32[k], new_residual f32[n])``."""
    flat = canon_f32(flat.reshape(-1).astype(jnp.float32) + residual)
    _, idx = jax.lax.top_k(jnp.abs(flat), k)
    idx = idx.astype(jnp.int32)
    vals = flat[idx]
    return idx, vals, flat.at[idx].set(jnp.float32(0))


@partial(jax.jit, static_argnames=("n",))
def topk_decode(idx: jnp.ndarray, vals: jnp.ndarray, n: int) -> jnp.ndarray:
    """Inverse of :func:`topk_encode` (zeros off-support)."""
    return jnp.zeros(n, vals.dtype).at[idx].set(vals)


@jax.jit
def bf16_cast(x: jnp.ndarray) -> jnp.ndarray:
    """bf16 down-cast for the Get reply wire (table.py's snapshot encode:
    half the download bytes). Deliberately NON-donating — the only f32
    this path ever casts is the live table data, which must survive the
    cast. (A donating variant was dropped: every other bf16 encode in
    the system is a host-side numpy cast before upload, so there is no
    throwaway device f32 to donate.)"""
    return x.astype(jnp.bfloat16)


def host_codec_device() -> Optional[jax.Device]:
    """A CPU device for encoding HOST payloads: compression must shrink
    the bytes crossing the accelerator seam, so the f32 input cannot be
    shipped to the accelerator just to be encoded. None when the CPU
    platform is unavailable (callers fall back to the numpy filters)."""
    try:
        devs = jax.local_devices(backend="cpu")
    except RuntimeError:
        return None
    return devs[0] if devs else None


def onebit_compressed_nbytes(n: int, block: int = 1024) -> int:
    """Wire bytes of a 1-bit payload (bits + scales) for n f32 elements."""
    nb = -(-n // block)
    return nb * block // 8 + nb * 8


def topk_compressed_nbytes(k: int) -> int:
    """Wire bytes of a top-k payload (i32 idx + f32 vals)."""
    return 8 * k


def default_topk(n: int) -> int:
    """Default sparse-encode support: ~3% of entries (≈16x fewer wire
    bytes than f32), at least one."""
    return max(n // 32, 1)
