"""Hand-written TPU kernels and quantized-tensor ops."""
from multiverso_tpu.ops.attention_kernels import flash_attention
from multiverso_tpu.ops.quantization import (
    QuantizedTensor, dequantize, quantize, quantize_lm_params)

__all__ = ["QuantizedTensor", "dequantize", "flash_attention",
           "quantize", "quantize_lm_params"]
