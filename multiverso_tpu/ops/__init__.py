from multiverso_tpu.ops.attention_kernels import flash_attention
from multiverso_tpu.ops.embedding_kernels import (
    embedding_gather, embedding_scatter_add, pallas_supported)
from multiverso_tpu.ops.quantization import (
    QuantizedTensor, dequantize, quantize, quantize_lm_params)

__all__ = ["QuantizedTensor", "dequantize", "embedding_gather",
           "embedding_scatter_add", "flash_attention", "pallas_supported",
           "quantize", "quantize_lm_params"]
