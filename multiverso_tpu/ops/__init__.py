from multiverso_tpu.ops.attention_kernels import flash_attention
from multiverso_tpu.ops.embedding_kernels import (
    embedding_gather, embedding_scatter_add, pallas_supported)

__all__ = ["embedding_gather", "embedding_scatter_add", "flash_attention",
           "pallas_supported"]
