"""Binding-style table handlers.

Parity surface for the reference Python binding
(ref: binding/python/multiverso/tables.py — ArrayTableHandler /
MatrixTableHandler over the C ABI; float32-only; the *master-init convention*:
worker 0 Adds the init value while the others Add zeros so the shared value is
initialized exactly once, tables.py:50-57). Users of the reference binding
can switch imports and keep their code.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import multiverso_tpu as mv


class ArrayTableHandler:
    def __init__(self, size: int, init_value=None, name: str = "array"):
        self._table = mv.ArrayTable(int(size), dtype=np.float32, name=name)
        self.size = int(size)
        if init_value is not None:
            init_value = np.asarray(init_value, dtype=np.float32).reshape(-1)
            # master-init: only worker 0 contributes the value; everyone
            # participates in the Add so the barrier semantics match
            # (ref tables.py:50-57)
            if mv.is_master_worker():
                self._table.add(init_value)
            else:
                self._table.add(np.zeros_like(init_value))
            mv.barrier()

    def get(self, out: Optional[np.ndarray] = None) -> np.ndarray:
        return self._table.get(out=out)

    def add(self, data, sync: bool = False) -> None:
        """ref tables.py add(data, sync=False): async by default; a later
        get always reflects this add regardless (the table chains state at
        dispatch), sync=True additionally blocks until it completes."""
        data = np.asarray(data, dtype=np.float32).reshape(-1)
        if sync:
            self._table.add(data)
        else:
            self._table.add_async(data)

    @property
    def table(self) -> mv.ArrayTable:
        return self._table


class MatrixTableHandler:
    def __init__(self, num_row: int, num_col: int, init_value=None,
                 name: str = "matrix"):
        self._table = mv.MatrixTable(int(num_row), int(num_col),
                                     dtype=np.float32, name=name)
        self.num_row, self.num_col = int(num_row), int(num_col)
        if init_value is not None:
            init_value = np.asarray(init_value, dtype=np.float32).reshape(
                self.num_row, self.num_col)
            if mv.is_master_worker():
                self._table.add(init_value)
            else:
                self._table.add(np.zeros_like(init_value))
            mv.barrier()

    @staticmethod
    def _check_row_ids(row_ids):
        # the table's _prep_ids re-validates; checking here too makes the
        # legacy-positional mistake (h.get(buf) / h.add(data, False)) fail
        # before any table work, with empty batches deferred to the
        # table's clearer "empty row_ids" error
        arr = np.asarray(row_ids)
        if arr.size and not np.issubdtype(arr.dtype, np.integer):
            raise TypeError(
                f"row_ids must be integers, got dtype {arr.dtype} (out= and "
                f"sync= are keyword-only to keep this surface unambiguous)")

    def get(self, row_ids=None, *,
            out: Optional[np.ndarray] = None) -> np.ndarray:
        """Whole table, or just ``row_ids`` when given — the reference
        binding's single-method surface (ref tables.py:108
        ``get(row_ids=None)``). ``out`` is keyword-only so a legacy
        positional buffer cannot be misread as row ids."""
        if row_ids is None:
            return self._table.get(out=out)
        self._check_row_ids(row_ids)
        return self._table.get_rows(row_ids, out=out)

    def add(self, data, row_ids=None, *, sync: bool = False) -> None:
        """Whole-table add, or a row-batch add when ``row_ids`` is given
        (ref tables.py:132 ``add(data, row_ids=None, sync=False)``);
        ``sync`` is keyword-only for the same ambiguity reason as ``get``
        and async by default like the reference (later gets still see the
        add — the table chains state at dispatch)."""
        if row_ids is not None:
            self._check_row_ids(row_ids)
            return self.add_rows(row_ids, data, sync=sync)
        data = np.asarray(data, dtype=np.float32).reshape(
            self.num_row, self.num_col)
        if sync:
            self._table.add(data)
        else:
            self._table.add_async(data)

    def get_rows(self, row_ids, out: Optional[np.ndarray] = None) -> np.ndarray:
        return self._table.get_rows(row_ids, out=out)

    def add_rows(self, row_ids, values, sync: bool = False) -> None:
        if sync:
            self._table.add_rows(row_ids, values)
        else:
            self._table.add_rows_async(row_ids, values)

    @property
    def table(self) -> mv.MatrixTable:
        return self._table
