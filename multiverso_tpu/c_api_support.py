"""Python side of the C ABI shim (see native/mv_capi.cpp).

The C layer passes raw pointers as integers; this module wraps them with
ctypes into zero-copy numpy views and forwards to the real tables. Handles
are small integers into a registry (the reference's ``TableHandler = void*``,
ref include/multiverso/c_api.h:14).
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict

import numpy as np

if os.environ.get("MV_CAPI_PLATFORM"):
    # Embedded-interpreter platform pin (the C test driver runs on the CPU
    # mesh so it can't fight another process for the one TPU chip). Env
    # JAX_PLATFORMS is overridden by the site hook here, so this must go
    # through jax.config before any backend use — same trick as
    # utils/platform.force_cpu_mesh.
    import jax
    jax.config.update("jax_platforms", os.environ["MV_CAPI_PLATFORM"])
    if os.environ.get("MV_CAPI_CPU_DEVICES"):
        jax.config.update("jax_num_cpu_devices",
                          int(os.environ["MV_CAPI_CPU_DEVICES"]))

import multiverso_tpu as mv

_tables: Dict[int, object] = {}
_next_handle = 1


def _view(addr: int, size: int) -> np.ndarray:
    return np.ctypeslib.as_array(
        (ctypes.c_float * size).from_address(addr))


def _iview(addr: int, size: int) -> np.ndarray:
    return np.ctypeslib.as_array(
        (ctypes.c_int32 * size).from_address(addr))


def init() -> None:
    mv.init()


def shutdown() -> None:
    mv.shutdown()


def barrier() -> None:
    # The C ABI has no flush entry point; FFI clients (the reference's Lua
    # test battery) use MV_Barrier as the fence after async adds. Sync
    # tables are fenced by mv.barrier()'s dirty-shard walk; async-plane
    # tables need an explicit flush of this process's outstanding ops.
    # The barrier itself must run even if a flush raises (a swept
    # fire-and-forget failure or dead peer): aborting early would leave
    # the other ranks blocked in mv.barrier() forever — the C layer only
    # prints-and-clears Python errors, it cannot unwind the peers.
    errors = []
    for t in list(_tables.values()):
        flush = getattr(t, "flush", None)
        if callable(flush):
            try:
                flush()
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(exc)
    mv.barrier()
    if errors:
        # Surface EVERY flush failure, not just the first: the remaining
        # ones are logged (a multi-table flush failure must not vanish
        # behind the one that raises) and chained onto the raised
        # exception as its __cause__ so tracebacks show at least two.
        from multiverso_tpu.utils import log
        for exc in errors[1:]:
            log.error("barrier: additional async-table flush failure "
                      "(first one is raised): %s: %s",
                      type(exc).__name__, exc)
        if len(errors) > 1:
            raise errors[0] from errors[1]
        raise errors[0]


def num_workers() -> int:
    return mv.num_workers()


def worker_id() -> int:
    return mv.worker_id()


def server_id() -> int:
    return mv.server_id()


def _register(table) -> int:
    global _next_handle
    handle = _next_handle
    _next_handle += 1
    _tables[handle] = table
    return handle


def new_array_table(size: int) -> int:
    return _register(mv.ArrayTable(size, dtype=np.float32,
                                   name=f"c_array_{_next_handle}"))


def array_get(handle: int, addr: int, size: int) -> None:
    _tables[handle].get(out=_view(addr, size))


def array_add(handle: int, addr: int, size: int, do_wait: int) -> None:
    t = _tables[handle]
    data = _view(addr, size).copy()
    if do_wait:
        t.add(data)
    else:
        t.add_async(data)


def new_matrix_table(num_row: int, num_col: int) -> int:
    return _register(mv.MatrixTable(num_row, num_col, dtype=np.float32,
                                    name=f"c_matrix_{_next_handle}"))


def new_async_array_table(size: int) -> int:
    """Uncoordinated-plane array table for FFI clients (beyond the
    reference C API, which only reached the sync tables): every process
    owns a row range served by its PSService, ops ride the native C++
    transport where built. The generic array_get/array_add accessors
    work unchanged — the async tables share the op surface."""
    return _register(mv.AsyncArrayTable(size, dtype=np.float32,
                                        name=f"c_async_array_{_next_handle}"))


def new_async_matrix_table(num_row: int, num_col: int) -> int:
    """Uncoordinated-plane matrix table for FFI clients (see
    new_async_array_table); matrix_* accessors work unchanged."""
    return _register(mv.AsyncMatrixTable(
        num_row, num_col, dtype=np.float32,
        name=f"c_async_matrix_{_next_handle}"))


def matrix_get_all(handle: int, addr: int, size: int) -> None:
    t = _tables[handle]
    _view(addr, size)[:] = t.get().reshape(-1)[:size]


def matrix_add_all(handle: int, addr: int, size: int, do_wait: int) -> None:
    t = _tables[handle]
    data = _view(addr, size).copy().reshape(t.num_row, t.num_col)
    if do_wait:
        t.add(data)
    else:
        t.add_async(data)


def matrix_get_rows(handle: int, addr: int, size: int, ids_addr: int,
                    ids_n: int) -> None:
    t = _tables[handle]
    ids = _iview(ids_addr, ids_n).copy()
    rows = t.get_rows(ids)
    _view(addr, size)[:] = rows.reshape(-1)[:size]


def matrix_add_rows(handle: int, addr: int, size: int, ids_addr: int,
                    ids_n: int, do_wait: int) -> None:
    t = _tables[handle]
    ids = _iview(ids_addr, ids_n).copy()
    vals = _view(addr, size).copy().reshape(ids_n, t.num_col)
    if do_wait:
        t.add_rows(ids, vals)
    else:
        t.add_rows_async(ids, vals)
