"""DLRM online serving: train-while-serve over the async PS + replica.

The "millions of users" workload (ROADMAP open item 3): a recommender
whose embedding tables live in the sharded async PS, with TWO traffic
classes hitting them at once —

* **training** (class ``"train"``): workers pull the minibatch's rows
  straight from the owning shards (read-your-writes), compute the DLRM
  loss/gradients in one jitted program (models/dlrm.py), and push the
  row gradients back as ``add_rows`` deltas the server-side updater
  applies (AdaGrad by default) — the reference's async PS loop;
* **inference** (class ``"infer"``): a pool of clients scores
  (user, item) candidates against a **bounded-staleness read replica**
  (serving/replica.py) instead of the shards — zero wire hops per
  request, a device-resident hot-row cache under the zipf head, and
  admission control shedding excess load before it can crowd the
  training writes (serving/admission.py).

The two classes meet only at the replica's epoch cadence (MSG_SNAPSHOT
pulls), which is the whole point: inference QPS scales without loading
the write path, at a staleness cost that is bounded and advertised.

Driven by ``tools/bench_serving.py`` (served QPS, tail latency,
staleness, shed rate -> bench ``extra.serving``); the operator story is
docs/SERVING.md.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from multiverso_tpu.models import dlrm
from multiverso_tpu.ps.tables import AsyncMatrixTable
from multiverso_tpu.serving.admission import AdmissionController
from multiverso_tpu.serving.replica import ReadReplica
from multiverso_tpu.telemetry import devstats as _devstats
from multiverso_tpu.telemetry import profiler as _prof
from multiverso_tpu.updaters import AddOption


class DLRMServing:
    """One process's view of the train-while-serve recommender.

    The embedding table is the PS object (shared across ranks); the
    dot-interaction MLP is deliberately local to the trainer — it is
    tiny next to the embeddings (the PS story is the sparse side), and
    inference reads it in-process. ``start_replica=False`` leaves the
    replica in manual-refresh mode (tests, step-driven loops).
    """

    def __init__(self, cfg: dlrm.DLRMConfig, ctx=None,
                 name: str = "dlrm_serving", updater: str = "adagrad",
                 lr: float = 0.1, seed: int = 0,
                 infer_qps: float = 0.0,
                 cache_rows: Optional[int] = None,
                 refresh_s: Optional[float] = None,
                 staleness_s: Optional[float] = None,
                 start_replica: bool = True):
        self.cfg = cfg
        self.emb = AsyncMatrixTable(
            dlrm.total_rows(cfg), cfg.embed_dim, updater=updater,
            seed=seed, init_scale=0.05, name=f"{name}_emb", ctx=ctx)
        self.mlp = dlrm.init_mlp_params(cfg, seed)
        self._offsets = dlrm.field_offsets(cfg)
        self._opt = AddOption(learning_rate=lr, rho=0.1)
        self._mlp_lr = lr
        cfg_ = cfg

        def _grad(mlp, rows, dense, labels):
            loss, (g_mlp, g_rows) = jax.value_and_grad(
                dlrm.loss_fn, argnums=(0, 1))(mlp, rows, dense, labels,
                                              cfg_)
            return loss, g_mlp, g_rows

        self._grad = jax.jit(_grad)
        self._fwd = jax.jit(
            lambda mlp, rows, dense: jax.nn.sigmoid(
                dlrm.forward(mlp, rows, dense, cfg_)))
        self.admission = AdmissionController()
        if infer_qps > 0:
            self.admission.set_limit(self.emb.name, "infer", infer_qps)
        # MLP updates from concurrent trainer threads apply DELTAS to
        # the current params under this lock (async-SGD semantics,
        # same contract as the embedding side: gradients computed
        # against a pulled snapshot, applied to whatever the params
        # are now) — an unguarded read-modify-write rebind would let
        # two trainers silently drop each other's updates
        self._mlp_lock = threading.Lock()
        self.replica = ReadReplica(
            self.emb, admission=self.admission, cache_rows=cache_rows,
            refresh_s=refresh_s, staleness_s=staleness_s,
            start=start_replica)

    # ------------------------------------------------------------------ #
    def _ids(self, cat: np.ndarray) -> np.ndarray:
        """[B, F] per-field categorical ids -> flat global row ids in
        the one concatenated embedding table."""
        return (np.asarray(cat, np.int64)
                + self._offsets[None, :]).reshape(-1)

    def train_step(self, cat, dense, labels) -> Tuple[float, float]:
        """One async-PS training step: gather rows from the shards,
        grad, push row-gradient deltas (blocking — the ack means
        applied). Returns ``(loss, write_ms)``: the write latency is
        the serving bench's protected metric (admission control exists
        so THIS number survives an inference storm). Profiled as one
        step (flag ``step_profile``): prepare / ps_wait / compute
        phases + the table layer's ps.get / ps.add async spans."""
        import time
        with _prof.step("dlrm.train_step"):
            with _prof.phase("prepare"):
                b, f = np.asarray(cat).shape
                ids = self._ids(cat)
            with _prof.phase("ps_wait"):
                rows = self.emb.get_rows(ids).reshape(
                    b, f, self.cfg.embed_dim)
            with _prof.phase("compute"):
                if _prof.enabled():
                    _prof.watch_jit("dlrm.grad", self._grad)
                # pulled rows ride to device through the devstats
                # chokepoint (per-direction device-plane accounting +
                # the profiler's per-step transfer delta)
                _devstats.note_transfer(rows.nbytes, "h2d")
                loss, g_mlp, g_rows = self._grad(
                    self.mlp, jnp.asarray(rows), jnp.asarray(dense),
                    jnp.asarray(labels))
                with self._mlp_lock:
                    self.mlp = jax.tree.map(
                        lambda p, g: p - self._mlp_lr * g,
                        self.mlp, g_mlp)
                g_host = np.asarray(g_rows).reshape(
                    b * f, self.cfg.embed_dim)
                _devstats.note_transfer(g_host.nbytes, "d2h")
            t0 = time.perf_counter()
            # duplicate ids (same user twice in a batch) f64-accumulate
            # in the client's _dedupe_batch — scatter-add semantics,
            # exactly the fused path's .at[].add
            with _prof.phase("push"):
                self.emb.add_rows(ids, g_host, self._opt)
            return float(loss), (time.perf_counter() - t0) * 1e3

    def infer(self, cat, dense, cls: str = "infer") -> np.ndarray:
        """Score candidates against the replica (bounded staleness;
        may shed with SheddingError under admission pressure).
        Returns click probabilities [B]."""
        b, f = np.asarray(cat).shape
        rows = self.replica.get_rows(self._ids(cat), cls=cls).reshape(
            b, f, self.cfg.embed_dim)
        return np.asarray(self._fwd(self.mlp, jnp.asarray(rows),
                                    jnp.asarray(dense)))

    def serving_stats(self) -> Dict[str, Any]:
        return self.replica.stats()

    def close(self) -> None:
        self.replica.close()
