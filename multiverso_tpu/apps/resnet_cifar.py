"""ResNet-CIFAR data-parallel trainer (BASELINE config 5 analogue).

Reference workload: Torch fb.resnet ResNet-18 / Lasagne ResNet-32 on
CIFAR-10, data-parallel across Multiverso workers with all parameters in one
ArrayTable (ref: binding/lua/docs/BENCHMARK.md, binding/python/docs/
BENCHMARK.md — 4 workers ≈ 3.2-3.4x speedup). TPU-native shape:

* every parameter in one ArrayTable with the server-side **Adam** updater
* the batch sharded over the mesh (each shard = one reference "worker");
  XLA's sharding propagation inserts the gradient psum the PS Add used to
  carry over MPI
* the whole epoch is one jitted ``lax.scan`` — worker compute, gradient
  merge, and server update fuse into a single program per step
* BatchNorm running stats stay worker-local (the reference keeps BN local
  per GPU too) and ride the scan carry

Usage: ``python -m multiverso_tpu.apps.resnet_cifar -depth 20 -epochs 2``
(synthetic CIFAR unless ``-train_npz`` pointing at arrays is given).
"""

from __future__ import annotations

import sys
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import multiverso_tpu as mv
from multiverso_tpu.models import resnet as resnet_lib
from multiverso_tpu.updaters import AddOption
from multiverso_tpu.utils import log


class ResNetTrainer:
    def __init__(self, depth: int = 20, num_classes: int = 10,
                 image_size: int = 32, batch_size: int = 128,
                 learning_rate: float = 1e-3, seed: int = 0):
        if not mv.Zoo.get().started:
            mv.init()
        self.batch_size = batch_size
        self.learning_rate = learning_rate
        params, bn = resnet_lib.init_resnet(
            jax.random.key(seed), depth=depth, num_classes=num_classes)
        flat, self._meta = resnet_lib.flatten_params(params)
        self.n_params = flat.size
        self.table = mv.ArrayTable(flat.size, updater="adam", init=flat,
                                   name=f"resnet{depth}_params")
        self.bn = bn
        self._mesh = mv.mesh()
        self._axis = mv.Zoo.get().shard_axis()

    def _shard_batches(self, x: np.ndarray, y: np.ndarray):
        b = self.batch_size
        n = (len(y) // b) * b
        xb = x[:n].reshape(-1, b, *x.shape[1:])
        yb = y[:n].reshape(-1, b)
        sharding = NamedSharding(self._mesh, P(None, self._axis))
        return (jax.device_put(jnp.asarray(xb),
                               NamedSharding(self._mesh,
                                             P(None, self._axis, None, None,
                                               None))),
                jax.device_put(jnp.asarray(yb), sharding))

    def _epoch_fn(self):
        if hasattr(self, "_epoch_jit"):
            return self._epoch_jit
        table, meta = self.table, self._meta
        opt = AddOption(learning_rate=self.learning_rate)

        def step(carry, batch):
            state, bn = carry
            x, y = batch
            flat = state["data"][: self.n_params]
            params = resnet_lib.unflatten_params(flat, meta)

            def lf(p):
                return resnet_lib.loss_fn(p, bn, x, y, train=True)

            (loss, new_bn), grads = jax.value_and_grad(lf, has_aux=True)(
                params)
            gflat, _ = jax.tree.flatten(grads)
            delta = jnp.concatenate([g.reshape(-1) for g in gflat])
            delta = jnp.zeros(table.padded_shape, table.dtype
                              ).at[: delta.size].set(delta)
            state = table.functional_add(state, delta, opt)
            return (state, new_bn), loss

        @jax.jit
        def epoch(state, bn, xb, yb):
            (state, bn), losses = jax.lax.scan(step, (state, bn), (xb, yb))
            return state, bn, losses

        self._epoch_jit = epoch
        return epoch

    def train(self, x: np.ndarray, y: np.ndarray,
              epochs: int = 1) -> Dict[str, float]:
        xb, yb = self._shard_batches(x, y)
        epoch = self._epoch_fn()
        state, bn = self.table.state, self.bn
        t0, losses = time.perf_counter(), None
        for _ in range(epochs):
            state, bn, losses = epoch(state, bn, xb, yb)
        # host readback = reliable device drain (block_until_ready can
        # return early over a remote/tunneled PJRT transport)
        loss = float(jnp.mean(losses))
        dt = time.perf_counter() - t0
        self.table.adopt(state)
        self.bn = bn
        n = int(np.prod(yb.shape)) * epochs
        return {"loss": loss,
                "images_per_sec": n / dt, "seconds": dt,
                "sec_per_epoch": dt / epochs}

    def evaluate(self, x: np.ndarray, y: np.ndarray) -> float:
        params = resnet_lib.unflatten_params(
            self.table.get()[: self.n_params], self._meta)
        logits, _ = resnet_lib.apply_resnet(params, self.bn,
                                            jnp.asarray(x), train=False)
        return float(jnp.mean((jnp.argmax(logits, -1) == jnp.asarray(y))
                              .astype(jnp.float32)))


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    kw = {}
    i = 0
    while i < len(argv) - 1:
        if argv[i].startswith("-"):
            kw[argv[i].lstrip("-")] = argv[i + 1]
            i += 2
        else:
            i += 1
    depth = int(kw.get("depth", 20))
    epochs = int(kw.get("epochs", 1))
    batch = int(kw.get("batch_size", 128))
    n = int(kw.get("num_samples", 2048))
    mv.init()
    trainer = ResNetTrainer(depth=depth, batch_size=batch)
    x, y = resnet_lib.synthetic_cifar(n, seed=1)
    stats = trainer.train(x, y, epochs=epochs)
    log.info("resnet%d train: %s", depth, stats)
    xt, yt = resnet_lib.synthetic_cifar(512, seed=2)
    log.info("eval accuracy: %.4f", trainer.evaluate(xt, yt))
    mv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
