"""WordEmbedding application (distributed word2vec).

TPU-native re-build of the reference WordEmbedding app
(ref: Applications/WordEmbedding/src/distributed_wordembedding.cpp — block
pipeline driver; src/communicator.cpp — PS glue pulling rows per block and
pushing (new-old)/workers deltas; src/trainer.cpp — words/sec reporting;
src/util.cpp — argv config). Capability parity:

* skipgram / CBOW, negative sampling / hierarchical softmax
* min_count vocab pruning, frequent-word subsampling, dynamic window
* block pipeline: per data block, pull the block's vocabulary rows from the
  parameter tables, train the block as ONE packed ``lax.scan``, push deltas
  — block N+1's prep/pull overlaps training block N (ref :178-227 OMP
  overlap; here prefetch threads on the device plane, the async-dispatch
  pull on the host plane)
* KVTable word-count aggregation across workers (ref communicator.cpp:17-31)
* stopword filtering (-stopwords 1 -sw_file; ref reader.cpp:11-47) and
  binary vector output (-binary 1; ref util.h:26 + the WriteToFile .bin
  layout), with a round-tripping loader (``load_embeddings``)
* words/sec per chip reporting

Two execution paths:
* ``train_fused``: the whole corpus trains on device via a jitted scan — the
  TPU-first path used for the headline words/sec benchmark.
* ``train_ps_blocks``: the reference's block Get/Add flow — the
  semantics-parity path. Single-worker sync runs fuse each block's
  pull/train/push into one device program (``ps_device_plane``);
  multi-worker and async runs pull/push through the table wire with the
  same packed-scan compute.

Usage: ``python -m multiverso_tpu.apps.word_embedding -train_file f.txt
-output vec.txt -size 128 ...`` (argv keys mirror ref util.cpp ParseArgs).
"""

from __future__ import annotations

import sys
import threading
import time
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import multiverso_tpu as mv
from multiverso_tpu import native
from multiverso_tpu.data.dictionary import Dictionary, build_huffman
from multiverso_tpu.io.sample_reader import BlockPrepareQueue
from multiverso_tpu.models import word2vec as w2v
from multiverso_tpu.ops import row_assemble as _rowasm
from multiverso_tpu.telemetry import devstats as _devstats
from multiverso_tpu.telemetry import memstats as _memstats
from multiverso_tpu.telemetry import profiler as _prof
from multiverso_tpu.utils import config, log
from multiverso_tpu.tables.matrix_table import _bucket_size
from multiverso_tpu.utils.async_buffer import AsyncBuffer
from multiverso_tpu.utils.dashboard import monitor

config.define_int(
    "we_prepare_depth", 4,
    "WordEmbedding prepared-block queue depth (blocks produced but not "
    "yet trained, BOTH PS planes): bounds host prep memory while letting "
    "producers run ahead of the consumer — the ISSUE-11 pipeline's K")
config.define_int(
    "we_prepare_threads", 2,
    "producer threads feeding the WordEmbedding prepared-block queue "
    "(pair generation, negative sampling, remap/pack run here, OFF the "
    "training thread's critical path)")
config.define_int(
    "we_pair_cache_corpora", 4,
    "bounded LRU capacity (corpora) of the fused path's device-resident "
    "pair-batch cache — multi-corpus alternating epochs used to thrash "
    "the old keep-one cache every epoch")


def _gen_pairs(ids: np.ndarray, window: int, seed: int):
    """Prefer the native C++ pair generator (mv_data.cpp); fall back to the
    vectorized numpy path."""
    if native.available():
        return native.generate_pairs(ids, window, seed=seed)
    return w2v.generate_pairs(ids, window, seed=seed)


def prepare_ids(dictionary: Dictionary, ids: np.ndarray,
                cfg: "WEConfig") -> np.ndarray:
    """THE training-stream policy — one implementation shared by every
    entry point (app method, load_corpus, bench) so id streams can't
    diverge. Order matches the reference reader (reader.cpp:36-57
    GetSentence): stopword drop first, then frequency subsampling."""
    if getattr(cfg, "stopwords", False):
        # O(|sw|) id lookup, not an O(V) scan: the banned set resolves
        # against word2id once per call (the stopword list is small)
        banned = np.array(
            [dictionary.word2id[w] for w in _load_stopwords(cfg.sw_file)
             if w in dictionary.word2id], np.int64)
        if banned.size:
            ids = ids[~np.isin(ids, banned)]
    if cfg.sample <= 0:
        return ids
    if native.available():
        return native.subsample(ids, dictionary.counts, cfg.sample,
                                seed=cfg.seed).astype(np.int64)
    return dictionary.subsample(ids, cfg.sample, seed=cfg.seed)


def _load_stopwords(path: str) -> set:
    """Whitespace-separated stopword list (ref reader.cpp:11-23 — the
    table the Reader loads from ``sw_file``)."""
    with open(path, "rb") as f:
        return {t.decode("utf-8", errors="replace")
                for t in f.read().split()}


class WEConfig:
    """ref util.cpp ParseArgs keys (-size -window -negative -hs -cbow -alpha
    -epoch -min_count -sample -batch_size -data_block_size)."""

    def __init__(self, **kw):
        self.size = int(kw.get("size", 128))
        self.window = int(kw.get("window", 5))
        self.negative = int(kw.get("negative", 5))
        # TPU-first extension: >0 = batch-shared negative pool of this size
        # in the fused path (gradients rescaled to the -negative objective);
        # 0 = reference per-pair semantics.
        self.shared_negatives = int(kw.get("shared_negatives", 64))
        self.hs = str(kw.get("hs", "0")) in ("1", "true", "True")
        self.cbow = str(kw.get("cbow", "0")) in ("1", "true", "True")
        self.alpha = float(kw.get("alpha", 0.025))
        self.epoch = int(kw.get("epoch", 1))
        self.min_count = int(kw.get("min_count", 5))
        self.sample = float(kw.get("sample", 1e-4))
        self.batch_size = int(kw.get("batch_size", 1024))
        self.data_block_size = int(kw.get("data_block_size", 100_000))
        # reference-shaped PS block pipeline (pull rows / train / push
        # deltas, ref ps_model-style use_ps) instead of the fused path
        self.use_ps = str(kw.get("use_ps", "0")) in ("1", "true", "True")
        # uncoordinated async tables (multiverso_tpu.ps): workers trade
        # rows at independent rates — the reference's default Server mode
        self.async_ps = str(kw.get("async_ps", "0")) in ("1", "true", "True")
        # PS-block execution plane: "auto" fuses pull+train+push into one
        # device program when this process is the only worker (the sync
        # single-controller case); "0" forces the host Get/Add plane (the
        # multi-worker wire path); "1" asserts the device plane.
        self.ps_device_plane = str(kw.get("ps_device_plane", "auto"))
        # compute dtype INSIDE the block scan (both planes): "bf16" casts
        # the pulled rows for the scan (the table stays f32; deltas are
        # measured against the bf16-rounded baseline so untrained rows get
        # exactly-zero deltas). Default f32 — the block step is
        # gather-bound; measured bf16 gain on-chip is ~2%.
        self.ps_block_dtype = str(kw.get("ps_block_dtype", "f32"))
        if self.ps_block_dtype not in ("f32", "bf16"):
            raise ValueError(
                f"unknown ps_block_dtype {self.ps_block_dtype!r}")
        # ISSUE-11 pipelined prepare: "1" (default) produces blocks on a
        # bounded K-deep queue of producer threads and dispatches the row
        # pulls at dequeue (same program-order point as inline, so results
        # stay bit-identical); "0" = the legacy inline one-lookahead path
        # (the parity oracle)
        self.pipeline = str(kw.get("pipeline", "1")) in ("1", "true",
                                                         "True")
        self.data_presplit = str(kw.get("data_presplit", "0")) in (
            "1", "true", "True")
        self.max_vocab = kw.get("max_vocab")
        self.train_file = kw.get("train_file", "")
        # pre-counted vocabulary file ("word count" lines, the
        # tools/word_count.py output; ref -read_vocab consuming the
        # preprocess/word_count.cpp output) and its writer twin
        self.read_vocab = kw.get("read_vocab", "")
        self.save_vocab = kw.get("save_vocab", "")
        self.output = kw.get("output", "")
        # -binary 1: classic word2vec .bin output (ref util.h:26
        # output_binary, writer distributed_wordembedding.cpp:310-325)
        self.output_binary = str(kw.get("binary", "0")) in ("1", "true",
                                                            "True")
        # -stopwords 1 -sw_file <path>: drop listed words from the
        # TRAINING stream; the dictionary keeps them (ref reader.cpp:11-47
        # — stopwords count toward word_count and stay in the vocab, they
        # are only skipped when building sentences; option defaults
        # util.cpp:10,24)
        self.stopwords = str(kw.get("stopwords", "0")) in ("1", "true",
                                                           "True")
        self.sw_file = kw.get("sw_file", "")
        if self.stopwords and not self.sw_file:
            raise ValueError("-stopwords 1 needs -sw_file (ref util.cpp:75)")
        self.seed = int(kw.get("seed", 0))

    @classmethod
    def from_argv(cls, argv: List[str]) -> "WEConfig":
        kw = {}
        i = 0
        while i < len(argv):
            a = argv[i]
            if a.startswith("-") and "=" in a:
                i += 1   # "-key=value" runtime flag: mv.init's to parse
            elif a.startswith("-") and i + 1 < len(argv):
                kw[a.lstrip("-")] = argv[i + 1]
                i += 2
            else:
                i += 1
        return cls(**kw)


class WordEmbedding:
    def __init__(self, cfg: WEConfig, dictionary: Dictionary):
        if not mv.Zoo.get().started:
            mv.init()
        self.cfg = cfg
        self.dict = dictionary
        v, d = len(dictionary), cfg.size
        if v < 2:
            raise ValueError("vocabulary too small; lower min_count")
        # input/output embedding tables (ref communicator.cpp:17-31: two
        # MatrixTables; input randomly initialized server-side). async_ps
        # swaps in the uncoordinated tables — same client API, no lockstep.
        if cfg.async_ps:
            matrix, kv = mv.AsyncMatrixTable, mv.AsyncKVTable
        else:
            matrix, kv = mv.MatrixTable, mv.KVTable
        self.table_in = matrix(v, d, name="embed_in", updater="default",
                               seed=cfg.seed + 17, init_scale=0.5 / d)
        self.table_out = matrix(v, d, name="embed_out", updater="default")
        self.word_count = kv(name="word_count")
        self.unigram = dictionary.unigram_table()
        self._trained_words = 0
        # caller already sharded the corpus (skip the blocks[wid::nw] split;
        # we_async_worker-style drivers that feed per-rank shards set it via
        # -data_presplit 1)
        self._data_presplit = cfg.data_presplit
        self._neg_host: Optional[np.ndarray] = None
        self._neg_dev = None
        # device-plane in-graph negative re-derivation pays one remap upload
        # of V ids per block; worth it unless the vocab dwarfs the block's
        # negative traffic (a 21M-vocab run keeps the packed-negs upload)
        self._dev_negs = (not cfg.hs and cfg.negative > 0
                          and 4 * v <= cfg.data_block_size * cfg.negative)
        self._fused_cache: Dict[str, object] = {}
        # bounded LRU of device-resident pair batches, keyed by corpus
        # fingerprint (flag we_pair_cache_corpora): multi-corpus
        # alternating epochs no longer thrash it every epoch, and its
        # device bytes ride the PR-10 ledger
        self._pair_cache: "OrderedDict[object, object]" = OrderedDict()
        # guards the LRU against the memstats sampler thread's gauge
        # pull (mutation is per corpus-epoch — the lock is never hot)
        self._pair_cache_lock = threading.Lock()
        _memstats.register(f"we.pair_cache[{self.table_in.name}]", self,
                           attr="pair_cache_memory_stats")
        if cfg.hs:
            codes, points, lengths = build_huffman(dictionary.counts)
            self._hs = (codes, points, lengths)
            self.table_hs = matrix(max(v - 1, 1), d, name="embed_hs",
                                   updater="default")
        else:
            self._hs = None

    # ------------------------------------------------------------------ #
    # corpus -> id stream
    # ------------------------------------------------------------------ #
    def prepare_ids(self, tokens) -> np.ndarray:
        return prepare_ids(self.dict, self.dict.encode(tokens), self.cfg)

    def _batches(self, centers: np.ndarray, contexts: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
        b = self.cfg.batch_size
        n = (centers.size // b) * b
        if n == 0:
            raise ValueError(
                f"corpus too small: {centers.size} pairs < batch {b}")
        return (centers[:n].reshape(-1, b), contexts[:n].reshape(-1, b))

    def _device_pairs(self, ids: np.ndarray):
        """Batched (centers, contexts) pair arrays, resident on device.

        Pair generation is one-time corpus preprocessing; caching the
        device-resident batches (keyed by a corpus fingerprint) keeps repeat
        epochs off the host->device path entirely.
        """
        key = (ids.shape, hash(ids.tobytes()),
               self.cfg.window, self.cfg.seed, self.cfg.batch_size)
        with self._pair_cache_lock:
            hit = self._pair_cache.get(key)
            if hit is not None:
                self._pair_cache.move_to_end(key)
                return hit
        # pair gen + device put happen OFF the lock (one-time corpus
        # preprocessing — a concurrent gauge pull must not stall on it);
        # a racing duplicate build just overwrites with equal content
        centers, contexts = _gen_pairs(ids, self.cfg.window,
                                       self.cfg.seed)
        cb, xb = self._batches(centers, contexts)
        hit = (jnp.asarray(cb), jnp.asarray(xb), cb.size)
        with self._pair_cache_lock:
            self._pair_cache[key] = hit
            cap = max(1, int(config.get_flag("we_pair_cache_corpora")))
            while len(self._pair_cache) > cap:   # bounded LRU
                self._pair_cache.popitem(last=False)
        return hit

    def pair_cache_memory_stats(self) -> Dict[str, int]:
        """PR-10 ledger gauges for the pair-batch LRU (pull-only)."""
        with self._pair_cache_lock:   # vs the training thread's insert
            entries = list(self._pair_cache.values())
        dev = sum(int(getattr(a, "nbytes", 0) or 0)
                  for cb, xb, _n in entries
                  for a in (cb, xb))
        return {"corpora": len(entries), "device_bytes": dev}

    # ------------------------------------------------------------------ #
    # fused path (device-resident training)
    # ------------------------------------------------------------------ #
    def train_fused(self, ids: np.ndarray,
                    epochs: Optional[int] = None) -> Dict[str, float]:
        cfg = self.cfg
        epochs = epochs or cfg.epoch
        w2v_cfg = w2v.W2VConfig(len(self.dict), cfg.size, cfg.negative,
                                cfg.window, cfg.alpha, cfg.cbow, cfg.hs,
                                cfg.shared_negatives)
        key = jax.random.key(cfg.seed)
        t0, loss, pairs = time.perf_counter(), None, 0

        if cfg.cbow:
            windows, masks, targets = w2v.generate_cbow_batches(ids, cfg.window)
            b = cfg.batch_size
            n = (targets.size // b) * b
            if n == 0:
                raise ValueError("corpus too small for batch size")
            wb = jnp.asarray(windows[:n].reshape(-1, b, windows.shape[1]))
            mb = jnp.asarray(masks[:n].reshape(-1, b, masks.shape[1]))
            tb = jnp.asarray(targets[:n].reshape(-1, b))
            pairs = n
            state_in = self.table_in.state
            win = state_in["data"]
            if cfg.hs:
                codes, points, lengths = self._hs
                epoch_fn = self._fused_cache.get("cbow_hs")
                if epoch_fn is None:
                    epoch_fn = self._fused_cache["cbow_hs"] = (
                        w2v.make_fused_cbow_hs_epoch(w2v_cfg, codes, points,
                                                     lengths))
                state_hs = self.table_hs.state
                hs_out = state_hs["data"]
                for _ in range(epochs):
                    key, sub = jax.random.split(key)
                    win, hs_out, loss = epoch_fn(win, hs_out, wb, mb, tb,
                                                 sub)
                jax.block_until_ready(win)
                self.table_hs.adopt({"data": hs_out,
                                     "ustate": state_hs["ustate"]})
            else:
                epoch_fn = self._fused_cache.get("cbow")
                if epoch_fn is None:
                    epoch_fn = self._fused_cache["cbow"] = (
                        w2v.make_fused_cbow_epoch(w2v_cfg, self.unigram))
                state_out = self.table_out.state
                wout = state_out["data"]
                for _ in range(epochs):
                    key, sub = jax.random.split(key)
                    win, wout, loss = epoch_fn(win, wout, wb, mb, tb, sub)
                jax.block_until_ready(win)
                self.table_out.adopt({"data": wout,
                                      "ustate": state_out["ustate"]})
            self.table_in.adopt({"data": win, "ustate": state_in["ustate"]})
        else:
            cbd, xbd, pairs = self._device_pairs(ids)
            state_in = self.table_in.state
            win = state_in["data"]
            if cfg.hs:
                codes, points, lengths = self._hs
                epoch_fn = self._fused_cache.get("hs")
                if epoch_fn is None:
                    epoch_fn = self._fused_cache["hs"] = (
                        w2v.make_fused_hs_epoch(w2v_cfg, codes, points,
                                                lengths))
                state_hs = self.table_hs.state
                hs_out = state_hs["data"]
                for _ in range(epochs):
                    key, sub = jax.random.split(key)
                    win, hs_out, loss = epoch_fn(win, hs_out, cbd, xbd, sub)
                jax.block_until_ready(win)
                self.table_hs.adopt({"data": hs_out,
                                     "ustate": state_hs["ustate"]})
            elif cfg.shared_negatives > 0:
                # TPU-first fast path: batch-shared negatives on the MXU
                epoch_fn = self._fused_cache.get("sg_shared")
                if epoch_fn is None:
                    cd = (jnp.bfloat16
                          if jax.devices()[0].platform == "tpu"
                          else jnp.float32)
                    epoch_fn = self._fused_cache["sg_shared"] = (
                        w2v.make_fused_shared_epoch(w2v_cfg, self.unigram,
                                                    compute_dtype=cd))
                    self._lcg = jnp.asarray(w2v.init_lcg_state(
                        cfg.shared_negatives, cfg.seed))
                state_out = self.table_out.state
                # epoch_fn donates its table args; chain from copies so the
                # live table buffers survive a mid-epoch failure (OOM/^C)
                win = jnp.copy(win)
                wout = jnp.copy(state_out["data"])
                for _ in range(epochs):
                    win, wout, loss, self._lcg = epoch_fn(
                        win, wout, cbd, xbd, self._lcg)
                jax.block_until_ready(win)
                self.table_out.adopt({"data": wout,
                                      "ustate": state_out["ustate"]})
            else:
                epoch_fn = self._fused_cache.get("sg")
                if epoch_fn is None:
                    epoch_fn = self._fused_cache["sg"] = (
                        w2v.make_fused_epoch(w2v_cfg, self.unigram))
                state_out = self.table_out.state
                wout = state_out["data"]
                for _ in range(epochs):
                    key, sub = jax.random.split(key)
                    win, wout, loss = epoch_fn(win, wout, cbd, xbd, sub)
                jax.block_until_ready(win)
                self.table_out.adopt({"data": wout,
                                      "ustate": state_out["ustate"]})
            self.table_in.adopt({"data": win, "ustate": state_in["ustate"]})

        # host readback of the scalar loss is the reliable device-drain sync
        # (block_until_ready alone can return early over a remote/tunneled
        # PJRT transport), so fetch it BEFORE stopping the clock
        loss_f = float(loss)
        dt = time.perf_counter() - t0
        # words/sec follows the word2vec convention: corpus *tokens* consumed
        # per second (ref trainer.cpp words/sec), not training pairs.
        words = epochs * int(ids.size)
        self._trained_words += words
        self.word_count.add([0], [words])
        return {"loss": loss_f, "words_per_sec": words / dt,
                "seconds": dt, "pairs": int(pairs),
                "pairs_per_sec": epochs * pairs / dt}

    # ------------------------------------------------------------------ #
    # PS block path (reference block pipeline; multi-worker capable)
    # ------------------------------------------------------------------ #
    def _use_device_plane(self, num_workers: int) -> bool:
        """The single-worker sync case fuses each block's pull+train+push
        into ONE device program (see :meth:`_fused_block_fn`); multi-worker
        and uncoordinated runs keep the host Get/Add wire."""
        mode = self.cfg.ps_device_plane
        eligible = num_workers == 1 and not self.cfg.async_ps
        if mode == "1":
            if not eligible:
                raise ValueError(
                    "ps_device_plane=1 requires a single worker on the sync "
                    "plane; multi-worker runs exchange deltas over the "
                    "Get/Add wire")
            return True
        if mode == "0":
            return False
        return eligible

    def train_ps_blocks(self, ids: np.ndarray,
                        epochs: Optional[int] = None) -> Dict[str, float]:
        """ref distributed_wordembedding.cpp:147-252: per block pull rows,
        train locally, push (new - old) deltas. The pull for block N+1 is
        dispatched before block N trains (ref :202-223 OMP overlap thread) —
        its device gather + host transfer proceed while block N computes, at
        the cost of the same one-block staleness the reference accepts.

        Single-worker sync runs take the *device plane*: the worker's pull /
        local-train / push collapses into one jitted program per block, so
        block traffic never crosses the host boundary (the reference's
        worker and server are separate address spaces; here both live on
        the same chip, so the Get/Add hop is a device gather/scatter — the
        semantics, not the message flow, is the parity surface)."""
        cfg = self.cfg
        epochs = epochs or cfg.epoch
        rng = np.random.default_rng(cfg.seed)
        nw, wid = self._ps_topology()
        device_plane = self._use_device_plane(nw)
        t0, losses, words = time.perf_counter(), [], 0
        dev_losses: List[jax.Array] = []
        blocks = [ids[lo: lo + cfg.data_block_size]
                  for lo in range(0, ids.size, cfg.data_block_size)]
        blocks = [b for b in blocks if b.size >= 2]
        # Delta scaling is ALWAYS 1/nw on the multi-worker planes
        # (ref communicator.cpp:154). Note the convergence consequence,
        # measured at np4/1M tokens: with each worker sweeping the FULL
        # corpus (reference layout; set -data_presplit 1 and feed every
        # rank all the data), N sweeps x 1/N deltas net one epoch's
        # learning and the loss tracks the sync plane; with the
        # partitioned split below, each token contributes only 1/N of a
        # gradient per epoch (undertrains, loss 2.55 vs sync 0.70), and
        # dropping the divide instead makes zipf-hot rows absorb ~N
        # concurrent full-alpha pushes (diverges, loss 5.5). Partitioned
        # mode is the throughput/liveness fixture; reference-comparable
        # CONVERGENCE numbers come from the full-sweep layout.
        if nw > 1 and cfg.async_ps and not self._data_presplit:
            # data split evenly per worker (ref BENCHMARK.md common
            # settings). ONLY on the uncoordinated plane: sync-table
            # add_rows is a collective, so unequal per-worker block counts
            # would leave the worker with more blocks waiting forever.
            blocks = blocks[wid::nw]
        # one flat schedule across all epochs so the pull of the next block
        # overlaps training of the current one at every step, including
        # across epoch boundaries (ref :202-223 keeps its overlap thread
        # alive for the whole multi-epoch run)
        schedule = [b for _ in range(epochs) for b in blocks]
        # per-block child rngs: identical draws whether blocks are prepped
        # serially (host plane) or by prefetch threads (device plane) — the
        # two planes must stay bit-comparable
        child_rngs = rng.spawn(len(schedule)) if schedule else []
        if device_plane and schedule:
            if self._neg_host is None and not cfg.hs:
                self._host_negs(1, 1, np.random.default_rng(0))  # build once
            # K-deep ordered producer queue (io/sample_reader): replaces
            # the PR-5 fixed pool — same 2-thread default, but depth and
            # threads are now the shared we_prepare_* knobs and the
            # producers report io.produce / the consumer io_wait
            with BlockPrepareQueue(
                    list(range(len(schedule))),
                    lambda idx, _i: self._prepare_block_device(
                        schedule[idx], child_rngs[idx]),
                    depth=int(config.get_flag("we_prepare_depth")),
                    threads=int(config.get_flag("we_prepare_threads"))
                    ) as q:
                for i, block in enumerate(schedule):
                    prepared = q.next()
                    if prepared is not None:
                        dev_losses.append(self._train_block_device(prepared))
                    words += block.size
        elif schedule and cfg.pipeline and len(schedule) > 1:
            # ISSUE-11 pipelined host plane: producers run the CPU-heavy
            # prepare (pair gen, negative sampling, remap/pack) K blocks
            # ahead; the consumer dispatches each block's row pulls at
            # DEQUEUE — the same point in program order (before the
            # previous block's push) the inline path dispatches them, so
            # the pulled rows, and therefore the training results, are
            # bit-identical to pipeline=0
            if self._neg_host is None and not cfg.hs:
                self._host_negs(1, 1, np.random.default_rng(0))  # build once
            with BlockPrepareQueue(
                    list(range(len(schedule))),
                    lambda idx, _i: self._produce_block(
                        schedule[idx], child_rngs[idx]),
                    depth=int(config.get_flag("we_prepare_depth")),
                    threads=int(config.get_flag("we_prepare_threads"))
                    ) as q:
                prepared = self._dispatch_pulls(q.next())
                for i, block in enumerate(schedule):
                    with _prof.step("we.block"):
                        nxt = None
                        if i + 1 < len(schedule):
                            produced = q.next()   # io_wait-timed
                            with _prof.phase("we.pipeline"):
                                nxt = self._dispatch_pulls(produced)
                        losses.append(self._train_prepared(prepared, nw))
                    words += block.size
                    prepared = nxt
        else:
            # legacy inline one-lookahead path (-pipeline 0): the parity
            # oracle the pipelined path is asserted bit-identical to.
            # Pipeline-fill prepare happens outside any step: steady-
            # state steps each cover ONE (prepare of block N+1, train of
            # block N) pair — the overlap the profiler exists to measure
            prepared = (self._prepare_block(schedule[0], child_rngs[0])
                        if schedule else None)
            for i, block in enumerate(schedule):
                with _prof.step("we.block"):
                    nxt = (self._prepare_block(schedule[i + 1],
                                               child_rngs[i + 1])
                           if i + 1 < len(schedule) else None)
                    losses.append(self._train_prepared(prepared, nw))
                words += block.size
                prepared = nxt
        if dev_losses:
            # ONE host readback for the whole run: materializing the stacked
            # per-block losses drains the device program chain, so the
            # trained state is durable when the clock stops
            losses = [float(x) for x in np.asarray(jnp.stack(dev_losses))]
        # drain in-flight async pushes so the trained state is durable
        # before the caller reads embeddings (sync tables order by program
        # order; async tables need the explicit flush)
        for t in (self.table_in, self.table_out,
                  getattr(self, "table_hs", None)):
            if t is not None and hasattr(t, "flush"):
                t.flush()
        dt = time.perf_counter() - t0
        self._trained_words += words
        self.word_count.add([0], [words])
        return {"loss": float(np.mean(losses)) if losses else 0.0,
                "words_per_sec": words / dt, "seconds": dt}

    def _host_negs(self, n: int, k: int, rng) -> Tuple[np.ndarray, np.uint32]:
        """Negative draws from a precomputed unigram^0.75 slot table
        (word2vec.c's 1e8-slot design, ref wordembedding NS branch). Slot
        indices come from a counter-based hash (w2v.splitmix32) seeded per
        block, so the device plane can RE-DERIVE the identical draws
        in-graph from just the 4-byte seed instead of shipping the
        (nb, B, K) id array across the host->device wire."""
        if self._neg_host is None:
            self._neg_host = w2v.build_negative_table(self.unigram)
        seed = np.uint32(rng.integers(0, 1 << 32))
        idx = w2v.counter_negs(seed, max(n, 1) * k, self._neg_host.size - 1)
        return (self._neg_host[idx].reshape(max(n, 1), k).astype(np.int32),
                seed)

    def _block_arrays(self, block: np.ndarray, rng) -> Dict:
        """Host-side block prep shared by both PS planes: the mode-specific
        training arrays, the block's input-vocab set/remap, and — for HS
        modes — the block's Huffman inner-node set/remap
        (ref RequestParameter's needed-row collection,
        communicator.cpp:104-142)."""
        cfg = self.cfg
        prep: Dict = {}
        if cfg.cbow:
            windows, masks, targets = w2v.generate_cbow_batches(
                block, cfg.window)
            prep.update(windows=windows, masks=masks, targets=targets)
            used = [windows.reshape(-1), targets, np.zeros(1, np.int64)]
            examples = targets   # the word whose path/negs are scored
        else:
            centers, contexts = _gen_pairs(block, cfg.window,
                                           int(rng.integers(1 << 31)))
            prep.update(centers=centers, contexts=contexts)
            used = [centers, contexts]
            examples = contexts
        prep["examples"] = examples
        if cfg.hs:
            codes, points, lengths = self._hs
            t = np.asarray(examples, np.int64)
            pmask = (np.arange(codes.shape[1])[None, :]
                     < lengths[t][:, None])
            prep.update(codes=codes[t], points=points[t], pmask=pmask)
            prep["hs_rows"] = self._used_ids(
                self.table_hs.shape[0], [prep["points"][pmask]])
        else:
            negs, neg_seed = self._host_negs(examples.size, cfg.negative, rng)
            prep.update(negs=negs, neg_seed=neg_seed)
            used.append(negs.reshape(-1))
        prep["vocab"] = self._used_ids(len(self.dict), used)
        return prep

    @staticmethod
    def _used_ids(limit: int, arrays) -> np.ndarray:
        """Sorted unique ids across ``arrays`` via a presence mask — O(n + V)
        instead of np.unique's O(n log n) sort (block prep is on the
        words/sec critical path)."""
        seen = np.zeros(limit, bool)
        for a in arrays:
            seen[np.asarray(a).reshape(-1)] = True
        return np.flatnonzero(seen)

    def _produce_block(self, block: np.ndarray, rng,
                       dispatch_early: bool = False) -> Optional[Dict]:
        """The PURE host-CPU half of host-plane block prep — pair/negative
        generation, remap, packing — safe on a producer thread: it reads
        no table state, so K-deep production cannot reorder the wire.
        The pulls are dispatched separately (:meth:`_dispatch_pulls`) on
        the consumer thread, in program order — EXCEPT the inline path
        (``dispatch_early``, consumer thread by definition), which
        dispatches them before the ~35 ms packing work so the
        wire/gather latency hides under it (packing makes no wire ops,
        so the dispatch point within prepare never changes results)."""
        cfg = self.cfg
        b = cfg.batch_size
        with monitor("we.prepare"):
            prep = self._block_arrays(block, rng)
            n = (prep["examples"].size // b) * b
            if n == 0:
                return None
            nbb = -(-(n // b) // 8) * 8
            vocab = prep["vocab"]
            k = vocab.size
            # bucket the pulled-row count so the jitted scan compiles once
            # per bucket, not once per block's distinct vocab size (the
            # device plane buckets for the same reason); the pulled rows
            # are zero-padded to the bucket before the scan
            kb = _bucket_size(k, 1 << 30)
            remap_hs, hkb = None, 0
            if cfg.hs:
                hs_rows = prep["hs_rows"]
                hkb = _bucket_size(hs_rows.size, 1 << 30)
                # remap path points into the pulled hs block; padded path
                # slots route to a dummy extra row (their grads are masked
                # to zero, the scatter just needs a valid index)
                remap_hs = np.full(self.table_hs.shape[0] + 1, hkb, np.int64)
                remap_hs[hs_rows] = np.arange(hs_rows.size)
            remap = np.full(len(self.dict), kb, np.int64)   # default: dummy
            remap[vocab] = np.arange(k)
            prep.update(kb=kb, hkb=hkb)
            if dispatch_early:
                self._dispatch_pulls(prep)
            batch, valid = self._pack_batches(prep, n, nbb, remap, kb,
                                              remap_hs, hkb)
            prep.update(batch=batch, valid=valid)
            return prep

    def _dispatch_pulls(self, prep: Optional[Dict]) -> Optional[Dict]:
        """Dispatch a produced block's row pulls (ref RequestParameter,
        communicator.cpp:104-142) — the one ordered step kept on the
        consumer thread: a pull must enter the conn FIFO before the
        PREVIOUS block's push, exactly where the inline path dispatches
        it, or the pulled rows (hence the results) would change. Tables
        with a warm training cache serve a fully-covered block as a
        device-resident (bucket, D) array instead — one fused gather/pad
        program (ops/row_assemble), nothing crossing the host boundary —
        and cold/partial blocks fall back to get_rows_async, whose cache
        split fetches only the residual cold rows over the wire."""
        if prep is None:
            return None
        if "dev_in" in prep or "pull_in" in prep:
            return prep   # already dispatched (the inline early path)
        cfg = self.cfg

        def pull(table, ids, bucket, k_dev, k_pull):
            f = getattr(table, "train_cache_device_block", None)
            blk = f(ids, bucket) if f is not None else None
            if blk is not None:
                prep[k_dev] = blk
            else:
                prep[k_pull] = table.get_rows_async(ids)

        pull(self.table_in, prep["vocab"], prep["kb"], "dev_in", "pull_in")
        if cfg.hs:
            pull(self.table_hs, prep["hs_rows"], prep["hkb"],
                 "dev_sec", "pull_hs")
        else:
            pull(self.table_out, prep["vocab"], prep["kb"],
                 "dev_sec", "pull_out")
        return prep

    def _prepare_block(self, block: np.ndarray, rng) -> Optional[Dict]:
        """Inline host-plane block prep (-pipeline 0, the parity oracle):
        produce + dispatch on the calling thread, profiled as the step's
        ``prepare`` phase. Compute is the SAME packed ``lax.scan`` as the
        device plane — only pull/push differ (table Get/Add over the wire
        here, in-graph gather/scatter there)."""
        with _prof.phase("prepare"):
            return self._produce_block(block, rng, dispatch_early=True)

    def _train_prepared(self, prep: Optional[Dict],
                        num_workers: int) -> float:
        """Consume the pulls, run the block's packed scan, push the
        (new - old)/workers deltas ASYNC like the reference
        (ref communicator.cpp:144-236 AddAsync) — the push overlaps the
        next block's prep/compute. Ordering is safe: sync tables dispatch
        in program order; on the async plane arrival-order accumulation
        is the semantics."""
        cfg = self.cfg
        if prep is None:
            return 0.0
        with monitor("we.block"):
            # device pad (ops/row_assemble): ONE transfer of the real
            # rows, the zero padding materializes in-graph — the old
            # np.pad + jnp.asarray paid a host copy of the padded block
            padded = _rowasm.pad_rows

            sec_t = self._sec_table()
            # ps_wait: the residual of the pulls dispatched during
            # prepare — the part the prefetch overlap did NOT hide.
            # Cache-served blocks (dev_in/dev_sec) already sit on device.
            with _prof.phase("ps_wait"):
                rows_in = (None if "dev_in" in prep
                           else self.table_in.wait(prep["pull_in"]))
                rows_sec = (None if "dev_sec" in prep
                            else sec_t.wait(
                                prep["pull_hs" if cfg.hs else "pull_out"]))
            with _prof.phase("compute"):
                win_l = (prep["dev_in"] if rows_in is None
                         else padded(rows_in, prep["kb"]))
                wsec_l = (prep["dev_sec"] if rows_sec is None
                          else padded(rows_sec,
                                      prep["hkb"] if cfg.hs
                                      else prep["kb"]))
                if _prof.enabled():
                    _prof.watch_jit("we.local_train",
                                    self._local_train_fn())
                # batch upload through the devstats chokepoint (feeds
                # the per-direction device-plane counters AND, when
                # profiling, the step's transfer_bytes delta)
                _devstats.note_transfer(sum(
                    int(np.asarray(a).nbytes)
                    for a in prep["batch"]), "h2d")
                d_in, d_sec, loss = self._local_train_fn()(
                    win_l, wsec_l, jnp.asarray(prep["valid"]),
                    jax.device_put(prep["batch"]))
                # materialize the deltas HERE: np.asarray is the device
                # sync, so the scan's runtime lands in `compute`, not in
                # the push's enqueue accounting (the push itself is an
                # async ps.add span via the table layer)
                d_in = np.asarray(d_in)
                d_sec = np.asarray(d_sec)
                _devstats.note_transfer(d_in.nbytes + d_sec.nbytes, "d2h")
            with monitor("we.push"), _prof.phase("push"):
                k = prep["vocab"].size
                self.table_in.add_rows_async(
                    prep["vocab"], d_in[:k] / num_workers)
                ids_sec = prep["hs_rows"] if cfg.hs else prep["vocab"]
                sec_t.add_rows_async(
                    ids_sec, d_sec[:ids_sec.size] / num_workers)
            return float(loss)

    # ------------------------------------------------------------------ #
    # PS block path: shared packed-scan compute, two pull/push planes
    # ------------------------------------------------------------------ #
    def _sec_table(self):
        return self.table_hs if self.cfg.hs else self.table_out

    @staticmethod
    def _idt(limit: int):
        """Smallest index dtype covering [0, limit] — the packed batches
        cross the host->device wire; int16 halves the bytes."""
        return np.int16 if limit < (1 << 15) else np.int32

    def _pack_batches(self, prep: Dict, n: int, nbb: int,
                      remap: np.ndarray, dummy_in: int,
                      remap_hs: Optional[np.ndarray], dummy_hs: int,
                      dev_negs: bool = False
                      ) -> Tuple[Tuple[np.ndarray, ...], np.ndarray]:
        """Remap + pack the block's training arrays into the (nbb, B, ...)
        scan layout shared by BOTH planes. Index spaces: ids are remapped
        into the pulled-row array; pad slots and padded minibatches point
        at the dummy extra row appended after the pulled rows, so their
        (masked) garbage never touches real rows."""
        cfg = self.cfg
        b = cfg.batch_size
        nb = n // b

        def pack(x, fill, dtype):
            out = np.full((nbb, b) + x.shape[1:], fill, dtype)
            out[:nb] = x[:n].reshape((nb, b) + x.shape[1:])
            return out

        din = self._idt(dummy_in)
        if cfg.hs:
            dhs = self._idt(dummy_hs)
            points = remap_hs[prep["points"][:n]]
            points[~prep["pmask"][:n]] = dummy_hs  # mask off-path garbage
            sec_batch = (pack(prep["codes"][:n], 0, np.int8),
                         pack(points, dummy_hs, dhs),
                         pack(prep["pmask"][:n], False, bool))
        elif dev_negs:
            sec_batch = ()  # negatives re-derived in-graph from the seed
        else:
            sec_batch = (pack(remap[prep["negs"][:n]], dummy_in, din),)
        if cfg.cbow:
            head = (pack(remap[prep["windows"][:n]], dummy_in, din),
                    pack(prep["masks"][:n], False, bool))
            if cfg.hs:          # cbow_hs_step(w, m, codes, points, pmask)
                batch = head + sec_batch
            else:               # cbow_ns_step(w, m, targets, negs)
                batch = head + (pack(remap[prep["targets"][:n]],
                                     dummy_in, din),) + sec_batch
        else:
            centers = pack(remap[prep["centers"][:n]], dummy_in, din)
            if cfg.hs:          # skipgram_hs_step(c, codes, points, pmask)
                batch = (centers,) + sec_batch
            else:               # skipgram_ns_step(c, contexts, negs)
                batch = (centers,
                         pack(remap[prep["contexts"][:n]], dummy_in, din),
                         ) + sec_batch
        valid = np.zeros(nbb, np.float32)
        valid[:nb] = 1.0
        return batch, valid

    def _step_fn_raw(self):
        """Unjitted per-minibatch step for the active (cbow, hs) mode —
        all four reference variants (ref wordembedding.cpp FeedForward/
        HS/NS branches); scanned by both PS planes."""
        cfg = self.cfg
        alpha = cfg.alpha
        if cfg.cbow and cfg.hs:
            return lambda a, s, w, m, c, p, pm: w2v.cbow_hs_step(
                a, s, w, m, c, p, pm, alpha)
        if cfg.cbow:
            return lambda a, s, w, m, t, g: w2v.cbow_ns_step(
                a, s, w, m, t, g, alpha)
        if cfg.hs:
            return lambda a, s, c, cd, p, pm: w2v.skipgram_hs_step(
                a, s, c, cd, p, pm, alpha)
        return lambda a, s, c, x, g: w2v.skipgram_ns_step(
            a, s, c, x, g, alpha)

    def _compute_dtype(self):
        return jnp.bfloat16 if self.cfg.ps_block_dtype == "bf16" else None

    def _run_block_scan(self, step, rows_in, rows_sec, valid, batch,
                        neg_fn=None):
        """THE block-train scan, traced inside both planes' jits: pulled
        rows in, (new - old) deltas + mean loss out. ``neg_fn(w, stp)``
        appends in-graph negatives (device plane's dev-negs mode; batch[0]
        is then the step-index array). Deltas are measured against the
        SAME baseline the scan started from — in bf16 mode the rounded
        rows — so a pulled-but-untrained row gets an exactly-zero delta."""
        cdtype = self._compute_dtype()

        def dummy(r):   # padded slots train against this extra row
            r = r.astype(cdtype) if cdtype is not None else r
            return jnp.concatenate(
                [r, jnp.zeros((1, r.shape[1]), r.dtype)])

        def body(carry, xs):
            ri, rs = carry
            w, arrs = xs[0], xs[1:]
            if neg_fn is not None:
                stp, arrs = arrs[0], arrs[1:]
            arrs = tuple(a.astype(jnp.int32)
                         if a.dtype == jnp.int16 else a for a in arrs)
            if neg_fn is not None:
                arrs = arrs + (neg_fn(w, stp),)
            ri, rs, loss = step(ri, rs, *arrs)
            return (ri, rs), loss * w

        (ri, rs), losses = jax.lax.scan(
            body, (dummy(rows_in), dummy(rows_sec)), (valid,) + batch)
        loss = losses.sum().astype(jnp.float32) / jnp.maximum(
            valid.sum(), 1.0)

        def base(old):
            if cdtype is None:
                return old
            return old.astype(cdtype).astype(old.dtype)

        d_in = ri[:-1].astype(rows_in.dtype) - base(rows_in)
        d_sec = rs[:-1].astype(rows_sec.dtype) - base(rows_sec)
        return d_in, d_sec, loss

    def _local_train_fn(self):
        """Jitted local-train scan for the host plane — the packed
        equivalent of the reference's per-block OMP train loop
        (ref distributed_wordembedding.cpp:178-227), minus the per-
        minibatch dispatch round-trips."""
        fn = self._fused_cache.get("ps_local")
        if fn is not None:
            return fn
        step = self._step_fn_raw()
        fn = self._fused_cache["ps_local"] = jax.jit(
            lambda ri, rs, v, b: self._run_block_scan(step, ri, rs, v, b))
        return fn

    def _prepare_block_device(self, block: np.ndarray, rng) -> Optional[Dict]:
        """Device-plane block prep: bucketed table-id lists + packed
        batches, shipped in ONE pytree device_put per block (overlapped
        with the previous block's compute by JAX async dispatch)."""
        cfg = self.cfg
        b = cfg.batch_size
        with monitor("we.prepare"):
            prep = self._block_arrays(block, rng)
            n = (prep["examples"].size // b) * b
            if n == 0:
                return None
            # multiple-of-8 bucket: pair counts per fixed-size block jitter
            # by << 8 minibatches, so this stays on one compiled program
            # while wasting far less upload padding than pow2 would
            nbb = -(-(n // b) // 8) * 8
            vocab = prep["vocab"]
            k = vocab.size
            vbb = _bucket_size(k, self.table_in.padded_shape[0])
            # bucket the pulled-row count; pad ids gather the table's
            # scratch row (zero delta scatters back into it, a no-op)
            ids_in = np.full(vbb, self.table_in.scratch_row, np.int32)
            ids_in[:k] = vocab
            remap = np.full(len(self.dict), vbb, np.int64)  # default: dummy
            remap[vocab] = np.arange(k)
            remap_hs, hsb = None, 0
            if cfg.hs:
                hs_rows = prep["hs_rows"]
                hk = hs_rows.size
                hsb = _bucket_size(hk, self._sec_table().padded_shape[0])
                ids_sec = np.full(hsb, self._sec_table().scratch_row,
                                  np.int32)
                ids_sec[:hk] = hs_rows
                remap_hs = np.full(self.table_hs.shape[0] + 1, hsb, np.int64)
                remap_hs[hs_rows] = np.arange(hk)
            else:
                ids_sec = ids_in
            batch, valid = self._pack_batches(prep, n, nbb, remap, vbb,
                                              remap_hs, hsb,
                                              dev_negs=self._dev_negs)
            payload = {"ids_in": ids_in, "ids_sec": ids_sec, "valid": valid,
                       "batch": batch, "remap": None, "neg_seed": None}
            if self._dev_negs:
                # in-graph negatives need the step index, the global->local
                # remap (V small ids), and the block's 4-byte draw seed
                payload["batch"] = (np.arange(nbb, dtype=np.uint32),) + batch
                payload["remap"] = remap.astype(self._idt(vbb))
                payload["neg_seed"] = np.uint32(prep["neg_seed"])
            return jax.device_put(
                payload,
                jax.sharding.NamedSharding(mv.mesh(),
                                           jax.sharding.PartitionSpec()))

    def _fused_block_fn(self):
        """One jitted program = the whole reference block cycle: pull
        (device gather of the block's rows), local train (lax.scan over
        minibatches), push (new - old deltas through the table updater,
        functional_add_rows). Donates both tables' buffers — the block
        chain re-uses device memory like the reference's in-place server
        shard (ref distributed_wordembedding.cpp:147-252 collapsed into
        XLA)."""
        fn = self._fused_cache.get("ps_block")
        if fn is not None:
            return fn
        cfg = self.cfg
        t_in, t_sec = self.table_in, self._sec_table()
        step = self._step_fn_raw()
        dev_negs = self._dev_negs
        bsz, k = cfg.batch_size, cfg.negative
        if dev_negs and self._neg_host is None:
            self._host_negs(1, 1, np.random.default_rng(0))  # build table
        tbl_mask = (self._neg_host.size - 1) if dev_negs else 0

        def fused(din, uin, dsec, usec, ids_in, ids_sec, valid, batch,
                  remap, neg_seed, neg_table):
            old_in = jnp.take(din, ids_in, axis=0)
            old_sec = jnp.take(dsec, ids_sec, axis=0)
            neg_fn = None
            if dev_negs:
                dummy_id = ids_in.shape[0]

                def neg_fn(w, stp):
                    # same splitmix32 counter stream the host used to
                    # build the pull set — only the 4-byte seed crossed
                    # the wire
                    base = neg_seed + stp * jnp.uint32(bsz * k)
                    slots = w2v.counter_negs(base, bsz * k, tbl_mask)
                    ng = jnp.take(neg_table, slots).reshape(bsz, k)
                    nl = jnp.take(remap, ng).astype(jnp.int32)
                    # padded steps: their counters weren't in the host's
                    # vocab pass, so point them at the dummy row
                    return jnp.where(w > 0, nl, jnp.int32(dummy_id))

            d_in, d_sec, loss = self._run_block_scan(
                step, old_in, old_sec, valid, batch, neg_fn)
            s_in = t_in.functional_add_rows(
                {"data": din, "ustate": uin}, ids_in, d_in)
            s_sec = t_sec.functional_add_rows(
                {"data": dsec, "ustate": usec}, ids_sec, d_sec)
            return (s_in["data"], s_in["ustate"],
                    s_sec["data"], s_sec["ustate"], loss)

        fn = jax.jit(fused, donate_argnums=(0, 1, 2, 3))
        self._fused_cache["ps_block"] = fn
        return fn

    def _train_block_device(self, prep: Dict) -> jax.Array:
        """Dispatch one fused block program; returns the block loss as a
        DEVICE scalar (readback deferred to end of run)."""
        t_in, t_sec = self.table_in, self._sec_table()
        fn = self._fused_block_fn()
        if self._dev_negs and self._neg_dev is None:
            self._neg_dev = jax.device_put(
                self._neg_host, jax.sharding.NamedSharding(
                    mv.mesh(), jax.sharding.PartitionSpec()))
        with monitor("we.block"), t_in._dispatch_lock, t_sec._dispatch_lock:
            si, ss = t_in.state, t_sec.state
            din, uin, dsec, usec, loss = fn(
                si["data"], si["ustate"], ss["data"], ss["ustate"],
                prep["ids_in"], prep["ids_sec"], prep["valid"],
                prep["batch"], prep.get("remap"), prep.get("neg_seed"),
                self._neg_dev)
            t_in.adopt({"data": din, "ustate": uin})
            t_sec.adopt({"data": dsec, "ustate": usec})
        return loss

    def _ps_topology(self) -> Tuple[int, int]:
        """(num_workers, worker_id) of the PS plane in use: the async
        context's world for uncoordinated tables, the collective runtime's
        otherwise."""
        if self.cfg.async_ps:
            ctx = self.table_in.ctx
            return max(ctx.world, 1), ctx.rank
        return max(mv.num_workers(), 1), mv.rank()

    def total_word_count(self) -> int:
        """Global trained-word count across all workers — the reference reads
        the server-aggregated KV value (ref communicator.cpp:17-31 +
        kv_table.h:44-99), so this uses the aggregated Get, not the local
        view. Async tables aggregate on every get (uncoordinated); the sync
        KVTable needs the collective global_=True read."""
        return int(self.word_count.get([0], global_=True)[0])

    # ------------------------------------------------------------------ #
    def embeddings(self) -> np.ndarray:
        return self.table_in.get()

    def nearest(self, word: str, k: int = 10) -> List[str]:
        wid = self.dict.word2id[word]
        ids = w2v.nearest_neighbors(self.embeddings(), wid, k)
        return [self.dict.words[i] for i in ids]

    def save_embeddings(self, path: Optional[str] = None,
                        binary: Optional[bool] = None) -> None:
        """ref SaveEmbedding (distributed_wordembedding.cpp:263-306):
        word2vec text format, or the classic .bin layout with -binary 1
        (ref util.h:26 output_binary; writer WriteToFile
        distributed_wordembedding.cpp:310-325 — header line, then per row
        ``word `` + embedding_size raw float32 + newline)."""
        path = path or self.cfg.output
        if not path:
            return
        binary = self.cfg.output_binary if binary is None else binary
        emb = self.embeddings()
        if binary:
            with open(path, "wb") as f:
                f.write(f"{len(self.dict)} {self.cfg.size}\n".encode())
                for w, row in zip(self.dict.words, emb):
                    f.write(w.encode() + b" "
                            + np.asarray(row, np.float32).tobytes() + b"\n")
            return
        with open(path, "w") as f:
            f.write(f"{len(self.dict)} {self.cfg.size}\n")
            for w, row in zip(self.dict.words, emb):
                f.write(w + " " + " ".join(f"{v:.6f}" for v in row) + "\n")


def load_embeddings(path: str) -> Tuple[List[str], np.ndarray]:
    """Read embeddings written by :meth:`WordEmbedding.save_embeddings`,
    auto-detecting text vs binary (both carry the same ``"V D\\n"``
    header; the binary body is the classic word2vec .bin row layout).
    Returns (words, (V, D) float32 matrix) — binary round-trips
    bit-exact."""
    with open(path, "rb") as f:
        head = f.readline().split()
        v, d = int(head[0]), int(head[1])
        rest = f.read()
    # text rows are pure ASCII floats; binary rows embed raw float bytes.
    # Decide ONCE from the first row (the reference had no marker either);
    # after that, parse errors mean a malformed file and must propagate —
    # falling back would silently reinterpret broken text as binary.
    def _first_row_is_text() -> bool:
        try:   # probe ONLY the first line — no full-file decode
            nl = rest.find(b"\n")
            row = rest[: nl if nl >= 0 else len(rest)].decode(
                "utf-8", errors="strict")
            vals = np.asarray(row.split()[1:], np.float32)
            return vals.size == d
        except (ValueError, UnicodeDecodeError, IndexError):
            return False

    if _first_row_is_text():
        rows = rest.decode("utf-8").splitlines()
        if len(rows) != v:
            raise ValueError(
                f"{path}: malformed text embeddings (header says {v} "
                f"rows, file has {len(rows)})")
        twords: List[str] = []
        emb = np.empty((v, d), np.float32)
        for i, row in enumerate(rows):
            parts = row.split()
            twords.append(parts[0])
            emb[i] = np.asarray(parts[1:], np.float32)
        return twords, emb
    words: List[str] = []
    emb = np.empty((v, d), np.float32)
    off = 0
    for i in range(v):
        sp = rest.index(b" ", off)
        words.append(rest[off:sp].decode("utf-8", errors="replace"))
        start = sp + 1
        emb[i] = np.frombuffer(rest, np.float32, count=d, offset=start)
        off = start + 4 * d + 1   # skip the trailing newline
    return words, emb


def synthetic_corpus(num_tokens: int = 200_000, vocab: int = 2000,
                     seed: int = 0) -> List[str]:
    """Zipf-distributed token stream with local co-occurrence structure
    (bench/test stand-in for text8 in a zero-egress environment): tokens are
    drawn in correlated runs so that nearby words share topics."""
    rng = np.random.default_rng(seed)
    base = rng.zipf(1.3, size=num_tokens) % vocab
    # topic runs: overwrite stretches with a narrow band of ids
    out = base.copy()
    pos = 0
    while pos < num_tokens:
        run = int(rng.integers(5, 50))
        topic = int(rng.integers(0, max(vocab - 50, 1)))
        out[pos: pos + run] = topic + (base[pos: pos + run] % 50)
        pos += run
    return [f"w{t}" for t in out]


def read_vocab_file(path: str, min_count: int,
                    max_vocab: Optional[int] = None) -> Dictionary:
    """Adopt a pre-counted vocabulary ("word count" lines, any order —
    re-sorted count-desc like the reference's loader, capped at
    ``max_vocab`` like Dictionary.build; ref
    distributed_wordembedding.cpp:415-446 consuming the
    preprocess/word_count.cpp output)."""
    items = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) < 2:
                continue
            c = int(parts[-1])
            if c >= min_count:
                items.append((" ".join(parts[:-1]), c))
    if not items:
        raise ValueError(f"vocab file {path} has no words >= min_count")
    items.sort(key=lambda wc: (-wc[1], wc[0]))
    if max_vocab is not None:
        items = items[:max_vocab]
    return Dictionary.from_counts([w for w, _ in items],
                                  np.array([c for _, c in items], np.int64),
                                  min_count)


def load_corpus(cfg: WEConfig):
    """Build (Dictionary, encoded ids) for cfg.train_file, preferring the
    native C++ loader (mv_data.cpp: tokenize+count+prune+encode in one
    pass); -read_vocab adopts a pre-counted vocabulary instead of
    re-scanning, -save_vocab writes one (ref word_count preprocess)."""
    max_vocab = int(cfg.max_vocab) if cfg.max_vocab else None
    dictionary = None
    if cfg.read_vocab:
        dictionary = read_vocab_file(cfg.read_vocab, cfg.min_count,
                                     max_vocab)
        if cfg.train_file and native.available():
            # keep the native one-pass tokenizer: encode under ITS vocab,
            # then remap native ids onto the adopted vocabulary (ids not
            # in it drop, same OOV rule as Dictionary.encode)
            corpus = native.NativeCorpus(cfg.train_file, 1, None)
            remap = np.array(
                [dictionary.word2id.get(w, -1) for w in corpus.words()],
                np.int64)
            ids = remap[corpus.ids().astype(np.int64)]
            _maybe_save_vocab(cfg, dictionary)
            return dictionary, prepare_ids(dictionary, ids[ids >= 0], cfg)
    if cfg.train_file and dictionary is None and native.available():
        corpus = native.NativeCorpus(cfg.train_file, cfg.min_count,
                                     max_vocab)
        dictionary = Dictionary.from_counts(corpus.words(), corpus.counts(),
                                            cfg.min_count)
        _maybe_save_vocab(cfg, dictionary)
        return dictionary, prepare_ids(dictionary,
                                       corpus.ids().astype(np.int64), cfg)
    if cfg.train_file:
        # byte-level ASCII-whitespace split, matching the native tokenizer
        # exactly (mv_data.cpp is_space) so results don't depend on whether
        # the C++ build is available
        with open(cfg.train_file, "rb") as f:
            tokens = [t.decode("utf-8", errors="replace")
                      for t in f.read().split()]
    else:
        log.info("no -train_file given; using synthetic corpus")
        tokens = synthetic_corpus()
    if dictionary is None:
        dictionary = Dictionary.build(tokens, cfg.min_count, max_vocab)
    _maybe_save_vocab(cfg, dictionary)
    return dictionary, prepare_ids(dictionary, dictionary.encode(tokens), cfg)


def _maybe_save_vocab(cfg: WEConfig, dictionary: Dictionary) -> None:
    if not cfg.save_vocab:
        return
    with open(cfg.save_vocab, "w") as f:
        for w, c in zip(dictionary.words, dictionary.counts.tolist()):
            f.write(f"{w} {c}\n")


def main(argv=None) -> int:
    # honor JAX_PLATFORMS/XLA_FLAGS even under a site-registered
    # accelerator plugin (same contract as the harness): multi-process
    # runs on one host set JAX_PLATFORMS=cpu per worker, since only one
    # process can hold the accelerator
    from multiverso_tpu.utils.platform import apply_platform_env
    apply_platform_env()
    argv = argv if argv is not None else sys.argv[1:]
    # "-key=value" entries flow into the runtime flag registry exactly like
    # the reference's MV_Init(&argc, argv) (ref src/multiverso.cpp:10) —
    # e.g. -ps_rank=0 -ps_world=4 -ps_rendezvous=/dir launches the
    # uncoordinated plane straight from the app command line
    from multiverso_tpu.utils import config as config_lib
    argv = config_lib.consume_runtime_flags(argv)
    cfg = WEConfig.from_argv(argv)
    mv.init()
    dictionary, ids = load_corpus(cfg)
    log.info("vocab %d words, %d training tokens (native=%s)",
             len(dictionary), ids.size, native.available())
    we = WordEmbedding(cfg, dictionary)
    if cfg.use_ps:
        stats = we.train_ps_blocks(ids)
    else:
        stats = we.train_fused(ids)
    log.info("trained: %s", stats)
    we.save_embeddings()
    mv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
