"""WordEmbedding application (distributed word2vec).

TPU-native re-build of the reference WordEmbedding app
(ref: Applications/WordEmbedding/src/distributed_wordembedding.cpp — block
pipeline driver; src/communicator.cpp — PS glue pulling rows per block and
pushing (new-old)/workers deltas; src/trainer.cpp — words/sec reporting;
src/util.cpp — argv config). Capability parity:

* skipgram / CBOW, negative sampling / hierarchical softmax
* min_count vocab pruning, frequent-word subsampling, dynamic window
* block pipeline: per data block, pull the block's vocabulary rows from the
  parameter tables, train the block, push deltas — with the pull of block
  N+1 overlapped with training block N (ref :178-227 OMP overlap) via
  AsyncBuffer
* KVTable word-count aggregation across workers (ref communicator.cpp:17-31)
* words/sec per chip reporting

Two execution paths:
* ``train_fused``: the whole corpus trains on device via a jitted scan — the
  TPU-first path used for the headline words/sec benchmark.
* ``train_ps_blocks``: the reference's block Get/Add flow against
  MatrixTables — the semantics-parity path (and the multi-process one).

Usage: ``python -m multiverso_tpu.apps.word_embedding -train_file f.txt
-output vec.txt -size 128 ...`` (argv keys mirror ref util.cpp ParseArgs).
"""

from __future__ import annotations

import sys
import time
from typing import Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import multiverso_tpu as mv
from multiverso_tpu import native
from multiverso_tpu.data.dictionary import Dictionary, build_huffman
from multiverso_tpu.models import word2vec as w2v
from multiverso_tpu.utils import log
from multiverso_tpu.utils.async_buffer import AsyncBuffer
from multiverso_tpu.utils.dashboard import monitor


def _gen_pairs(ids: np.ndarray, window: int, seed: int):
    """Prefer the native C++ pair generator (mv_data.cpp); fall back to the
    vectorized numpy path."""
    if native.available():
        return native.generate_pairs(ids, window, seed=seed)
    return w2v.generate_pairs(ids, window, seed=seed)


def prepare_ids(dictionary: Dictionary, ids: np.ndarray,
                cfg: "WEConfig") -> np.ndarray:
    """THE subsampling policy — one implementation shared by every entry
    point (app method, load_corpus, bench) so id streams can't diverge."""
    if cfg.sample <= 0:
        return ids
    if native.available():
        return native.subsample(ids, dictionary.counts, cfg.sample,
                                seed=cfg.seed).astype(np.int64)
    return dictionary.subsample(ids, cfg.sample, seed=cfg.seed)


class WEConfig:
    """ref util.cpp ParseArgs keys (-size -window -negative -hs -cbow -alpha
    -epoch -min_count -sample -batch_size -data_block_size)."""

    def __init__(self, **kw):
        self.size = int(kw.get("size", 128))
        self.window = int(kw.get("window", 5))
        self.negative = int(kw.get("negative", 5))
        # TPU-first extension: >0 = batch-shared negative pool of this size
        # in the fused path (gradients rescaled to the -negative objective);
        # 0 = reference per-pair semantics.
        self.shared_negatives = int(kw.get("shared_negatives", 64))
        self.hs = str(kw.get("hs", "0")) in ("1", "true", "True")
        self.cbow = str(kw.get("cbow", "0")) in ("1", "true", "True")
        self.alpha = float(kw.get("alpha", 0.025))
        self.epoch = int(kw.get("epoch", 1))
        self.min_count = int(kw.get("min_count", 5))
        self.sample = float(kw.get("sample", 1e-4))
        self.batch_size = int(kw.get("batch_size", 1024))
        self.data_block_size = int(kw.get("data_block_size", 100_000))
        # reference-shaped PS block pipeline (pull rows / train / push
        # deltas, ref ps_model-style use_ps) instead of the fused path
        self.use_ps = str(kw.get("use_ps", "0")) in ("1", "true", "True")
        # uncoordinated async tables (multiverso_tpu.ps): workers trade
        # rows at independent rates — the reference's default Server mode
        self.async_ps = str(kw.get("async_ps", "0")) in ("1", "true", "True")
        self.max_vocab = kw.get("max_vocab")
        self.train_file = kw.get("train_file", "")
        self.output = kw.get("output", "")
        self.seed = int(kw.get("seed", 0))

    @classmethod
    def from_argv(cls, argv: List[str]) -> "WEConfig":
        kw = {}
        i = 0
        while i < len(argv):
            a = argv[i]
            if a.startswith("-") and i + 1 < len(argv):
                kw[a.lstrip("-")] = argv[i + 1]
                i += 2
            else:
                i += 1
        return cls(**kw)


class WordEmbedding:
    def __init__(self, cfg: WEConfig, dictionary: Dictionary):
        if not mv.Zoo.get().started:
            mv.init()
        self.cfg = cfg
        self.dict = dictionary
        v, d = len(dictionary), cfg.size
        if v < 2:
            raise ValueError("vocabulary too small; lower min_count")
        # input/output embedding tables (ref communicator.cpp:17-31: two
        # MatrixTables; input randomly initialized server-side). async_ps
        # swaps in the uncoordinated tables — same client API, no lockstep.
        if cfg.async_ps:
            matrix, kv = mv.AsyncMatrixTable, mv.AsyncKVTable
        else:
            matrix, kv = mv.MatrixTable, mv.KVTable
        self.table_in = matrix(v, d, name="embed_in", updater="default",
                               seed=cfg.seed + 17, init_scale=0.5 / d)
        self.table_out = matrix(v, d, name="embed_out", updater="default")
        self.word_count = kv(name="word_count")
        self.unigram = dictionary.unigram_table()
        self._trained_words = 0
        self._data_presplit = False   # caller already sharded the corpus
        self._fused_cache: Dict[str, object] = {}
        self._pair_cache: Dict[object, object] = {}
        if cfg.hs:
            codes, points, lengths = build_huffman(dictionary.counts)
            self._hs = (codes, points, lengths)
            self.table_hs = matrix(max(v - 1, 1), d, name="embed_hs",
                                   updater="default")
        else:
            self._hs = None

    # ------------------------------------------------------------------ #
    # corpus -> id stream
    # ------------------------------------------------------------------ #
    def prepare_ids(self, tokens) -> np.ndarray:
        return prepare_ids(self.dict, self.dict.encode(tokens), self.cfg)

    def _batches(self, centers: np.ndarray, contexts: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
        b = self.cfg.batch_size
        n = (centers.size // b) * b
        if n == 0:
            raise ValueError(
                f"corpus too small: {centers.size} pairs < batch {b}")
        return (centers[:n].reshape(-1, b), contexts[:n].reshape(-1, b))

    def _device_pairs(self, ids: np.ndarray):
        """Batched (centers, contexts) pair arrays, resident on device.

        Pair generation is one-time corpus preprocessing; caching the
        device-resident batches (keyed by a corpus fingerprint) keeps repeat
        epochs off the host->device path entirely.
        """
        key = (ids.shape, hash(ids.tobytes()),
               self.cfg.window, self.cfg.seed, self.cfg.batch_size)
        hit = self._pair_cache.get(key)
        if hit is None:
            centers, contexts = _gen_pairs(ids, self.cfg.window,
                                           self.cfg.seed)
            cb, xb = self._batches(centers, contexts)
            hit = (jnp.asarray(cb), jnp.asarray(xb), cb.size)
            self._pair_cache = {key: hit}  # hold one corpus at a time
        return hit

    # ------------------------------------------------------------------ #
    # fused path (device-resident training)
    # ------------------------------------------------------------------ #
    def train_fused(self, ids: np.ndarray,
                    epochs: Optional[int] = None) -> Dict[str, float]:
        cfg = self.cfg
        epochs = epochs or cfg.epoch
        w2v_cfg = w2v.W2VConfig(len(self.dict), cfg.size, cfg.negative,
                                cfg.window, cfg.alpha, cfg.cbow, cfg.hs,
                                cfg.shared_negatives)
        key = jax.random.key(cfg.seed)
        t0, loss, pairs = time.perf_counter(), None, 0

        if cfg.cbow:
            windows, masks, targets = w2v.generate_cbow_batches(ids, cfg.window)
            b = cfg.batch_size
            n = (targets.size // b) * b
            if n == 0:
                raise ValueError("corpus too small for batch size")
            wb = jnp.asarray(windows[:n].reshape(-1, b, windows.shape[1]))
            mb = jnp.asarray(masks[:n].reshape(-1, b, masks.shape[1]))
            tb = jnp.asarray(targets[:n].reshape(-1, b))
            pairs = n
            state_in = self.table_in.state
            win = state_in["data"]
            if cfg.hs:
                codes, points, lengths = self._hs
                epoch_fn = self._fused_cache.get("cbow_hs")
                if epoch_fn is None:
                    epoch_fn = self._fused_cache["cbow_hs"] = (
                        w2v.make_fused_cbow_hs_epoch(w2v_cfg, codes, points,
                                                     lengths))
                state_hs = self.table_hs.state
                hs_out = state_hs["data"]
                for _ in range(epochs):
                    key, sub = jax.random.split(key)
                    win, hs_out, loss = epoch_fn(win, hs_out, wb, mb, tb,
                                                 sub)
                jax.block_until_ready(win)
                self.table_hs.adopt({"data": hs_out,
                                     "ustate": state_hs["ustate"]})
            else:
                epoch_fn = self._fused_cache.get("cbow")
                if epoch_fn is None:
                    epoch_fn = self._fused_cache["cbow"] = (
                        w2v.make_fused_cbow_epoch(w2v_cfg, self.unigram))
                state_out = self.table_out.state
                wout = state_out["data"]
                for _ in range(epochs):
                    key, sub = jax.random.split(key)
                    win, wout, loss = epoch_fn(win, wout, wb, mb, tb, sub)
                jax.block_until_ready(win)
                self.table_out.adopt({"data": wout,
                                      "ustate": state_out["ustate"]})
            self.table_in.adopt({"data": win, "ustate": state_in["ustate"]})
        else:
            cbd, xbd, pairs = self._device_pairs(ids)
            state_in = self.table_in.state
            win = state_in["data"]
            if cfg.hs:
                codes, points, lengths = self._hs
                epoch_fn = self._fused_cache.get("hs")
                if epoch_fn is None:
                    epoch_fn = self._fused_cache["hs"] = (
                        w2v.make_fused_hs_epoch(w2v_cfg, codes, points,
                                                lengths))
                state_hs = self.table_hs.state
                hs_out = state_hs["data"]
                for _ in range(epochs):
                    key, sub = jax.random.split(key)
                    win, hs_out, loss = epoch_fn(win, hs_out, cbd, xbd, sub)
                jax.block_until_ready(win)
                self.table_hs.adopt({"data": hs_out,
                                     "ustate": state_hs["ustate"]})
            elif cfg.shared_negatives > 0:
                # TPU-first fast path: batch-shared negatives on the MXU
                epoch_fn = self._fused_cache.get("sg_shared")
                if epoch_fn is None:
                    cd = (jnp.bfloat16
                          if jax.devices()[0].platform == "tpu"
                          else jnp.float32)
                    epoch_fn = self._fused_cache["sg_shared"] = (
                        w2v.make_fused_shared_epoch(w2v_cfg, self.unigram,
                                                    compute_dtype=cd))
                    self._lcg = jnp.asarray(w2v.init_lcg_state(
                        cfg.shared_negatives, cfg.seed))
                state_out = self.table_out.state
                # epoch_fn donates its table args; chain from copies so the
                # live table buffers survive a mid-epoch failure (OOM/^C)
                win = jnp.copy(win)
                wout = jnp.copy(state_out["data"])
                for _ in range(epochs):
                    win, wout, loss, self._lcg = epoch_fn(
                        win, wout, cbd, xbd, self._lcg)
                jax.block_until_ready(win)
                self.table_out.adopt({"data": wout,
                                      "ustate": state_out["ustate"]})
            else:
                epoch_fn = self._fused_cache.get("sg")
                if epoch_fn is None:
                    epoch_fn = self._fused_cache["sg"] = (
                        w2v.make_fused_epoch(w2v_cfg, self.unigram))
                state_out = self.table_out.state
                wout = state_out["data"]
                for _ in range(epochs):
                    key, sub = jax.random.split(key)
                    win, wout, loss = epoch_fn(win, wout, cbd, xbd, sub)
                jax.block_until_ready(win)
                self.table_out.adopt({"data": wout,
                                      "ustate": state_out["ustate"]})
            self.table_in.adopt({"data": win, "ustate": state_in["ustate"]})

        # host readback of the scalar loss is the reliable device-drain sync
        # (block_until_ready alone can return early over a remote/tunneled
        # PJRT transport), so fetch it BEFORE stopping the clock
        loss_f = float(loss)
        dt = time.perf_counter() - t0
        # words/sec follows the word2vec convention: corpus *tokens* consumed
        # per second (ref trainer.cpp words/sec), not training pairs.
        words = epochs * int(ids.size)
        self._trained_words += words
        self.word_count.add([0], [words])
        return {"loss": loss_f, "words_per_sec": words / dt,
                "seconds": dt, "pairs": int(pairs),
                "pairs_per_sec": epochs * pairs / dt}

    # ------------------------------------------------------------------ #
    # PS block path (reference block pipeline; multi-worker capable)
    # ------------------------------------------------------------------ #
    def _block_step_fn(self):
        """Jitted per-minibatch step for the active (cbow, hs) mode; the
        PS-block path supports all four variants like the reference's
        distributed trainer (ref wordembedding.cpp FeedForward/HS/NS
        branches)."""
        if not hasattr(self, "_block_jit"):
            cfg = self.cfg
            if cfg.cbow and cfg.hs:
                fn = lambda a, b, w, m, c, p, pm: w2v.cbow_hs_step(
                    a, b, w, m, c, p, pm, cfg.alpha)
            elif cfg.cbow:
                fn = lambda a, b, w, m, t, n: w2v.cbow_ns_step(
                    a, b, w, m, t, n, cfg.alpha)
            elif cfg.hs:
                fn = lambda a, b, c, cd, p, pm: w2v.skipgram_hs_step(
                    a, b, c, cd, p, pm, cfg.alpha)
            else:
                fn = lambda a, b, c, x, n: w2v.skipgram_ns_step(
                    a, b, c, x, n, cfg.alpha)
            self._block_jit = jax.jit(fn)
        return self._block_jit

    def train_ps_blocks(self, ids: np.ndarray,
                        epochs: Optional[int] = None) -> Dict[str, float]:
        """ref distributed_wordembedding.cpp:147-252: per block pull rows,
        train locally, push (new - old) deltas. The pull for block N+1 is
        dispatched before block N trains (ref :202-223 OMP overlap thread) —
        its device gather + host transfer proceed while block N computes, at
        the cost of the same one-block staleness the reference accepts."""
        cfg = self.cfg
        epochs = epochs or cfg.epoch
        rng = np.random.default_rng(cfg.seed)
        nw, wid = self._ps_topology()
        t0, losses, words = time.perf_counter(), [], 0
        blocks = [ids[lo: lo + cfg.data_block_size]
                  for lo in range(0, ids.size, cfg.data_block_size)]
        blocks = [b for b in blocks if b.size >= 2]
        if nw > 1 and cfg.async_ps and not self._data_presplit:
            # data split evenly per worker (ref BENCHMARK.md common
            # settings). ONLY on the uncoordinated plane: sync-table
            # add_rows is a collective, so unequal per-worker block counts
            # would leave the worker with more blocks waiting forever.
            blocks = blocks[wid::nw]
        # one flat schedule across all epochs so the pull of the next block
        # overlaps training of the current one at every step, including
        # across epoch boundaries (ref :202-223 keeps its overlap thread
        # alive for the whole multi-epoch run)
        schedule = [b for _ in range(epochs) for b in blocks]
        prepared = self._prepare_block(schedule[0], rng) if schedule else None
        for i, block in enumerate(schedule):
            nxt = (self._prepare_block(schedule[i + 1], rng)
                   if i + 1 < len(schedule) else None)
            losses.append(self._train_prepared(prepared, nw))
            words += block.size
            prepared = nxt
        # drain in-flight async pushes so the trained state is durable
        # before the caller reads embeddings (sync tables order by program
        # order; async tables need the explicit flush)
        for t in (self.table_in, self.table_out,
                  getattr(self, "table_hs", None)):
            if t is not None and hasattr(t, "flush"):
                t.flush()
        dt = time.perf_counter() - t0
        self._trained_words += words
        self.word_count.add([0], [words])
        return {"loss": float(np.mean(losses)) if losses else 0.0,
                "words_per_sec": words / dt, "seconds": dt}

    def _prepare_block(self, block: np.ndarray, rng) -> Dict:
        """Host-side block prep + *dispatch* of the row pulls
        (ref RequestParameter, communicator.cpp:104-142). Builds the
        mode-specific training arrays, the block's input-vocab remap, and
        — for HS modes — the block's Huffman inner-node set/remap."""
        cfg = self.cfg
        with monitor("we.prepare"):
            prep: Dict = {}
            if cfg.cbow:
                windows, masks, targets = w2v.generate_cbow_batches(
                    block, cfg.window)
                prep.update(windows=windows, masks=masks, targets=targets)
                used = [windows.reshape(-1), targets, np.zeros(1, np.int64)]
                examples = targets   # the word whose path/negs are scored
            else:
                centers, contexts = _gen_pairs(block, cfg.window,
                                               int(rng.integers(1 << 31)))
                prep.update(centers=centers, contexts=contexts)
                used = [centers, contexts]
                examples = contexts
            if cfg.hs:
                codes, points, lengths = self._hs
                t = np.asarray(examples, np.int64)
                pmask = (np.arange(codes.shape[1])[None, :]
                         < lengths[t][:, None])
                prep.update(codes=codes[t], points=points[t], pmask=pmask)
                hs_rows = np.unique(prep["points"][pmask])
                # remap path points into the pulled hs block; padded path
                # slots route to a dummy extra row (their grads are masked
                # to zero, the scatter just needs a valid index)
                remap_hs = np.full(self.table_hs.shape[0] + 1,
                                   hs_rows.size, np.int64)
                remap_hs[hs_rows] = np.arange(hs_rows.size)
                prep.update(hs_rows=hs_rows, remap_hs=remap_hs,
                            pull_hs=self.table_hs.get_rows_async(hs_rows))
            else:
                negs = rng.choice(
                    len(self.dict),
                    size=(max(examples.size, 1), cfg.negative),
                    p=self.unigram).astype(np.int32)
                prep["negs"] = negs
                used.append(negs.reshape(-1))
            vocab = np.unique(np.concatenate(
                [np.asarray(u).reshape(-1) for u in used]))
            remap = np.full(len(self.dict), -1, np.int64)
            remap[vocab] = np.arange(vocab.size)
            prep.update(
                vocab=vocab, remap=remap,
                pull_in=self.table_in.get_rows_async(vocab))
            if not cfg.hs:
                prep["pull_out"] = self.table_out.get_rows_async(vocab)
            return prep

    def _read_pull(self, table, msg_id):
        return jnp.asarray(table.wait(msg_id))

    def _train_prepared(self, prep: Dict, num_workers: int) -> float:
        cfg = self.cfg
        with monitor("we.block"):
            win_l = self._read_pull(self.table_in, prep["pull_in"])
            examples = (prep["targets"] if cfg.cbow
                        else prep["centers"])
            if examples.size == 0:
                return 0.0
            old_in = win_l
            if cfg.hs:
                pulled = self._read_pull(self.table_hs, prep["pull_hs"])
                # one dummy extra row catches padded path slots (their
                # grads are masked to zero; the scatter needs a valid id)
                wsec_l = jnp.concatenate(
                    [pulled, jnp.zeros((1, pulled.shape[1]),
                                       pulled.dtype)])
            else:
                wsec_l = self._read_pull(self.table_out, prep["pull_out"])
            old_sec = wsec_l
            step = self._block_step_fn()
            remap = prep["remap"]
            b = cfg.batch_size
            n = max((examples.size // b) * b, 0)
            # loss accumulates ON DEVICE; one host readback per block, not
            # one per minibatch (each readback is a full dispatch round-trip)
            loss_acc, nb = jnp.zeros(()), 0
            for i in range(0, n, b):
                sl = slice(i, i + b)
                if cfg.cbow:
                    head = (jnp.asarray(remap[prep["windows"][sl]],
                                        jnp.int32),
                            jnp.asarray(prep["masks"][sl]))
                else:
                    head = (jnp.asarray(remap[prep["centers"][sl]],
                                        jnp.int32),)
                if cfg.hs:
                    tail = (jnp.asarray(prep["codes"][sl], jnp.int32),
                            jnp.asarray(prep["remap_hs"][prep["points"][sl]],
                                        jnp.int32),
                            jnp.asarray(prep["pmask"][sl]))
                elif cfg.cbow:
                    tail = (jnp.asarray(remap[prep["targets"][sl]],
                                        jnp.int32),
                            jnp.asarray(remap[prep["negs"][sl]], jnp.int32))
                else:
                    tail = (jnp.asarray(remap[prep["contexts"][sl]],
                                        jnp.int32),
                            jnp.asarray(remap[prep["negs"][sl]], jnp.int32))
                win_l, wsec_l, loss = step(win_l, wsec_l, *head, *tail)
                loss_acc, nb = loss_acc + loss, nb + 1
            # AddDeltaParameter: (new - old) / workers, pushed ASYNC like
            # the reference (ref communicator.cpp:144-236 AddAsync) — the
            # push overlaps the next block's prep/compute. Ordering is
            # safe: sync tables dispatch in program order, and on the
            # async plane arrival-order accumulation is the semantics.
            with monitor("we.push"):
                d_in = np.asarray(win_l - old_in) / num_workers
                self.table_in.add_rows_async(prep["vocab"], d_in)
                d_sec = np.asarray(wsec_l - old_sec) / num_workers
                if cfg.hs:
                    self.table_hs.add_rows_async(prep["hs_rows"],
                                                 d_sec[:-1])  # drop dummy
                else:
                    self.table_out.add_rows_async(prep["vocab"], d_sec)
            return float(loss_acc) / max(nb, 1)

    def _ps_topology(self) -> Tuple[int, int]:
        """(num_workers, worker_id) of the PS plane in use: the async
        context's world for uncoordinated tables, the collective runtime's
        otherwise."""
        if self.cfg.async_ps:
            ctx = self.table_in.ctx
            return max(ctx.world, 1), ctx.rank
        return max(mv.num_workers(), 1), mv.rank()

    def total_word_count(self) -> int:
        """Global trained-word count across all workers — the reference reads
        the server-aggregated KV value (ref communicator.cpp:17-31 +
        kv_table.h:44-99), so this uses the aggregated Get, not the local
        view. Async tables aggregate on every get (uncoordinated); the sync
        KVTable needs the collective global_=True read."""
        return int(self.word_count.get([0], global_=True)[0])

    # ------------------------------------------------------------------ #
    def embeddings(self) -> np.ndarray:
        return self.table_in.get()

    def nearest(self, word: str, k: int = 10) -> List[str]:
        wid = self.dict.word2id[word]
        ids = w2v.nearest_neighbors(self.embeddings(), wid, k)
        return [self.dict.words[i] for i in ids]

    def save_embeddings(self, path: Optional[str] = None) -> None:
        """ref SaveEmbedding (distributed_wordembedding.cpp:263-306):
        word2vec text format."""
        path = path or self.cfg.output
        if not path:
            return
        emb = self.embeddings()
        with open(path, "w") as f:
            f.write(f"{len(self.dict)} {self.cfg.size}\n")
            for w, row in zip(self.dict.words, emb):
                f.write(w + " " + " ".join(f"{v:.6f}" for v in row) + "\n")


def synthetic_corpus(num_tokens: int = 200_000, vocab: int = 2000,
                     seed: int = 0) -> List[str]:
    """Zipf-distributed token stream with local co-occurrence structure
    (bench/test stand-in for text8 in a zero-egress environment): tokens are
    drawn in correlated runs so that nearby words share topics."""
    rng = np.random.default_rng(seed)
    base = rng.zipf(1.3, size=num_tokens) % vocab
    # topic runs: overwrite stretches with a narrow band of ids
    out = base.copy()
    pos = 0
    while pos < num_tokens:
        run = int(rng.integers(5, 50))
        topic = int(rng.integers(0, max(vocab - 50, 1)))
        out[pos: pos + run] = topic + (base[pos: pos + run] % 50)
        pos += run
    return [f"w{t}" for t in out]


def load_corpus(cfg: WEConfig):
    """Build (Dictionary, encoded ids) for cfg.train_file, preferring the
    native C++ loader (mv_data.cpp: tokenize+count+prune+encode in one pass)."""
    max_vocab = int(cfg.max_vocab) if cfg.max_vocab else None
    if cfg.train_file and native.available():
        corpus = native.NativeCorpus(cfg.train_file, cfg.min_count,
                                     max_vocab)
        dictionary = Dictionary.from_counts(corpus.words(), corpus.counts(),
                                            cfg.min_count)
        return dictionary, prepare_ids(dictionary,
                                       corpus.ids().astype(np.int64), cfg)
    if cfg.train_file:
        # byte-level ASCII-whitespace split, matching the native tokenizer
        # exactly (mv_data.cpp is_space) so results don't depend on whether
        # the C++ build is available
        with open(cfg.train_file, "rb") as f:
            tokens = [t.decode("utf-8", errors="replace")
                      for t in f.read().split()]
    else:
        log.info("no -train_file given; using synthetic corpus")
        tokens = synthetic_corpus()
    dictionary = Dictionary.build(tokens, cfg.min_count, max_vocab)
    return dictionary, prepare_ids(dictionary, dictionary.encode(tokens), cfg)


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    cfg = WEConfig.from_argv(argv)
    mv.init()
    dictionary, ids = load_corpus(cfg)
    log.info("vocab %d words, %d training tokens (native=%s)",
             len(dictionary), ids.size, native.available())
    we = WordEmbedding(cfg, dictionary)
    if cfg.use_ps:
        stats = we.train_ps_blocks(ids)
    else:
        stats = we.train_fused(ids)
    log.info("trained: %s", stats)
    we.save_embeddings()
    mv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
